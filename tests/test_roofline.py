"""Fixture tests for the roofline instrument's HLO parsers.

Two silent-overcount regressions are pinned here because each one poisoned
a committed artifact before it was caught:

- the conv FLOP counter applied a kernel-shaped heuristic to
  activation-shaped rhs operands, attributing ~30x over-counts (petaflops)
  to grad-w convolutions (densenet);
- the naive 2*out*window*rhs_i count charges padding positions as real
  MACs, a 4096x over-count on the grad-x of a 1x1 conv, which XLA
  canonicalizes into a 64x64-window conv over the 63-padded weight
  (mobilenet_v2) — pushing Σ attainable above the *measured* step time,
  an impossible "lower bound".

The fixed semantics: per-axis valid-MAC counting (padding/dilation
positions excluded), window-less convs scored as the dots they are, and
HBM byte accounting that skips VMEM/SMEM-pinned (``S(n)``) buffers and
alias-only ops (``*-done``, ``ConcatBitcast``).
"""

import pytest

import tools.roofline as rl


def _parse_line(line):
    m = rl._INSTR_RE.match(line)
    assert m, f"instruction regex failed on: {line}"
    return m.group(1), m.group(2), m.group(3), m.group(4)


def _conv_flops_from(lines, target):
    shapes, rows = {}, {}
    for line in lines:
        name, shape, op, rest = _parse_line(line)
        shapes[name] = shape
        rows[name] = (shape, op, rest)
    shape, _, rest = rows[target]
    return rl.conv_flops(shape, rest, shapes)


def test_forward_conv_flops_valid_macs():
    # resnet stem shape: 7x7 s2 conv, 3->64 channels, 128px -> 64px.
    lines = [
        "  %p0 = bf16[8,128,128,3]{3,2,1,0} parameter(0)",
        "  %p1 = bf16[7,7,3,64]{3,2,1,0} parameter(1)",
        "  %conv = bf16[8,64,64,64]{3,2,1,0} convolution(%p0, %p1),"
        " window={size=7x7 stride=2x2 pad=3_3x3_3}, dim_labels=b01f_01io->b01f",
    ]
    # Per-axis valid (o,k) pairs: j = 2o + k - 3 must land in [0,128).
    t_axis = sum(
        1
        for k in range(7)
        for o in range(64)
        if 0 <= 2 * o + k - 3 < 128
    )
    assert t_axis == 442  # naive O*W = 448; 6 edge pairs hit padding
    expected = 2 * (8 * 64) * (t_axis**2) * 3
    got = _conv_flops_from(lines, "conv")
    assert got == expected
    # within ~3% of the padding-blind count — edge effects only
    naive = 2 * (8 * 64 * 64 * 64) * 49 * 3
    assert 0.97 < got / naive < 1.0


def test_gradx_of_1x1_conv_not_4096x():
    """XLA canonicalizes the grad-x of a 1x1 conv into a full-image-window
    conv over the (W-1)-padded weight: 4095 of 4096 window positions hit
    padding. The naive count was 4096x the true cost (mobilenet_v2)."""
    lines = [
        "  %w = bf16[1,1,16,96]{3,2,1,0} parameter(0)",
        "  %dy = bf16[1024,64,64,96]{0,3,2,1} parameter(1)",
        "  %dx = bf16[1024,64,64,16]{0,3,2,1} convolution(%w, %dy),"
        " window={size=64x64 pad=63_63x63_63 rhs_reversal=1x1},"
        " dim_labels=01bf_o01i->f01b",
    ]
    got = _conv_flops_from(lines, "dx")
    # true grad-x cost: 2 * N * H * W * Cin * Cout
    assert got == 2 * 1024 * 64 * 64 * 16 * 96
    naive = 2 * (1024 * 64 * 64 * 16) * (64 * 64) * 96
    assert got * 4096 == naive  # the regression magnitude, pinned


def test_gradw_style_conv_not_exaflops():
    """grad-w convs have an ACTIVATION rhs and an image-sized window; the
    old heuristic (kernel_numel/Cout) attributed petaflops here."""
    lines = [
        "  %acts = bf16[8,32,32,112]{3,2,1,0} parameter(0)",
        "  %grads = bf16[8,32,32,128]{3,2,1,0} parameter(1)",
        "  %dw = bf16[3,3,112,128]{3,2,1,0} convolution(%acts, %grads),"
        " window={size=32x32 pad=1_1x1_1}, dim_labels=f01b_i01o->01bf",
    ]
    # Valid (o,k) pairs along one axis: out=3, lhs=32, window=32, pad 1.
    t_axis = sum(
        1 for k in range(32) for o in range(3) if 0 <= o + k - 1 < 32
    )
    assert t_axis == 94  # naive O*W = 96
    # rhs labels i01o: i at dim 0 -> rhs_dims[0] = 8 (the batch, which is
    # the contracted "feature" dim of a grad-w conv in this layout).
    expected = 2 * (112 * 128) * (t_axis**2) * 8
    got = _conv_flops_from(lines, "dw")
    assert got == expected
    assert got < 1e12  # the regression: old code returned ~1e15 here


def test_strided_backward_lhs_dilation_counts_real_macs_only():
    """grad-x of a stride-2 conv: lhs_dilate=2 inserts zeros between every
    lhs element; window positions landing on inserted zeros are skipped."""
    lines = [
        "  %dy = bf16[8,16,16,64]{3,2,1,0} parameter(0)",
        "  %w = bf16[3,3,32,64]{3,2,1,0} parameter(1)",
        "  %dx = bf16[8,32,32,32]{3,2,1,0} convolution(%dy, %w),"
        " window={size=3x3 pad=1_2x1_2 lhs_dilate=2x2 rhs_reversal=1x1},"
        " dim_labels=b01f_01oi->b01f",
    ]
    t_axis = 0
    for k in range(3):
        for o in range(32):
            j = o + k - 1
            if 0 <= j <= (16 - 1) * 2 and j % 2 == 0:
                t_axis += 1
    expected = 2 * (8 * 32) * (t_axis**2) * 64
    assert _conv_flops_from(lines, "dx") == expected
    # roughly half the window positions land on dilation zeros
    naive = 2 * (8 * 32 * 32 * 32) * 9 * 64
    assert expected < 0.6 * naive


def test_windowless_conv_is_a_dot():
    """XLA prints the head matmul as `convolution ... dim_labels=bf_io->bf`
    with NO window attribute; skipping it dropped ~500 GFLOP/step of the
    64 500-class head from mobilenet's roofline."""
    lines = [
        "  %x = bf16[1024,1280]{1,0} parameter(0)",
        "  %w = bf16[1280,64500]{1,0} parameter(1)",
        "  %mm = bf16[1024,64500]{1,0} convolution(%x, %w),"
        " dim_labels=bf_io->bf",
    ]
    assert _conv_flops_from(lines, "mm") == 2 * 1024 * 64500 * 1280


def test_grouped_conv_uses_hlo_per_group_features():
    """Depthwise conv: HLO rhs input-feature dim is already Cin/groups=1."""
    lines = [
        "  %x = bf16[8,56,56,32]{3,2,1,0} parameter(0)",
        "  %w = bf16[3,3,1,32]{3,2,1,0} parameter(1)",
        "  %dwise = bf16[8,56,56,32]{3,2,1,0} convolution(%x, %w),"
        " window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f,"
        " feature_group_count=32",
    ]
    t_axis = sum(
        1 for k in range(3) for o in range(56) if 0 <= o + k - 1 < 56
    )
    expected = 2 * (8 * 32) * (t_axis**2) * 1
    assert _conv_flops_from(lines, "dwise") == expected


def test_unparseable_conv_returns_zero_not_garbage():
    lines = [
        "  %x = bf16[8,56,56,32]{3,2,1,0} parameter(0)",
        "  %w = bf16[3,3,1,32]{3,2,1,0} parameter(1)",
        "  %weird = bf16[8,56,56,32]{3,2,1,0} convolution(%x, %w)",
    ]
    assert _conv_flops_from(lines, "weird") == 0.0


def test_dot_flops_mnk():
    lines = [
        "  %a = bf16[2048,512]{1,0} parameter(0)",
        "  %b = bf16[512,64500]{1,0} parameter(1)",
        "  %mm = bf16[2048,64500]{1,0} dot(%a, %b),"
        " lhs_contracting_dims={1}, rhs_contracting_dims={0}",
    ]
    shapes, rows = {}, {}
    for line in lines:
        name, shape, op, rest = _parse_line(line)
        shapes[name] = shape
        rows[name] = (shape, op, rest)
    shape, _, rest = rows["mm"]
    assert rl.dot_flops(shape, rest, shapes) == 2 * 2048 * 64500 * 512


def test_vmem_pinned_buffers_are_not_hbm_bytes():
    """S(n) memory-space layouts (VMEM/SMEM/sync) consume no HBM bandwidth;
    counting them pushed mobilenet's Σ attainable ABOVE its measured step."""
    hbm = "bf16[1024,64,64,96]{0,3,2,1:T(8,128)(2,1)}"
    vmem = "bf16[1024,16,16,32]{0,3,2,1:T(8,128)(2,1)S(1)}"
    smem_flag = "u32[]{:S(2)}"
    assert rl.shape_hbm_bytes(hbm) == 1024 * 64 * 64 * 96 * 2
    assert rl.shape_hbm_bytes(vmem) == 0
    assert rl.shape_hbm_bytes(smem_flag) == 0
    # tuple: only the HBM element counts
    assert rl.shape_hbm_bytes(f"({hbm}, {vmem})") == 1024 * 64 * 64 * 96 * 2
    # plain shape_bytes (cost attribution, not HBM) still counts everything
    assert rl.shape_bytes(vmem) == 1024 * 16 * 16 * 32 * 2


def test_alias_ops_carry_no_bytes():
    """*-done ops re-surface the transfer their *-start already counted;
    ConcatBitcast stitches async slice DMAs by aliasing. Counting either
    double-charges the same bytes."""
    hlo = """\
ENTRY %main (p0: bf16[1024,1024]) -> bf16[1024,1024] {
  %p0 = bf16[1024,1024]{1,0} parameter(0)
  %copy-start.1 = (bf16[1024,1024]{1,0:S(1)}, bf16[1024,1024]{1,0}, u32[]{:S(2)}) copy-start(%p0)
  %copy-done.1 = bf16[1024,1024]{1,0:S(1)} copy-done(%copy-start.1)
  %concat = bf16[1024,1024]{1,0} custom-call(%copy-done.1), custom_call_target="ConcatBitcast"
  ROOT %out = bf16[1024,1024]{1,0} fusion(%concat), kind=kLoop, calls=%fc
}
"""
    rows = rl.roofline(hlo, 197.0, 819.0)
    ops = {r["op"] for r in rows}
    assert "copy-done" not in ops
    assert "custom-call" not in ops  # the ConcatBitcast
    # copy-start counted once: reads p0 from HBM (1024*1024*2); the result
    # tuple's HBM element is an ALIAS of the operand (the real destination
    # is the S(1) element), so no write is charged.
    start = next(r for r in rows if r["op"] == "copy-start")
    assert start["bytes"] == 1024 * 1024 * 2


def test_collective_start_write_is_not_subtracted():
    """all-reduce-start's result is a real write (no operand alias in the
    tuple); zeroing it would understate multi-chip bounds."""
    hlo = """\
ENTRY %main (p0: bf16[4096,512]) -> bf16[4096,512] {
  %p0 = bf16[4096,512]{1,0} parameter(0)
  ROOT %ar = bf16[4096,512]{1,0} all-reduce-start(%p0), replica_groups={}
}
"""
    rows = rl.roofline(hlo, 197.0, 819.0)
    ar = next(r for r in rows if r["op"] == "all-reduce-start")
    assert ar["bytes"] == 2 * 4096 * 512 * 2  # read + write, both charged


def test_aliasing_collective_start_operand_is_subtracted():
    """all-gather-start / collective-permute-start return (operand, result)
    tuples whose first element ALIASES the input; charging it as an HBM
    write double-counts the operand on multi-chip HLOs (the exact
    impossible-lower-bound failure class the S(1) fix addressed)."""
    hlo = """\
ENTRY %main (p0: bf16[4096,512]) -> bf16[4096,4096] {
  %p0 = bf16[4096,512]{1,0} parameter(0)
  %ag = (bf16[4096,512]{1,0}, bf16[4096,4096]{1,0}) all-gather-start(%p0), replica_groups={}, dimensions={1}
  %p1 = bf16[4096,512]{1,0} parameter(1)
  ROOT %cp = (bf16[4096,512]{1,0}, bf16[4096,512]{1,0}) collective-permute-start(%p1), source_target_pairs={{0,1}}
}
"""
    rows = rl.roofline(hlo, 197.0, 819.0)
    ag = next(r for r in rows if r["op"] == "all-gather-start")
    # read p0 once + write only the RESULT element (8x the shard), not the
    # aliased operand element.
    assert ag["bytes"] == 4096 * 512 * 2 + 4096 * 4096 * 2
    cp = next(r for r in rows if r["op"] == "collective-permute-start")
    # (operand_alias, result): charge read + one result write.
    assert cp["bytes"] == 2 * 4096 * 512 * 2


def test_sizeless_window_does_not_zero_conv_flops():
    """A window={...} attribute without size= must degrade to the
    dot-degenerate count (like a missing window), never to 0 FLOPs."""
    assert rl._parse_window("window={stride=1x1}") == ([], [], [], [], [])
    shapes = {
        "lhs": "bf16[2048,512]{1,0}",
        "rhs": "bf16[512,64500]{1,0}",
    }
    rest = ("%lhs, %rhs), window={stride=1}, dim_labels=bf_io->bf")
    fl = rl.conv_flops("bf16[2048,64500]{1,0}", rest, shapes)
    assert fl == 2.0 * 2048 * 64500 * 512
