"""Offline torchvision → Flax weight converter (the ``use_pretrained`` path).

The reference gets pretrained backbones by downloading torchvision ImageNet
weights at model-construction time (``models.py:33,41,50,59,68,77,87``). This
environment has no torchvision and no egress, so the conversion runs offline,
once, wherever torchvision (or a saved ``.pth`` state_dict) is available:

    # with torchvision installed (downloads ImageNet weights):
    python tools/convert_torchvision.py --model resnet18 --out pretrained/

    # or from a saved state_dict file (no torchvision needed, torch only):
    python tools/convert_torchvision.py --model resnet18 \
        --state-dict resnet18-imagenet.pth --out pretrained/

The output ``pretrained/<model>.msgpack`` is what
``mpi_pytorch_tpu.models.pretrained.load_pretrained`` consumes when a config
sets ``use_pretrained=True`` (head layers always keep their fresh
``num_classes`` init, mirroring the reference's head replacement).
"""

from __future__ import annotations

import argparse
import os
import re
import sys

import jax

# Offline host-side tool: weight conversion never needs an accelerator, and
# forcing CPU here keeps it runnable on machines where the TPU plugin is
# absent or claimed (must land before first device use — see tests/conftest.py).
jax.config.update("jax_platforms", "cpu")

import numpy as np
from flax import serialization

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_pytorch_tpu.models import create_model_bundle  # noqa: E402
from mpi_pytorch_tpu.models.pretrained import CONVERTIBLE_MODELS as _MODELS  # noqa: E402
from mpi_pytorch_tpu.models.torch_mapping import convert_state_dict  # noqa: E402


def fetch_state_dict(model_name: str, state_dict_path: str | None) -> dict:
    """numpy state_dict either from a .pth file or live torchvision."""
    if state_dict_path:
        import torch

        sd = torch.load(state_dict_path, map_location="cpu", weights_only=True)
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
    else:
        try:
            import torchvision.models as tvm
        except ImportError:
            raise SystemExit(
                "torchvision is not installed here; pass --state-dict with a "
                ".pth file exported on a machine that has it"
            )
        kwargs = {"aux_logits": True} if model_name == "inception_v3" else {}
        sd = getattr(tvm, model_name)(weights="IMAGENET1K_V1", **kwargs).state_dict()
    out = {}
    # Legacy densenet hub checkpoints use norm.1/conv.2-style keys inside
    # denselayers (torchvision re-maps them in its own loader); normalize to
    # the modern norm1/conv2 names the mapping emits.
    legacy = re.compile(r"(denselayer\d+\.(?:norm|conv))\.(\d)\.")
    for k, v in sd.items():
        if k.endswith("num_batches_tracked"):
            continue
        k = legacy.sub(r"\g<1>\g<2>.", k)
        out[k] = v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)
    return out


def convert(model_name: str, out_dir: str, state_dict_path: str | None = None,
            num_classes: int = 64500) -> str:
    state_dict = fetch_state_dict(model_name, state_dict_path)
    bundle, variables = create_model_bundle(
        model_name, num_classes, rng=jax.random.PRNGKey(0),
    )
    converted = convert_state_dict(model_name, variables, state_dict)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{model_name}.msgpack")
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(converted))
    return path


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True, choices=sorted(_MODELS))
    ap.add_argument("--out", default="pretrained")
    ap.add_argument("--state-dict", default=None,
                    help=".pth state_dict file (otherwise torchvision downloads)")
    ap.add_argument("--num-classes", type=int, default=64500)
    args = ap.parse_args(argv)
    path = convert(args.model, args.out, args.state_dict, args.num_classes)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
