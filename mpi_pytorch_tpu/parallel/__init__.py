from mpi_pytorch_tpu.parallel import collectives
from mpi_pytorch_tpu.parallel.mesh import (
    create_mesh,
    named_shardings,
    param_specs,
    shard_batch,
)
from mpi_pytorch_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_forward,
    stack_stage_params,
)

__all__ = [
    "collectives",
    "create_mesh",
    "named_shardings",
    "param_specs",
    "pipeline_apply",
    "pipeline_forward",
    "shard_batch",
    "stack_stage_params",
]
