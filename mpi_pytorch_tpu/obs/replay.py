"""Trace-replay workloads (ISSUE 18): turn a recorded fleet trace into a
canonical, replayable **workload artifact**.

The fleet trace file (ISSUE 13, ``Config.fleet_trace_file``) is a JSONL
stream of spans; every request that completed at the front door has a
``route/request`` root span whose ``t0`` is its arrival wall time and
whose v14 attrs carry ``model``/``bucket``/``rows``/``precision``.  This
module extracts those roots into a :class:`Workload` — per-request
arrival offsets normalized to t=0, tenant/model, bucket row counts,
precision, and recorded outcomes — stamped with a content fingerprint so
a tuning claim can cite exactly which load shape it was measured under.

Layering: like the rest of ``obs`` this module never imports jax (or the
serve package).  The replay driver talks to a server object through its
``submit()`` surface only and classifies rejections by duck type, so it
drives ``InferenceServer``, ``FleetServer``, ``ZooServer``, and
``RemoteFleet`` alike.

Fidelity caveats, documented rather than hidden:

- The trace file is *tail sampled*.  At ``trace_sample_rate=1.0`` every
  trace is kept and the extracted workload is exact; at lower rates the
  arrival process is thinned toward kept traces (failed/slow/redispatched
  requests are over-represented).  Record with sample rate 1.0 when the
  workload is the point of the recording.
- Pre-v14 traces lack ``model``/``bucket``/``rows``/``precision`` root
  attrs.  They replay with documented defaults (``model=None``,
  ``bucket=None``, ``rows=1``, ``precision=None``) instead of erroring;
  ``Workload.defaults_applied`` counts how many requests were defaulted.
- Replay re-drives **every recorded arrival**, including requests the
  recorded fleet rejected: the arrival process is the workload, admission
  is the candidate config's decision to make.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
import time
from dataclasses import dataclass, field, replace

ROOT_SPAN = "route/request"

#: Defaults applied to pre-v14 root spans (documented, not an error).
DEFAULT_MODEL = None
DEFAULT_BUCKET = None
DEFAULT_ROWS = 1
DEFAULT_PRECISION = None

_SPAN_REQUIRED = {"name": str, "t0": (int, float), "t1": (int, float)}


class WorkloadError(ValueError):
    """Typed rejection for malformed or truncated fleet-trace input.

    Raised with the offending line number so a clipped recording (process
    death mid-write) points at exactly where the stream went bad.
    """


@dataclass(frozen=True)
class WorkloadRequest:
    """One recorded front-door arrival."""

    offset_s: float           # arrival offset from workload t=0
    model: str | None         # tenant, None for single-model fleets
    bucket: int | None        # bucket that served it (None pre-v14/rejected)
    rows: int                 # rows in the flush that carried it
    precision: str | None     # executable set that ran it
    outcome: str              # "ok" | "rejected" | "failed:<Type>"

    def key(self) -> tuple:
        return (round(self.offset_s, 6), self.model, self.bucket,
                self.rows, self.precision, self.outcome)

    def to_dict(self) -> dict:
        return {"offset_s": round(self.offset_s, 6), "model": self.model,
                "bucket": self.bucket, "rows": self.rows,
                "precision": self.precision, "outcome": self.outcome}


@dataclass
class Workload:
    """A canonical replayable workload: the recorded arrival process plus
    the recorded per-phase latency summary it should be compared against."""

    requests: list[WorkloadRequest]
    source: str = ""
    recorded: dict = field(default_factory=dict)
    defaults_applied: int = 0

    # ------------------------------------------------------------ identity

    @property
    def fingerprint(self) -> str:
        """Content fingerprint over the canonical request tuples.  Derived
        stats (recorded percentiles, source path) are excluded: two
        recordings of the same arrival process fingerprint identically,
        and a warp/trim produces a *different* workload identity."""
        blob = json.dumps([r.key() for r in self.requests],
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # ------------------------------------------------------------- summary

    @property
    def duration_s(self) -> float:
        return self.requests[-1].offset_s if self.requests else 0.0

    @property
    def accepted(self) -> int:
        return sum(1 for r in self.requests if r.outcome == "ok")

    @property
    def rejected(self) -> int:
        return sum(1 for r in self.requests if r.outcome == "rejected")

    @property
    def offered_rps(self) -> float:
        if not self.requests:
            return 0.0
        return round(len(self.requests) / max(self.duration_s, 1e-6), 3)

    @property
    def rows_per_s(self) -> float:
        if not self.requests:
            return 0.0
        rows = sum(r.rows for r in self.requests)
        return round(rows / max(self.duration_s, 1e-6), 3)

    @property
    def models(self) -> list:
        return sorted({r.model for r in self.requests if r.model is not None})

    # ------------------------------------------------------------ transforms

    def warp(self, speed: float) -> "Workload":
        """Time-warp: ``speed=2.0`` replays twice as fast (offsets halved).
        Returns a new workload with a new fingerprint — warped load is a
        different load shape and must never share a trend line."""
        if speed <= 0:
            raise WorkloadError(f"speed must be > 0, got {speed}")
        if speed == 1.0:
            return self
        reqs = [replace(r, offset_s=round(r.offset_s / speed, 6))
                for r in self.requests]
        return Workload(requests=reqs, source=self.source,
                        recorded=dict(self.recorded),
                        defaults_applied=self.defaults_applied)

    def trim(self, start_s: float = 0.0,
             end_s: float = math.inf) -> "Workload":
        """Window trim to arrivals in ``[start_s, end_s)`` (offsets re-zeroed
        to the window start)."""
        if end_s <= start_s:
            raise WorkloadError(
                f"empty trim window [{start_s}, {end_s})")
        kept = [r for r in self.requests if start_s <= r.offset_s < end_s]
        if not kept:
            raise WorkloadError(
                f"trim window [{start_s}, {end_s}) contains no arrivals "
                f"(workload spans 0..{self.duration_s:.3f}s)")
        t0 = kept[0].offset_s
        reqs = [replace(r, offset_s=round(r.offset_s - t0, 6)) for r in kept]
        return Workload(requests=reqs, source=self.source,
                        recorded=dict(self.recorded),
                        defaults_applied=self.defaults_applied)

    # ---------------------------------------------------------- persistence

    def to_record(self) -> dict:
        return {
            "kind": "workload",
            "fingerprint": self.fingerprint,
            "source": self.source,
            "requests": [r.to_dict() for r in self.requests],
            "recorded": self.recorded,
            "defaults_applied": self.defaults_applied,
        }

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_record(), fh)
            fh.write("\n")


def _percentile(durs: list, q: float) -> float:
    """Same rank formula as ``FleetCollector.drain_phase_stats`` so the
    recorded and replayed sides of a differential are comparable."""
    n = len(durs)
    return round(durs[max(0, math.ceil(q * n) - 1)], 3)


def _parse_span(line: str, lineno: int) -> dict:
    try:
        span = json.loads(line)
    except json.JSONDecodeError as e:
        raise WorkloadError(
            f"trace line {lineno}: not valid JSON "
            f"(truncated recording?): {e}") from None
    if not isinstance(span, dict):
        raise WorkloadError(
            f"trace line {lineno}: span must be an object, "
            f"got {type(span).__name__}")
    for k, typ in _SPAN_REQUIRED.items():
        if k not in span:
            raise WorkloadError(f"trace line {lineno}: span missing {k!r}")
        if not isinstance(span[k], typ) or isinstance(span[k], bool):
            raise WorkloadError(
                f"trace line {lineno}: span field {k!r} has type "
                f"{type(span[k]).__name__}")
    if span["t1"] < span["t0"]:
        raise WorkloadError(
            f"trace line {lineno}: span ends before it starts "
            f"(t1 {span['t1']} < t0 {span['t0']})")
    return span


def extract_workload(path: str) -> Workload:
    """Extract a :class:`Workload` from a fleet-trace JSONL file.

    Every ``route/request`` root span becomes one arrival; all spans feed
    the recorded per-phase percentile summary the differential report
    compares against.  Malformed rows raise :class:`WorkloadError` with
    the line number — a recording is an artifact, and a silently-skipped
    row would corrupt the fingerprint.
    """
    roots: list[dict] = []
    phases: dict = {}
    defaults = 0
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            if not line.strip():
                continue
            span = _parse_span(line, lineno)
            phases.setdefault(span["name"], []).append(
                1e3 * (span["t1"] - span["t0"]))
            if span["name"] == ROOT_SPAN:
                roots.append(span)
    if not roots:
        raise WorkloadError(
            f"{path}: no {ROOT_SPAN!r} root spans — not a fleet trace "
            "(or recorded before ISSUE 13 tracing)")
    roots.sort(key=lambda s: s["t0"])
    t_zero = roots[0]["t0"]
    requests = []
    for span in roots:
        attrs = span.get("attrs") or {}
        if not {"bucket", "rows", "precision"} & attrs.keys():
            defaults += 1  # pre-v14 root: replay with documented defaults
        requests.append(WorkloadRequest(
            offset_s=round(span["t0"] - t_zero, 6),
            model=attrs.get("model", DEFAULT_MODEL),
            bucket=attrs.get("bucket", DEFAULT_BUCKET),
            rows=attrs.get("rows", DEFAULT_ROWS),
            precision=attrs.get("precision", DEFAULT_PRECISION),
            outcome=str(attrs.get("status", "ok")),
        ))
    per_phase = {}
    for name, durs in sorted(phases.items()):
        durs.sort()
        per_phase[name] = {"count": len(durs),
                           "p50_ms": _percentile(durs, 0.50),
                           "p99_ms": _percentile(durs, 0.99)}
    wl = Workload(requests=requests, source=path,
                  defaults_applied=defaults)
    wl.recorded = {
        "per_phase": per_phase,
        "requests": len(requests),
        "accepted": wl.accepted,
        "rejected": wl.rejected,
        "duration_s": round(wl.duration_s, 3),
        "offered_rps": wl.offered_rps,
    }
    return wl


def load_workload(path: str) -> Workload:
    """Load either a saved workload artifact (``kind: workload`` JSON) or a
    raw fleet-trace JSONL (auto-extracted)."""
    with open(path) as fh:
        head = fh.read(4096)
    if '"kind"' in head.split("\n", 1)[0] and '"workload"' in head:
        with open(path) as fh:
            try:
                rec = json.load(fh)
            except json.JSONDecodeError as e:
                raise WorkloadError(
                    f"{path}: not a valid workload artifact: {e}") from None
        if rec.get("kind") != "workload":
            raise WorkloadError(
                f"{path}: kind={rec.get('kind')!r}, expected 'workload'")
        try:
            reqs = [WorkloadRequest(**r) for r in rec["requests"]]
        except (KeyError, TypeError) as e:
            raise WorkloadError(
                f"{path}: malformed workload request rows: {e}") from None
        return Workload(requests=reqs, source=rec.get("source", path),
                        recorded=rec.get("recorded", {}),
                        defaults_applied=rec.get("defaults_applied", 0))
    return extract_workload(path)


# ---------------------------------------------------------------- replay


def replay_workload(submit, workload: Workload, *,
                    speed: float = 1.0, timeout_s: float = 120.0,
                    clock=time.monotonic, sleep=time.sleep) -> dict:
    """Re-drive the recorded arrival process against a candidate server.

    ``submit(index, request)`` is called once per recorded arrival at its
    recorded offset (warped by ``speed``) and must return a Future (or
    raise — a raise with a ``retry_after_ms`` attribute or named
    ``QueueFullError`` counts as an admission rejection, anything else as
    a failure).  The caller owns image selection and the model kwarg, so
    one driver serves every transport and the fake-clock tests.

    Latency is measured from the *intended* arrival instant — scheduling
    jitter counts against the replayed latency exactly as it does in the
    recorded trace.  Returns the replayed point plus the measured arrival
    fidelity (max |actual - intended| submit skew).
    """
    if speed != 1.0:
        workload = workload.warp(speed)
    lat_ms: list[float] = []
    failures: list[str] = []
    lock = threading.Lock()
    pending: list = []
    rejected = 0
    max_skew_ms = 0.0
    t_start = clock()
    for i, req in enumerate(workload.requests):
        target = t_start + req.offset_s
        now = clock()
        if target > now:
            sleep(target - now)
            now = clock()
        max_skew_ms = max(max_skew_ms, 1e3 * abs(now - target))
        t_intended = target

        def _done(fut, t0=t_intended):
            err = fut.exception()
            with lock:
                if err is None:
                    lat_ms.append(1e3 * (clock() - t0))
                else:
                    failures.append(type(err).__name__)

        try:
            fut = submit(i, req)
        except Exception as e:  # noqa: BLE001 — classify by duck type
            if (hasattr(e, "retry_after_ms")
                    or type(e).__name__ == "QueueFullError"):
                rejected += 1
            else:
                failures.append(type(e).__name__)
            continue
        fut.add_done_callback(_done)
        pending.append(fut)
    deadline = clock() + timeout_s
    for fut in pending:
        try:
            fut.result(timeout=max(0.0, deadline - clock()))
        except Exception:  # noqa: BLE001 — recorded in the done callback
            pass
    wall = max(clock() - t_start, 1e-6)
    with lock:
        lat = sorted(lat_ms)
        failed = len(failures)
    out = {
        "submitted": len(workload.requests),
        "accepted": len(lat),
        "rejected": rejected,
        "failed": failed,
        "wall_s": round(wall, 3),
        "images_per_sec": round(len(lat) / wall, 2),
        "max_arrival_skew_ms": round(max_skew_ms, 3),
        "lat_ms": lat,
    }
    if lat:
        out["p50_ms"] = _percentile(lat, 0.50)
        out["p95_ms"] = _percentile(lat, 0.95)
        out["p99_ms"] = _percentile(lat, 0.99)
    return out


# ---------------------------------------------------- differential report


def differential_report(workload: Workload, replayed: dict,
                        replayed_per_phase: dict | None = None) -> dict:
    """Recorded vs replayed, per phase: where the candidate config moved
    each phase, plus throughput and reject-rate deltas."""
    rec = workload.recorded
    rec_phases = rec.get("per_phase") or {}
    rep_phases = replayed_per_phase or {}
    phases = {}
    for name in sorted(set(rec_phases) | set(rep_phases)):
        r0, r1 = rec_phases.get(name), rep_phases.get(name)
        ent = {}
        if r0:
            ent["recorded_p50_ms"] = r0["p50_ms"]
            ent["recorded_p99_ms"] = r0["p99_ms"]
        if r1:
            ent["replayed_p50_ms"] = r1["p50_ms"]
            ent["replayed_p99_ms"] = r1["p99_ms"]
        if r0 and r1:
            ent["delta_p99_pct"] = round(
                100.0 * (r1["p99_ms"] - r0["p99_ms"])
                / max(r0["p99_ms"], 1e-9), 1)
        phases[name] = ent
    rec_n = max(rec.get("requests", 0), 1)
    rep_n = max(replayed.get("submitted", 0), 1)
    return {
        "workload": workload.fingerprint,
        "phases": phases,
        "recorded_reject_rate": round(rec.get("rejected", 0) / rec_n, 4),
        "replayed_reject_rate": round(replayed.get("rejected", 0) / rep_n, 4),
        "recorded_offered_rps": rec.get("offered_rps", 0.0),
        "replayed_images_per_sec": replayed.get("images_per_sec", 0.0),
    }


def render_diff(diff: dict) -> list:
    """Human-readable REPLAY diff lines — shared by bench_serve stderr,
    ``report_run.py``, and ``summarize_benches.py``."""
    lines = [
        f"REPLAY [{diff.get('workload', '?')}] reject rate "
        f"{diff.get('recorded_reject_rate', 0.0):.2%} recorded -> "
        f"{diff.get('replayed_reject_rate', 0.0):.2%} replayed"
    ]
    for name, ent in sorted((diff.get("phases") or {}).items()):
        if "recorded_p99_ms" in ent and "replayed_p99_ms" in ent:
            lines.append(
                f"  {name}: p99 {ent['recorded_p99_ms']:.1f}ms recorded -> "
                f"{ent['replayed_p99_ms']:.1f}ms replayed "
                f"({ent['delta_p99_pct']:+.1f}%)")
        elif "replayed_p99_ms" in ent:
            lines.append(
                f"  {name}: p99 {ent['replayed_p99_ms']:.1f}ms replayed "
                "(not in recording)")
    return lines
