"""Fused head-matmul+CE kernel vs the plain-XLA reference: loss values and
all three gradients (features, weights, bias), including label<0 padding
rows and a vocab size that is not a multiple of the kernel's block."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.ops.fused_head_ce import fused_head_ce, head_ce_reference

B, D, V = 16, 64, 5000  # V % 2048 != 0 → exercises the -inf padding path


def _inputs():
    rng = np.random.default_rng(0)
    # Pre-round to bf16 grid so the kernel's bf16 MXU matmul and the f32
    # reference see identical operands (accumulation is f32 in both).
    feats = (
        jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
        .astype(jnp.bfloat16)
        .astype(jnp.float32)
    )
    w = (
        jnp.asarray(rng.normal(size=(D, V)) * 0.05, jnp.float32)
        .astype(jnp.bfloat16)
        .astype(jnp.float32)
    )
    b = jnp.asarray(rng.normal(size=(V,)) * 0.1, jnp.float32)
    labels = np.asarray(rng.integers(0, V, size=(B,)), np.int32)
    labels[3] = -1  # padding rows
    labels[11] = -1
    return feats, w, b, jnp.asarray(labels)


def test_forward_matches_reference():
    feats, w, b, labels = _inputs()
    got = fused_head_ce(feats, w, b, labels, interpret=True)
    want = head_ce_reference(feats, w, b, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)
    assert float(got[3]) == 0.0 and float(got[11]) == 0.0


def test_grads_match_reference():
    feats, w, b, labels = _inputs()

    def total_fused(f, w_, b_):
        return jnp.sum(fused_head_ce(f, w_, b_, labels, interpret=True))

    def total_ref(f, w_, b_):
        return jnp.sum(head_ce_reference(f, w_, b_, labels))

    gf, gw, gb = jax.grad(total_fused, argnums=(0, 1, 2))(feats, w, b)
    rf, rw, rb = jax.grad(total_ref, argnums=(0, 1, 2))(feats, w, b)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(rf), rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=2e-2, atol=2e-3)
    # padding rows carry exactly zero feature-gradient
    np.testing.assert_array_equal(np.asarray(gf[3]), np.zeros(D, np.float32))


def test_weighted_upstream_gradient():
    """Non-uniform cotangents route through the custom VJP correctly."""
    feats, w, b, labels = _inputs()
    weights = jnp.asarray(np.random.default_rng(1).uniform(0.1, 2.0, size=(B,)), jnp.float32)

    def weighted(f):
        return jnp.sum(fused_head_ce(f, w, b, labels, interpret=True) * weights)

    def weighted_ref(f):
        return jnp.sum(head_ce_reference(f, w, b, labels) * weights)

    gf = jax.grad(weighted)(feats)
    rf = jax.grad(weighted_ref)(feats)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(rf), rtol=2e-2, atol=2e-3)


def test_head_predict_matches_reference():
    """The inference sibling: per-example loss AND argmax predictions from
    one streaming pass — vs explicit-logits CE + argmax."""
    from mpi_pytorch_tpu.ops.fused_head_ce import (
        head_predict,
        head_predict_reference,
    )

    feats, w, b, labels = _inputs()
    loss, preds = head_predict(feats, w, b, labels, interpret=True)
    ref_loss, ref_preds = head_predict_reference(feats, w, b, labels)
    np.testing.assert_allclose(
        np.asarray(loss), np.asarray(ref_loss), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_array_equal(np.asarray(preds), np.asarray(ref_preds))
    assert preds.dtype == jnp.int32
    assert float(loss[3]) == 0.0 and float(loss[11]) == 0.0  # padding rows


def test_head_predict_cross_block_tie_prefers_first():
    """An exact tie across vocab blocks must resolve to the LOWER index —
    jnp.argmax's convention over the concatenated vocab."""
    from mpi_pytorch_tpu.ops.fused_head_ce import head_predict

    feats = jnp.ones((2, 8), jnp.float32)
    v = 5000
    w = jnp.zeros((8, v), jnp.float32)
    b = np.zeros((v,), np.float32)
    b[100] = 7.0   # block 0
    b[4000] = 7.0  # block 1, exact same logit
    _, preds = head_predict(feats, w, jnp.asarray(b), jnp.zeros((2,), jnp.int32),
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(preds), [100, 100])


@pytest.mark.parametrize("rows", [2048, 4096])
def test_head_predict_row_tiled_beyond_envelope(rows):
    """Batches beyond PREDICT_MAX_ROWS stream through the kernel's row
    tiling (a (rows, vocab) grid) instead of falling back — the former
    B=4096 compile-rejection envelope is now an internal loop. Cross-ROW-
    BLOCK independence is pinned by exact agreement with the reference on
    every row."""
    from mpi_pytorch_tpu.ops.fused_head_ce import (
        PREDICT_MAX_ROWS,
        _predict_row_block,
        head_predict,
        head_predict_reference,
    )

    assert rows > PREDICT_MAX_ROWS
    assert _predict_row_block(rows) == PREDICT_MAX_ROWS  # tiled, not fallback
    rng = np.random.default_rng(2)
    feats = jnp.asarray(rng.normal(size=(rows, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 600)) * 0.05, jnp.float32)
    b = jnp.asarray(rng.normal(size=(600,)) * 0.1, jnp.float32)
    labels = np.asarray(rng.integers(0, 600, size=(rows,)), np.int32)
    labels[5] = -1
    labels[rows - 1] = -1  # padding in the LAST row block
    loss, preds = head_predict(feats, w, b, jnp.asarray(labels), interpret=True)
    rl, rp = head_predict_reference(feats, w, b, jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(loss), np.asarray(rl), rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(preds), np.asarray(rp))
    assert float(loss[5]) == 0.0 and float(loss[rows - 1]) == 0.0


def test_head_predict_keeps_f32_compute():
    """An f32-compute model must NOT be silently downcast: with f32
    features the kernel matmuls in f32 and matches the f32 reference to
    f32 tolerance (the bf16 cast is gated on the feature dtype)."""
    from mpi_pytorch_tpu.ops.fused_head_ce import head_predict, head_predict_reference

    rng = np.random.default_rng(3)
    # NOT bf16-grid-aligned: a silent bf16 downcast would show up as
    # rounding well above the assertion tolerance.
    feats = jnp.asarray(rng.normal(size=(B, D)) * (1 + 1e-4), jnp.float32)
    w = jnp.asarray(rng.normal(size=(D, V)) * 0.05, jnp.float32)
    b = jnp.asarray(rng.normal(size=(V,)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B,)), np.int32)
    loss, preds = head_predict(feats, w, b, labels, interpret=True)
    rl, rp = head_predict_reference(feats, w, b, labels)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(rl), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(preds), np.asarray(rp))


def test_head_predict_shard_map_multi_device():
    """dp_mesh partitions the kernel call over the 8-device data axis:
    per-row losses and predictions equal the single-call/reference output
    exactly (each device streams its own row shard; W/b replicated)."""
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.ops.fused_head_ce import head_predict, head_predict_reference

    n = len(jax.devices())
    assert n == 8  # conftest virtual-CPU mesh
    mesh = Mesh(np.array(jax.devices()).reshape(n, 1), ("data", "model"))
    rng = np.random.default_rng(4)
    rows = 16 * n
    feats = jnp.asarray(rng.normal(size=(rows, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 600)) * 0.05, jnp.float32)
    b = jnp.asarray(rng.normal(size=(600,)) * 0.1, jnp.float32)
    labels = np.asarray(rng.integers(0, 600, size=(rows,)), np.int32)
    labels[0] = -1
    loss, preds = head_predict(
        feats, w, b, jnp.asarray(labels), interpret=True, dp_mesh=mesh
    )
    rl, rp = head_predict_reference(feats, w, b, jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(loss), np.asarray(rl), rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(preds), np.asarray(rp))


@pytest.mark.parametrize("n_data", [1, 8])
def test_fused_head_predict_step_matches_plain(tmp_path, monkeypatch, n_data):
    """The eval driver's fused-head predict step returns the same metrics
    and predictions as the plain logits-materializing step, through a real
    zoo model — with the REAL kernel (Pallas interpreter) on BOTH mesh
    shapes. n_data=8 drives the shard_map-partitioned multi-data-axis path
    (formerly a silent fallback to the plain step; now each device runs
    the kernel on its own row shard)."""
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.evaluate import _make_predict_step, _make_predict_step_impl
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.train.state import TrainState

    import optax

    bundle, variables = create_model_bundle(
        "resnet18", 200, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=optax.identity(), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(
        np.array(jax.devices()[:n_data]).reshape(n_data, 1), ("data", "model")
    )
    images = np.random.default_rng(0).normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = np.asarray([3, 5, -1, 9, 0, 1, -1, 7], np.int32)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    # The interpret gate is read at TRACE time, and the step builder is
    # lru-cached on (mesh, dtype, fused) — clear so this env takes effect
    # and does not leak into other tests' builds.
    monkeypatch.setenv("MPT_HEAD_INTERPRET", "1")
    _make_predict_step_impl.cache_clear()
    try:
        plain = _make_predict_step(mesh, jnp.float32)
        fused = _make_predict_step(mesh, jnp.float32, fused_head=True)
        # No more multi-axis fallback: the fused build is its own step on
        # EVERY mesh shape (the n_data>1 case shard_maps the kernel).
        assert fused is not plain
        m1, p1 = plain(state, batch)
        m2, p2 = fused(state, batch)
    finally:
        monkeypatch.delenv("MPT_HEAD_INTERPRET")
        _make_predict_step_impl.cache_clear()
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    for k in ("loss", "correct", "count"):
        np.testing.assert_allclose(
            float(m1[k]), float(m2[k]), rtol=1e-4, atol=1e-4
        )


def test_fused_head_predict_step_falls_back_for_conv_head(tmp_path):
    """squeezenet's classifier is an nn.Conv named 'head' (and not the last
    op) — the interceptor must not fire, and the step must return the plain
    path's results instead of failing."""
    from jax.sharding import Mesh

    import optax

    from mpi_pytorch_tpu.evaluate import _make_predict_step
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.train.state import TrainState

    bundle, variables = create_model_bundle(
        "squeezenet1_0", 50, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=optax.identity(), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("data", "model"))
    images = np.random.default_rng(0).normal(size=(4, 32, 32, 3)).astype(np.float32)
    labels = np.asarray([3, -1, 9, 0], np.int32)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    plain = _make_predict_step(mesh, jnp.float32)
    fused = _make_predict_step(mesh, jnp.float32, fused_head=True)
    m1, p1 = plain(state, batch)
    m2, p2 = fused(state, batch)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    for k in ("loss", "correct", "count"):
        np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-5, atol=1e-5)


def test_fused_head_predict_step_rejects_intermediate_head_dense():
    """A future zoo model with an INTERMEDIATE Dense named 'head' (more
    layers after it) must fail loudly at trace time — the interceptor's
    captured features would not be the logits' features, and without the
    shape assert the step would silently compute metrics from the wrong
    layer (advisor r5)."""
    from flax import linen as nn
    from jax.sharding import Mesh

    import optax

    from mpi_pytorch_tpu.evaluate import _make_predict_step
    from mpi_pytorch_tpu.train.state import TrainState

    class MidHead(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(16, name="head")(x)  # fires the interceptor filter
            return nn.Dense(12, name="out")(x)  # ...but is NOT the output

    model = MidHead()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    state = TrainState.create(
        apply_fn=model.apply, variables=variables,
        tx=optax.identity(), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("data", "model"))
    images = np.zeros((4, 8, 8, 3), np.float32)
    labels = np.asarray([1, 2, -1, 3], np.int32)

    fused = _make_predict_step(mesh, jnp.float32, fused_head=True)
    with pytest.raises(AssertionError, match="does not match the model output"):
        fused(state, (jnp.asarray(images), jnp.asarray(labels)))


def test_fused_head_fallback_warns_once_on_run_logger():
    """The silent-degrade advisor finding: when a gate forces
    --fused-head-eval back to the plain step, a warning must land on the
    rank-tagged run logger (the one with real handlers), exactly once per
    reason per process."""
    import logging

    from mpi_pytorch_tpu import evaluate as ev
    from mpi_pytorch_tpu.utils.logging import run_logger

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = run_logger()
    logger.addHandler(handler)
    try:
        ev._fused_head_warned.discard("test-reason")
        ev._warn_fused_head_fallback("test-reason")
        ev._warn_fused_head_fallback("test-reason")  # deduped
        assert len(records) == 1
        msg = records[0].getMessage()
        assert "fused-head-eval" in msg and "test-reason" in msg
    finally:
        logger.removeHandler(handler)
        ev._fused_head_warned.discard("test-reason")
