import numpy as np
import pytest

from mpi_pytorch_tpu.config import Config
from mpi_pytorch_tpu.data import DataLoader, load_manifests, normalize_image, synthetic_image
from mpi_pytorch_tpu.data.manifest import Manifest


@pytest.fixture(scope="module")
def cfg():
    c = Config()
    c.test_csv = "/root/repo/data/test_sample.csv"
    c.train_csv = "/root/repo/data/train_sample.csv"
    c.debug = True
    return c


@pytest.fixture(scope="module")
def manifests(cfg):
    return load_manifests(cfg)


def test_debug_sampling_semantics(manifests):
    # main.py:77-79: 1000-row sample seed 0, 80/20 split
    train, test = manifests
    assert len(train) == 800
    assert len(test) == 200


def test_sharding_matches_array_split(manifests):
    train, _ = manifests
    shards = [train.shard(3, i) for i in range(3)]
    sizes = [len(s) for s in shards]
    expected = [len(a) for a in np.array_split(np.arange(len(train)), 3)]
    assert sizes == expected
    # shards partition the manifest without overlap
    all_files = [f for s in shards for f in s.filenames]
    assert all_files == list(train.filenames)


def test_labels_fit_head(manifests):
    train, test = manifests
    assert train.labels.max() < 64500  # utils.py:39 head size
    assert train.labels.min() >= 0


def test_normalize_matches_torch_semantics():
    # transforms.Normalize((0.485,...),(0.229,...)) — main.py:65
    img = np.full((4, 4, 3), 0.5, dtype=np.float32)
    out = normalize_image(img)
    expected = (0.5 - np.array([0.485, 0.456, 0.406])) / np.array([0.229, 0.224, 0.225])
    np.testing.assert_allclose(out[0, 0], expected, rtol=1e-5)


def test_synthetic_deterministic():
    a = synthetic_image(7, (16, 16))
    b = synthetic_image(7, (16, 16))
    c = synthetic_image(8, (16, 16))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.shape == (16, 16, 3)
    assert a.min() >= 0.0 and a.max() <= 1.0


def _tiny_manifest(n=20, classes=4):
    labels = np.arange(n, dtype=np.int32) % classes
    return Manifest(
        filenames=tuple(f"img_{i}.jpg" for i in range(n)),
        labels=labels,
        category_ids=labels.astype(np.int64),
        img_dir="unused",
    )


def test_loader_shapes_and_determinism():
    m = _tiny_manifest()
    dl = DataLoader(m, batch_size=8, image_size=(32, 32), synthetic=True, seed=3)
    batches = list(dl.epoch(0))
    assert len(batches) == 2  # drop_remainder: 20 // 8
    imgs, labels = batches[0]
    assert imgs.shape == (8, 32, 32, 3) and imgs.dtype == np.float32
    assert labels.shape == (8,) and labels.dtype == np.int32
    # same (seed, epoch) → same order; different epoch → different order
    again = list(dl.epoch(0))
    np.testing.assert_array_equal(batches[0][1], again[0][1])
    other = list(dl.epoch(1))
    assert not all(np.array_equal(b[1], o[1]) for b, o in zip(batches, other))


def test_loader_no_drop_remainder():
    m = _tiny_manifest(n=10)
    dl = DataLoader(m, batch_size=8, image_size=(8, 8), synthetic=True, drop_remainder=False,
                    shuffle=False)
    batches = list(dl.epoch(0))
    assert [b[0].shape[0] for b in batches] == [8, 2]


def test_create_dataset_metadata_join(tmp_path):
    """read→join→sample→split→write parity with reference create_dataset.py."""
    import json

    from mpi_pytorch_tpu.data.create_dataset import read_metadata, sample_and_split, write_split

    meta = {
        "images": [
            {"id": i, "file_name": f"f{i}.jpg", "height": 100, "width": 80, "license": 1}
            for i in range(50)
        ],
        "annotations": [
            {"image_id": i, "category_id": i % 7, "id": 1000 + i} for i in range(50)
        ],
    }
    mpath = tmp_path / "metadata.json"
    mpath.write_text(json.dumps(meta))

    df = read_metadata(str(mpath))
    assert len(df) == 50
    assert set(["file_name", "category_id"]).issubset(df.columns)

    train_df, test_df = sample_and_split(df, 40, seed=0)
    assert len(train_df) == 32 and len(test_df) == 8  # 80/20 of 40

    train_csv, test_csv = write_split(train_df, test_df, str(tmp_path / "out"), copy_images=False)
    import pandas as pd

    assert len(pd.read_csv(train_csv)) == 32
    # deterministic: seed 0 resample gives the same rows
    t2, _ = sample_and_split(df, 40, seed=0)
    assert list(t2["file_name"]) == list(train_df["file_name"])


@pytest.mark.slow
def test_synthetic_jpeg_dataset_trains_via_decode_path(tmp_path):
    """--synthetic generates real JPEGs; training with synthetic_data=False
    exercises the actual PIL decode→resize→normalize path end to end."""
    from mpi_pytorch_tpu.data.create_dataset import main as create_main
    from mpi_pytorch_tpu.train.trainer import train

    out = str(tmp_path / "data")
    create_main(["--synthetic", "96", "--num-classes", "8", "--image-size", "48",
                 "--out", out])

    cfg = Config()
    cfg.debug = True
    cfg.debug_sample_size = 64
    cfg.train_csv = f"{out}/train_sample.csv"
    cfg.test_csv = f"{out}/test_sample.csv"
    cfg.train_img_dir = f"{out}/img/train"
    cfg.test_img_dir = f"{out}/img/test"
    cfg.synthetic_data = False  # decode the JPEGs for real
    cfg.num_classes = 8
    cfg.batch_size = 16
    cfg.width = cfg.height = 32
    cfg.num_epochs = 1
    cfg.compute_dtype = "float32"
    cfg.validate = False
    cfg.checkpoint_dir = str(tmp_path / "ckpt")
    cfg.log_file = str(tmp_path / "training.log")
    cfg.loader_workers = 2
    cfg.log_every_steps = 0
    cfg.validate_config()

    summary = train(cfg)
    assert summary.epochs_run == 1
    assert np.isfinite(summary.final_loss)


def test_loader_bfloat16_batches():
    import ml_dtypes

    m = _tiny_manifest(n=16)
    dl = DataLoader(m, batch_size=8, image_size=(16, 16), synthetic=True,
                    shuffle=False, image_dtype="bfloat16")
    imgs, labels = next(iter(dl.epoch(0)))
    assert imgs.dtype == np.dtype(ml_dtypes.bfloat16)
    assert labels.dtype == np.int32
    # values match the float32 pipeline to bf16 precision
    dl32 = DataLoader(m, batch_size=8, image_size=(16, 16), synthetic=True, shuffle=False)
    imgs32, _ = next(iter(dl32.epoch(0)))
    np.testing.assert_allclose(imgs.astype(np.float32), imgs32, atol=0.02, rtol=0.02)


def test_host_cache_adoption():
    """adopt_cache shares a completed cache by reference only when the two
    loaders walk identical data; mismatches refuse."""
    from mpi_pytorch_tpu.data.manifest import Manifest
    from mpi_pytorch_tpu.data.pipeline import DataLoader

    m = Manifest(
        filenames=tuple(f"f{i}" for i in range(8)),
        labels=np.arange(8, dtype=np.int32),
        category_ids=np.arange(8),
        img_dir="unused",
    )
    a = DataLoader(m, batch_size=4, image_size=(16, 16), shuffle=False,
                   synthetic=True, host_cache=True)
    for _ in a.epoch(0):
        pass
    assert a._cache_complete

    b = DataLoader(m, batch_size=4, image_size=(16, 16), shuffle=False,
                   synthetic=True, host_cache=True)
    assert b.adopt_cache(a)
    assert b._cache_images is a._cache_images

    c = DataLoader(m, batch_size=4, image_size=(8, 8), shuffle=False,
                   synthetic=True, host_cache=True)
    assert not c.adopt_cache(a)  # different image size: refuse


def test_host_cache_completes_after_early_close():
    """The multi-host globally-truncated step count closes the epoch iterator
    before the loader is exhausted; the cache must still complete (in the
    background) so 'decode once' holds on the default drop_remainder path."""
    import time

    from mpi_pytorch_tpu.data.manifest import Manifest
    from mpi_pytorch_tpu.data.pipeline import DataLoader

    m = Manifest(
        filenames=tuple(f"f{i}" for i in range(10)),
        labels=np.arange(10, dtype=np.int32),
        category_ids=np.arange(10),
        img_dir="unused",
    )
    dl = DataLoader(m, batch_size=4, image_size=(16, 16), shuffle=False,
                    drop_remainder=True, synthetic=True, host_cache=True)
    it = dl.epoch(0)
    next(it)       # consume ONE of the two full batches
    it.close()     # early close, as synchronized_batches does after n_steps
    deadline = time.monotonic() + 30
    while not dl._cache_complete and time.monotonic() < deadline:
        time.sleep(0.02)
    assert dl._cache_complete
    assert dl._cache_filled.all()
    # next epoch serves from the cache (fast slice path)
    batches = list(dl.epoch(1))
    assert len(batches) == 2


def test_host_cache_backfill_error_surfaces(tmp_path):
    """A decode failure in the post-close backfill must not be silent: a
    failure past the quarantine budget surfaces through
    wait_cache_complete (within-budget failures quarantine instead —
    tests/test_selfheal.py)."""
    from mpi_pytorch_tpu.data.manifest import Manifest
    from mpi_pytorch_tpu.data.pipeline import BadSampleLimitError, DataLoader

    img_dir = tmp_path / "img"
    img_dir.mkdir()
    from PIL import Image

    names = []
    for i in range(10):
        name = f"f{i}.jpg"
        if i < 8:  # the last two (the drop_remainder tail) stay missing
            Image.new("RGB", (32, 32)).save(img_dir / name)
        names.append(name)
    m = Manifest(
        filenames=tuple(names), labels=np.arange(10, dtype=np.int32),
        category_ids=np.arange(10), img_dir=str(img_dir),
    )
    dl = DataLoader(m, batch_size=4, image_size=(16, 16), shuffle=False,
                    drop_remainder=True, synthetic=False, host_cache=True,
                    max_bad_samples=1, decode_retries=0)
    it = dl.epoch(0)
    next(it)
    next(it)  # both full batches decode fine (files 0-7)
    it.close()  # backfill of the missing tail files now fails in background
    with pytest.raises(BadSampleLimitError):
        dl.wait_cache_complete()
    assert not dl._cache_complete


def test_host_cache_next_epoch_waits_for_backfill():
    """epoch(N+1) must not race the still-running backfill of epoch N: it
    joins the filler and then serves from the completed cache."""
    from mpi_pytorch_tpu.data.manifest import Manifest
    from mpi_pytorch_tpu.data.pipeline import DataLoader

    m = Manifest(
        filenames=tuple(f"f{i}" for i in range(10)),
        labels=np.arange(10, dtype=np.int32),
        category_ids=np.arange(10),
        img_dir="unused",
    )
    dl = DataLoader(m, batch_size=4, image_size=(16, 16), shuffle=False,
                    drop_remainder=True, synthetic=True, host_cache=True)
    it = dl.epoch(0)
    next(it)
    it.close()  # backfill continues in the background
    batches = list(dl.epoch(1))  # joins the filler, then slices the cache
    assert dl._cache_complete
    assert dl._fill_thread is None or not dl._fill_thread.is_alive()
    assert len(batches) == 2


def _jpeg_dataset(tmp_path, n=96, classes=8, size=48):
    """Synthetic-JPEG dataset on disk + its (train, test) manifests."""
    from mpi_pytorch_tpu.data.create_dataset import main as create_main

    out = str(tmp_path / "data")
    create_main(["--synthetic", str(n), "--num-classes", str(classes),
                 "--image-size", str(size), "--out", out])
    c = Config()
    c.debug = False
    c.train_csv = f"{out}/train_sample.csv"
    c.test_csv = f"{out}/test_sample.csv"
    c.train_img_dir = f"{out}/img/train"
    c.test_img_dir = f"{out}/img/test"
    c.synthetic_data = False
    c.num_classes = classes
    return c, load_manifests(c)


def test_packed_dataset_matches_streaming_exactly(tmp_path):
    """Packed batches must be BIT-identical to the streaming PIL decode path
    (the pack stores PIL's resize output pre-float), including when a shard
    resolves against the full-split pack by filename."""
    from mpi_pytorch_tpu.data.packed import write_pack

    _, (train_m, _) = _jpeg_dataset(tmp_path)
    packed_dir = str(tmp_path / "packed")
    write_pack(train_m, (32, 32), f"{packed_dir}/train_32x32", num_workers=2)

    kw = dict(batch_size=8, image_size=(32, 32), shuffle=True, seed=7,
              native_decode=False, num_workers=2)
    streamed = list(DataLoader(train_m, **kw).epoch(0))
    packed = list(DataLoader(train_m, packed_dir=packed_dir, **kw).epoch(0))
    assert len(streamed) == len(packed) > 0
    for (si, sl), (pi, pl) in zip(streamed, packed):
        np.testing.assert_array_equal(sl, pl)
        np.testing.assert_array_equal(si, pi)  # bit-for-bit, not allclose

    shard = train_m.shard(2, 1)
    s_shard = list(DataLoader(shard, **kw).epoch(0))
    p_shard = list(DataLoader(shard, packed_dir=packed_dir, **kw).epoch(0))
    for (si, _), (pi, _) in zip(s_shard, p_shard):
        np.testing.assert_array_equal(si, pi)


def test_packed_resolution_is_strict(tmp_path):
    """A configured packed_dir with no covering pack must raise (silent
    fallback to per-epoch decode would hide the cost the format removes)."""
    from mpi_pytorch_tpu.data.packed import write_pack

    _, (train_m, _) = _jpeg_dataset(tmp_path, n=48)
    packed_dir = str(tmp_path / "packed")
    write_pack(train_m, (32, 32), f"{packed_dir}/train_32x32", num_workers=2)
    with pytest.raises(FileNotFoundError, match="image_size"):
        DataLoader(train_m, batch_size=8, image_size=(16, 16),
                   packed_dir=packed_dir)


def test_packed_accepts_relative_img_dir_spelling(tmp_path, monkeypatch):
    """A pack recorded with a relative spelling of the manifest's img_dir is
    the SAME pack: find_pack compares realpaths, so the strict no-fallback
    policy doesn't turn a path-spelling difference into a hard error."""
    import json
    import os

    from mpi_pytorch_tpu.data.packed import find_pack, write_pack

    _, (train_m, _) = _jpeg_dataset(tmp_path, n=48)
    packed_dir = str(tmp_path / "packed")
    write_pack(train_m, (32, 32), f"{packed_dir}/train_32x32", num_workers=2)

    meta_path = f"{packed_dir}/train_32x32.meta.json"
    with open(meta_path) as f:
        meta = json.load(f)
    monkeypatch.chdir(tmp_path)
    meta["img_dir"] = os.path.relpath(meta["img_dir"], str(tmp_path))
    assert meta["img_dir"] != train_m.img_dir  # genuinely different spellings
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    handle = find_pack(packed_dir, train_m, (32, 32), synthetic=False)
    assert handle.rows.shape[0] == len(train_m.filenames)


@pytest.mark.slow
def test_packed_cli_then_train(tmp_path):
    """The pack CLI writes both splits; the trainer consumes them through
    --packed-dir end to end."""
    import os

    from mpi_pytorch_tpu.data.packed import main as pack_main
    from mpi_pytorch_tpu.train.trainer import train

    c, _ = _jpeg_dataset(tmp_path, n=64, classes=4)
    packed_dir = str(tmp_path / "packed")
    pack_main([
        "--packed-dir", packed_dir, "--debug", "false",
        "--train-csv", c.train_csv, "--test-csv", c.test_csv,
        "--train-img-dir", c.train_img_dir, "--test-img-dir", c.test_img_dir,
        "--synthetic-data", "false", "--num-classes", "4",
        "--image-size", "32", "--loader-workers", "2",
    ])
    assert sorted(n for n in os.listdir(packed_dir) if n.endswith(".meta.json")) == [
        "test_32x32.meta.json", "train_32x32.meta.json"
    ]

    c.packed_dir = packed_dir
    c.batch_size = 16
    c.width = c.height = 32
    c.num_epochs = 1
    c.compute_dtype = "float32"
    c.validate = True
    c.val_on_train = False  # resolves the test-split pack for validation
    c.checkpoint_dir = str(tmp_path / "ckpt")
    c.log_file = str(tmp_path / "training.log")
    c.loader_workers = 2
    c.log_every_steps = 0
    c.validate_config()
    summary = train(c)
    assert summary.epochs_run == 1 and np.isfinite(summary.final_loss)
    assert summary.val_accuracy is not None


def test_packed_synthetic_label_mismatch_rejected(tmp_path):
    """Synthetic images are functions of their labels, so a synthetic pack
    whose stored labels disagree with the manifest must be rejected — it
    would silently serve images of the wrong classes."""
    from mpi_pytorch_tpu.data.packed import write_pack

    m = _tiny_manifest(n=12, classes=3)
    packed_dir = str(tmp_path / "packed")
    write_pack(m, (16, 16), f"{packed_dir}/train_16x16", synthetic=True,
               num_workers=2)
    # Same filenames, shifted labels ≙ a regenerated dataset.
    shifted = Manifest(
        filenames=m.filenames,
        labels=(m.labels + 1) % 3,
        category_ids=m.category_ids,
        img_dir=m.img_dir,
    )
    with pytest.raises(FileNotFoundError, match="labels disagree"):
        DataLoader(shifted, batch_size=4, image_size=(16, 16), synthetic=True,
                   packed_dir=packed_dir)
    # The matching manifest still resolves.
    dl = DataLoader(m, batch_size=4, image_size=(16, 16), synthetic=True,
                    packed_dir=packed_dir)
    assert dl._pack is not None


def test_uint8_ingest_matches_host_normalize(tmp_path):
    """input_dtype='uint8' batches + on-device normalize (step.ingest_images)
    must equal the host-normalized float path exactly: same uint8 source,
    same op order, f32 both ways."""
    import jax.numpy as jnp

    from mpi_pytorch_tpu.train.step import ingest_images

    _, (train_m, _) = _jpeg_dataset(tmp_path, n=48)
    kw = dict(batch_size=8, image_size=(32, 32), shuffle=False,
              native_decode=False, num_workers=2)
    f32_batches = list(DataLoader(train_m, **kw).epoch(0))
    u8_batches = list(DataLoader(train_m, image_dtype="uint8", **kw).epoch(0))
    assert u8_batches[0][0].dtype == np.uint8
    for (fi, fl), (ui, ul) in zip(f32_batches, u8_batches):
        np.testing.assert_array_equal(fl, ul)
        on_device = np.asarray(ingest_images(jnp.asarray(ui), jnp.float32))
        np.testing.assert_allclose(on_device, fi, rtol=0, atol=1e-6)
