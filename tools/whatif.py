"""Offline what-if planner (ISSUE 18): search fleet configs against a
RECORDED workload, ranked by the fitted per-phase latency model, with the
winner validated by actually replaying it.

The closed loop ROADMAP item 4b asks for, first cut:

1. Extract the workload from a fleet-trace JSONL (``obs/replay.py``) and
   fit the per-(model, bucket, precision, residency) device-time +
   queueing model from the same spans (``obs/model.py``).
2. Enumerate candidates over (bucket sets x precision x host count x
   pack budget x max_wait x residency — incl. ``pipe:K``) and rank them
   by model-predicted total p99 (ties break toward fewer hosts — the
   cheaper fleet). Unpriceable residencies are reported, never dropped.
3. ``--validate``: stamp the model's calibration error by replaying on a
   holdout window (the second half of the workload), then replay the
   WINNER on the full workload and check its prediction lands within the
   stamped error. The plan is only as good as that number says it is.

Output is an ``explain()``-style plan (the zoo packing planner's idiom)
plus one ``kind="whatif"`` JSONL record (schema v14). Promoting the
winning plan to the live fleet (ROADMAP 4c) is out of scope here.

Run:  python tools/whatif.py --trace /tmp/fleet_trace.jsonl --smoke \
          --hosts 1,2 --max-wait-ms 2,8 [--validate] [--out whatif.json]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rank_candidates(model, workload, *, bucket_sets, precisions, hosts,
                    waits, budgets, residencies=("replicated",)):
    """Every candidate config scored by the fitted model; returns the
    ranked list (best first). Saturated candidates carry the end-of-burst
    backlog-drain queue term, so they still rank against each other
    (more hosts -> smaller backlog) instead of tying on a sentinel.
    ``residencies`` is the ISSUE 20 axis: "replicated"/"tp:K"/"fsdp:K"/
    "pipe:K" candidates price against their OWN fitted trend (pipe keys
    fit from per-stage spans) — one the model never saw is reported
    unpriceable, never silently dropped."""
    from mpi_pytorch_tpu.obs.model import ModelError

    ranked = []
    for bs, prec, h, wait, budget, res in itertools.product(
            bucket_sets, precisions, hosts, waits, budgets, residencies):
        config = {
            "buckets": [int(b) for b in bs.split(",") if b.strip()],
            "max_wait_ms": wait,
            "hosts": h,
            "precision": prec,
            "pack_budget_mb": budget,
        }
        if res and res != "replicated":
            config["residency"] = res
        try:
            pred = model.predict(config, workload)
        except ModelError as e:
            # A candidate the model cannot price (nothing fitted for its
            # precision or residency, say) is reported, not silently
            # dropped.
            ranked.append({"config": config, "error": str(e)})
            continue
        ranked.append({"config": config, "predicted": pred})
    ranked.sort(key=lambda c: (
        c.get("predicted", {}).get("p99_ms", float("inf")),
        c["config"]["hosts"],
        max(c["config"]["buckets"]),
    ))
    for i, c in enumerate(ranked, start=1):
        c["rank"] = i
    return ranked


def explain_plan(ranked, workload, model) -> list:
    """The human-readable plan, one line per candidate (best first)."""
    calib = model.calibration_error_pct
    lines = [
        f"what-if plan [workload {workload.fingerprint}]: "
        f"{len(workload.requests)} arrivals over "
        f"{workload.duration_s:.2f}s ({workload.offered_rps} rps), "
        f"{len(ranked)} candidate(s), calibration "
        + (f"±{calib:.1f}%" if calib is not None else "UNSTAMPED")
    ]
    for c in ranked:
        cfg = c["config"]
        base = (f"  #{c['rank']} buckets={','.join(map(str, cfg['buckets']))}"
                f" precision={cfg['precision'] or '-'} hosts={cfg['hosts']}"
                f" wait={cfg['max_wait_ms']:g}ms"
                + (f" budget={cfg['pack_budget_mb']:g}MB"
                   if cfg.get("pack_budget_mb") else "")
                + (f" residency={cfg['residency']}"
                   if cfg.get("residency") else ""))
        if "error" in c:
            lines.append(base + f" -> UNPRICEABLE ({c['error']})")
            continue
        p = c["predicted"]
        ph = p["per_phase"]
        lines.append(
            base + f" -> p99 {p['p99_ms']:.1f}ms "
            f"(queue {ph['serve/queue']:.1f} + prep "
            f"{ph['serve/preprocess']:.1f} + device "
            f"{ph['serve/device']:.1f}) rho={p['rho']:.2f}"
            + (" SATURATED" if p["saturated"] else ""))
        for note in p.get("notes", []):
            lines.append(f"       note: {note}")
    return lines


def _build_server(cfg_args, config):
    """A real fleet for a candidate config (validation replays only).
    Always a FleetServer — even at one host — because the replayed
    per-phase stats come from its collector, and the trace context is
    minted at the router front door."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import FleetServer

    cfg = Config(
        model_name=cfg_args.model, num_classes=cfg_args.num_classes,
        width=cfg_args.image, height=cfg_args.image, synthetic_data=True,
        compute_dtype=cfg_args.compute_dtype,
        serve_buckets=",".join(str(b) for b in config["buckets"]),
        serve_max_wait_ms=config["max_wait_ms"],
        serve_queue_depth=cfg_args.queue_depth,
        serve_topk=cfg_args.topk,
        serve_fleet_hosts=max(1, config["hosts"]),
        trace_sample_rate=1.0,
        serve_collect_interval_s=0.1,
        metrics_file="", log_file="", eval_log_file="",
    )
    cfg.validate_config()
    return FleetServer(cfg, load_checkpoint=False)


def _replay_against(server, workload, args):
    """Replay ``workload`` and return its per-phase stats + total p99."""
    import numpy as np

    from mpi_pytorch_tpu.obs.replay import replay_workload

    rng = np.random.default_rng(args.seed)
    pool = [rng.integers(0, 256, size=(args.image, args.image, 3))
            .astype(np.uint8) for _ in range(32)]
    res = replay_workload(
        lambda i, req: server.submit(pool[i % len(pool)]),
        workload, timeout_s=args.timeout_s)
    collector = getattr(server, "collector", None)
    per_phase = None
    if collector is not None:
        collector.tick()
        per_phase = collector.drain_phase_stats()
    return res, per_phase


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True,
                    help="fleet-trace JSONL to plan against (both the "
                    "workload and the model are fitted from it)")
    ap.add_argument("--bucket-sets", default="1,4;1,8",
                    help="semicolon-separated candidate bucket sets")
    ap.add_argument("--precisions", default="",
                    help="comma list of candidate precisions (default: "
                    "whatever the recorded trace used)")
    ap.add_argument("--hosts", default="1,2,3",
                    help="comma list of candidate host counts")
    ap.add_argument("--max-wait-ms", default="2,8",
                    help="comma list of candidate batching windows")
    ap.add_argument("--residencies", default="",
                    help="comma list of candidate weight residencies "
                    "(replicated, tp:K, fsdp:K, pipe:K; default: every "
                    "residency the fitted trace carries)")
    ap.add_argument("--pack-budgets", default="0",
                    help="comma list of candidate per-host packing budgets "
                    "in MB (0 = unbounded)")
    ap.add_argument("--top", type=int, default=0,
                    help="print only the top N candidates (0 = all)")
    ap.add_argument("--validate", action="store_true",
                    help="stamp the calibration error on a holdout window, "
                    "then replay the WINNER and check its prediction lands "
                    "within the stamped error (exit 1 if it does not)")
    ap.add_argument("--calib-floor-pct", type=float, default=10.0,
                    help="floor on the stamped calibration error — a "
                    "single noisy holdout must not stamp an impossibly "
                    "tight bound (CPU smoke boxes need a generous floor)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU validation shapes: tiny resnet18, 32px, 64 "
                    "classes (matches bench_serve --smoke)")
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--num-classes", type=int, default=64500)
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--compute-dtype", default="bfloat16")
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--out", default="",
                    help="also write the kind='whatif' record to this "
                    "JSONL file (overwritten)")
    args = ap.parse_args()
    if args.smoke:
        args.model, args.image, args.num_classes = "resnet18", 32, 64
        args.topk, args.compute_dtype = 3, "float32"

    if args.validate:
        # Validation builds real servers — pin the platform before jax
        # loads (the sitecustomize-registers-TPU trick, see bench_serve).
        platform = (os.environ.get("MPT_PLATFORM")
                    or os.environ.get("JAX_PLATFORMS")
                    or ("cpu" if args.smoke else ""))
        if platform:
            import jax

            jax.config.update(
                "jax_platforms", platform.split(",")[0].strip())

    from mpi_pytorch_tpu.obs.model import ModelError, PhaseLatencyModel
    from mpi_pytorch_tpu.obs.replay import WorkloadError, extract_workload

    try:
        workload = extract_workload(args.trace)
        model = PhaseLatencyModel()
        model.fit_trace(args.trace)
    except (OSError, WorkloadError, ModelError) as e:
        print(f"whatif: {e}", file=sys.stderr)
        return 2

    bucket_sets = [b for b in args.bucket_sets.split(";") if b.strip()]
    if args.precisions:
        precisions = [p.strip() or None
                      for p in args.precisions.split(",")]
    else:
        precisions = sorted(
            {k.precision for k in model.keys}, key=str) or [None]
    hosts = [int(h) for h in args.hosts.split(",") if h.strip()]
    waits = [float(w) for w in args.max_wait_ms.split(",") if w.strip()]
    budgets = [float(b) for b in args.pack_budgets.split(",") if b.strip()]
    if args.residencies:
        residencies = [r.strip() or "replicated"
                       for r in args.residencies.split(",")]
    else:
        residencies = sorted(
            {k.residency for k in model.keys}) or ["replicated"]

    record = {"kind": "whatif", "ts": time.time(),
              "workload": workload.fingerprint}
    ok = True
    if args.validate:
        # Calibration FIRST, on a holdout window (the second half of the
        # workload) replayed under the RECORDED shape — so the error the
        # plan is stamped with predates, and is independent of, the
        # winner comparison below.
        holdout = workload.trim(workload.duration_s / 2.0)
        # Calibrate against a config shaped like the RECORDING: the
        # buckets that actually served it and the host count its
        # serve-side spans came from.
        rec_hosts = set()
        with open(args.trace) as fh:
            for line in fh:
                if '"serve/request"' in line:
                    rec_hosts.add(json.loads(line).get("host"))
        rec_config = {
            "buckets": sorted({r.bucket for r in workload.requests
                               if r.bucket is not None}) or [1],
            "max_wait_ms": waits[0], "hosts": max(1, len(rec_hosts)),
            "precision": precisions[0],
        }
        pred_hold = model.predict(rec_config, holdout)
        server = _build_server(args, rec_config)
        try:
            _, per_phase_hold = _replay_against(server, holdout, args)
        finally:
            server.close()
        if not per_phase_hold:
            print("whatif: holdout replay produced no per-phase stats "
                  "(single-host validation has no collector) — cannot "
                  "stamp calibration", file=sys.stderr)
            return 2
        measured = model.calibrate(pred_hold, per_phase_hold,
                                   window="holdout")
        model.calibration_error_pct = max(measured, args.calib_floor_pct)
        print(f"calibration: measured ±{measured:.1f}% on the "
              f"holdout window (stamped "
              f"±{model.calibration_error_pct:.1f}% with the "
              f"{args.calib_floor_pct:g}% floor)", file=sys.stderr)

    ranked = rank_candidates(
        model, workload, bucket_sets=bucket_sets, precisions=precisions,
        hosts=hosts, waits=waits, budgets=budgets, residencies=residencies)
    shown = ranked[:args.top] if args.top else ranked
    for line in explain_plan(shown, workload, model):
        print(line)
    for line in model.explain():
        print(line)

    priced = [c for c in ranked if "predicted" in c]
    record["ranked"] = [
        {"rank": c["rank"], "config": c["config"],
         **({"p99_ms": c["predicted"]["p99_ms"],
             "per_phase": c["predicted"]["per_phase"],
             "rho": c["predicted"]["rho"],
             "saturated": c["predicted"]["saturated"]}
            if "predicted" in c else {"error": c["error"]})}
        for c in ranked
    ]
    record["candidates"] = len(ranked)
    record["model"] = model.to_record()
    if priced:
        record["winner"] = record["ranked"][priced[0]["rank"] - 1]

    if args.validate and priced:
        winner = priced[0]
        pred = model.predict(winner["config"], workload)
        server = _build_server(args, winner["config"])
        try:
            res, per_phase = _replay_against(server, workload, args)
            compiles = server.stats().get("compiles_after_warmup", 0)
        finally:
            server.close()
        replayed_p99 = res.get("p99_ms")
        if replayed_p99 is None:
            print("whatif: winner replay completed no requests",
                  file=sys.stderr)
            return 1
        err_pct = (100.0 * abs(pred["p99_ms"] - replayed_p99)
                   / max(replayed_p99, 1e-9))
        within = err_pct <= model.calibration_error_pct
        record["validated_p99_ms"] = replayed_p99
        record["within_calibration"] = int(within)
        record["calibration_error_pct"] = model.calibration_error_pct
        print(f"validated winner: predicted p99 {pred['p99_ms']:.1f}ms vs "
              f"replayed {replayed_p99:.1f}ms "
              f"({err_pct:.1f}% off, stamped bound "
              f"±{model.calibration_error_pct:.1f}%) — "
              f"{'WITHIN' if within else 'OUTSIDE'} calibration; "
              f"compiles_after_warmup={compiles}")
        ok = within and compiles == 0

    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(json.dumps(record) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
