"""Pallas TPU kernel: fused stem tail — BN-affine + ReLU + 3×3/s2/p1
max-pool (+ window argmax) in one VMEM pass, index-unpool backward.

Why this op exists (docs/RESULTS.md §4d): the resnet18 headline HLO's five
largest byte rows are ALL the stem tail around ``jvp(ResNet)/bn1..max_pool``
(named in ``docs/hlo_resnet18_r5.txt``; B=2048, 128px ⇒ conv1 out
[2048,64,64,64] bf16 = 1 073 MB):

=========================  ========  ==========================================
instruction                bytes/MB  role
=========================  ========  ==========================================
``fusion.29``                 2 147  BN-apply + relu fwd (read conv, write act)
``fusion.765``                1 342  reduce_window max fwd (read act, write 268)
``select_and_scatter.9``      2 416  maxpool bwd (re-reads the FULL activation
                                     to re-discover the winner it knew at fwd)
``fusion.1``                  2 147  bn1 bwd reduces (read grad + activation)
``fusion.11``                 2 348  conv1 wgrad (+ inline BN-dx)
=========================  ========  ==========================================

≈10.4 GB — 12.7 ms of the 62.3 ms bandwidth bound — and XLA's own cost
model prices the fusions well ABOVE those bounds (``estimated_cycles``
⇒ ~3.6–5.8 ms each at ~1.67 GHz, vs 1.3–2.9 ms bounds), with
select-and-scatter's windowed scan worse still.

This kernel pair removes the intermediate activation tensor entirely:

- forward: read conv1 output y once, apply the FOLDED batchnorm affine
  (a = γ·rsqrt(var+ε), b = β − μ·a) in f32, relu, 3×3/s2/p1 max-pool with
  a first-match window argmax, all in VMEM; write the pooled [B,32,32,64]
  activation + a window-offset index. ≈1.6 GB, replacing fusion.29 +
  fusion.765's 3.5 GB.
- backward: the pool+relu gradient is a static phase-GATHER through the
  saved index (each input position is covered by ≤4 windows; offset
  parity decides which — the in-VMEM version of round 4's XLA-level
  phase decomposition (the since-deleted ``ops/pooling.py``), which LOST
  as an XLA graph because the interleave copies would not fuse but costs
  nothing inside one kernel).
  The relu mask is ``pooled > 0`` (the window max is post-relu: max > 0
  ⟺ the winner was a live activation). The same pass accumulates the
  BN reduces Σdu and Σdu·y across the sequential TPU grid, replacing
  select-and-scatter + fusion.1's 4.6 GB with ≈2.8 GB and NO
  select-and-scatter.

LAYOUT IS THE WHOLE GAME (three measured failures preceded this design):

1. Natural [B,H,W,C] per-image blocks: C=64 half-fills every 128-lane
   vreg and the 9-candidate phase build needs sublane reshapes — the
   kernel ran 10× over its byte bound and the headline step LOST 50%.
2. W-pair lane packing ([B,H,W/2,128]): full vregs, kernel ≈ parity with
   the XLA chain it replaces — but the custom call's required row-major
   {3,2,1,0} operand/result layouts FIGHT the backbone's batch-minor
   {0,3,2,1} preference, so XLA wrapped the call in ~3 ms layout copies
   at EVERY residual conv (measured: step 85 → 140 ms despite the
   kernel itself winning its microbench).
3. This version: the kernel operates on logically TRANSPOSED arrays
   [H, W, C, B] — whose row-major layout is physically IDENTICAL to the
   batch-minor layout XLA already prefers for every conv activation
   ("all batch in lanes"). The wrapper's transposes are layout bitcasts,
   the backbone keeps its layouts, and in-kernel the batch rides the
   lanes (128/block), channels the sublanes (8/block), and both spatial
   dims are outer vector axes where shifts, subsampling (reshape-split +
   unit slice + squeeze — the one 2× pattern that passes Mosaic
   verification; strided vector slices and N-D gathers both fail), and
   the backward interleave (stack+reshape) are all cheap probed ops.

The pooling itself is a SEPARABLE column-then-row pass; column-first
preserves select-and-scatter's row-major first-match tie semantics
exactly (the row fold picks minimal dh among value-maxima, and within
that dh the column fold already picked minimal dw — lexicographic
(dh, dw), pinned on tie-heavy inputs in tests/test_fused_stem.py).

Reference parity: this fuses the torch stem sequence
``bn1 → relu → maxpool(3,2,1)`` of the reference's resnet family
(``/root/reference/models.py:30-45`` via torchvision resnet18/34);
semantics pinned against the unfused XLA composition in
tests/test_fused_stem.py (values AND gradients).

Non-TPU backends fall back to the identical-math XLA composition
(``_reference_impl``), mirroring ``ops/flash_attention.py``'s gating;
``MPT_STEM_INTERPRET=1`` drives the real kernel through the Pallas
interpreter on CPU (how the tests run it).

Multi-chip: pass ``dp_mesh`` (the training mesh) and the public wrapper
``shard_map``s the kernel over the mesh's leading (data) axis — each chip
runs the Mosaic call on its own batch shard, which is exactly the shape
regime the kernel was tuned for, instead of XLA replicating the call's
operands behind an activation all-gather (a Mosaic custom call has no
GSPMD partitioning rule of its own). The BN affine (a, b) stays replicated
(``P()``), and shard_map's transpose psums the per-shard da/db cotangents,
so gradients equal the single-call gradients exactly. Inside an ALREADY
shard_map'd context over the same axis (the ``--spmd-mode`` train step),
the wrapper detects the bound axis (``compat.axis_is_manual``) and runs
the single per-shard call directly — so the mesh can be threaded
unconditionally and spmd-mode VALIDATION (a plain-jit eval step over the
same model) still gets the partitioned call instead of a global-batch
replicated one.

Byte-bound levers (docs/RESULTS.md §4d; the fwd runs 4.25 ms vs a 2.0 ms
byte bound, the bwd ~6.1 vs 3.6): four candidates are implemented behind
env gates, each microbenched by ``tools/bench_stem.py --levers`` and
recorded as a ship-or-rejection row in §4d —

- ``MPT_STEM_BF16_POOL=1``  — pooling compares/phases in bf16 (halves the
  in-VMEM f32 working set; the affine stays f32);
- ``MPT_STEM_LANES=256``    — 256-image batch block (two full vregs per op);
- ``MPT_STEM_IDX_INT8=1``   — int8 window-argmax storage (k ∈ [0, 8] needs
  4 bits; halves the idx tensor's HBM traffic vs bf16);
- ``MPT_STEM_C_BLOCK=16``   — 16-channel sublane block (half the grid
  steps at the same per-step tile bytes).

All four preserve the reference semantics, pinned per-lever (values and
all three gradients) in tests/test_fused_stem.py. Three are exact
re-tilings; bf16 pooling is pinned tightly against pooling over
bf16-ROUNDED activations (rounding is monotone, so window winners and
first-match tie semantics transfer exactly).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = float("-inf")

# Pool geometry is fixed: the torchvision stem (3×3, stride 2, pad 1).
_WIN, _STRIDE, _PAD = 3, 2, 1

# Channels per grid step (sublane dim: 8 = one full f32 sublane tile;
# MPT_STEM_C_BLOCK=16 is the measured-lever override — see module docstring).
_C_BLOCK = 8


def _levers() -> dict:
    """The §4d byte-bound lever configuration, read from the env at trace
    time (defaults = the shipped round-5 kernel)."""
    from mpi_pytorch_tpu.utils.env import env_flag

    return {
        "c_block": int(os.environ.get("MPT_STEM_C_BLOCK", str(_C_BLOCK))),
        "lanes": int(os.environ.get("MPT_STEM_LANES", "128")),
        "bf16_pool": env_flag("MPT_STEM_BF16_POOL"),
        "idx_int8": env_flag("MPT_STEM_IDX_INT8"),
    }

# Mosaic's stack allocation for the fold's temporaries exceeds the 16 MB
# default scoped-vmem budget at useful block sizes; v5e has 128 MB
# physical VMEM, so grant headroom instead of shrinking blocks.
_VMEM_LIMIT = 100 * 1024 * 1024


def _tpu_params():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)


def _reference_impl(y, a, b):
    """Unfused XLA composition — the semantics this kernel is pinned to."""
    z = jax.nn.relu(y.astype(jnp.float32) * a + b)
    pooled = nn_max_pool_f32(z)
    return pooled.astype(y.dtype)


def nn_max_pool_f32(z):
    return lax.reduce_window(
        z, _NEG, lax.max,
        (1, _WIN, _WIN, 1), (1, _STRIDE, _STRIDE, 1),
        ((0, 0), (_PAD, _PAD), (_PAD, _PAD), (0, 0)),
    )


# --- in-kernel building blocks (T-space: [H, W, C_blk, B_blk]) -----------
# All operate on the two OUTER vector axes (H=0, W=1); the minor (sublane,
# lane) dims are never restructured.


def _shift(x, axis, by, fill):
    """t[i] = x[i + by] along an outer axis, ``fill`` off the edge —
    static pad + unit-offset slice."""
    n = x.shape[axis]
    pad = [(0, 0)] * x.ndim
    pad[axis] = (max(0, -by), max(0, by))
    sl = [slice(None)] * x.ndim
    sl[axis] = slice(max(0, by), max(0, by) + n)
    return jnp.pad(x, pad, constant_values=fill)[tuple(sl)]


def _even_odd(x, axis):
    """(x[0::2], x[1::2]) along an outer axis via reshape-SPLIT + unit
    slice + squeeze — the one 2× subsampling pattern that passes Mosaic
    verification (strided vector slices and N-D gathers both fail)."""
    n = x.shape[axis]
    shape = x.shape[:axis] + (n // 2, 2) + x.shape[axis + 1 :]
    x5 = x.reshape(shape)

    def take(o):
        starts = (0,) * len(shape)
        limits = list(shape)
        limits[axis + 1] = o + 1
        starts = list(starts)
        starts[axis + 1] = o
        sl = lax.slice(x5, tuple(starts), tuple(limits))
        return sl.reshape(x.shape[: axis] + (n // 2,) + x.shape[axis + 1 :])

    return take(0), take(1)


def _interleave(e, o, axis):
    """Inverse of ``_even_odd``: t[2i]=e[i], t[2i+1]=o[i]."""
    st = jnp.stack([e, o], axis=axis + 1)
    n = e.shape[axis]
    return st.reshape(e.shape[:axis] + (2 * n,) + e.shape[axis + 1 :])


def _pool_argmax_t(z):
    """3×3/s2/p1 max-pool + first-match argmax of ``z`` [H, W, C, B]
    (T-space). Returns (pooled [H/2, W/2, C, B], k [same], k = dh·3+dw).
    Dtype-generic: runs in ``z.dtype`` (f32, or bf16 under the
    MPT_STEM_BF16_POOL lever — phase codes 0..8 are exact in bf16)."""
    neg = jnp.asarray(_NEG, z.dtype)
    # --- column pass at every row: fold over dw ∈ {0,1,2} -------------
    cm = _shift(z, 1, -1, neg)  # z[w-1]  (dw=0 candidate)
    cp = _shift(z, 1, +1, neg)  # z[w+1]  (dw=2)
    v = cm
    dw = jnp.zeros_like(z)
    better = z > v  # strict: the FIRST max keeps the window
    v = jnp.maximum(v, z)  # NaN-propagating, like reduce_window's lax.max
    dw = jnp.where(better, 1.0, dw)
    better = cp > v
    v = jnp.maximum(v, cp)
    dw = jnp.where(better, 2.0, dw)
    # keep even columns (the window centers, w = 2·ow)
    v, _ = _even_odd(v, 1)
    dw, _ = _even_odd(dw, 1)
    # --- row pass: fold over dh ∈ {0,1,2}, carrying (value, dw) -------
    ev, od = _even_odd(v, 0)        # rows 2h' (dh=1), 2h'+1 (dh=2)
    edw, odw = _even_odd(dw, 0)
    bv = _shift(od, 0, -1, neg)     # rows 2h'-1 (dh=0)
    bdw = _shift(odw, 0, -1, 0.0)
    bdh = jnp.zeros_like(bv)
    better = ev > bv
    bv = jnp.maximum(bv, ev)
    bdh = jnp.where(better, 1.0, bdh)
    bdw = jnp.where(better, edw, bdw)
    better = od > bv
    bv = jnp.maximum(bv, od)
    bdh = jnp.where(better, 2.0, bdh)
    bdw = jnp.where(better, odw, bdw)
    return bv, bdh * 3.0 + bdw


def _fwd_kernel(yt_ref, a_ref, b_ref, out_ref, idx_ref, *, bf16_pool=False):
    yt = yt_ref[...].astype(jnp.float32)  # [H, W, C_blk, B_blk]
    a = a_ref[...].reshape(1, 1, a_ref.shape[0], 1)
    b = b_ref[...].reshape(1, 1, b_ref.shape[0], 1)
    z = jax.nn.relu(yt * a + b)
    if bf16_pool:
        # Lever: the affine is exact in f32; the pool fold's working set
        # (3 candidate tensors + phases) drops to half the VMEM bytes. The
        # pooled VALUE is bf16-rounded — the same rounding the bf16 output
        # store applies anyway — and near-ties within bf16 eps may pick a
        # different (equal-value) window than the f32 fold.
        z = z.astype(jnp.bfloat16)
    best, bestk = _pool_argmax_t(z)
    out_ref[...] = best.astype(out_ref.dtype)
    if idx_ref is not None:
        idx_ref[...] = bestk.astype(idx_ref.dtype)


def _primal_kernel(yt_ref, a_ref, b_ref, out_ref, *, bf16_pool=False):
    _fwd_kernel(yt_ref, a_ref, b_ref, out_ref, None, bf16_pool=bf16_pool)


def _bwd_kernel(g_ref, idx_ref, pooled_ref, yt_ref, a_ref,
                dy_ref, da_ref, db_ref, da_scr, db_scr, *, n_c, n_b, nc):
    jc, ib = pl.program_id(0), pl.program_id(1)

    @pl.when((jc == 0) & (ib == 0))
    def _init():
        da_scr[:] = jnp.zeros_like(da_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    g = g_ref[...].astype(jnp.float32)  # [H2, W2, C_blk, B_blk]
    idx = idx_ref[...].astype(jnp.float32)
    live = pooled_ref[...].astype(jnp.float32) > 0  # window max post-relu
    gm = jnp.where(live, g, 0.0)

    def d(k):
        return jnp.where(idx == float(k), gm, 0.0)

    # Input parity phases: position (2m+i, 2n+j) is covered by ≤4 windows;
    # offset parity decides which — a static gather over the masked pooled
    # gradient, assembled by outer-axis interleaves.
    ee = d(4)
    eo = d(5) + _shift(d(3), 1, +1, 0.0)
    oe = d(7) + _shift(d(1), 0, +1, 0.0)
    oo = (d(8) + _shift(d(6), 1, +1, 0.0) + _shift(d(2), 0, +1, 0.0)
          + _shift(_shift(d(0), 0, +1, 0.0), 1, +1, 0.0))
    even_rows = _interleave(ee, eo, 1)  # [H2, W, C_blk, B_blk]
    odd_rows = _interleave(oe, oo, 1)
    du = _interleave(even_rows, odd_rows, 0)  # [H, W, C_blk, B_blk]

    yt = yt_ref[...].astype(jnp.float32)
    a = a_ref[...].reshape(1, 1, a_ref.shape[0], 1)
    dy_ref[...] = (du * a).astype(dy_ref.dtype)
    red_a = jnp.sum(du * yt, axis=(0, 1, 3))  # [C_blk]
    red_b = jnp.sum(du, axis=(0, 1, 3))
    # Accumulate into lane jc via a one-hot mask: a dynamic lane index in
    # a scratch store is not provably 128-aligned for Mosaic.
    onehot = (
        lax.broadcasted_iota(jnp.int32, (nc, 128), 1) == jc
    ).astype(jnp.float32)
    da_scr[:, :] += red_a[:, None] * onehot
    db_scr[:, :] += red_b[:, None] * onehot

    @pl.when((jc == n_c - 1) & (ib == n_b - 1))
    def _emit():
        da_ref[:] = da_scr[:]
        db_ref[:] = db_scr[:]


def _lane_block(bsz: int, max_lanes: int = 128) -> int:
    """Batch images per grid step (the lane dim): a full 128-lane tile
    when the batch allows it — or two (MPT_STEM_LANES=256, the §4d lever:
    every vector op then covers two full vregs per sublane row)."""
    for nb in (256, 128, 64, 32, 16, 8, 4, 2):
        if nb <= max_lanes and bsz % nb == 0:
            return nb
    return 1


def _check_shapes(y, a, b):
    bsz, h, w, c = y.shape
    if h % 2 or w % 2:
        raise ValueError(f"fused stem needs even spatial dims, got {h}x{w}")
    if a.shape != (c,) or b.shape != (c,):
        raise ValueError(f"affine shape mismatch: {a.shape}/{b.shape} vs C={c}")


def _fwd_impl(yt, a, b, *, want_idx, interpret):
    lev = _levers()
    h, w, c, bsz = yt.shape
    nb, nc = _lane_block(bsz, lev["lanes"]), lev["c_block"]
    a2 = a.astype(jnp.float32).reshape(c, 1)
    b2 = b.astype(jnp.float32).reshape(c, 1)
    h2, w2 = h // 2, w // 2
    in_specs = [
        pl.BlockSpec((h, w, nc, nb), lambda j, i: (0, 0, j, i)),
        pl.BlockSpec((nc, 1), lambda j, i: (j, 0)),
        pl.BlockSpec((nc, 1), lambda j, i: (j, 0)),
    ]
    out_spec = pl.BlockSpec((h2, w2, nc, nb), lambda j, i: (0, 0, j, i))
    grid = (c // nc, bsz // nb)
    idx_dtype = jnp.int8 if lev["idx_int8"] else jnp.bfloat16
    if want_idx:
        return pl.pallas_call(
            functools.partial(_fwd_kernel, bf16_pool=lev["bf16_pool"]),
            grid=grid,
            in_specs=in_specs,
            out_specs=[out_spec, out_spec],
            out_shape=[
                jax.ShapeDtypeStruct((h2, w2, c, bsz), yt.dtype),
                jax.ShapeDtypeStruct((h2, w2, c, bsz), idx_dtype),
            ],
            interpret=interpret,
            compiler_params=_tpu_params() if not interpret else None,
        )(yt, a2, b2)
    return pl.pallas_call(
        functools.partial(_primal_kernel, bf16_pool=lev["bf16_pool"]),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((h2, w2, c, bsz), yt.dtype),
        interpret=interpret,
        compiler_params=_tpu_params() if not interpret else None,
    )(yt, a2, b2)


def _bwd_impl(gt, idxt, pooledt, yt, a, *, interpret):
    from jax.experimental.pallas import tpu as pltpu

    lev = _levers()
    h, w, c, bsz = yt.shape
    nb, nc = _lane_block(bsz, lev["lanes"]), lev["c_block"]
    h2, w2 = h // 2, w // 2
    a2 = a.astype(jnp.float32).reshape(c, 1)
    small = pl.BlockSpec((h2, w2, nc, nb), lambda j, i: (0, 0, j, i))
    big = pl.BlockSpec((h, w, nc, nb), lambda j, i: (0, 0, j, i))
    dyt, da8, db8 = pl.pallas_call(
        functools.partial(_bwd_kernel, n_c=c // nc, n_b=bsz // nb, nc=nc),
        grid=(c // nc, bsz // nb),
        in_specs=[
            small,  # g
            small,  # idx
            small,  # pooled
            big,    # yt
            pl.BlockSpec((nc, 1), lambda j, i: (j, 0)),
        ],
        out_specs=[
            big,
            pl.BlockSpec((nc, 128), lambda j, i: (0, 0)),
            pl.BlockSpec((nc, 128), lambda j, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, w, c, bsz), yt.dtype),
            jax.ShapeDtypeStruct((nc, 128), jnp.float32),
            jax.ShapeDtypeStruct((nc, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((nc, 128), jnp.float32),
            pltpu.VMEM((nc, 128), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_tpu_params() if not interpret else None,
    )(gt, idxt, pooledt, yt, a2)
    # scr[s, j] = grad for channel j*nc + s.
    n_c = c // nc
    da = jnp.transpose(da8[:, :n_c]).reshape(c)
    db = jnp.transpose(db8[:, :n_c]).reshape(c)
    return dyt, da, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _stem_pool_t(yt, a, b, interpret):
    return _fwd_impl(yt, a, b, want_idx=False, interpret=interpret)


def _stem_pool_t_fwd(yt, a, b, interpret):
    pooled, idx = _fwd_impl(yt, a, b, want_idx=True, interpret=interpret)
    return pooled, (yt, a, pooled, idx)


def _stem_pool_t_bwd(interpret, res, gt):
    yt, a, pooledt, idxt = res
    dyt, da, db = _bwd_impl(gt, idxt, pooledt, yt, a, interpret=interpret)
    return dyt, da.astype(a.dtype), db.astype(a.dtype)


_stem_pool_t.defvjp(_stem_pool_t_fwd, _stem_pool_t_bwd)


def _stem_call(y, a, b, interpret):
    """One (per-shard) kernel invocation: T-space transpose wrappers around
    the custom-vjp Pallas pair."""
    yt = jnp.transpose(y, (1, 2, 3, 0))
    outt = _stem_pool_t(yt, a, b, interpret)
    return jnp.transpose(outt, (3, 0, 1, 2))


def stem_affine_relu_pool(y, a, b, *, interpret: bool | None = None, dp_mesh=None):
    """``max_pool3x3s2p1(relu(y·a + b))`` fused in VMEM, differentiable.

    ``y``: [B, H, W, C] (H, W even), any float dtype (bf16 in
    production). ``a``/``b``: f32 [C] — the FOLDED batchnorm affine.
    Returns [B, H/2, W/2, C] in ``y.dtype``.

    Internally the kernels run in T-space [H, W, C, B]: the surrounding
    transposes are layout BITCASTS on TPU because T-space row-major ==
    the batch-minor physical layout XLA already prefers for conv
    activations (see module docstring, failure #2).

    ``interpret``: None = Pallas kernel on TPU, XLA composition elsewhere
    (or the Pallas interpreter when ``MPT_STEM_INTERPRET`` is set); True
    forces the interpreter; False forces the compiled kernel.

    ``dp_mesh``: the training/eval mesh. When its leading (data) axis has
    >1 device, the kernel call is ``shard_map``-partitioned over that axis
    — each device runs the Mosaic call on its batch shard (see module
    docstring, Multi-chip). The batch must divide the axis (the trainer
    validates this; indivisible batches fall back to the XLA composition
    rather than silently replicating the call). If the axis is ALREADY
    bound (calling from inside the spmd-mode step's shard_map), the
    per-shard call runs directly — no nesting."""
    from mpi_pytorch_tpu.utils.hardware import tpu_backend

    _check_shapes(y, a, b)
    n_data = 1
    if dp_mesh is not None:
        from mpi_pytorch_tpu.parallel.compat import axis_is_manual

        axis = dp_mesh.axis_names[0]
        # Inside a shard_map over the data axis (the spmd-mode train step)
        # the operands are already per-shard and a nested wrap over the
        # same axis would be an error — run the single call directly.
        if not axis_is_manual(axis):
            n_data = dp_mesh.shape[axis]
    if y.shape[-1] % _levers()["c_block"] or (n_data > 1 and y.shape[0] % n_data):
        # Channel count must tile the sublane block (every 7×7 stem in the
        # zoo has C=64) and the batch must tile the data axis. Anything
        # else takes the XLA path.
        return _reference_impl(y, a, b)
    if interpret is None:
        from mpi_pytorch_tpu.utils.env import env_flag

        if env_flag("MPT_STEM_INTERPRET"):
            interpret = True
        elif not tpu_backend():
            return _reference_impl(y, a, b)
        else:
            interpret = False
    a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
    if n_data > 1:
        from jax.sharding import PartitionSpec as P

        from mpi_pytorch_tpu.parallel.compat import shard_map

        axis = dp_mesh.axis_names[0]
        return shard_map(
            functools.partial(_stem_call, interpret=interpret),
            mesh=dp_mesh,
            in_specs=(P(axis), P(), P()),
            out_specs=P(axis),
            check_vma=False,
        )(y, a32, b32)
    return _stem_call(y, a32, b32, interpret)
