"""Deterministic fault-injection harness (ISSUE 7 / ROADMAP item 4).

Chaos testing for the elastic-training stack, CLI + library. Every fault is
DETERMINISTIC — a given gate value produces the same failure at the same
point every run — so a chaos test that passes means the recovery path ran,
not that the fault happened to miss. Two halves:

- **Env gates** (``MPT_FAULT_*``, registered in ``utils/env.py
  FAULT_GATES``): in-process faults the framework itself honors — kill a
  rank right after step N, delay a host's steps to fake a straggler, wedge
  backend init for N attempts, fail the first N resume placements, crash
  the first N serve preprocess calls, NaN-poison the Nth train batch
  (the ``--bad-step-policy`` drills), fail the first N image decodes
  (the quarantine drill), fake a preemption notice after step N
  (the exact-step mid-epoch-resume drill), and rotate a tenant's served
  top-k answers (``MPT_FAULT_LOGIT_NOISE_PCT`` + ``_MODEL`` targeting —
  the quality-canary/drift drill of ISSUE 19). ``fault_env()`` builds
  the env-var dict a test hands its trainer subprocess.

- **File faults** (this module's actions): corrupt the NEWEST checkpoint
  (truncate / garbage / empty) so the restore fallback path
  (``train/elastic.restore_latest`` → previous checkpoint + a
  ``kind="anomaly"`` record) is exercised against real on-disk damage, and
  SIGKILL/SIGTERM a live training process by pid.

CLI::

    python tools/inject_faults.py corrupt-latest --checkpoint-dir ckpt [--mode truncate]
    python tools/inject_faults.py kill --pid 1234 [--signal TERM]
    python tools/inject_faults.py kill-serve-host --host-index 1 [--metrics-file m.jsonl]
    python tools/inject_faults.py list-gates

The end-to-end chaos drive (kill an 8-device CPU-mesh run mid-step, resume
on a 4-device mesh) lives in ``tests/test_elastic.py`` and the
``__graft_entry__`` dryrun's elastic leg, both built on these helpers.

Trace linkage (ISSUE 13): a gate that fires INSIDE a traced request (the
router's kill gate striking a traced dispatch, a preprocess crash taking
a traced flush) stamps the victim's trace id on its announcing
``kind="fault"`` record, so the chaos evidence joins the exact waterfall
it disrupted (``tools/trace_report.py``).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CORRUPT_MODES = ("truncate", "garbage", "empty")


def corrupt_latest(ckpt_dir: str, mode: str = "truncate", keep_bytes: int = 64) -> str:
    """Damage the NEWEST checkpoint file in ``ckpt_dir`` in place and return
    its path. Modes: ``truncate`` keeps the first ``keep_bytes`` bytes (a
    crash mid-write past the atomic rename — possible only via bit rot or a
    partial copy, but exactly what the loader must survive); ``garbage``
    overwrites the middle third with 0xFF; ``empty`` leaves a zero-byte
    file. The manifest sidecar is left intact — damage to the payload must
    be detected from the payload."""
    from mpi_pytorch_tpu import checkpoint as ckpt

    if mode not in CORRUPT_MODES:
        raise ValueError(f"mode must be one of {CORRUPT_MODES}, got {mode!r}")
    latest = ckpt.latest_checkpoint(ckpt_dir)
    if latest is None:
        raise FileNotFoundError(f"no checkpoint in {ckpt_dir}")
    size = os.path.getsize(latest)
    if mode == "empty":
        with open(latest, "wb"):
            pass
    elif mode == "truncate":
        with open(latest, "rb+") as f:
            f.truncate(min(keep_bytes, size))
    else:  # garbage
        with open(latest, "rb+") as f:
            f.seek(size // 3)
            f.write(b"\xff" * max(1, size // 3))
    return latest


def kill(pid: int, sig: str = "KILL") -> None:
    """Deliver ``SIG<sig>`` to ``pid`` — the external-kill half of the
    harness (SIGKILL = hard crash, SIGTERM = graceful-preemption drill)."""
    os.kill(pid, getattr(signal, f"SIG{sig.upper()}"))


def find_serve_host_pids(host_index: int | None = None) -> list[int]:
    """PIDs of live ``python -m mpi_pytorch_tpu.serve.host`` processes on
    this machine, optionally filtered to ``--serve-host-index N`` — the
    target finder of the ``kill-serve-host`` chaos drill (scans
    ``/proc/*/cmdline``; own pid excluded)."""
    pids: list[int] = []
    me = os.getpid()
    for entry in os.listdir("/proc"):
        if not entry.isdigit() or int(entry) == me:
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                argv = f.read().decode(errors="replace").split("\0")
        except OSError:
            continue  # raced a process exit
        if "mpi_pytorch_tpu.serve.host" not in argv:
            continue
        if host_index is not None:
            try:
                flag_at = argv.index("--serve-host-index")
                if argv[flag_at + 1] != str(host_index):
                    continue
            except (ValueError, IndexError):
                continue
        pids.append(int(entry))
    return sorted(pids)


def kill_serve_host(
    host_index: int, sig: str = "KILL", metrics_file: str = "",
) -> list[int]:
    """The by-hand twin of the generalized ``MPT_FAULT_SERVE_KILL_HOST``
    gate (ISSUE 12): find the serving SUBPROCESS carrying
    ``--serve-host-index N``, announce the strike with a ``kind="fault"``
    record (a gate never strikes silently — the inject_faults
    discipline), then SIGKILL it. The fleet's router/supervisor must then
    drain, re-dispatch, promote the spare, and restart the corpse —
    which is exactly what the drill exists to watch."""
    pids = find_serve_host_pids(host_index)
    if not pids:
        raise ProcessLookupError(
            f"no live serve-host process with --serve-host-index "
            f"{host_index} (is the fleet up, and on THIS machine?)"
        )
    writer = None
    if metrics_file:
        from mpi_pytorch_tpu.utils.logging import MetricsWriter

        writer = MetricsWriter(metrics_file)
    try:
        for pid in pids:
            if writer is not None:
                writer.write({
                    "kind": "fault",
                    "reason": "injected_host_kill",
                    "detail": (
                        f"serve host index {host_index} pid {pid} "
                        f"SIG{sig.upper()} (kill-serve-host)"
                    ),
                })
            kill(pid, sig)
    finally:
        if writer is not None:
            writer.close()
    return pids


def fault_env(
    *,
    kill_at_step: int | None = None,
    delay_step_ms: int | None = None,
    delay_process: int | None = None,
    backend_wedge: int | None = None,
    device_put_fail: int | None = None,
    preprocess_crash: int | None = None,
    preempt_file: str | None = None,
    nonfinite_at_step: int | None = None,
    decode_fail: int | None = None,
    preempt_at_step: int | None = None,
    wire_delay_ms: int | None = None,
    wire_delay_host: int | None = None,
    wire_delay_jitter_ms: int | None = None,
    logit_noise_pct: int | None = None,
    logit_noise_model: str | None = None,
    base: dict | None = None,
) -> dict:
    """The env-var dict arming the in-process gates — hand it to a trainer
    subprocess (``env={**os.environ, **fault_env(kill_at_step=5)}``). Only
    explicitly requested gates appear; every name is validated against the
    ``utils/env.py`` registry so a renamed gate fails tests loudly."""
    from mpi_pytorch_tpu.utils.env import FAULT_GATES

    values = {
        "MPT_FAULT_KILL_AT_STEP": kill_at_step,
        "MPT_FAULT_DELAY_STEP_MS": delay_step_ms,
        "MPT_FAULT_DELAY_PROCESS": delay_process,
        "MPT_FAULT_BACKEND_WEDGE_N": backend_wedge,
        "MPT_FAULT_DEVICE_PUT_N": device_put_fail,
        "MPT_FAULT_PREPROCESS_N": preprocess_crash,
        "MPT_PREEMPT_FILE": preempt_file,
        "MPT_FAULT_NONFINITE_AT_STEP": nonfinite_at_step,
        "MPT_FAULT_DECODE_N": decode_fail,
        "MPT_FAULT_PREEMPT_AT_STEP": preempt_at_step,
        "MPT_FAULT_WIRE_DELAY_MS": wire_delay_ms,
        "MPT_FAULT_WIRE_DELAY_HOST": wire_delay_host,
        "MPT_FAULT_WIRE_DELAY_JITTER_MS": wire_delay_jitter_ms,
        "MPT_FAULT_LOGIT_NOISE_PCT": logit_noise_pct,
        "MPT_FAULT_LOGIT_NOISE_MODEL": logit_noise_model,
    }
    env = dict(base) if base else {}
    for name, value in values.items():
        assert name in FAULT_GATES, name
        if value is not None:
            env[name] = str(value)
    return env


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_corrupt = sub.add_parser(
        "corrupt-latest", help="damage the newest checkpoint file in place"
    )
    p_corrupt.add_argument("--checkpoint-dir", required=True)
    p_corrupt.add_argument("--mode", choices=CORRUPT_MODES, default="truncate")
    p_corrupt.add_argument("--keep-bytes", type=int, default=64)

    p_kill = sub.add_parser("kill", help="signal a live training process")
    p_kill.add_argument("--pid", type=int, required=True)
    p_kill.add_argument("--signal", default="KILL", dest="sig")

    p_ksh = sub.add_parser(
        "kill-serve-host",
        help="SIGKILL the serving subprocess with this --serve-host-index "
        "(announce-then-strike; the remote-fleet chaos drill by hand)",
    )
    p_ksh.add_argument("--host-index", type=int, required=True)
    p_ksh.add_argument("--signal", default="KILL", dest="sig")
    p_ksh.add_argument(
        "--metrics-file", default="",
        help="append the announcing kind='fault' record here (the fleet's "
        "shared stream, so the strike is on the record it disrupts)",
    )

    sub.add_parser("list-gates", help="print the registered MPT_FAULT_* gates")

    args = parser.parse_args(argv)
    if args.cmd == "corrupt-latest":
        path = corrupt_latest(args.checkpoint_dir, args.mode, args.keep_bytes)
        print(f"corrupted ({args.mode}): {path}")
    elif args.cmd == "kill":
        kill(args.pid, args.sig)
        print(f"sent SIG{args.sig.upper()} to {args.pid}")
    elif args.cmd == "kill-serve-host":
        pids = kill_serve_host(args.host_index, args.sig, args.metrics_file)
        print(
            f"sent SIG{args.sig.upper()} to serve host index "
            f"{args.host_index} (pid(s) {', '.join(map(str, pids))})"
        )
    else:
        from mpi_pytorch_tpu.utils.env import FAULT_GATES

        for name, doc in sorted(FAULT_GATES.items()):
            print(f"{name}\n    {doc}")
        print(
            "\nTrace linkage (ISSUE 13): a gate firing INSIDE a traced "
            "request stamps the active trace id on its announcing "
            "kind='fault' record (schema v9 trace_id), so chaos evidence "
            "joins the exact victim waterfall — assemble it with "
            "tools/trace_report.py over the collector's trace file."
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
