"""Fused tiny-S attention (Pallas, interpret mode on CPU) vs the plain
``full_attention`` reference — values, grads, bf16, padded sequences, the
bh-grouping lever, the multi-chip shard_map path, and the spmd (bound-axis)
path. The kernel computes the SAME function as full attention, so every
check is an exact-to-tolerance comparison (docs/RESULTS.md §4: the staged
vit_s16 candidate)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from mpi_pytorch_tpu.ops.fused_attention_small import (
    _bh_block,
    fused_attention_small,
)
from mpi_pytorch_tpu.ops.ring_attention import full_attention

B, S, H, D = 2, 64, 2, 64  # the vit_s16 attention geometry (S=64, Dh=64)


def _qkv(seed, b=B, s=S, d=D, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, H, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("s", [64, 65, 50, 128])
def test_values_match_full_attention(s):
    """S=64 (the vit_s16 regime), odd S=65 (class-token variant — padded
    rows + a different bh-grouping), padded S=50, and the envelope edge
    S=128."""
    q, k, v = _qkv(0, s=s)
    got = fused_attention_small(q, k, v, interpret=True)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s", [64, 50])
def test_grads_match_full_attention(s):
    q, k, v = _qkv(1, s=s)

    def grads(fn):
        f = lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_).astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_fused = grads(lambda *a: fused_attention_small(*a, interpret=True))
    g_full = grads(full_attention)
    for a, b in zip(g_fused, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)
        assert np.isfinite(np.asarray(a)).all()


def test_causal_matches_full_attention():
    q, k, v = _qkv(2)
    got = fused_attention_small(q, k, v, causal=True, interpret=True)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_bf16_values_and_grads():
    q, k, v = _qkv(3, dtype=jnp.bfloat16)
    got = fused_attention_small(q, k, v, interpret=True)
    want = full_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,  # bf16 quantization on in/out
    )

    def grads(fn):
        f = lambda q_: jnp.sum(fn(q_, k, v).astype(jnp.float32) ** 2)
        return jax.grad(f)(q)

    g_fused = grads(lambda *a: fused_attention_small(*a, interpret=True))
    g_full = grads(full_attention)
    np.testing.assert_allclose(
        np.asarray(g_fused, np.float32), np.asarray(g_full, np.float32),
        rtol=5e-2, atol=5e-1,
    )


@pytest.mark.parametrize("g", [1, 2, 4])
def test_bh_block_lever_is_exact(g):
    """The bh-grouping lever re-tiles the grid; the masked off-diagonal
    blocks must contribute exactly nothing (values AND grads)."""
    q, k, v = _qkv(4)
    got = fused_attention_small(q, k, v, bh_block=g, interpret=True)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    g_fused = jax.grad(
        lambda q_: jnp.sum(
            fused_attention_small(q_, k, v, bh_block=g, interpret=True) ** 2
        )
    )(q)
    g_full = jax.grad(lambda q_: jnp.sum(full_attention(q_, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_full),
                               rtol=5e-5, atol=5e-5)


def test_bh_block_env_gate(monkeypatch):
    """MPT_ATTN_BH_BLOCK overrides the default; non-divisors are reduced."""
    assert _bh_block(12, 64) == 2
    assert _bh_block(12, 128) == 1
    assert _bh_block(12, 56) == 2
    monkeypatch.setenv("MPT_ATTN_BH_BLOCK", "4")
    assert _bh_block(12, 64) == 4
    assert _bh_block(9, 64) == 3  # 4 does not divide 9 → reduced
    # the explicit kwarg beats the env gate
    assert _bh_block(12, 64, override=6) == 6
    # VMEM envelope: G·S_pad capped at 512, so an aggressive override
    # degrades to a buildable grouping instead of a compile failure
    assert _bh_block(12288, 64, override=64) == 8
    assert _bh_block(12288, 128, override=64) == 4
    q, k, v = _qkv(5)
    got = fused_attention_small(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_cpu_fallback_and_envelope():
    """interpret=None off-TPU routes to full_attention exactly; so does a
    sequence outside the tiny-S envelope (S > 128) even with interpret."""
    q, k, v = _qkv(6)
    np.testing.assert_array_equal(
        np.asarray(fused_attention_small(q, k, v)),
        np.asarray(full_attention(q, k, v)),
    )
    q, k, v = _qkv(6, s=196)  # vit at 224px — flash/full own this regime
    np.testing.assert_array_equal(
        np.asarray(fused_attention_small(q, k, v, interpret=True)),
        np.asarray(full_attention(q, k, v)),
    )


def test_vit_fused_small_matches_full_through_model(monkeypatch):
    """A whole ViT forward with attn_impl='fused-small' — routed through the
    REAL Pallas kernel via MPT_ATTN_INTERPRET — equals attn_impl='full' on
    the same params: the trainer flag changes execution, never the
    function."""
    from mpi_pytorch_tpu.models.vit import VisionTransformer

    kw = dict(num_classes=7, patch_size=4, hidden=16, depth=2, num_heads=2,
              mlp_dim=32, dtype=jnp.float32, param_dtype=jnp.float32)
    full = VisionTransformer(**kw)
    fused = VisionTransformer(attn_impl="fused-small", **kw)
    x = jnp.asarray(
        np.random.default_rng(7).standard_normal((2, 16, 16, 3)), jnp.float32
    )
    variables = full.init({"params": jax.random.PRNGKey(0)}, x, train=False)

    monkeypatch.setenv("MPT_ATTN_INTERPRET", "1")
    got = fused.apply(variables, x, train=False)
    want = full.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def _mesh():
    return Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))


@pytest.mark.parametrize("dtype,s", [
    (jnp.float32, 64), (jnp.float32, 50),
    (jnp.bfloat16, 64), (jnp.bfloat16, 50),
])
def test_shard_map_multi_device_matches_single_call(monkeypatch, dtype, s):
    """dp_mesh with an 8-device data axis: the wrapper shard_maps the kernel
    call; values AND all three grads must equal the single-call path — for
    f32 and bf16, at S=64 and padded S (the acceptance shapes)."""
    monkeypatch.setenv("MPT_ATTN_INTERPRET", "1")
    mesh = _mesh()
    n = mesh.shape["data"]
    q, k, v = _qkv(8, b=2 * n, s=s, dtype=dtype)
    vtol = dict(rtol=2e-5, atol=2e-5) if dtype == jnp.float32 else dict(
        rtol=2e-2, atol=2e-2)
    gtol = dict(rtol=5e-5, atol=5e-5) if dtype == jnp.float32 else dict(
        rtol=5e-2, atol=5e-1)

    got = fused_attention_small(q, k, v, dp_mesh=mesh)
    assert got.dtype == dtype
    want = fused_attention_small(q, k, v, interpret=True)
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(want, np.float32))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(full_attention(q, k, v), np.float32),
                               **vtol)

    def grads(fn):
        f = lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_).astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_sharded = grads(lambda *a: fused_attention_small(*a, dp_mesh=mesh))
    g_full = grads(full_attention)
    for a, b in zip(g_sharded, g_full):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **gtol)


def test_indivisible_batch_falls_back(monkeypatch):
    """A batch that does not tile the data axis must take the XLA path
    (exactly full attention), not replicate the Mosaic call."""
    monkeypatch.setenv("MPT_ATTN_INTERPRET", "1")
    mesh = _mesh()
    q, k, v = _qkv(9, b=mesh.shape["data"] + 1)
    got = fused_attention_small(q, k, v, dp_mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full_attention(q, k, v)))


def test_spmd_bound_axis_runs_per_shard_call(monkeypatch):
    """Inside a shard_map over the data axis (the spmd-mode step), the
    wrapper must detect the bound axis and run the per-shard call directly
    — no nested shard_map — and still match full attention."""
    from mpi_pytorch_tpu.parallel.compat import shard_map

    monkeypatch.setenv("MPT_ATTN_INTERPRET", "1")
    mesh = _mesh()
    n = mesh.shape["data"]
    q, k, v = _qkv(10, b=2 * n)

    inner = functools.partial(fused_attention_small, dp_mesh=mesh)
    got = shard_map(
        lambda q_, k_, v_: inner(q_, k_, v_),
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=P("data"),
        check_vma=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(full_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_spmd_training_step_with_fused_small(monkeypatch):
    """One spmd-mode (explicit-collective shard_map) training step over a
    ViT with attn_impl='fused-small' and the mesh threaded — the trainer's
    --spmd-mode --attn-impl fused-small recipe, real kernel code path."""
    from mpi_pytorch_tpu.models.vit import VisionTransformer
    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import (
        make_spmd_train_step,
        make_train_step,
        place_state_on_mesh,
    )

    monkeypatch.setenv("MPT_ATTN_INTERPRET", "1")
    mesh = _mesh()
    n = mesh.shape["data"]
    model = VisionTransformer(
        num_classes=5, patch_size=8, hidden=16, depth=1, num_heads=2,
        mlp_dim=32, attn_impl="fused-small", dp_mesh=mesh,
    )
    rng = np.random.default_rng(11)
    images = rng.standard_normal((2 * n, 16, 16, 3)).astype(np.float32)
    labels = rng.integers(0, 5, size=(2 * n,)).astype(np.int32)
    def one_step(step_factory):
        # Fresh init per leg: the donated step deletes buffers that
        # place_state_on_mesh may alias with the init arrays.
        variables = model.init(
            {"params": jax.random.PRNGKey(0)}, jnp.asarray(images[:2]),
            train=False,
        )
        state = place_state_on_mesh(
            TrainState.create(
                apply_fn=model.apply, variables=variables,
                tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
            ),
            mesh,
        )
        _, metrics = step_factory(state, shard_batch((images, labels), mesh))
        return float(metrics["loss"])

    spmd_loss = one_step(make_spmd_train_step(mesh, jnp.float32))
    auto_loss = one_step(make_train_step(jnp.float32))
    # Same model, same batch: the spmd (bound-axis direct call) and auto
    # (self-shard_mapping) paths compute the same step loss.
    assert np.isfinite(spmd_loss) and np.isfinite(auto_loss)
    np.testing.assert_allclose(spmd_loss, auto_loss, rtol=1e-5, atol=1e-5)


def test_attn_impl_config_validation():
    from mpi_pytorch_tpu.config import parse_config

    ok = parse_config(["--model-name", "vit_s16", "--attn-impl", "fused-small"])
    assert ok.attn_impl == "fused-small"
    with pytest.raises(ValueError, match="no\\s+attention|has no"):
        parse_config(["--attn-impl", "fused-small"])  # default resnet18
    with pytest.raises(ValueError, match="choose one"):
        parse_config(["--model-name", "vit_s16", "--attn-impl", "fused-small",
                      "--sp-strategy", "ring"])
