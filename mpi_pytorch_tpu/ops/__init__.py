from mpi_pytorch_tpu.ops.losses import (
    AUX_LOSS_WEIGHT,
    accuracy_count,
    classification_loss,
    cross_entropy,
)

__all__ = ["AUX_LOSS_WEIGHT", "accuracy_count", "classification_loss", "cross_entropy"]
