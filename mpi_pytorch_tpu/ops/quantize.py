"""Post-training int8 quantization for the serve path (ISSUE 11).

The eval head over 64 500 classes is byte-bound (``docs/roofline_*.json``;
RESULTS §4): the serve path's raw-speed ceiling is set by how many weight/
activation bytes move through the MXU, not by FLOPs. The bf16 fused head
already halved the f32 bytes; this module halves them AGAIN with
post-training int8 — the single biggest remaining lever for the serving
half, and it compounds multiplicatively with the fleet (N hosts × int8
throughput).

Three layers, smallest trusted base first:

1. **Per-channel weight quantization** (``quantize_per_channel``): every
   conv/dense kernel leaf becomes int8 values + a per-OUTPUT-channel f32
   dequant scale (``scale = max|w|/127`` over the channel's fan-in).
   Symmetric, no zero points — the MXU's signed-int8 contract.
2. **A quantized params tree** (``quantize_state``): the trained
   ``TrainState``'s kernels are replaced by int8 leaves; the state's
   ``apply_fn`` is wrapped so the forward dequantizes on the fly
   (``q.astype(f32) * scale`` fuses into each consumer under jit — the
   HBM-resident weights are int8, the dequant is a register-level cast).
   With ``keep_head_int8=True`` the classifier-head Dense kernel is NOT
   dequantized: it stays int8 for the fused kernel below, whose input
   activations are quantized with a scale **calibrated from a small
   sample batch** (``calibrate_head_act_scale``).
3. **The fused int8 head-predict kernel** (``head_predict_int8``): the
   sibling of ``ops/fused_head_ce.head_predict`` — int8 feats × int8 W
   on the MXU with int32 accumulation, dequantized per vocab block
   (``acc * (act_scale · w_scale[col]) + bias``) and fed through the SAME
   online softmax/argmax accumulator (``online_predict_update``), so the
   [B, V] logits never exist and the streamed weight bytes halve again
   vs the bf16 kernel. ``MPT_QHEAD_INTERPRET`` (or the existing
   ``MPT_HEAD_INTERPRET``) drives the real kernel through the Pallas
   interpreter on CPU; non-TPU backends without the gate fall back to
   ``head_predict_int8_reference`` — the exact-integer XLA computation
   the kernel is validated against (``tests/test_quantize.py``).

Int8 tiling note (TPU Mosaic): int8 operands tile at (32, 128) minimum —
the kernel keeps the whole [B, D] feats block and [D, 2048] weight blocks
resident, both well-shaped for the int8 MXU path. The compiled-TPU cells
are staged per the artifact discipline (ROADMAP item 6); this round
validates interpret-mode semantics only.

Accuracy is a measured contract, not an assumption: ``parity_probe`` runs
the SAME fixed sample through the bf16 and int8 predict paths and reports
top-1/top-5 agreement + max logit drift — the oracle behind
``evaluate --quantize-eval``, the serve-side startup parity stamp, and
the top-1 gates in the ``_dryrun_quant`` CI leg.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from mpi_pytorch_tpu.ops.fused_head_ce import (
    _BLOCK_V,
    _predict_row_block,
    online_predict_update,
)

# ---------------------------------------------------------------------------
# per-channel weight quantization
# ---------------------------------------------------------------------------


def quantize_per_channel(w, axis: int = -1):
    """``w`` → (int8 values, f32 per-channel scale) with symmetric range
    [-127, 127] per OUTPUT channel (``axis``; the last dim for both Dense
    [in, out] and conv [kh, kw, in, out] kernels). ``dequantize`` inverts
    to within scale/2 per element — the round-trip bound the tests pin."""
    w = jnp.asarray(w, jnp.float32)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes)
    # All-zero channels get scale 1/127 (quantize to exact zeros) instead
    # of a divide-by-zero; 1e-8 floors denormal channels.
    scale = jnp.maximum(amax, 1e-8) / 127.0
    shape = [1] * w.ndim
    shape[axis] = -1
    q = jnp.clip(jnp.round(w / scale.reshape(shape)), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, axis: int = -1, dtype=jnp.float32):
    """int8 values + per-channel scale → float tensor."""
    axis = axis % q.ndim
    shape = [1] * q.ndim
    shape[axis] = -1
    return q.astype(dtype) * scale.reshape(shape).astype(dtype)


def quantize_activations(x, act_scale):
    """Symmetric per-tensor int8 activation quantization with a CALIBRATED
    scale (``calibrate_head_act_scale``) — the other operand of the int8
    MXU matmul. Out-of-range activations saturate at ±127 (the calibration
    batch sets the clip point; saturation error shows up honestly in the
    parity probe, never as wraparound)."""
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / act_scale), -127, 127
    ).astype(jnp.int8)


# ---------------------------------------------------------------------------
# quantized params tree + dequantizing apply
# ---------------------------------------------------------------------------


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def _should_quantize(path, leaf) -> bool:
    # Conv/Dense kernels only: ndim >= 2 float leaves named 'kernel'.
    # Biases, BN scale/bias, and batch_stats stay f32 — they are a
    # rounding error of the byte budget and carry the calibration-free
    # precision the head's dequant chain leans on.
    keys = [str(getattr(k, "key", k)) for k in path]
    return (
        bool(keys)
        and keys[-1] == "kernel"
        and hasattr(leaf, "ndim")
        and leaf.ndim >= 2
        and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    )


def quantize_params(params):
    """params tree → (same-structured tree with int8 kernels, {path:
    per-channel scale}). Non-kernel leaves pass through untouched."""
    scales: dict[str, jnp.ndarray] = {}

    def qleaf(path, leaf):
        if not _should_quantize(path, leaf):
            return leaf
        q, s = quantize_per_channel(leaf)
        scales[_path_str(path)] = s
        return q

    qtree = jax.tree_util.tree_map_with_path(qleaf, params)
    return qtree, scales


def head_kernel_key(scales: dict, qtree=None) -> str | None:
    """The quantized classifier-head DENSE kernel's scale key, or None.
    Matches the fused-head interceptor's filter (a module NAMED 'head';
    ``evaluate._make_predict_step``): segment 'head' + leaf 'kernel'.
    Conv heads (squeezenet) are ndim-4 kernels — the fused int8 path does
    not apply to them, so with ``qtree`` given they are excluded (and
    dequantize normally; the interceptor would never fire on them)."""
    for key in scales:
        seg = key.split("/")
        if seg[-1] == "kernel" and "head" in seg[:-1]:
            if qtree is not None:
                leaf = qtree
                for s in seg:
                    leaf = leaf[s]
                if leaf.ndim != 2:
                    continue
            return key
    return None


def dequantize_params(qtree, scales: dict, skip=frozenset(), dtype=jnp.float32):
    """Invert ``quantize_params`` inside the traced forward — per leaf a
    cast+multiply that XLA fuses into the consumer, so the weights resident
    in HBM are the int8 tree. ``skip``: scale keys left int8 (the fused
    head's kernel, consumed directly by ``head_predict_int8``)."""

    def dleaf(path, leaf):
        key = _path_str(path)
        if key in scales and key not in skip:
            return dequantize(leaf, scales[key], dtype=dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(dleaf, qtree)


def quantize_state(state, *, keep_head_int8: bool = False, act_scale: float = 1.0):
    """A trained ``TrainState`` → its post-training-int8 twin.

    ``state.params`` becomes ``{"q": <int8-kernel tree>, "scale": {path:
    per-channel scale}, "act_scale": <f32 scalar>}`` and ``apply_fn`` is
    wrapped to dequantize on the fly, so EVERY existing consumer —
    ``eval_logits``, the predict steps, ``place_state_on_mesh``, AOT
    ``jit(...).lower(state, ...)`` — works on the quantized state
    unchanged: the quantized params are ordinary executable inputs, which
    is what lets a serve host hold a bf16 and an int8 executable set over
    the same predict function and switch between them without compiling.

    ``keep_head_int8``: leave the classifier-head Dense kernel int8 (the
    fused ``head_predict_int8`` path consumes it raw, with ``act_scale``
    quantizing its input features). Conv heads have no fused path and
    dequantize normally regardless.
    """
    qtree, scales = quantize_params(state.params)
    skip = frozenset()
    if keep_head_int8:
        hk = head_kernel_key(scales, qtree)
        if hk is not None:
            skip = frozenset({hk})
    orig_apply = state.apply_fn

    def quantized_apply(variables, *args, **kwargs):
        v = dict(variables)
        packed = v["params"]
        v["params"] = dequantize_params(packed["q"], packed["scale"], skip=skip)
        return orig_apply(v, *args, **kwargs)

    packed = {
        "q": qtree,
        "scale": scales,
        "act_scale": jnp.asarray(act_scale, jnp.float32),
    }
    return state.replace(params=packed, apply_fn=quantized_apply)


def fused_head_gate(cfg) -> bool:
    """ONE definition of "does this config serve/probe through the fused
    head kernels": the ``--fused-head-eval`` flag AND a backend that can
    run them (TPU, or the interpret test gates). Shared by the serve
    executables and the ``--quantize-eval`` oracle so the probe can never
    measure a different contract than the server actually runs."""
    from mpi_pytorch_tpu.utils.env import env_flag
    from mpi_pytorch_tpu.utils.hardware import tpu_backend

    return bool(
        cfg.fused_head_eval and (
            tpu_backend() or env_flag("MPT_HEAD_INTERPRET")
            or env_flag("MPT_QHEAD_INTERPRET")
        )
    )


def calibration_batch(cfg) -> np.ndarray:
    """THE fixed calibration/parity sample: ``--quantize-calib`` seeded
    raw-pixel images (``--seed``). One definition so the offline oracle
    and every serve host calibrate on the identical batch — their act
    scales (and therefore the probed contract) can never drift apart."""
    h, w = cfg.image_size
    rng = np.random.default_rng(cfg.seed)
    return rng.integers(
        0, 256, size=(cfg.quantize_calib, h, w, 3)
    ).astype(np.uint8)


def calibrate_head_act_scale(state, images, compute_dtype) -> float:
    """The int8 activation scale for the head's input features, measured
    on a small sample batch through the FLOAT model: ``max|feats| / 127``
    (symmetric per-tensor). Returns 1.0 when no Dense named 'head' fires
    (conv-head models — the fused int8 path does not apply there)."""
    from flax import linen as flax_nn

    from mpi_pytorch_tpu.train.step import ingest_images

    box = {}

    def grab(next_fn, args, kwargs, context):
        m = context.module
        if m.name == "head" and isinstance(m, flax_nn.Dense):
            box["feats"] = args[0]
            return jnp.zeros(args[0].shape[:-1] + (m.features,), jnp.float32)
        return next_fn(*args, **kwargs)

    with flax_nn.intercept_methods(grab):
        state.apply_fn(
            state.variables, ingest_images(jnp.asarray(images), compute_dtype),
            train=False,
        )
    if "feats" not in box:
        return 1.0
    amax = float(jnp.max(jnp.abs(box["feats"].astype(jnp.float32))))
    return max(amax, 1e-6) / 127.0


# ---------------------------------------------------------------------------
# the fused int8 head-predict kernel (sibling of fused_head_ce.head_predict)
# ---------------------------------------------------------------------------


def _predict_int8_kernel(
    labels_ref, feats_ref, w_ref, s_ref, b_ref,
    loss_ref, pred_ref, m_ref, l_ref, picked_ref, arg_ref,
):
    """Per (row block, vocab block): int8×int8 matmul on the MXU with
    int32 accumulation, per-channel dequant (``acc * scale + bias``), then
    the SAME online softmax/argmax update as the bf16 predict kernel —
    one shared definition (``online_predict_update``), two matmul dtypes."""
    j = pl.program_id(1)
    feats = feats_ref[...]  # [B, D] int8
    w = w_ref[...]  # [D, BV] int8
    acc = lax.dot_general(
        feats, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # exact: |acc| <= D * 127^2 << 2^31
    logits = acc.astype(jnp.float32) * s_ref[...] + b_ref[...]  # [B, BV] f32
    online_predict_update(
        j, pl.num_programs(1), logits, labels_ref,
        loss_ref, pred_ref, m_ref, l_ref, picked_ref, arg_ref,
    )


def _pad_int8(w_q, b, scale, block: int):
    """Pad the vocab dim to the block size: zero int8 columns, -inf bias
    (padded logits are ``0*scale + (-inf)`` — never the argmax, add
    ``exp(-inf)=0`` to l), unit scales."""
    v = w_q.shape[1]
    pad = (-v) % block
    if pad:
        w_q = jnp.pad(w_q, ((0, 0), (0, pad)))
        b = jnp.pad(b, (0, pad), constant_values=-jnp.inf)
        scale = jnp.pad(scale, (0, pad), constant_values=1.0)
    return w_q, b, scale, v


_int8_fallback_warned: set[str] = set()


def _warn_int8_fallback(reason: str) -> None:
    if reason in _int8_fallback_warned:
        return
    _int8_fallback_warned.add(reason)
    from mpi_pytorch_tpu.utils.logging import run_logger

    run_logger().warning(
        "head_predict_int8 falling back to the XLA int8 reference (logits "
        "materialized): %s", reason,
    )


def head_predict_int8_reference(feats, w_q, b, labels, w_scale, act_scale):
    """Plain-XLA int8 reference/fallback: the exact integer matmul the
    kernel computes (int32 accumulate), explicit logits, CE + argmax.
    Shares ``quantize_activations`` and the combined-scale expression with
    the kernel, so in interpret mode the two paths agree BITWISE on the
    logits (and therefore exactly on the argmax)."""
    import optax

    q = quantize_activations(feats, act_scale)
    scale_v = (jnp.asarray(w_scale, jnp.float32) * act_scale).astype(jnp.float32)
    acc = lax.dot_general(
        q.astype(jnp.int32), w_q.astype(jnp.int32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32,
    )
    logits = acc.astype(jnp.float32) * scale_v + b.astype(jnp.float32)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    valid = labels >= 0
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(labels, 0)
    )
    return jnp.where(valid, per, 0.0), preds


def _predict_int8_call(labels, feats_q, wp, sp, bp, *, block_r: int, interpret: bool):
    """One (per-shard) row-tiled kernel invocation over pre-padded
    W/scale/bias (the ``_predict_call`` shape with one extra operand)."""
    bsz, d = feats_q.shape
    row_spec = pl.BlockSpec((block_r, 1), lambda i, j: (i, 0))
    loss, pred, *_ = pl.pallas_call(
        _predict_int8_kernel,
        grid=(bsz // block_r, wp.shape[1] // _BLOCK_V),
        in_specs=[
            row_spec,  # labels
            pl.BlockSpec((block_r, d), lambda i, j: (i, 0)),  # int8 feat rows
            pl.BlockSpec((d, _BLOCK_V), lambda i, j: (0, j)),  # int8 W block
            pl.BlockSpec((1, _BLOCK_V), lambda i, j: (0, j)),  # scale block
            pl.BlockSpec((1, _BLOCK_V), lambda i, j: (0, j)),  # bias block
        ],
        out_specs=[row_spec] * 6,
        out_shape=[jax.ShapeDtypeStruct((bsz, 1), jnp.float32)] * 6,
        interpret=interpret,
    )(labels.reshape(bsz, 1), feats_q, wp, sp.reshape(1, -1), bp.reshape(1, -1))
    return loss[:, 0], pred[:, 0].astype(jnp.int32)


def head_predict_int8(
    feats: jnp.ndarray,
    w_q: jnp.ndarray,
    b: jnp.ndarray,
    labels: jnp.ndarray,
    w_scale: jnp.ndarray,
    act_scale,
    interpret: bool | None = None,
    dp_mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(per-example CE [B] f32, argmax predictions [B] int32) of the
    int8-quantized head ``softmax(dequant(q(feats) @ w_q) + b)`` without
    materializing [B, V] — ``head_predict``'s int8 sibling, streaming the
    weight blocks through VMEM at HALF the bf16 kernel's bytes.

    ``feats`` is float (the model tower's output); its int8 quantization
    (calibrated ``act_scale``) happens here so the caller never handles
    int8 activations. ``w_q``/``w_scale`` come from
    ``quantize_per_channel`` (kept raw by ``quantize_state(...,
    keep_head_int8=True)``). ``interpret=None`` auto-selects: the Pallas
    interpreter under ``MPT_QHEAD_INTERPRET``/``MPT_HEAD_INTERPRET``
    (the CPU test gates), the compiled kernel on TPU, the XLA int8
    reference elsewhere. ``dp_mesh`` shard_maps the call over the data
    axis exactly like ``head_predict`` (W/scales/bias replicated)."""
    if interpret is None:
        from mpi_pytorch_tpu.utils.env import env_flag
        from mpi_pytorch_tpu.utils.hardware import tpu_backend

        if env_flag("MPT_QHEAD_INTERPRET") or env_flag("MPT_HEAD_INTERPRET"):
            interpret = True
        elif not tpu_backend():
            return head_predict_int8_reference(
                feats, w_q, b, labels, w_scale, act_scale
            )
        else:
            interpret = False
    n_data = 1
    if dp_mesh is not None:
        from mpi_pytorch_tpu.parallel.compat import axis_is_manual

        if not axis_is_manual(dp_mesh.axis_names[0]):
            n_data = dp_mesh.shape[dp_mesh.axis_names[0]]
    rows = feats.shape[0]
    if rows % n_data:
        _warn_int8_fallback(
            f"batch rows {rows} not divisible by the data axis ({n_data})"
        )
        return head_predict_int8_reference(
            feats, w_q, b, labels, w_scale, act_scale
        )
    block_r = _predict_row_block(rows // n_data)
    if block_r is None:
        _warn_int8_fallback(
            f"no power-of-two row tiling divides {rows // n_data} per-shard "
            "rows within the VMEM envelope"
        )
        return head_predict_int8_reference(
            feats, w_q, b, labels, w_scale, act_scale
        )
    labels = labels.astype(jnp.int32)
    feats_q = quantize_activations(feats, act_scale)
    scale_v = (jnp.asarray(w_scale, jnp.float32) * act_scale).astype(jnp.float32)
    wp, bp, sp, _ = _pad_int8(
        w_q, b.astype(jnp.float32), scale_v, _BLOCK_V
    )
    call = functools.partial(
        _predict_int8_call, block_r=block_r, interpret=interpret
    )
    if n_data > 1:
        from jax.sharding import PartitionSpec as P

        from mpi_pytorch_tpu.parallel.compat import shard_map

        axis = dp_mesh.axis_names[0]
        return shard_map(
            call,
            mesh=dp_mesh,
            in_specs=(P(axis), P(axis), P(), P(), P()),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )(labels, feats_q, wp, sp, bp)
    return call(labels, feats_q, wp, sp, bp)


# ---------------------------------------------------------------------------
# the parity oracle (evaluate --quantize-eval + the serve startup stamp)
# ---------------------------------------------------------------------------


def parity_probe(
    state, qstate, mesh, compute_dtype, images, *,
    topk: int = 5, fused_head: bool = False,
) -> dict:
    """Run the SAME fixed sample through the bf16 and int8 predict paths
    and measure agreement — the reusable oracle behind ``evaluate
    --quantize-eval`` and the serve-side parity gates.

    Returns ``{"samples", "top1_agree", "top5_agree"}``: top-1 is the
    fraction of rows where both paths pick the same class; top-5 (None
    when topk < 5) the fraction where the bf16 argmax appears in the int8
    path's top 5. Metrics compare the SERVED contract (the fused paths
    when ``fused_head``), not an idealized one."""
    from mpi_pytorch_tpu.evaluate import _make_predict_step

    images = jnp.asarray(images)
    n = images.shape[0]
    labels = jnp.full((n,), -1, jnp.int32)
    batch = (images, labels)
    predict_ref = _make_predict_step(
        mesh, compute_dtype, fused_head=fused_head, topk=topk
    )
    predict_q = _make_predict_step(
        mesh, compute_dtype, fused_head=fused_head, topk=topk,
        int8_head=fused_head,
    )
    _, p_ref = predict_ref(state, batch)
    _, p_q = predict_q(qstate, batch)
    p_ref = np.asarray(jax.device_get(p_ref)).reshape(n, -1)
    p_q = np.asarray(jax.device_get(p_q)).reshape(n, -1)
    top1 = float(np.mean(p_ref[:, 0] == p_q[:, 0]))
    top5 = None
    if p_ref.shape[1] >= 5 and p_q.shape[1] >= 5:
        top5 = float(
            np.mean([p_ref[i, 0] in p_q[i, :5] for i in range(n)])
        )
    return {"samples": int(n), "top1_agree": round(top1, 4),
            "top5_agree": None if top5 is None else round(top5, 4)}


def max_logit_drift(state, qstate_plain, images, compute_dtype) -> float:
    """max |bf16-path logit − int8-path logit| over the sample — the
    scalar that turns "quantization error" into a number next to the
    agreement rate. ``qstate_plain`` must be a FULLY-dequantizing
    quantized state (``keep_head_int8=False``): with the head kept int8
    the plain forward has no comparable logits."""
    from mpi_pytorch_tpu.train.step import eval_logits

    images = jnp.asarray(images)
    l_ref = eval_logits(state, images, compute_dtype)
    l_q = eval_logits(qstate_plain, images, compute_dtype)
    return float(jnp.max(jnp.abs(l_ref - l_q)))
