"""Process-tagged logging, shared by the train and eval drivers.

The reference duplicates an ``init_logger()`` in both entry points
(``main.py:22-41``, ``evaluation_pipeline.py:19-38``): a rank-tagged Python
logger with dual stream+file handlers. This is the single shared equivalent,
tagged with ``jax.process_index()`` instead of an MPI rank, plus a structured
JSONL metrics writer the reference lacks.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Mapping


def process_index() -> int:
    # Resolved lazily so importing this module never forces jax initialization.
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def init_logger(name: str = "MPT", log_file: str | None = "training.log",
                level: int = logging.INFO) -> logging.Logger:
    """Rank-tagged logger with stream+file handlers (parity: ``main.py:22-41``)."""
    rank = process_index()
    logger = logging.getLogger(f"{name}_R{rank}")
    logger.setLevel(level)
    logger.propagate = False

    fmt = logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s: %(message)s", datefmt="%Y-%m-%d %H:%M:%S"
    )
    if not any(isinstance(h, logging.StreamHandler) and not isinstance(h, logging.FileHandler)
               for h in logger.handlers):
        sh = logging.StreamHandler(sys.stdout)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
    if log_file:
        target = os.path.abspath(log_file)
        file_handlers = [h for h in logger.handlers if isinstance(h, logging.FileHandler)]
        if not any(h.baseFilename == target for h in file_handlers):
            # Re-init with a different path (new run/config): swap file handlers.
            for h in file_handlers:
                logger.removeHandler(h)
                h.close()
            os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
            fh = logging.FileHandler(log_file)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
            logger.info("Logger Initialized (process %d)", rank)
    return logger


def run_logger() -> logging.Logger:
    """The rank-tagged run logger — the SAME logger ``init_logger`` configures
    (stream + file handlers, ``propagate=False``). Library modules that need
    to surface messages outside the trainer (e.g. checkpoint restore
    warnings) must log here, not to a module-named logger: the run logger
    doesn't propagate, and an unconfigured module logger would fall to the
    bare stderr last-resort handler and never reach ``training.log``."""
    return logging.getLogger(f"MPT_R{process_index()}")


class MetricsWriter:
    """Structured JSONL metrics (throughput, loss, MFU) — SURVEY §5 observability.

    Only process 0 writes, mirroring the reference's rank-0-only result
    reporting (``main.py:173-185``).
    """

    def __init__(self, path: str | None):
        self._fh = None
        if path and process_index() == 0:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def write(self, record: Mapping[str, Any]) -> None:
        if self._fh is None:
            return
        rec = {"ts": time.time(), **record}
        self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
