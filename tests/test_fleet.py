"""Tests for the fleet-serving subsystem (mpi_pytorch_tpu/serve/fleet/).

The ISSUE 9 acceptance surface: load-aware dispatch picks the shorter
queue under a fake-slow host (MPT_FAULT_DELAY_PROCESS), kill-one-host
failover re-dispatches every in-flight request exactly once with the
warm spare promoted (the in-process twin of the ``_dryrun_fleet`` CI
leg), admission control rejects at the FRONT DOOR before any per-host
queue overflows, controller retunes change ``max_wait_ms`` / the active
bucket set with ``compiles_after_warmup == 0`` throughout, continuous
batching keeps responses correctly routed across overlapping flushes,
the ``retry_after_ms`` backpressure hint, the ``--fleet N`` bench mode,
schema-v5 ``route``/``fleet`` records, and the report/regression-gate
tooling over them.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env(**extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def _images(n, size=32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
        for _ in range(n)
    ]


# ----------------------------------------------------- shared fleet fixtures


@pytest.fixture(scope="module")
def fleet_cfg():
    from mpi_pytorch_tpu.config import Config

    cfg = Config(
        model_name="resnet18", num_classes=16, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=2.0, serve_topk=3,
        serve_queue_depth=64, loader_workers=4,
        serve_fleet_hosts=2, serve_probe_interval_ms=50.0,
        metrics_file="", log_file="", eval_log_file="",
    )
    cfg.validate_config()
    return cfg


@pytest.fixture(scope="module")
def shared_exe(fleet_cfg):
    """ONE warmed executable set for the whole module — every FleetServer
    below shares it, so tests pay the warmup compiles once."""
    import jax
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.evaluate import build_inference
    from mpi_pytorch_tpu.serve.executables import BucketExecutables
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    _, _, state, _ = build_inference(
        fleet_cfg, mesh=mesh, manifests=(None, None)
    )
    state = place_state_on_mesh(state, mesh)
    exe = BucketExecutables(fleet_cfg, state, mesh)
    exe.warmup()
    return exe


def _make_fleet(fleet_cfg, shared_exe, **overrides):
    import dataclasses

    from mpi_pytorch_tpu.serve.fleet import FleetServer

    cfg = dataclasses.replace(fleet_cfg, **overrides)
    cfg.validate_config()
    return FleetServer(cfg, executables=shared_exe)


# ------------------------------------------------------------ schema (v5)


def test_route_and_fleet_record_schema():
    from mpi_pytorch_tpu.obs.schema import validate_record

    good_route = {
        "kind": "route", "ts": 1.0, "host": "h0", "requests": 12,
        "share": 0.5, "score": 3.2, "queue_depth": 4, "inflight": 2,
        "window_s": 1.0,
    }
    assert validate_record(good_route) == []
    assert validate_record({"kind": "route", "ts": 1.0, "host": "h0"})
    good_fleet = {
        "kind": "fleet", "ts": 1.0, "event": "failover", "host": "h0",
        "redispatched": 3, "spare": "h2",
    }
    assert validate_record(good_fleet) == []
    retune = {
        "kind": "fleet", "ts": 1.0, "event": "retune", "host": "h1",
        "max_wait_ms_from": 2.0, "max_wait_ms_to": 1.0,
        "buckets_from": "1,4", "buckets_to": "1", "p99_ms": 9.0,
        "target_p99_ms": 5.0, "compiles_after_warmup": 0,
    }
    assert validate_record(retune) == []
    assert validate_record({"kind": "fleet", "ts": 1.0})  # event required


def test_serve_bench_fleet_fields_schema():
    from mpi_pytorch_tpu.obs.schema import validate_record

    row = {
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "requests": 10, "p50_ms": 1.0, "p95_ms": 2.0,
        "p99_ms": 3.0, "images_per_sec": 100.0, "fleet_hosts": 3,
        "per_host": {"h0": {"requests": 4}},
    }
    assert validate_record(row) == []


def test_config_fleet_knob_validation():
    from mpi_pytorch_tpu.config import Config

    Config(serve_fleet_hosts=3, serve_fleet_spare=True).validate_config()
    with pytest.raises(ValueError):
        Config(serve_fleet_hosts=-1).validate_config()
    # Fleet-only knobs without a fleet would be silently ignored → error.
    with pytest.raises(ValueError):
        Config(serve_fleet_spare=True).validate_config()
    with pytest.raises(ValueError):
        Config(serve_target_p99_ms=50.0).validate_config()
    with pytest.raises(ValueError):
        Config(serve_admission_tokens=8).validate_config()
    with pytest.raises(ValueError):
        Config(serve_fleet_hosts=2, serve_fail_probes=0).validate_config()
    with pytest.raises(ValueError):
        Config(
            serve_fleet_hosts=2, serve_probe_interval_ms=0
        ).validate_config()


# ------------------------------------------------- retry_after_ms satellite


def test_queue_full_carries_retry_after_hint():
    """ISSUE 9 bugfix satellite: the typed rejection now tells the client
    HOW LONG to back off, derived from the observed drain rate."""
    from mpi_pytorch_tpu.serve import (
        DynamicBatcher,
        PendingRequest,
        QueueFullError,
    )

    b = DynamicBatcher(buckets=(4,), max_wait_s=0.05, max_queue=2)
    b.submit(PendingRequest(payload=0, future=None))
    b.submit(PendingRequest(payload=1, future=None))
    with pytest.raises(QueueFullError) as exc:
        b.submit(PendingRequest(payload=2, future=None))
    # Cold server: the fallback hint (2× the flush deadline), never None
    # on a batcher-level rejection.
    assert exc.value.retry_after_ms and exc.value.retry_after_ms > 0

    # With an observed drain rate the hint tracks backlog/rate.
    b2 = DynamicBatcher(buckets=(2,), max_wait_s=0.0, max_queue=4)
    for i in range(4):
        b2.submit(PendingRequest(payload=i, future=None))
    assert len(b2.next_flush()) == 2
    time.sleep(0.01)
    assert len(b2.next_flush()) == 2  # two timed drains → a rate estimate
    with pytest.raises(QueueFullError) as exc2:
        for i in range(9):
            b2.submit(PendingRequest(payload=i, future=None))
    assert exc2.value.retry_after_ms > 0
    assert b2.retry_after_ms() > 0


# ------------------------------------------- batcher: active buckets, top-up


def test_batcher_active_buckets_and_drain_ready():
    from mpi_pytorch_tpu.serve import DynamicBatcher, PendingRequest

    b = DynamicBatcher(buckets=(1, 4, 8), max_wait_s=10.0, max_queue=32)
    assert b.active_buckets == (1, 4, 8)
    b.set_active_buckets((1, 4))
    assert b.active_buckets == (1, 4)
    with pytest.raises(ValueError):
        b.set_active_buckets((1, 16))  # 16 was never compiled
    with pytest.raises(ValueError):
        b.set_active_buckets(())
    # The flush-full threshold follows the ACTIVE largest bucket: 4
    # queued requests flush immediately even though 8 is compiled.
    for i in range(4):
        b.submit(PendingRequest(payload=i, future=None))
    t0 = time.monotonic()
    assert len(b.next_flush()) == 4
    assert time.monotonic() - t0 < 1.0

    # drain_ready: already-queued requests come back instantly, bounded.
    for i in range(3):
        b.submit(PendingRequest(payload=i, future=None))
    got = b.drain_ready(2)
    assert [r.payload for r in got] == [0, 1]
    assert [r.payload for r in b.drain_ready(8)] == [2]
    assert b.drain_ready(8) == []


def test_batcher_shrink_mid_wait_caps_flush_and_carries():
    """Review fix pinned: a retune that SHRINKS the active set while
    requests sit out the deadline must not hand the server more rows
    than any active executable's shape — the flush caps at the new
    largest bucket and the excess leads the next flush."""
    from mpi_pytorch_tpu.serve import DynamicBatcher, PendingRequest

    b = DynamicBatcher(buckets=(1, 4, 8), max_wait_s=0.4, max_queue=32)
    for i in range(6):
        b.submit(PendingRequest(payload=i, future=None))
    out = []
    t = threading.Thread(target=lambda: out.append(b.next_flush()))
    t.start()  # 6 < 8 and the deadline is 400 ms away: it waits
    time.sleep(0.1)
    b.set_active_buckets((1, 4))  # the controller's emergency shrink
    t.join(timeout=10)
    assert [r.payload for r in out[0]] == [0, 1, 2, 3]  # capped at 4
    # The displaced requests lead the NEXT flush, oldest-first.
    assert [r.payload for r in b.next_flush()] == [4, 5]


def test_continuous_batching_tops_up_inflight_flush(fleet_cfg, shared_exe):
    """The continuous-batching seam, deterministically: requests that
    arrive while a flush is stuck in preprocess ride THAT flush (topped
    up to the active bucket), instead of waiting out another deadline.
    Without the top-up this scenario dispatches a 1-request flush."""
    import dataclasses

    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = dataclasses.replace(
        fleet_cfg, serve_fleet_hosts=0, serve_max_wait_ms=0.0,
    )
    cfg.validate_config()
    server = InferenceServer(cfg, executables=shared_exe)
    try:
        release = threading.Event()
        real_preprocess = server._preprocess

        def gated_preprocess(image):
            if isinstance(image, np.ndarray) and image[0, 0, 0] == 255:
                release.wait(timeout=30)
            return real_preprocess(image)

        server._preprocess = gated_preprocess
        slow = np.full((32, 32, 3), 255, np.uint8)
        fast = _images(3, seed=1)
        for im in fast:
            im[0, 0, 0] = 0
        futs = [server.submit(slow)]
        time.sleep(0.2)  # the 1-request flush is now blocked in preprocess
        futs += [server.submit(im) for im in fast]
        time.sleep(0.2)  # the late arrivals are queued behind it
        release.set()
        for f in futs:
            assert f.result(timeout=120).shape == (3,)
        stats = server.stats()
        # One topped-up flush of all 4 — not a flush of 1 then one of 3.
        assert stats["batches"] == 1, stats
        assert stats["by_bucket"][4] == 1, stats
        assert stats["compiles_after_warmup"] == 0
    finally:
        server._preprocess = real_preprocess
        server.close()


def test_continuous_batching_routes_responses_across_overlapping_flushes(
    fleet_cfg, shared_exe
):
    """Responses stay correctly routed while flush n+1 is admitted and
    dispatched behind on-device flush n: every request's top-k equals
    the prediction the same image gets in isolation."""
    import dataclasses

    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = dataclasses.replace(fleet_cfg, serve_fleet_hosts=0)
    cfg.validate_config()
    server = InferenceServer(cfg, executables=shared_exe)
    try:
        images = _images(12, seed=3)
        # Isolated references, one at a time (each its own flush).
        ref = [server.predict_batch([im], timeout=120)[0] for im in images]
        # Now a rapid-fire wave: flushes overlap (dispatch n+1 while n is
        # on-device) and requests top up in-flight flushes.
        futs = [server.submit(im) for im in images]
        for f, expect in zip(futs, ref):
            np.testing.assert_array_equal(f.result(timeout=120), expect)
        assert server.stats()["compiles_after_warmup"] == 0
    finally:
        server.close()


# ------------------------------------------------------ load-aware dispatch


def test_load_aware_dispatch_avoids_slow_host(
    fleet_cfg, shared_exe, monkeypatch
):
    """A fake-slow host (MPT_FAULT_DELAY_PROCESS targets fleet-host 0,
    MPT_FAULT_DELAY_STEP_MS delays its every dispatch) builds queue
    depth; the router's EWMA scores must observe it via the registry
    snapshots and route the bulk of the traffic to the healthy host.

    The injected delay must DOMINATE the real step time and the arrival
    rate must be one the healthy host can actually drain — on a slow
    single-core box, 250 ms/step against a 100 req/s wave saturated BOTH
    hosts equally (lockstep scores, ~50/50 split) and the premise
    collapsed. 1 s/step at 25 req/s keeps h1's queue near-empty while
    h0 visibly wedges, on any hardware."""
    monkeypatch.setenv("MPT_FAULT_DELAY_STEP_MS", "1000")
    monkeypatch.setenv("MPT_FAULT_DELAY_PROCESS", "0")
    fleet = _make_fleet(fleet_cfg, shared_exe)
    try:
        images = _images(8)
        futs = []
        for i in range(40):
            futs.append(fleet.submit(images[i % 8]))
            time.sleep(0.04)
        for f in futs:
            assert f.result(timeout=120).shape == (3,)
        by_host = fleet.router.stats()["dispatched_by_host"]
        assert by_host["h0"] + by_host["h1"] == 40
        # The healthy host must carry the clear majority.
        assert by_host["h1"] > by_host["h0"], by_host
        assert by_host["h1"] >= 24, by_host
    finally:
        fleet.close()


def test_stale_snapshots_fall_back_to_power_of_two(fleet_cfg, shared_exe):
    """With the probe thread effectively off (huge interval → every
    snapshot stale), picking degrades to po2 over the router's own
    outstanding counts — it must still spread load, not wedge."""
    fleet = _make_fleet(
        fleet_cfg, shared_exe, serve_probe_interval_ms=60_000.0
    )
    try:
        preds = fleet.predict_batch(_images(16, seed=5), timeout=120)
        assert preds.shape == (16, 3)
        by_host = fleet.router.stats()["dispatched_by_host"]
        assert sum(by_host.values()) == 16
        assert all(v > 0 for v in by_host.values()), by_host  # both used
    finally:
        fleet.close()


# ------------------------------------------------------- admission control


def test_admission_rejects_at_front_door_before_host_overflow(
    fleet_cfg, shared_exe, monkeypatch
):
    """The global token budget rejects at the ROUTER with a typed,
    hint-carrying QueueFullError; no per-host queue ever overflows (the
    hosts' own rejected counters stay 0)."""
    from mpi_pytorch_tpu.serve import QueueFullError

    monkeypatch.setenv("MPT_FAULT_DELAY_STEP_MS", "150")  # both hosts slow
    fleet = _make_fleet(fleet_cfg, shared_exe, serve_admission_tokens=6)
    try:
        assert fleet.router.budget == 6
        images = _images(4, seed=7)
        futs, rejections = [], []
        for i in range(30):
            try:
                futs.append(fleet.submit(images[i % 4]))
            except QueueFullError as e:
                rejections.append(e)
        assert rejections, "the front door never engaged"
        assert all(
            e.retry_after_ms and e.retry_after_ms > 0 for e in rejections
        )
        for f in futs:
            assert f.result(timeout=120).shape == (3,)
        stats = fleet.stats()
        assert stats["router"]["front_door_rejections"] == len(rejections)
        # The point of the budget: hosts never saw their queues overflow.
        for name, s in stats["hosts"].items():
            assert s["rejected"] == 0, (name, s)
    finally:
        fleet.close()


# ------------------------------------------------------------- failover


def test_kill_one_host_failover_redispatches_exactly_once(
    fleet_cfg, shared_exe, monkeypatch, tmp_path
):
    """The in-process twin of the ``_dryrun_fleet`` CI leg: host h0 is
    hard-killed mid-traffic via the registered serve fault gates; every
    accepted request still resolves (zero lost), each re-dispatched
    in-flight request is re-dispatched EXACTLY once, the spare is
    promoted, and one kind="fleet" failover record lands in the stream
    with the surviving hosts at zero steady-state compiles."""
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    monkeypatch.setenv("MPT_FAULT_SERVE_KILL_HOST", "0")
    monkeypatch.setenv("MPT_FAULT_SERVE_KILL_AFTER", "5")
    # Slow flushes so the kill lands with requests genuinely in flight.
    monkeypatch.setenv("MPT_FAULT_DELAY_STEP_MS", "50")
    metrics_file = str(tmp_path / "fleet.jsonl")
    fleet = _make_fleet(
        fleet_cfg, shared_exe, serve_fleet_spare=True,
        metrics_file=metrics_file,
    )
    try:
        images = _images(8, seed=9)
        futs = []
        for i in range(40):
            futs.append(fleet.submit(images[i % 8]))
            time.sleep(0.005)
        for f in futs:
            assert f.result(timeout=120).shape == (3,)  # ZERO lost
        deadline = time.monotonic() + 10
        while not fleet.router.failovers and time.monotonic() < deadline:
            time.sleep(0.05)
        stats = fleet.stats()
        assert stats["router"]["failovers"] == ["h0"], stats["router"]
        assert "h2" in stats["hosts"], stats["hosts"].keys()  # spare in
        assert stats["router"]["spare"] is None  # ... and consumed
        # Exactly once: no flight id appears twice in the redispatch log.
        log = fleet.router.redispatch_log
        assert len(log) == len(set(log)), log
        assert stats["router"]["redispatched"] == len(log)
        for name, s in stats["hosts"].items():
            assert s["compiles_after_warmup"] == 0, (name, s)
    finally:
        fleet.close()
    assert validate_jsonl(metrics_file) == []
    records = load_records(metrics_file)
    failovers = [
        r for r in records
        if r["kind"] == "fleet" and r["event"] == "failover"
    ]
    assert len(failovers) == 1, failovers
    assert failovers[0]["host"] == "h0"
    assert failovers[0]["spare"] == "h2"
    assert any(
        r["kind"] == "fault" and r["reason"] == "injected_host_kill"
        for r in records
    ), "the kill gate must announce itself before striking"
    assert any(r["kind"] == "route" for r in records)


# ------------------------------------------------------------- controller


def test_controller_retunes_wait_then_buckets_with_zero_compiles(
    fleet_cfg, shared_exe, tmp_path
):
    """Breaching p99 halves max_wait_ms down to the floor, then deactivates
    the largest active bucket — every retune only activates pre-compiled
    executables and the compile counter stays 0 throughout."""
    import dataclasses

    from mpi_pytorch_tpu.serve import InferenceServer
    from mpi_pytorch_tpu.serve.fleet import FleetController, LocalHost
    from mpi_pytorch_tpu.utils.logging import MetricsWriter

    cfg = dataclasses.replace(fleet_cfg, serve_fleet_hosts=0)
    cfg.validate_config()
    server = InferenceServer(cfg, executables=shared_exe, host_index=0)
    host = LocalHost(server)
    writer = MetricsWriter(str(tmp_path / "ctl.jsonl"))
    ctl = FleetController(
        lambda: [host], target_p99_ms=0.001, metrics=writer,
    )
    try:
        images = _images(6, seed=11)
        assert host.max_wait_ms == 2.0
        server.predict_batch(images, timeout=120)
        assert ctl.tick() == 1  # real traffic breaches the absurd target
        assert host.max_wait_ms == 1.0
        # Each tick needs NEW observations — an idle fleet is not retuned.
        assert ctl.tick() == 0
        for _ in range(8):
            server.predict_batch(images, timeout=120)
            ctl.tick()
        # Wait pinned to the floor, then the largest bucket deactivated.
        assert host.max_wait_ms == 0.0
        assert host.active_buckets == (1,)
        assert set(host.active_buckets) <= set(host.buckets)
        assert host.compiles_after_warmup() == 0
        assert ctl.retunes >= 3
    finally:
        server.close()
        writer.close()
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    path = str(tmp_path / "ctl.jsonl")
    assert validate_jsonl(path) == []
    retunes = [
        r for r in load_records(path)
        if r["kind"] == "fleet" and r["event"] == "retune"
    ]
    assert retunes and all(
        r["compiles_after_warmup"] == 0 for r in retunes
    )
    assert retunes[0]["max_wait_ms_from"] == 2.0
    assert retunes[0]["max_wait_ms_to"] == 1.0
    assert any(r["buckets_to"] == "1" for r in retunes)


def test_controller_recovers_headroom(fleet_cfg, shared_exe):
    """With p99 far under target and poor fill, the controller restores
    deactivated buckets first, then grows the wait."""
    import dataclasses

    from mpi_pytorch_tpu.serve import InferenceServer
    from mpi_pytorch_tpu.serve.fleet import FleetController, LocalHost

    cfg = dataclasses.replace(fleet_cfg, serve_fleet_hosts=0)
    cfg.validate_config()
    server = InferenceServer(cfg, executables=shared_exe, host_index=0)
    host = LocalHost(server)
    # fill_low_pct above 100: the wait-growth branch triggers on any fill
    # (this test pins the mechanism; thresholds are policy).
    ctl = FleetController(
        lambda: [host], target_p99_ms=1e9, fill_low_pct=200.0
    )
    try:
        host.set_active_buckets((1,))
        host.set_max_wait_ms(1.0)
        images = _images(3, seed=13)
        server.predict_batch(images, timeout=120)  # batch-1 flushes: low fill
        assert ctl.tick() == 1
        assert host.active_buckets == (1, 4)  # bucket restored first
        server.predict_batch(images, timeout=120)
        assert ctl.tick() == 1
        assert host.max_wait_ms == 1.5  # then the wait grows
        assert host.compiles_after_warmup() == 0
    finally:
        server.close()


def test_set_active_buckets_rejects_uncompiled(fleet_cfg, shared_exe):
    import dataclasses

    from mpi_pytorch_tpu.serve import InferenceServer, ServeError

    cfg = dataclasses.replace(fleet_cfg, serve_fleet_hosts=0)
    cfg.validate_config()
    server = InferenceServer(cfg, executables=shared_exe)
    try:
        with pytest.raises(ServeError):
            server.set_active_buckets((1, 32))
        server.set_active_buckets((4,))
        assert server.active_buckets == (4,)
    finally:
        server.close()


# ----------------------------------------------------------- bench / tools


def test_bench_serve_fleet_smoke(tmp_path):
    """``--fleet 2 --smoke``: rows carry fleet_hosts + the per-host
    fill/latency breakdown, schema-valid, zero steady-state compiles."""
    from mpi_pytorch_tpu.obs.schema import validate_record

    out = tmp_path / "fleet_bench.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serve.py"),
         "--smoke", "--fleet", "2", "--out", str(out)],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(l) for l in out.read_text().splitlines() if l.strip()]
    assert rows and {r["mode"] for r in rows} == {"closed", "open"}
    for r in rows:
        assert not validate_record(r), validate_record(r)
        assert r["fleet_hosts"] == 2
        assert set(r["per_host"]) == {"h0", "h1"}
        assert r["compiles_after_warmup"] == 0
        assert sum(h["requests"] for h in r["per_host"].values()) > 0


def test_report_run_renders_fleet_sections(tmp_path, capsys):
    from tools import report_run

    path = tmp_path / "m.jsonl"
    records = [
        {"kind": "route", "ts": 1.0, "host": "h0", "requests": 30,
         "share": 0.75, "score": 2.1, "queue_depth": 1, "inflight": 0,
         "window_s": 1.0},
        {"kind": "route", "ts": 1.0, "host": "h1", "requests": 10,
         "share": 0.25, "score": 9.0, "queue_depth": 7, "inflight": 2,
         "window_s": 1.0},
        {"kind": "fleet", "ts": 2.0, "event": "failover", "host": "h1",
         "detail": "health-probe failures", "redispatched": 4,
         "spare": "h2"},
        {"kind": "fleet", "ts": 3.0, "event": "retune", "host": "h0",
         "max_wait_ms_from": 5.0, "max_wait_ms_to": 2.5,
         "buckets_from": "1,8,32", "buckets_to": "1,8", "p99_ms": 80.0,
         "target_p99_ms": 50.0, "compiles_after_warmup": 0},
    ]
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert report_run.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "fleet routing: 40 request(s) over 2 host(s)" in out
    assert "75.0" in out  # h0's share
    assert "FLEET failover: host h1 drained" in out
    assert "4 in-flight re-dispatched, spare h2 promoted" in out
    assert "FLEET retune: host h0" in out
    assert "1,8,32 → 1,8" in out
    # And the JSON mode carries the same structure.
    assert report_run.main([str(path), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["fleet_routing"]["hosts"]["h0"]["share_pct"] == 75.0
    assert js["fleet_events"][0]["event"] == "failover"


def test_check_regression_keys_fleet_rows_separately(tmp_path):
    """A fleet row and a single-host row at the same sweep point are
    different trend lines; and the gate still catches a fleet p99
    regression against a fleet baseline."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_regression", os.path.join(REPO, "tools", "check_regression.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base_row = {
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "offered_rps": 400.0, "model": "resnet18",
        "requests": 100, "p50_ms": 5.0, "p95_ms": 8.0, "p99_ms": 10.0,
        "images_per_sec": 1000.0,
    }
    fleet_row = dict(base_row, fleet_hosts=3, p99_ms=30.0)
    baseline = tmp_path / "prev.json"
    new = tmp_path / "new.json"
    with open(baseline, "w") as f:
        f.write(json.dumps(base_row) + "\n")
        f.write(json.dumps(fleet_row) + "\n")
    # The single-host point is unchanged; the FLEET point regressed 2x.
    with open(new, "w") as f:
        f.write(json.dumps(base_row) + "\n")
        f.write(json.dumps(dict(fleet_row, p99_ms=60.0)) + "\n")
    violations = mod.check_serve(str(new), str(baseline), 10.0)
    assert len(violations) == 1, violations
    assert "p99" in violations[0]
    # Distinct keys: a fleet row never pairs with a single-host row.
    assert mod._serve_key(base_row) != mod._serve_key(fleet_row)


def test_fleet_server_local_autoscale_spawns_from_shared_executables(
    fleet_cfg, shared_exe, tmp_path
):
    """ISSUE 12: the in-process twin of the remote autoscaler wiring — a
    local scale-up is a new InferenceServer over the SHARED warmed
    executable set (zero compiles by construction), admitted into the
    router with the admission budget growing to match."""
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    metrics_file = str(tmp_path / "autoscale.jsonl")
    fleet = _make_fleet(
        fleet_cfg, shared_exe, serve_autoscale=True,
        serve_fleet_min_hosts=1, serve_fleet_max_hosts=3,
        serve_scale_cooldown_s=0.0, serve_scale_reject_rate=0.5,
        serve_retune_interval_s=3600.0,  # drive tick() manually
        metrics_file=metrics_file,
    )
    try:
        assert fleet.autoscaler is not None
        budget_before = fleet.router.budget
        fleet.autoscaler.tick()  # baseline the signal deltas
        time.sleep(0.02)
        fleet.router.front_door_rejections += 100  # reject pressure
        assert fleet.autoscaler.tick() == "scale_up"
        hosts = fleet.hosts()
        assert len(hosts) == 3, [h.name for h in hosts]
        assert fleet.router.budget == budget_before + (
            fleet.cfg.serve_queue_depth
        )
        # The scaled-up host serves real traffic with zero compiles.
        preds = fleet.predict_batch(_images(8, seed=21), timeout=120)
        assert preds.shape == (8, 3)
        for name, s in fleet.stats()["hosts"].items():
            assert s["compiles_after_warmup"] == 0, (name, s)
    finally:
        fleet.close()
    assert validate_jsonl(metrics_file) == []
    ups = [
        r for r in load_records(metrics_file)
        if r["kind"] == "fleet" and r["event"] == "scale_up"
    ]
    assert len(ups) == 1
    assert ups[0]["hosts_from"] == 2 and ups[0]["hosts_to"] == 3
    assert ups[0]["compiles_after_warmup"] == 0


def test_fleet_rejects_shared_fixed_metrics_port(fleet_cfg):
    import dataclasses

    from mpi_pytorch_tpu.serve import ServeError
    from mpi_pytorch_tpu.serve.fleet import FleetServer

    cfg = dataclasses.replace(fleet_cfg, serve_metrics_port=8080)
    cfg.validate_config()
    with pytest.raises(ServeError, match="cannot be shared"):
        FleetServer(cfg, executables=object())
