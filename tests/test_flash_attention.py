"""Flash attention (Pallas, interpret mode on CPU) vs the plain
``full_attention`` reference — values, grads, causal masking, non-divisible
sequence padding, and bf16 inputs. The kernel computes the SAME function, so
every check is an exact-to-tolerance comparison."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.ops.flash_attention import flash_attention
from mpi_pytorch_tpu.ops.ring_attention import full_attention

B, S, H, D = 2, 32, 2, 8


def _qkv(seed, s=S, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, s, H, D)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_full_attention(causal):
    q, k, v = _qkv(0)
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_full_attention(causal):
    q, k, v = _qkv(1)
    y = jnp.asarray(np.random.default_rng(2).standard_normal((B, S, H, D)),
                    jnp.float32)

    def loss(fn):
        def f(q_, k_, v_):
            return jnp.mean((fn(q_, k_, v_) - y) ** 2)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_flash = loss(lambda *a: flash_attention(
        *a, causal=causal, block_q=16, block_k=16, interpret=True))
    g_full = loss(lambda *a: full_attention(*a, causal=causal))
    for a, b in zip(g_flash, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_flash_pads_non_divisible_sequence():
    """S=24 with 16-wide blocks: padded keys must contribute nothing and the
    output slice must equal the unpadded reference (values AND grads)."""
    q, k, v = _qkv(3, s=24)
    got = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    g_flash = jax.grad(
        lambda q_: jnp.sum(flash_attention(q_, k, v, block_q=16, block_k=16,
                                           interpret=True) ** 2)
    )(q)
    g_full = jax.grad(lambda q_: jnp.sum(full_attention(q_, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_full),
                               rtol=5e-5, atol=5e-5)
    assert np.isfinite(np.asarray(g_flash)).all()


def test_flash_bf16_inputs():
    q, k, v = _qkv(4, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    want = full_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,  # bf16 quantization on in/out
    )


@pytest.mark.parametrize("s", [64, 50])
def test_flash_tiny_s_values_and_grads(s):
    """Tiny-S pins at the vit_s16 geometry (S=64 / padded S=50, Dh=64):
    the flash kernel is the measured baseline the fused tiny-S kernel
    (ops/fused_attention_small.py) is A/B'd against, so its own parity at
    these shapes is pinned here — values AND all three grads vs full
    attention, through the real kernel path (interpret mode)."""
    rng = np.random.default_rng(20 + s)
    mk = lambda: jnp.asarray(rng.standard_normal((2, s, 2, 64)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    got = flash_attention(q, k, v, interpret=True)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def grads(fn):
        f = lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(grads(lambda *x: flash_attention(*x, interpret=True)),
                    grads(full_attention)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)
        assert np.isfinite(np.asarray(a)).all()


def test_flash_tiny_s_bf16():
    """bf16 at S=64/Dh=64 — the production dtype of the tiny-S regime."""
    rng = np.random.default_rng(30)
    mk = lambda: jnp.asarray(rng.standard_normal((2, 64, 2, 64)), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    got = flash_attention(q, k, v, interpret=True)
    want = full_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_flash_cpu_fallback_is_full_attention():
    """interpret=None off-TPU must route to full_attention (identical
    output, no Pallas involved) — the production CPU/GPU gating."""
    q, k, v = _qkv(5)
    got = flash_attention(q, k, v)  # auto: CPU → fallback
    want = full_attention(q, k, v)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_vit_flash_matches_full_through_model(monkeypatch):
    """A whole ViT forward with attn_impl='flash' — routed through the REAL
    Pallas kernel via MPT_FLASH_INTERPRET — equals attn_impl='full' on the
    same params: the trainer flag changes execution, never the function."""
    from mpi_pytorch_tpu.models.vit import VisionTransformer

    kw = dict(num_classes=7, patch_size=4, hidden=16, depth=2, num_heads=2,
              mlp_dim=32, dtype=jnp.float32, param_dtype=jnp.float32)
    full = VisionTransformer(**kw)
    flash = VisionTransformer(attn_impl="flash", **kw)
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((2, 16, 16, 3)), jnp.float32
    )
    variables = full.init({"params": jax.random.PRNGKey(0)}, x, train=False)

    monkeypatch.setenv("MPT_FLASH_INTERPRET", "1")
    got = flash.apply(variables, x, train=False)
    want = full.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_attn_impl_config_validation():
    from mpi_pytorch_tpu.config import parse_config

    ok = parse_config(["--model-name", "vit_s16", "--attn-impl", "flash"])
    assert ok.attn_impl == "flash"
    with pytest.raises(ValueError, match="no\\s+attention|has no"):
        parse_config(["--attn-impl", "flash"])  # default resnet18
    with pytest.raises(ValueError, match="choose one"):
        parse_config(["--model-name", "vit_s16", "--attn-impl", "flash",
                      "--sp-strategy", "ring"])
    with pytest.raises(ValueError, match="full|flash"):
        parse_config(["--model-name", "vit_s16", "--attn-impl", "typo"])
