"""Tests for the torchvision→Flax weight-mapping rules (use_pretrained path).

Three layers of checking, none requiring torchvision:
1. coverage: every non-head leaf of every architecture maps to a unique
   torchvision key, and a synthetic state_dict built from those keys converts
   cleanly (missing keys raise);
2. semantics: the layout transforms are validated against real torch ops
   (torch IS in this image) — a conv/linear computed by torch matches the
   flax op using the converted kernel;
3. head preservation: converted variables keep the fresh head init.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.models.common import head_filter
from mpi_pytorch_tpu.models.torch_mapping import (
    conv_kernel,
    convert_state_dict,
    flatten_dense_kernel,
    tv_entries,
)

from mpi_pytorch_tpu.models.pretrained import CONVERTIBLE_MODELS as ARCHS


def _flat(tree):
    return [
        (tuple(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


def _torch_shape(flax_shape):
    """Invert the layout convention to get the torch-side shape."""
    if len(flax_shape) == 4:  # conv HWIO ← OIHW
        return (flax_shape[3], flax_shape[2], flax_shape[0], flax_shape[1])
    if len(flax_shape) == 2:  # dense [in, out] ← [out, in]
        return (flax_shape[1], flax_shape[0])
    return flax_shape


@pytest.mark.parametrize("arch", ARCHS)
def test_mapping_covers_every_leaf_and_roundtrips(bundles, arch):
    _, variables = bundles[arch]
    rng = np.random.default_rng(0)
    state_dict = {}
    seen_keys = set()
    for collection in ("params", "batch_stats"):
        if collection not in variables:
            continue
        for path, leaf in _flat(variables[collection]):
            entry = tv_entries(arch, collection, path, tuple(leaf.shape))
            if entry is None:
                assert head_filter(path), f"non-head leaf unmapped: {path}"
                continue
            key, transform = entry
            assert key not in seen_keys, f"duplicate torchvision key {key}"
            seen_keys.add(key)
            tshape = _torch_shape(tuple(leaf.shape))
            state_dict[key] = rng.standard_normal(tshape).astype(np.float32)
            assert transform(state_dict[key]).shape == tuple(leaf.shape), (
                f"{arch} {key}: transform produces {transform(state_dict[key]).shape}, "
                f"flax leaf is {leaf.shape}"
            )

    converted = convert_state_dict(arch, variables, state_dict)
    # non-head leaves overlaid, head leaves untouched
    for (path, fresh), (_, conv) in zip(
        _flat(variables["params"]), _flat(converted["params"])
    ):
        if head_filter(path):
            np.testing.assert_array_equal(np.asarray(fresh), np.asarray(conv))
        else:
            assert not np.array_equal(np.asarray(fresh), np.asarray(conv)) or np.all(
                np.asarray(fresh) == 0
            ), f"{path} was not overlaid"

    # a missing key is an error, not a silent partial load
    key = sorted(state_dict)[0]
    broken = dict(state_dict)
    del broken[key]
    with pytest.raises(KeyError, match="missing"):
        convert_state_dict(arch, variables, broken)


def test_conv_kernel_transform_matches_torch():
    torch = pytest.importorskip("torch")
    from flax import linen as nn

    w = np.random.default_rng(1).standard_normal((8, 3, 3, 3)).astype(np.float32)  # OIHW
    x = np.random.default_rng(2).standard_normal((2, 3, 16, 16)).astype(np.float32)  # NCHW

    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=1, padding=1
    ).numpy()  # NCHW

    conv = nn.Conv(8, (3, 3), padding=1, use_bias=False)
    out = conv.apply(
        {"params": {"kernel": jnp.asarray(conv_kernel(w))}},
        jnp.asarray(x.transpose(0, 2, 3, 1)),  # NHWC
    )
    np.testing.assert_allclose(np.asarray(out), ref.transpose(0, 2, 3, 1), atol=1e-4)


def test_flatten_dense_transform_matches_torch():
    torch = pytest.importorskip("torch")

    c, h, wd, out = 5, 4, 4, 7
    rng = np.random.default_rng(3)
    w = rng.standard_normal((out, c * h * wd)).astype(np.float32)  # torch [out, CHW]
    x = rng.standard_normal((2, c, h, wd)).astype(np.float32)  # NCHW feature map

    ref = torch.nn.functional.linear(
        torch.from_numpy(x).flatten(1), torch.from_numpy(w)
    ).numpy()

    flax_w = flatten_dense_kernel(c, h, wd)(w)  # [HWC, out]
    flax_x = x.transpose(0, 2, 3, 1).reshape(2, -1)  # NHWC flatten
    np.testing.assert_allclose(flax_x @ flax_w, ref, atol=1e-4)
