"""Expert-parallel MoE vs the dense single-device evaluation on the 8-device
CPU mesh — values, gradients, aux-loss agreement, capacity drops, and guards.

The correctness property: sharding experts over the mesh and moving tokens
via all_to_all computes exactly the dense per-shard routing result (each
shard routes its own tokens with its own capacity budget — the documented
EP semantics), for both top-1 and top-2 routing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.ops.moe import (
    dense_moe,
    init_moe_params,
    moe_forward,
)

N_SHARDS = 8
E = 16  # 2 experts per shard
D = 8
H = 32
T = 64  # 8 tokens per shard


@pytest.fixture(scope="module")
def mesh():
    dev = np.asarray(jax.devices()[:N_SHARDS]).reshape(N_SHARDS, 1)
    return Mesh(dev, ("expert", "unused"))


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.PRNGKey(0), D, H, E)


def _x(seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((T, D)), jnp.float32)


def dense_per_shard(params, x, *, k, capacity):
    """Reference: run each shard's token block through the dense MoE with the
    shard's capacity budget — exactly the EP semantics, no collectives."""
    blocks, auxes = [], []
    for x_blk in jnp.split(x, N_SHARDS):
        y, aux = dense_moe(params, x_blk, k=k, capacity=capacity)
        blocks.append(y)
        auxes.append(aux)
    return jnp.concatenate(blocks), jnp.mean(jnp.asarray(auxes))


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 2])
def test_moe_matches_dense(mesh, params, k):
    x = _x()
    cap = T // N_SHARDS  # default capacity in moe_forward
    got, aux = moe_forward(params, x, mesh, expert_axis="expert", k=k)
    want, aux_want = dense_per_shard(params, x, k=k, capacity=cap)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_want), rtol=2e-5)


@pytest.mark.slow
def test_moe_grads_match_dense(mesh, params):
    x = _x(seed=2)
    cap = T // N_SHARDS

    def loss_ep(p, x_):
        y, aux = moe_forward(p, x_, mesh, expert_axis="expert", k=2)
        return jnp.sum(y * y) + 0.01 * aux

    def loss_dense(p, x_):
        y, aux = dense_per_shard(p, x_, k=2, capacity=cap)
        return jnp.sum(y * y) + 0.01 * aux

    ge, gxe = jax.grad(loss_ep, argnums=(0, 1))(params, x)
    gd, gxd = jax.grad(loss_dense, argnums=(0, 1))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(ge), jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gxe), np.asarray(gxd), rtol=5e-5, atol=5e-5)


def test_moe_capacity_drops_tokens(params):
    """With capacity 1, an expert chosen by several tokens serves only the
    first; dropped tokens contribute zero through that expert (combine=0)."""
    x = jnp.tile(_x(seed=3)[:1], (4, 1))  # 4 identical tokens → same expert
    y_tight, _ = dense_moe(params, x, k=1, capacity=1)
    y_loose, _ = dense_moe(params, x, k=1, capacity=4)
    # first token is served either way
    np.testing.assert_allclose(
        np.asarray(y_tight[0]), np.asarray(y_loose[0]), rtol=1e-5, atol=1e-6
    )
    # overflow tokens got dropped → zero output, unlike the loose run
    assert np.allclose(np.asarray(y_tight[1:]), 0.0)
    assert not np.allclose(np.asarray(y_loose[1:]), 0.0)


def test_moe_aux_penalizes_imbalance(params):
    """Routing everything to one expert yields a higher aux loss than the
    measured (roughly balanced) routing — the property the loss exists for."""
    x = _x(seed=4)
    _, aux_real = dense_moe(params, x, k=1)
    hot = {**params, "gate": jnp.zeros_like(params["gate"]).at[:, 0].set(10.0)}
    _, aux_hot = dense_moe(hot, x, k=1)
    assert float(aux_hot) > float(aux_real)


def test_moe_grouped_matches_ungrouped_when_no_drops(params):
    """Grouped routing with per-group no-drop capacity equals global routing
    with no drops: grouping only changes capacity competition scope, and
    with no overflow each token meets its top-k experts either way."""
    x = _x(seed=5)
    y_g, _ = dense_moe(params, x, k=2, capacity=16, group_size=16)
    y_u, _ = dense_moe(params, x, k=2, capacity=T)
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_u), rtol=2e-5, atol=2e-5)


def test_moe_grouped_ep_matches_grouped_dense(mesh, params):
    """The grouped EP dataflow (fold groups into slots → all_to_all → unfold)
    equals the per-shard dense evaluation with the same groups."""
    x = _x(seed=6)
    got, _ = moe_forward(
        params, x, mesh, expert_axis="expert", k=2, capacity=4, group_size=4
    )
    blocks = [
        dense_moe(params, x_blk, k=2, capacity=4, group_size=4)[0]
        for x_blk in jnp.split(x, N_SHARDS)
    ]
    want = jnp.concatenate(blocks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pick_group_size_always_divides():
    from mpi_pytorch_tpu.ops.moe import pick_group_size

    assert pick_group_size(64, None) == 64
    assert pick_group_size(64, 64) == 64
    assert pick_group_size(200, 64) == 50  # largest divisor <= 64
    assert pick_group_size(1936, 64) == 44
    assert pick_group_size(7, 4) == 1  # prime: one token per group
    for t, g in [(200, 64), (1936, 64), (7, 4), (30, 8)]:
        assert t % pick_group_size(t, g) == 0


def test_moe_rejects_indivisible(mesh, params):
    with pytest.raises(ValueError, match="divide"):
        moe_forward(params, _x()[:63], mesh, expert_axis="expert")
