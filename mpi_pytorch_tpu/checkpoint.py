"""Checkpoint save/restore — parity with ``helpers.py`` + its call sites.

Reference semantics preserved:
- epoch-granular save of ``{epoch, state_dict, optimizer, loss}``
  (``main.py:162-171``, ``helpers.py:4-7``) → here
  ``{epoch, params, batch_stats, opt_state, loss, step, config}``;
- rank-0-only writes (``main.py:162``) → process-0-only writes;
- ``FROM_CHECKPOINT`` resume restoring model+optimizer and returning the
  epoch (``main.py:127-130``, ``helpers.py:10-15``);
- post-restore broadcast (``sync_params``, ``main.py:131``) → restored
  arrays are ``device_put`` replicated/sharded onto the mesh.

Improvements the reference lacks (SURVEY §5 failure-detection row): the file
is written atomically (tmp+rename, so a crash mid-write can't corrupt the
resume path — the reference overwrites its single fixed path in place,
``helpers.py:6-7``), the last-k checkpoints are kept, and ``latest`` resolves
automatically for auto-resume.
"""

from __future__ import annotations

import functools
import os
import re
import threading
from typing import Any

import jax
import numpy as np
from flax import serialization

from mpi_pytorch_tpu.utils.logging import process_index, run_logger

_CKPT_RE = re.compile(r"ckpt_(\d+)\.msgpack$")

# Version of the msgpack payload layout ``_payload_from`` writes — stamped
# into the topology-manifest sidecar so a future payload change can be
# detected at load time instead of failing deep inside deserialization.
PAYLOAD_SCHEMA = 1

# Sidecar files that ride a checkpoint and share its lifecycle (written
# after the atomic rename, removed by retention alongside the payload).
_SIDECARS = (".dirty", ".manifest.json")


class CheckpointCorruptError(RuntimeError):
    """The checkpoint file exists but cannot be restored (truncated write,
    bit rot, or a payload that no longer matches the expected schema).
    ``train/elastic.py`` catches this and falls back to the previous
    checkpoint instead of crashing the resume."""


def _ckpt_path(ckpt_dir: str, epoch: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_{epoch:05d}.msgpack")


def checkpoint_epoch(path: str) -> int | None:
    """The epoch a checkpoint file is filed under, from its name."""
    m = _CKPT_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _state_arrays(state: Any) -> dict:
    """The device-array view of a TrainState that goes into a checkpoint —
    the one place that knows which state fields are persisted."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "rng": state.rng,
    }


# Chunked (leaf-sliced, sequential) D2H for the background writer was
# built and MEASURED AGAINST at headline scale: splitting the ~0.5 GB
# snapshot into 32 MB sequential fetches raised the per-epoch checkpoint
# stall 10.8 s -> 31 s through this environment's device relay — each
# chunk pays the relay's full request latency, while one whole-tree
# jax.device_get pipelines every leaf's transfer in a single async batch
# (docs/RESULTS.md §2, round 5). The snapshot-size lever that DOES work
# is ``moments_bf16``; the whole-tree async get stays.


def _payload_from(arrays: dict, epoch: int, loss: float) -> dict:
    """The single checkpoint schema, built from a ``_state_arrays`` dict
    (live state or async snapshot) — save paths and the restore template all
    route through here so they can never drift apart."""
    return {
        "epoch": epoch,
        "step": np.asarray(jax.device_get(arrays["step"])),
        "loss": np.asarray(loss, np.float32),
        "params": jax.device_get(arrays["params"]),
        "batch_stats": jax.device_get(arrays["batch_stats"])
        if arrays["batch_stats"] is not None
        else {},
        "opt_state": jax.device_get(arrays["opt_state"]),
        "rng": jax.device_get(arrays["rng"]),
    }


def _payload(state: Any, epoch: int = 0, loss: float = 0.0) -> dict:
    return _payload_from(_state_arrays(state), epoch, loss)


def _write_atomic(
    ckpt_dir: str, path: str, payload: dict, keep: int, dirty: bool = False,
    manifest: dict | None = None,
) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    # Topology manifest (ISSUE 7): the writer's world shape, so an elastic
    # restore knows what layout the payload was gathered FROM. Sidecar, so
    # the msgpack schema stays stable across checkpoint generations;
    # atomically written BEFORE the payload rename so a loadable payload
    # always has its manifest (a crash in between leaves an orphan sidecar
    # next to no payload — harmless noise, overwritten by the next save of
    # that epoch — whereas the reverse order would leave a manifest-less
    # checkpoint that restores as 'legacy' with its topology unrecorded).
    write_manifest(path, manifest)
    os.replace(tmp, path)  # atomic on POSIX
    # Dirty = the state carries a partial epoch's updates beyond the epoch it
    # is filed under (mid-epoch preemption). A sidecar rather than a payload
    # field keeps the msgpack schema stable across checkpoint generations;
    # written AFTER the rename so a marker never outlives a failed write,
    # and a clean overwrite of the same epoch clears it.
    marker = path + ".dirty"
    if dirty:
        with open(marker, "w") as f:
            f.write("partial-epoch state: resume replays the interrupted epoch\n")
    elif os.path.exists(marker):
        os.remove(marker)
    _cleanup(ckpt_dir, keep)


def write_manifest(ckpt_path: str, manifest: dict | None) -> None:
    """Atomically (re)write the topology-manifest sidecar of ``ckpt_path``
    (None clears it — an overwrite by a manifest-less writer must not leave
    a stale topology lying next to a new payload)."""
    import json

    sidecar = ckpt_path + ".manifest.json"
    if manifest is None:
        if os.path.exists(sidecar):
            os.remove(sidecar)
        return
    tmp = sidecar + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, sidecar)


def read_manifest(ckpt_path: str) -> dict | None:
    """The topology manifest saved next to ``ckpt_path``, or None for a
    legacy/manifest-less checkpoint (including an unreadable sidecar — a
    corrupt manifest downgrades the restore to legacy behavior rather than
    failing a resume the payload itself could serve)."""
    import json

    sidecar = ckpt_path + ".manifest.json"
    if not os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as f:
            return json.load(f)
    except (OSError, ValueError):
        run_logger().warning("unreadable checkpoint manifest %s (treating as legacy)", sidecar)
        return None


def save_checkpoint(
    ckpt_dir: str,
    *,
    epoch: int,
    state: Any,
    loss: float,
    keep: int = 3,
    dirty: bool = False,
    manifest: dict | None = None,
) -> str | None:
    """Synchronous save (process 0 only); returns the path written. The
    trainer uses ``AsyncCheckpointer``; this stays as the blocking variant
    for tools and tests."""
    if process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _ckpt_path(ckpt_dir, epoch)
    _write_atomic(ckpt_dir, path, _payload(state, epoch, loss), keep, dirty, manifest)
    return path


def _cleanup(ckpt_dir: str, keep: int) -> None:
    """Last-k retention — except the best-marked checkpoint (``best.json``),
    which survives however old it gets (≙ the reference's *intended*
    ``is_best``/``best_model_dir`` machinery, accepted-and-ignored at
    ``helpers.py:4-7``)."""
    best = best_marker(ckpt_dir)
    pinned = os.path.basename(best["checkpoint"]) if best else None
    ckpts = sorted(
        (m.group(1), name)
        for name in os.listdir(ckpt_dir)
        if (m := _CKPT_RE.search(name))
    )
    for _, name in ckpts[:-keep] if keep > 0 else []:
        if name != pinned:
            os.remove(os.path.join(ckpt_dir, name))
            for suffix in _SIDECARS:
                marker = os.path.join(ckpt_dir, name + suffix)
                if os.path.exists(marker):
                    os.remove(marker)


def best_marker(ckpt_dir: str) -> dict | None:
    """Read ``best.json`` ({epoch, accuracy, checkpoint}) if present."""
    import json

    path = os.path.join(ckpt_dir, "best.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_best_marker(ckpt_dir: str, *, epoch: int, accuracy: float, ckpt_path: str) -> None:
    """Atomically point ``best.json`` at the best-validation checkpoint
    (process 0 only)."""
    import json

    if process_index() != 0:
        return
    path = os.path.join(ckpt_dir, "best.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"epoch": epoch, "accuracy": accuracy,
             "checkpoint": os.path.basename(ckpt_path)},
            f,
        )
    os.replace(tmp, path)


def latest_checkpoint(ckpt_dir: str) -> str | None:
    paths = checkpoint_paths(ckpt_dir)
    return paths[-1] if paths else None


def checkpoint_paths(ckpt_dir: str) -> list[str]:
    """Every checkpoint in ``ckpt_dir``, oldest→newest — the fallback order
    (reversed) an elastic restore walks when the newest file is corrupt."""
    if not os.path.isdir(ckpt_dir):
        return []
    ckpts = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(ckpt_dir)
        if (m := _CKPT_RE.search(name))
    )
    return [os.path.join(ckpt_dir, name) for _, name in ckpts]


@functools.lru_cache(maxsize=None)
def _copy_fn(out_sharding=None):
    # jit output buffers never alias inputs (no donation), so this yields
    # FRESH device arrays — the snapshot the async writer reads while the
    # training loop donates the originals into the next step. With
    # ``out_sharding`` (a replicated NamedSharding) the copy additionally
    # gathers every leaf onto all devices, which makes ZeRO-sharded Adam
    # moments and the TP-sharded head process-0-addressable on multi-host
    # meshes — the all-gather that turns a distributed state into a
    # checkpointable one.
    copy = lambda t: jax.tree_util.tree_map(lambda x: x.copy(), t)  # noqa: E731
    if out_sharding is None:
        return jax.jit(copy)
    return jax.jit(copy, out_shardings=out_sharding)


# Optimizer-moment tensors at or above this element count are cast to bf16
# by the ``moments_bf16`` snapshot option; schedule scalars / step counts
# below it stay exact (a bf16 Adam count would corrupt bias correction).
_MOMENT_CAST_MIN_SIZE = 4096


@functools.lru_cache(maxsize=None)
def _moment_cast_fn():
    """Jitted device-side cast of the big f32 optimizer-moment tensors to
    bf16 — fused into the snapshot so the D2H transfer and the file carry
    half the bytes (~540 MB → ~270 MB of Adam moments at headline scale).
    Shardings pass through untouched (no donation: the live state keeps
    training). Lossy by design: restore returns moments quantized to bf16
    (~3 decimal digits), which perturbs the post-resume trajectory within
    optimizer-noise — the flag trades that for 2× faster snapshots."""

    import jax.numpy as jnp  # local: keep module import surface minimal

    def cast(opt_state):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 and x.size >= _MOMENT_CAST_MIN_SIZE
            else x,
            opt_state,
        )

    return jax.jit(cast)


def _replicated_sharding(arrays: dict):
    """``NamedSharding(mesh, P())`` over the mesh the state lives on, or None
    for states that aren't mesh-placed (plain host/numpy test states)."""
    from jax.sharding import NamedSharding, PartitionSpec

    for leaf in jax.tree_util.tree_leaves(arrays):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding):
            return NamedSharding(s.mesh, PartitionSpec())
    return None


def _any_sharded(arrays: dict) -> bool:
    for leaf in jax.tree_util.tree_leaves(arrays):
        s = getattr(leaf, "sharding", None)
        if s is not None and not s.is_fully_replicated:
            return True
    return False


# Leaves above this (unsharded) size gather individually; everything smaller
# shares one jitted gather. 4 MB ≈ where a leaf's transient replication
# starts to matter against HBM, while biases/BN stats stay batched.
_BIG_LEAF_BYTES = 4 * 1024 * 1024


def _gather_to_host(arrays: dict, repl) -> dict:
    """All-gather a SHARDED state (fsdp / zero_optimizer / TP) to host numpy.

    A whole-tree replicated gather would transiently hold the full unsharded
    state — params plus both Adam moments, ~3x params — on EVERY device at
    once, which can OOM exactly the configurations that needed sharding.
    Instead: every small leaf rides ONE jitted gather (one XLA compile, a
    few MB of transient HBM), and each BIG leaf (> ``_BIG_LEAF_BYTES``
    unsharded) gathers alone and is freed once on host — peak per-device
    overhead is the small-leaf total plus ONE big leaf. Strictly per-leaf
    gathering would bound memory the same way but costs one collective
    compile per leaf (observed: minutes of stall on a 2-process save). The
    device_get runs on the caller thread (the async writer then only
    serializes), a trade the sharded configs accept."""
    flat, treedef = jax.tree_util.tree_flatten(arrays)
    gather = _copy_fn(repl)
    p0 = process_index() == 0

    def to_host(g):
        # Only process 0 writes the checkpoint; the other processes skip the
        # D2H copy (and the full-state host allocation) they'd never use —
        # but EVERY process runs the collective gather itself.
        host = np.asarray(jax.device_get(g)) if p0 else None
        g.delete()  # free the replicated copy before the next gather
        return host

    big = {i for i, leaf in enumerate(flat) if leaf.nbytes > _BIG_LEAF_BYTES}
    out: list = [None] * len(flat)
    small_idx = [i for i in range(len(flat)) if i not in big]
    if small_idx:
        gathered = gather([flat[i] for i in small_idx])
        for i, g in zip(small_idx, gathered):
            out[i] = to_host(g)
    for i in sorted(big):
        out[i] = to_host(gather(flat[i]))
    return jax.tree_util.tree_unflatten(treedef, out)


def _cast_moments(opt_state):
    """``moments_bf16`` cast for a MIXED device/host optimizer tree:
    jax.Array leaves go through the jitted device-side cast (fused into the
    snapshot, as before); host numpy leaves — a ZeRO run's gathered-on-save
    moments (trainer ``_saveable``) — are cast on the HOST. Routing them
    through the jitted cast would device_put the full unsharded moment tree
    back onto every device: exactly the 2×params transient the sharding
    freed."""
    import jax.numpy as jnp

    flat, treedef = jax.tree_util.tree_flatten(opt_state)
    dev_idx = [i for i, leaf in enumerate(flat) if isinstance(leaf, jax.Array)]
    out = [
        leaf.astype(jnp.bfloat16)
        if (
            not isinstance(leaf, jax.Array)
            and hasattr(leaf, "dtype")
            and leaf.dtype == np.float32
            and leaf.size >= _MOMENT_CAST_MIN_SIZE
        )
        else leaf
        for leaf in flat
    ]
    if dev_idx:
        casted = _moment_cast_fn()([flat[i] for i in dev_idx])
        for i, c in zip(dev_idx, casted):
            out[i] = c
    return jax.tree_util.tree_unflatten(treedef, out)


def _snapshot_mixed(arrays: dict, repl) -> dict:
    """Donation-safe snapshot of a MIXED device/host state tree: jax.Array
    leaves get the ~ms on-device jitted copy (fresh buffers the background
    writer can read while the train loop donates the originals), host numpy
    leaves pass through untouched. Jitting the whole tree would silently
    device_put every host leaf replicated onto ALL devices — for a ZeRO
    run's gathered-on-save optimizer state (trainer ``_saveable``) that is
    exactly the 2×params transient HBM spike gather-on-save exists to
    avoid."""
    flat, treedef = jax.tree_util.tree_flatten(arrays)
    dev_idx = [i for i, leaf in enumerate(flat) if isinstance(leaf, jax.Array)]
    if dev_idx:
        copied = _copy_fn(repl)([flat[i] for i in dev_idx])
        jax.block_until_ready(copied)  # copy is cheap; be certain
        for i, c in zip(dev_idx, copied):
            flat[i] = c
    return jax.tree_util.tree_unflatten(treedef, flat)


class AsyncCheckpointer:
    """Non-blocking checkpointing: a ~ms on-device copy snapshots the state,
    then a background thread does the expensive ``device_get`` + serialize +
    atomic write while training continues.

    Rationale: the jitted train step donates the state (train/step.py), so a
    background transfer from the *live* arrays would race with their deletion
    on the next step; the device-side copy gives the writer its own buffers.
    One save in flight at a time (a new save waits for the previous write);
    call ``wait()`` before reading the file or exiting."""

    def __init__(self) -> None:
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

    def save(
        self,
        ckpt_dir: str,
        *,
        epoch: int,
        state: Any,
        loss: float,
        keep: int = 3,
        on_durable=None,
        dirty: bool = False,
        moments_bf16: bool = False,
        manifest: dict | None = None,
    ) -> str | None:
        """Snapshot now, write in the background; returns the path that will
        exist once the write completes (None on processes > 0).

        ``moments_bf16`` casts the large f32 optimizer-moment tensors to
        bf16 on device before the snapshot (``--ckpt-bf16-moments``):
        halves the moment D2H bytes and the file size; restore casts back
        to the optimizer's dtype (values quantized to bf16).

        EVERY process must call this (the trainer does): the snapshot is a
        global SPMD computation on multi-host meshes, so gating it to
        process 0 would diverge the programs the processes run. Only process
        0 spawns the writer thread. Replicated state takes the fast path (a
        ~ms on-device copy; the background thread does the device_get).
        Sharded state (fsdp / ZeRO-1 moments / the TP head) goes through
        ``_gather_to_host`` instead: a synchronous all-gather streamed to
        host numpy on the caller thread — all small leaves in one program,
        big leaves one at a time, so the peak device overhead is the
        small-leaf total plus one big unsharded leaf, not the whole state —
        after which the writer only serializes."""
        self.wait()
        arrays = _state_arrays(state)
        if moments_bf16:
            arrays = dict(arrays, opt_state=_cast_moments(arrays["opt_state"]))
        repl = _replicated_sharding(arrays)
        if repl is not None and _any_sharded(arrays):
            # Sharded state: leaf-by-leaf host gather (see _gather_to_host)
            # instead of materializing the whole unsharded state on-device.
            snapshot = _gather_to_host(arrays, repl)
        else:
            snapshot = _snapshot_mixed(arrays, repl)
        if process_index() != 0:
            return None
        os.makedirs(ckpt_dir, exist_ok=True)
        path = _ckpt_path(ckpt_dir, epoch)

        def _worker() -> None:
            try:
                _write_atomic(
                    ckpt_dir, path, _payload_from(snapshot, epoch, loss), keep, dirty,
                    manifest,
                )
                if on_durable is not None:
                    # Runs strictly AFTER the atomic rename: anything the
                    # callback publishes (e.g. the best.json marker) can
                    # never reference a file that doesn't exist yet.
                    on_durable(path)
            except BaseException as e:  # surfaced on the next save()/wait()
                self._error = e

        self._thread = threading.Thread(
            target=_worker, name="async-checkpoint", daemon=True
        )
        self._thread.start()
        return path

    def wait(self) -> None:
        """Block until the in-flight write (if any) has landed; re-raise any
        writer error on the caller thread."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def load_checkpoint(path: str, state: Any) -> tuple[Any, int, float]:
    """Restore (state, epoch, loss) from a checkpoint file (≙
    ``load_checkpoint``, helpers.py:10-15 — which returns the epoch so the
    driver can continue the epoch loop, main.py:127-129)."""
    if os.path.exists(path + ".dirty"):
        m = _CKPT_RE.search(os.path.basename(path))
        epoch_txt = (m.group(1).lstrip("0") or "0") if m else "the filed epoch"
        run_logger().warning(
            "resuming from a DIRTY checkpoint (%s): it was saved after a "
            "mid-epoch preemption, so the state already carries part of epoch "
            "%s+1's updates. When the saved data cursor validates, the "
            "trainer continues EXACTLY at the interrupted step (no replayed "
            "updates); otherwise that epoch is replayed, double-applying "
            "those batches' steps (trajectory may differ from an "
            "uninterrupted run)",
            path, epoch_txt,
        )
    with open(path, "rb") as f:
        data = f.read()
    try:
        restored = serialization.from_bytes(_payload(state), data)
        # A moments_bf16 checkpoint stores the big moment tensors in bf16; the
        # optimizer expects its own dtype (f32) back. Cast against the live
        # state's opt_state as the dtype template (no-op for exact saves).
        opt_state = jax.tree_util.tree_map(
            lambda tmpl, got: np.asarray(got).astype(tmpl.dtype)
            if hasattr(tmpl, "dtype") and got.dtype != tmpl.dtype
            else got,
            _state_arrays(state)["opt_state"],
            restored["opt_state"],
        )
    except OSError:
        raise  # a vanished file is a caller error, not payload corruption
    except MemoryError:
        # Host memory pressure, not on-disk damage: falling back to an
        # OLDER checkpoint would silently discard good progress while the
        # next attempt would fail the same way — surface it.
        raise
    except Exception as e:
        # Truncated msgpack, garbage bytes, missing/mismatched payload keys:
        # typed so the elastic restore (train/elastic.py) can fall back to
        # the previous checkpoint instead of crashing the resume.
        raise CheckpointCorruptError(
            f"checkpoint {path} failed to restore ({type(e).__name__}: {e})"
        ) from e
    new_state = state.replace(
        step=jax.numpy.asarray(restored["step"]),
        params=restored["params"],
        batch_stats=restored["batch_stats"] if state.batch_stats is not None else None,
        opt_state=opt_state,
        rng=jax.numpy.asarray(restored["rng"]),
    )
    return new_state, int(restored["epoch"]), float(restored["loss"])


def load_for_eval(path: str, state: Any) -> tuple[Any, int, float]:
    """Restore params + batch_stats only — the inference path (≙ predictor
    ranks loading just the ``state_dict``, ``evaluation_pipeline.py:142-144``).
    No optimizer template is needed, so eval never materializes Adam moments."""
    with open(path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    params = serialization.from_state_dict(jax.device_get(state.params), raw["params"])
    batch_stats = None
    if state.batch_stats is not None:
        batch_stats = serialization.from_state_dict(
            jax.device_get(state.batch_stats), raw["batch_stats"]
        )
    new_state = state.replace(params=params, batch_stats=batch_stats)
    return new_state, int(raw["epoch"]), float(raw["loss"])
