"""Inference/evaluation driver — the TPU-native ``evaluation_pipeline.py``.

The reference runs inference as a 4-stage MPI pipeline (``evaluation_pipeline
.py:162-199``): rank 0 reads images and streams them to rank 1 (resize), then
rank 2 (normalize), then a randomly-assigned predictor rank ≥3 runs a
single-image forward (``:149-158``), and a final ``comm.reduce`` sums
per-predictor accuracies (``:196``).

Here the same four capabilities collapse into a batched dataflow (the
BASELINE.json north star):

| reference stage (rank)            | here                                    |
|-----------------------------------|-----------------------------------------|
| read_images (rank 0, ``:53-71``)  | DataLoader worker threads (PIL decode)  |
| resize_images (rank 1, ``:74-96``)| same workers — decode+resize fused      |
| preprocess_image (rank 2,``:99-129``)| same workers — normalize fused       |
| predict (ranks ≥3, ``:132-159``)  | one jitted batched forward over all chips|
| reduce(acc, SUM) (``:196``)       | on-device sum via the sharded eval step |

The stage *overlap* the MPI pipeline bought with dedicated ranks is provided
by the loader's thread pool + prefetch queue; the random image→predictor
routing (``:178``) is just batch sharding over the ``data`` mesh axis; the
per-image ``model(image[None])`` forward becomes a full-batch MXU matmul.
"""

from __future__ import annotations

import functools
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax

from mpi_pytorch_tpu import checkpoint as ckpt
from mpi_pytorch_tpu.config import Config, parse_config
from mpi_pytorch_tpu.data import load_manifests
from mpi_pytorch_tpu.models import create_model_bundle
from mpi_pytorch_tpu.obs import Tracer
from mpi_pytorch_tpu.parallel.mesh import create_mesh, flat_mesh
from mpi_pytorch_tpu.train.state import TrainState
from mpi_pytorch_tpu.train.trainer import evaluate_manifest
from mpi_pytorch_tpu.utils.logging import MetricsWriter, init_logger, run_logger

# One warning per (process, reason): --fused-head-eval silently degrading to
# the plain step was an advisor r5 finding — the user could not tell the
# flag did nothing. Kept module-level so repeated evaluate() calls in one
# process (tests, notebooks) don't spam.
_fused_head_warned: set[str] = set()


def _warn_fused_head_fallback(reason: str) -> None:
    if reason in _fused_head_warned:
        return
    _fused_head_warned.add(reason)
    run_logger().warning(
        "--fused-head-eval requested but falling back to the plain XLA "
        "predict step: %s", reason,
    )


@dataclass
class EvalSummary:
    accuracy: float
    mean_loss: float
    num_images: int
    wall_s: float
    images_per_sec: float


def build_inference(cfg: Config, mesh=None, manifests=None):
    """Inference-only construction: model + params, no optimizer moments, no
    train-split loader — the predictor-rank setup (``evaluation_pipeline.py:
    132-144``) without the training baggage ``build_training`` carries.
    ``manifests``: pre-loaded (train, test) pair, so callers that need both
    splits (the predictions pass's label map) parse the CSVs only once."""
    mesh = mesh or create_mesh(cfg.mesh)
    _, test_manifest = manifests or load_manifests(cfg)
    compute_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[cfg.compute_dtype]
    bundle, variables = create_model_bundle(
        cfg.model_name,
        cfg.num_classes,
        use_pretrained=cfg.use_pretrained,
        rng=jax.random.PRNGKey(cfg.seed),
        image_size=cfg.image_size[0],
        dtype=compute_dtype,
        param_dtype=jnp.float32,
        pretrained_dir=cfg.pretrained_dir,
        sp_strategy=cfg.sp_strategy,
        sp_mesh=flat_mesh(mesh, "seq") if cfg.sp_strategy != "none" else None,
        ep_mesh=flat_mesh(mesh, "expert") if cfg.expert_parallel else None,
        attn_impl=cfg.attn_impl,
        qkv_fused=cfg.qkv_fused,
        stem_s2d=cfg.stem_s2d,
        fused_stem=cfg.fused_stem,
        # Multi-chip fused kernels: the model shard_maps the Mosaic calls
        # (fused stem, fused-small attention) over the mesh's data axis
        # (ops/fused_stem.py / ops/fused_attention_small.py, Multi-chip).
        dp_mesh=mesh if (cfg.fused_stem or cfg.attn_impl == "fused-small") else None,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply,
        variables=variables,
        tx=optax.identity(),
        rng=jax.random.PRNGKey(cfg.seed),
    )
    if cfg.pp_stages > 1:
        # Same seam as build_training: PP is an execution strategy keyed on
        # state.apply_fn, so --pp-stages pipelines inference too (identical
        # params and numerics; the eval batch streams through the stages).
        from mpi_pytorch_tpu.parallel.pp_vit import pp_apply_from_config

        state = state.replace(
            apply_fn=pp_apply_from_config(cfg, bundle.model, mesh)
        )
    return mesh, bundle, state, test_manifest


def evaluate(cfg: Config) -> EvalSummary:
    from mpi_pytorch_tpu.parallel.distributed import maybe_initialize_distributed

    from mpi_pytorch_tpu.config import apply_runtime_flags

    maybe_initialize_distributed()
    apply_runtime_flags(cfg)
    logger = init_logger("MPT_EVAL", cfg.eval_log_file)
    tracer = Tracer(cfg.trace_file)
    # finally-close: a failed evaluation (bad checkpoint, OOM, relay wedge)
    # is exactly the run whose trace is needed — the buffered spans must
    # reach disk on the failure path too.
    try:
        with tracer.span("build"):
            manifests = load_manifests(cfg)
            mesh, bundle, state, test_manifest = build_inference(cfg, manifests=manifests)

        latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
        if cfg.use_best:
            # Best-validation checkpoint (train --track-best), not merely the
            # newest — the reference's intended is_best machinery (helpers.py:4-7).
            marker = ckpt.best_marker(cfg.checkpoint_dir)
            if marker is None:
                raise FileNotFoundError(
                    f"use_best=True but no best.json in {cfg.checkpoint_dir} "
                    "(train with --track-best true --validate true)"
                )
            latest = os.path.join(cfg.checkpoint_dir, marker["checkpoint"])
            logger.info(
                "best checkpoint: epoch %d, val acc %.4f", marker["epoch"], marker["accuracy"]
            )
        if latest:
            # ≙ predictor ranks loading the trained checkpoint
            # (evaluation_pipeline.py:142-144); params/batch_stats only.
            with tracer.span("checkpoint_load"):
                state, epoch, loss = ckpt.load_for_eval(latest, state)
            logger.info("loaded checkpoint %s (epoch %d)", latest, epoch)
        else:
            logger.info("no checkpoint in %s — evaluating fresh init", cfg.checkpoint_dir)

        from mpi_pytorch_tpu.train.step import place_state_on_mesh

        state = place_state_on_mesh(state, mesh)

        t0 = time.perf_counter()
        if cfg.predictions_file:
            # One pass produces both the metrics and the submission CSV.
            with tracer.span("eval", args={"pass": "predictions"}):
                acc, mean_loss = evaluate_with_predictions(
                    cfg, state, mesh, manifests[0], test_manifest, logger
                )
        else:
            if cfg.fused_head_eval:
                # The metrics-only pass runs the shared eval step — the fused
                # head lives in the predictions step. Surface it instead of
                # letting the flag silently do nothing (advisor r5).
                _warn_fused_head_fallback(
                    "metrics-only evaluation uses the shared eval step; the "
                    "fused head applies to the predictions pass "
                    "(add --predictions-file)"
                )
            with tracer.span("eval", args={"pass": "metrics"}):
                acc, mean_loss = evaluate_manifest(cfg, state, mesh, test_manifest)
        wall = time.perf_counter() - t0
    finally:
        trace_out = tracer.close()
        if trace_out:
            logger.info("host trace spans written to %s (chrome://tracing)", trace_out)
    n = len(test_manifest)
    # ≙ rank-0 final accuracy log (evaluation_pipeline.py:198-199)
    logger.info("Accuracy of the network: %.4f (%d images, %.2f s)", acc, n, wall)
    writer = MetricsWriter(cfg.metrics_file)
    writer.write(
        {"kind": "eval", "accuracy": acc, "loss": mean_loss, "images": n, "time_s": wall}
    )
    writer.close()
    return EvalSummary(
        accuracy=acc,
        mean_loss=mean_loss,
        num_images=n,
        wall_s=wall,
        images_per_sec=n / wall if wall > 0 else 0.0,
    )


def _make_predict_step(
    mesh, compute_dtype, fused_head: bool = False, topk: int = 1,
    int8_head: bool = False,
):
    # Canonicalize to positional args: lru_cache keys keyword and
    # positional calls separately, which would double-compile the step.
    if fused_head and topk > 1:
        raise ValueError(
            "the fused head (head_predict) streams argmax only; top-k needs "
            "the plain predict path (serve forces topk=1 under "
            "--fused-head-eval, with a warning)"
        )
    if int8_head and not fused_head:
        raise ValueError(
            "int8_head selects the fused int8 kernel variant and requires "
            "fused_head=True; the plain int8 path is just the plain predict "
            "step over a quantized state (ops/quantize.quantize_state)"
        )
    return _make_predict_step_impl(
        mesh, compute_dtype, bool(fused_head), int(topk), bool(int8_head)
    )


def _row_sharding(mesh, batch: int):
    """The argmax/top-k pin: ``P(data)`` when the batch divides the data
    axis (the eval paths — required for ``_host_rows`` on multi-host),
    replicated otherwise (the serve buckets smaller than the device count,
    where a forced uneven shard would buy nothing)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_pytorch_tpu.parallel.mesh import data_axis_names, data_axis_size

    axes = data_axis_names(mesh)  # ("pod", "ici") on a nested mesh
    spec = P(axes) if batch % data_axis_size(mesh) == 0 else P()
    return NamedSharding(mesh, spec)


@functools.lru_cache(maxsize=None)
def _make_predict_step_impl(
    mesh, compute_dtype, fused_head: bool, topk: int, int8_head: bool = False,
):
    """ONE batched forward yielding both the eval metrics and the per-image
    argmax — predictions and accuracy come from the same pass (the
    reference's predictor ranks compute the per-image argmax and discard it,
    ``evaluation_pipeline.py:149-158``).

    The argmax is PINNED to ``P(data)``: on multi-host the global array
    spans non-addressable devices, and the caller reads back exactly its own
    host's rows from the addressable shards — a compiler-chosen layout
    (e.g. replicated) would silently hand every host all rows. (For batches
    that don't divide the data axis — the small serve buckets — the pin
    degrades to replicated, see ``_row_sharding``; the eval paths always
    divide.)

    ``topk`` (plain path only): > 1 returns [B, k] top-k class indices per
    row instead of the [B] argmax — the serving contract (a request wants
    candidates, not just the winner). Column 0 IS the argmax, which the
    parity test pins against ``head_predict``.

    ``fused_head`` (``--fused-head-eval``, TPU): the [B, 64 500] logits
    tensor never reaches HBM — a flax method interceptor captures the
    ``head`` Dense's INPUT features during the same traced forward, and
    ``ops.fused_head_ce.head_predict`` streams the head weights through
    VMEM computing per-example loss + argmax online (measured 2.31 vs
    2.74 ms per 1024-image batch against the XLA head — bench_eval
    --head). On a multi-device data axis the kernel call is shard_map-
    partitioned over the mesh inside ``head_predict`` (each chip streams
    its own row shard), and batches beyond the per-block VMEM envelope
    are row-tiled inside the kernel wrapper — no silent fallback on
    either axis. The metrics are loss-sum/correct/count over the SAME
    quantities ``metrics_from_logits`` computes, so accuracy is identical
    up to the bf16-matmul argmax caveat in ``head_predict``'s docstring."""
    from flax import linen as flax_nn

    from mpi_pytorch_tpu.train.step import (
        eval_logits,
        ingest_images,
        metrics_from_logits,
    )

    if not fused_head:

        @jax.jit
        def predict(state, batch):
            images, labels = batch
            logits = eval_logits(state, images, compute_dtype)
            row_sharding = _row_sharding(mesh, images.shape[0])
            if topk > 1:
                # lax.top_k's indices come back best-first, so [:, 0] is
                # exactly the argmax the k=1 path returns.
                _, idx = jax.lax.top_k(logits, topk)
                preds = idx.astype(jnp.int32)
            else:
                preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            preds = jax.lax.with_sharding_constraint(preds, row_sharding)
            return metrics_from_logits(logits, labels), preds

        return predict

    from mpi_pytorch_tpu.ops.fused_head_ce import head_predict

    def _intercepted_forward(state, images):
        """Run the forward with the 'head' Dense intercepted: its INPUT
        features/kernel/bias land in the returned box, its dummy output IS
        the model output (the head is every zoo model's last layer that
        fires this filter) — shared by the bf16 and int8 fused steps."""
        box = {}

        def grab_head_input(next_fn, args, kwargs, context):
            m = context.module
            if m.name == "head" and isinstance(m, flax_nn.Dense):
                box["feats"] = args[0]
                box["w"] = m.variables["params"]["kernel"]
                box["b"] = m.variables["params"].get(
                    "bias", jnp.zeros((m.features,), jnp.float32)
                )
                # The dummy return is discarded below; XLA dead-code-
                # eliminates it.
                return jnp.zeros(args[0].shape[:-1] + (m.features,), jnp.float32)
            return next_fn(*args, **kwargs)

        with flax_nn.intercept_methods(grab_head_input):
            out = state.apply_fn(
                state.variables, ingest_images(images, compute_dtype), train=False
            )
        return out, box

    def _plain_from_logits(out, labels, batch_rows):
        """The no-head-match fallback (conv-classifier models): ``out`` is
        the model's REAL logits — plain metrics + pinned argmax."""
        logits = jax.lax.optimization_barrier(out.astype(jnp.float32))
        preds = jax.lax.with_sharding_constraint(
            jnp.argmax(logits, axis=-1).astype(jnp.int32),
            _row_sharding(mesh, batch_rows),
        )
        return metrics_from_logits(logits, labels), preds

    def _fused_metrics(loss, preds, labels):
        valid = labels >= 0
        return {
            "loss": jnp.sum(loss),  # the kernels zero padding rows
            "correct": jnp.sum((preds == labels) & valid),
            "count": jnp.sum(valid.astype(jnp.int32)),
        }

    if int8_head:
        from mpi_pytorch_tpu.ops.quantize import head_kernel_key, head_predict_int8

        @jax.jit
        def predict_fused_int8(state, batch):
            """The int8 twin of ``predict_fused`` over a quantized state
            (``quantize_state(..., keep_head_int8=True)``): the head Dense
            kernel the interceptor captures is the RAW int8 tensor (the
            dequantizing apply wrapper skips it), and the Pallas int8
            kernel consumes it with the packed tree's per-channel scales
            and the calibrated activation scale."""
            images, labels = batch
            packed = state.params  # {"q", "scale", "act_scale"}
            out, box = _intercepted_forward(state, images)
            hk = head_kernel_key(packed["scale"], packed["q"])  # static
            if "feats" not in box or hk is None:
                # No int8-kept Dense head (conv classifiers): everything
                # was dequantized by the apply wrapper and ``out`` is the
                # real (weight-quantized) logits.
                return _plain_from_logits(out, labels, images.shape[0])
            assert out.shape == box["feats"].shape[:-1] + (box["w"].shape[1],), (
                "intercepted 'head' output shape does not match the model "
                f"output: {out.shape} vs "
                f"{box['feats'].shape[:-1] + (box['w'].shape[1],)}"
            )
            loss, preds = head_predict_int8(
                box["feats"], box["w"], box["b"], labels,
                w_scale=packed["scale"][hk],
                act_scale=packed["act_scale"],
                dp_mesh=mesh,
            )
            preds = jax.lax.with_sharding_constraint(
                preds, _row_sharding(mesh, images.shape[0])
            )
            return _fused_metrics(loss, preds, labels), preds

        return predict_fused_int8

    @jax.jit
    def predict_fused(state, batch):
        images, labels = batch
        out, box = _intercepted_forward(state, images)
        if "feats" not in box:
            # Head never matched (e.g. squeezenet's Conv classifier, which
            # is also not the final op): ``out`` is then the model's REAL
            # logits — take the plain path instead of failing.
            return _plain_from_logits(out, labels, images.shape[0])
        # The interceptor's dummy return must BE the model output — if an
        # architecture ever routes more layers after its 'head' Dense, the
        # captured features would not be the logits' features and the fused
        # metrics would be silently wrong. Shapes are static under jit, so
        # this costs nothing at runtime.
        assert out.shape == box["feats"].shape[:-1] + (box["w"].shape[1],), (
            "intercepted 'head' output shape does not match the model "
            f"output: {out.shape} vs {box['feats'].shape[:-1] + (box['w'].shape[1],)}"
        )
        # head_predict shard_maps itself over the mesh's data axis (each
        # chip streams its own row shard) and row-tiles beyond its
        # per-block VMEM envelope.
        loss, preds = head_predict(
            box["feats"], box["w"], box["b"], labels, dp_mesh=mesh
        )
        preds = jax.lax.with_sharding_constraint(
            preds, _row_sharding(mesh, images.shape[0])
        )
        return _fused_metrics(loss, preds, labels), preds

    return predict_fused


def _host_rows(p, host_batch: int):
    """This host's rows of a ``P(data)``-sharded [B] array, in global row
    order, read from the addressable shards only (``np.asarray`` on the
    global array raises on multi-host). Shards replicated across a model/
    pipe axis carry duplicate row blocks — deduped by start index."""
    import numpy as np

    by_start = {}
    for s in p.addressable_shards:
        start = s.index[0].start or 0
        by_start.setdefault(start, np.asarray(s.data))
    rows = np.concatenate([by_start[k] for k in sorted(by_start)])
    assert rows.shape[0] == host_batch, (rows.shape, host_batch)
    return rows


def evaluate_with_predictions(
    cfg: Config, state, mesh, train_manifest, test_manifest, logger
) -> tuple[float, float]:
    """One pass over the test manifest: accuracy/loss AND a predictions CSV
    (file_name, predicted_label, predicted_category_id) in manifest order —
    the submission file the Herbarium task actually wants. The filename key
    mirrors ``GetData`` returning ``(tensor, fname)`` for the test split
    (``data_loader.py:36-39``). Returns (accuracy, mean_loss).

    Multi-host: every host walks its manifest shard through the same
    synchronized global steps as ``evaluate_manifest`` (so the sharded
    forward uses every chip of the pod), slices its own rows out of each
    step's global argmax, and the per-host predictions — tiny int32 rows,
    not images — are all-gathered so process 0 writes the single CSV in
    global manifest order. No shared filesystem is required."""
    import numpy as np

    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.train.trainer import (
        global_step_count,
        make_eval_loader,
        pad_batch,
        synchronized_batches,
    )

    n_proc, pid = jax.process_count(), jax.process_index()
    host_batch = cfg.batch_size // n_proc
    loader = make_eval_loader(cfg, test_manifest)  # this host's shard
    local_n = len(loader.manifest)
    compute_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[cfg.compute_dtype]
    from mpi_pytorch_tpu.utils.env import env_flag
    from mpi_pytorch_tpu.utils.hardware import tpu_backend

    # MPT_HEAD_INTERPRET=1 drives the real kernel through the Pallas
    # interpreter on CPU (the driver-level test path), so it passes the gate.
    fused_head = cfg.fused_head_eval and (
        tpu_backend() or env_flag("MPT_HEAD_INTERPRET")
    )
    if cfg.fused_head_eval and not fused_head:
        _warn_fused_head_fallback(
            "backend is not TPU (the Mosaic kernel has no CPU/GPU build); "
            "metrics are identical, but the [B, num_classes] logits are "
            "materialized"
        )
    predict = _make_predict_step(mesh, compute_dtype, fused_head=fused_head)
    preds: list = []
    loss_sum = correct = count = 0.0
    n_steps = global_step_count(len(test_manifest), host_batch, drop_remainder=False)
    for images, labels in synchronized_batches(loader, 0, n_steps):
        batch = shard_batch(pad_batch(images, labels, host_batch), mesh)
        m, p = predict(state, batch)
        # Global batch rows [pid*hb, (pid+1)*hb) are THIS host's images
        # (shard_batch assembles the global array host-major), and the
        # P(data)-pinned argmax keeps them on this host's devices.
        preds.append(_host_rows(p, host_batch))
        loss_sum += float(m["loss"])
        correct += int(m["correct"])
        count += int(m["count"])
    local_preds = np.concatenate(preds)[:local_n]  # drop tail/filler padding

    if n_proc > 1:
        from jax.experimental import multihost_utils

        # array_split shard sizes are deterministic — every host computes the
        # same layout, pads its rows to the max, and the gather is one tiny
        # [P, max] int32 exchange.
        sizes = [
            len(part)
            for part in np.array_split(np.arange(len(test_manifest)), n_proc)
        ]
        buf = np.full((max(sizes),), -1, np.int32)
        buf[:local_n] = local_preds
        gathered = np.asarray(multihost_utils.process_allgather(buf))
        labels_pred = np.concatenate(
            [gathered[p, : sizes[p]] for p in range(n_proc)]
        )
    else:
        labels_pred = local_preds
    assert len(labels_pred) == len(test_manifest), (
        len(labels_pred), len(test_manifest),
    )

    if pid == 0:
        # Contiguous label -> raw Herbarium category_id, from BOTH splits (the
        # label map was built over both, data/manifest.py build_label_map).
        label_to_cat: dict[int, int] = {}
        for m in (train_manifest, test_manifest):
            label_to_cat.update(zip(m.labels.tolist(), m.category_ids.tolist()))

        tmp = cfg.predictions_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("file_name,predicted_label,predicted_category_id\n")
            for fname, p in zip(test_manifest.filenames, labels_pred.tolist()):
                f.write(f"{fname},{p},{label_to_cat.get(p, -1)}\n")
        os.replace(tmp, cfg.predictions_file)
        logger.info(
            "predictions written: %s (%d rows)", cfg.predictions_file, len(labels_pred)
        )
    acc = correct / count if count else 0.0
    return acc, (loss_sum / count if count else float("nan"))


def quantize_eval_report(cfg: Config) -> dict:
    """``--quantize-eval``: the offline int8-vs-bf16 parity report — the
    reusable oracle the serve-side parity gates lean on (``ops/quantize.
    parity_probe``), run against the checkpoint the server would load.

    A fixed seeded sample (``--quantize-calib`` images, ``--seed``) goes
    through the trained model on both paths — the served contract (fused
    int8 kernel when the ``--fused-head-eval`` gate is active, otherwise
    the plain predict over the weight-quantized state) — and the report
    carries top-1/top-5 agreement plus the max full-model logit drift.
    Written as a ``kind="quant_parity"`` record (schema v7) and returned.
    """
    from mpi_pytorch_tpu.config import apply_runtime_flags
    from mpi_pytorch_tpu.ops import quantize as qz
    from mpi_pytorch_tpu.parallel.distributed import maybe_initialize_distributed
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    maybe_initialize_distributed()
    apply_runtime_flags(cfg)
    logger = init_logger("MPT_EVAL", cfg.eval_log_file)
    # Serving has the request as data: the report needs no manifest either.
    mesh, _, state, _ = build_inference(cfg, manifests=(None, None))
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    if cfg.use_best:
        marker = ckpt.best_marker(cfg.checkpoint_dir)
        if marker is None:
            raise FileNotFoundError(
                f"use_best=True but no best.json in {cfg.checkpoint_dir}"
            )
        latest = os.path.join(cfg.checkpoint_dir, marker["checkpoint"])
    if latest:
        state, epoch, _ = ckpt.load_for_eval(latest, state)
        logger.info("quantize-eval: checkpoint %s (epoch %d)", latest, epoch)
    else:
        logger.info(
            "quantize-eval: no checkpoint in %s — probing fresh init",
            cfg.checkpoint_dir,
        )
    state = place_state_on_mesh(state, mesh)
    compute_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        cfg.compute_dtype
    ]
    # The SAME gate and calibration batch the serve executables use
    # (ops/quantize.fused_head_gate / calibration_batch): the oracle
    # measures the contract the server would actually run, by
    # construction rather than by textual coincidence.
    fused = qz.fused_head_gate(cfg)
    images = qz.calibration_batch(cfg)
    act_scale = qz.calibrate_head_act_scale(state, images, compute_dtype)
    q_plain = qz.quantize_state(state, keep_head_int8=False, act_scale=act_scale)
    drift = qz.max_logit_drift(state, q_plain, images, compute_dtype)
    if fused:
        qstate = qz.quantize_state(
            state, keep_head_int8=True, act_scale=act_scale
        )
        topk = 1  # the fused kernels stream argmax only (both precisions)
    else:
        qstate, topk = q_plain, min(cfg.serve_topk, cfg.num_classes)
    probe = qz.parity_probe(
        state, qstate, mesh, compute_dtype, images,
        topk=topk, fused_head=fused,
    )
    report = {
        "kind": "quant_parity",
        "precision": "int8",
        "model": cfg.model_name,
        "max_logit_drift": round(drift, 6),
        **probe,
    }
    logger.info(
        "quantize-eval parity: top1 %.4f, top5 %s, max logit drift %.4g "
        "over %d samples (%s path)",
        report["top1_agree"],
        "-" if report["top5_agree"] is None else f"{report['top5_agree']:.4f}",
        drift, report["samples"], "fused int8" if fused else "plain int8",
    )
    writer = MetricsWriter(cfg.metrics_file)
    writer.write(dict(report))
    writer.close()
    return report


def main(argv=None):
    cfg = parse_config(argv)
    if cfg.quantize_eval:
        return quantize_eval_report(cfg)
    return evaluate(cfg)


if __name__ == "__main__":
    main()
