"""Two-process ``jax.distributed`` smoke test (SURVEY §5 comm-backend row;
≙ the reference's multi-node ``mpiexec`` launch, ``README.md:30-38``).

Spawns 2 real OS processes, each with 4 virtual CPU devices, rendezvousing
through a local coordinator — the only way to exercise
``maybe_initialize_distributed`` + the ``make_array_from_process_local_data``
branch of ``shard_batch`` without a TPU pod."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_train_step(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        # A clean CPU world: without the pool vars the image's sitecustomize
        # never registers the TPU plugin in the children.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(flags + ["--xla_force_host_platform_device_count=4"])
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env["MPT_MULTIHOST"] = "1"
        env["MPT_TEST_SCRATCH"] = str(tmp_path)
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.join(repo, "tests", "distributed_child.py")],
                env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            # Generous: two children × (DP step + two full trainer runs +
            # predictions pass + preemption leg) on one starved CPU core.
            out, _ = p.communicate(timeout=1800)
            outs.append(out)
    finally:  # a hung rendezvous must not leak children holding the port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"
    losses = [
        line.split()[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("DIST_OK")
    ]
    assert len(losses) == 2, outs
    # both processes saw different local data; the all-reduce made them agree
    assert losses[0] == losses[1]
    # The full multi-host trainer run (host_cache, uneven shards, early-close
    # backfill, cached-val adoption): both processes must complete and agree
    # on the globally-reduced per-epoch losses and validation accuracy.
    train_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("TRAIN_OK")
    ]
    assert len(train_lines) == 2, outs
    assert train_lines[0] == train_lines[1], train_lines
    # Sharded device cache across processes: both must complete the
    # scan-epoch cached run and agree on per-epoch losses and accuracy.
    devcache_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("DEVCACHE_OK")
    ]
    assert len(devcache_lines) == 2, outs
    assert devcache_lines[0] == devcache_lines[1], devcache_lines
    # Pipeline parallelism across processes: both ran one PP x DP step on
    # different local data and agree on the all-reduced loss.
    pp_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("PP_OK")
    ]
    assert len(pp_lines) == 2, outs
    assert pp_lines[0] == pp_lines[1], pp_lines
    # Multi-host predictions: both processes ran the sharded predictions
    # pass and agree on its accuracy; process 0 wrote the single CSV.
    pred_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("PRED_OK")
    ]
    assert len(pred_lines) == 2, outs
    assert pred_lines[0] == pred_lines[1], pred_lines
    assert os.path.exists(os.path.join(str(tmp_path), "preds.csv"))
    # Agreed preemption: only process 1 was signaled; process 0 stopped via
    # the epoch-boundary all-reduce, and both agree on the epoch count.
    preempt_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("PREEMPT_OK")
    ]
    assert len(preempt_lines) == 2, outs
    assert preempt_lines[0] == preempt_lines[1], preempt_lines
