"""Per-step training-health metrics + the non-finite-loss sentinel (obs
tentpole part 2).

The per-epoch record answers "how fast was the epoch"; these records answer
"what is the step doing RIGHT NOW": data-wait vs device-compute ms, loss,
global gradient norm, live HBM bytes, and a recompile counter — the per-phase
instrumentation that turns "it's slow" into an actionable bottleneck
(Awan et al., arXiv:1810.11112, and SURVEY §5).

Costs are explicit: per-step records require one host sync per step (the
loss must be read back), so ``step_metrics`` defaults off and benchmarks
leave it off. The NaN/Inf sentinel defaults ON — it piggybacks on values
the trainer already reads (the epoch loss; the per-step loss only when step
telemetry is on), and training on a NaN'd loss is never the right outcome:
it writes a ``kind="anomaly"`` diagnostic record and aborts cleanly
(``NonFiniteLossError``) instead of burning an epoch on garbage.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

# Backend compiles observed process-wide since the listener was installed.
# jax.monitoring has no unregister, so ONE module-level listener increments
# this global forever and StepHealth instances read deltas against their
# epoch baseline — repeated train() calls in one process can't stack hooks.
_compile_count = 0
_listener_installed = False

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Registry gauges StepHealth publishes — ONLY when step telemetry is on.
# config.validate_config imports this set to reject SLO rules over these
# names without --step-metrics (the rule would silently never evaluate);
# keeping the set next to the registrations means a new gauge cannot
# escape that check.
STEP_GAUGES = (
    "train/loss",
    "train/grad_norm",
    "train/recompiles",
    "train/nonfinite_grad_streak",
    "train/sync_ms",
)


def ensure_compile_listener() -> None:
    """Arm the process-wide backend-compile counter (idempotent). Callers
    that assert zero steady-state compiles — serving after warmup, tests —
    arm it first, record ``compile_count()`` as a baseline, and read the
    delta later; the listener itself is installed at most once."""
    global _listener_installed
    if _listener_installed:
        return
    import jax

    def _on_event(name: str, _secs: float, **_kw) -> None:
        global _compile_count
        if name == _COMPILE_EVENT:
            _compile_count += 1

    jax.monitoring.register_event_duration_secs_listener(_on_event)
    _listener_installed = True


# Backwards-compatible private alias (pre-serve callers).
_ensure_compile_listener = ensure_compile_listener


def compile_count() -> int:
    """Backend compiles observed so far (0 until the listener is armed)."""
    return _compile_count


def device_bytes_in_use() -> int | None:
    """Live HBM bytes on this process's first device, or None where the
    backend has no ``memory_stats`` (CPU) — the record carries null rather
    than a confident fake zero."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        return int(stats.get("bytes_in_use"))
    except Exception:
        return None


class NonFiniteLossError(RuntimeError):
    """Raised by the sentinel AFTER the diagnostic record is written."""


class StepHealth:
    """Per-step health records (``kind="step"``) + the non-finite sentinel.

    ``on_step`` is a no-op unless ``step_metrics`` is on — the default train
    loop keeps its async dispatch; ``check_epoch`` runs regardless (the
    epoch loss is already a host float there, so the sentinel is free)."""

    def __init__(
        self,
        metrics,
        *,
        step_metrics: bool = False,
        nan_sentinel: bool = True,
        tracer=None,
        registry=None,
    ):
        self.metrics = metrics
        self.enabled = bool(step_metrics)
        self.nan_sentinel = bool(nan_sentinel)
        self.tracer = tracer
        # Live-telemetry publication (obs/metrics.MetricsRegistry): per-step
        # loss/grad-norm/recompile/streak gauges the SLO monitor reads.
        # Only advances when step telemetry is on — same gate as the
        # records, so registry publication never adds a host sync. Gauges
        # pre-bound (the registry's own hot-path guidance), and up front
        # rather than on first use: the cross-host metrics merge flattens
        # by name set, so registration must not depend on what a given
        # host happened to observe.
        self.registry = registry
        if registry is not None:
            self._g_loss = registry.gauge("train/loss")
            self._g_grad_norm = registry.gauge("train/grad_norm")
            self._g_recompiles = registry.gauge("train/recompiles")
            self._g_nonfinite = registry.gauge("train/nonfinite_grad_streak")
        self._baseline = 0
        # Gradient-sync telemetry (schema v2, optional): set by the trainer
        # when --grad-sync-buckets is on. overlap_frac is the static
        # bucket-plan estimate (train/step.py bucket_overlap_frac) stamped
        # onto every step record; sync_ms is a per-step measured value where
        # a caller has one (host code cannot decompose a fused device step,
        # so the trainer leaves it unset — records carry it only from
        # tooling that measures it by A/B).
        self.overlap_frac: float | None = None
        # Schema v11 (ISSUE 15): the cross-pod (DCN) overlap estimate of a
        # hierarchical bucket plan — stamped only on --mesh-pods > 1 runs,
        # so flat-mesh records stay byte-identical to prior generations.
        self.dcn_overlap_frac: float | None = None
        # Consecutive steps whose GRADIENT norm was non-finite while the
        # loss stayed finite — the slow-corruption signal the preemption
        # watchdog (train/elastic.py) can act on before the loss itself
        # goes NaN and the sentinel aborts. Only advances when step
        # telemetry is on (the norm is a host float there anyway).
        self.nonfinite_grad_streak = 0
        if self.enabled:
            _ensure_compile_listener()
            self._baseline = _compile_count

    def set_sync(
        self,
        *,
        overlap_frac: float | None = None,
        dcn_overlap_frac: float | None = None,
    ) -> None:
        """Arm the grad-sync fields on subsequent step records (trainer,
        after the bucket plan is known). ``dcn_overlap_frac`` is the
        hierarchical (--mesh-pods) twin: what fraction of cross-pod sync
        bytes are issued before the final bucket (train/step.py
        hier_dcn_overlap_frac)."""
        self.overlap_frac = overlap_frac
        self.dcn_overlap_frac = dcn_overlap_frac

    def start_epoch(self) -> None:
        """Re-arm the recompile counter: compiles BETWEEN epochs (first-call
        validation/eval jits) are expected, so each epoch's records count
        compiles since the epoch began — any nonzero value mid-epoch is the
        silent-recompile smell this field exists to surface."""
        if self.enabled:
            self._baseline = _compile_count

    def on_step(
        self,
        epoch: int,
        step: int,
        m: Mapping[str, Any],
        data_wait_s: float | None = None,
        step_s: float | None = None,
        sync_ms: float | None = None,
        skipped: int | None = None,
        steps_skipped: int | None = None,
    ) -> None:
        if not self.enabled:
            return
        loss = float(m["loss"])
        grad_norm = float(m["grad_norm"]) if "grad_norm" in m else None
        record = {
            "kind": "step",
            "epoch": epoch,
            "step": step,
            "loss": loss,
            "grad_norm": grad_norm,
            "data_wait_ms": None if data_wait_s is None else round(data_wait_s * 1e3, 3),
            "step_ms": None if step_s is None else round(step_s * 1e3, 3),
            "recompiles": _compile_count - self._baseline,
            "hbm_bytes": device_bytes_in_use(),
        }
        # Schema-v2 grad-sync fields only on runs that configured them —
        # records from lever-less runs stay byte-identical to v1.
        if self.overlap_frac is not None:
            record["overlap_frac"] = self.overlap_frac
        # v11: hierarchical runs only (same absent-when-off discipline).
        if self.dcn_overlap_frac is not None:
            record["dcn_overlap_frac"] = self.dcn_overlap_frac
        if sync_ms is not None:
            record["sync_ms"] = round(sync_ms, 3)
        # Schema-v6 bad-step-policy fields (--bad-step-policy skip only):
        # the trainer passes them when the policy is armed.
        if skipped is not None:
            record["skipped"] = int(skipped)
        if steps_skipped is not None:
            record["steps_skipped"] = int(steps_skipped)
        self.metrics.write(record)
        if grad_norm is not None:
            self.nonfinite_grad_streak = (
                0 if math.isfinite(grad_norm) else self.nonfinite_grad_streak + 1
            )
        if self.registry is not None:
            self._g_loss.set(loss)
            if grad_norm is not None:
                self._g_grad_norm.set(grad_norm)
            self._g_recompiles.set(record["recompiles"])
            self._g_nonfinite.set(self.nonfinite_grad_streak)
            if sync_ms is not None:
                # train/sync_ms intentionally NOT pre-registered: no
                # trainer path passes sync_ms today (schema-v2 note), so
                # the name would be a permanently-null gauge; any future
                # caller passes it from step 0 on every host alike.
                self.registry.gauge("train/sync_ms").set(sync_ms)
        self._sentinel(epoch, step, loss, grad_norm)

    def on_scan_epoch(
        self, epoch: int, m: Mapping[str, Any], steps_skipped_base: int = 0
    ) -> None:
        """Per-step records for the scan-epoch mode, post-hoc from the
        ``[n_steps]`` metric arrays (the scan ran entirely on device, so
        there is no per-step host timing to report — those fields are
        null; loss/grad-norm/recompiles are real). ``steps_skipped_base``
        is the run's skip total BEFORE this epoch, so scan-mode records
        carry the same run-cumulative ``steps_skipped`` the per-step path
        reports (the schema's contract)."""
        if not self.enabled:
            return
        import numpy as np

        loss_v = np.asarray(m["loss"], np.float64)
        norm_v = (
            np.asarray(m["grad_norm"], np.float64) if "grad_norm" in m else None
        )
        skip_v = (
            np.asarray(m["skipped"], np.int64) if "skipped" in m else None
        )
        skipped_total = int(steps_skipped_base)
        for step in range(loss_v.shape[0]):
            record = {
                "kind": "step",
                "epoch": epoch,
                "step": step,
                "loss": float(loss_v[step]),
                "grad_norm": None if norm_v is None else float(norm_v[step]),
                "data_wait_ms": None,
                "step_ms": None,
                "recompiles": _compile_count - self._baseline,
                "hbm_bytes": device_bytes_in_use(),
            }
            if skip_v is not None:
                skipped_total += int(skip_v[step])
                record["skipped"] = int(skip_v[step])
                record["steps_skipped"] = skipped_total
            self.metrics.write(record)
            self._sentinel(
                epoch, step, float(loss_v[step]),
                None if norm_v is None else float(norm_v[step]),
            )

    def check_epoch(self, epoch: int, loss: float) -> None:
        """Epoch-granularity sentinel — the check every run gets for free."""
        self._sentinel(epoch, None, float(loss), None)

    def _sentinel(
        self, epoch: int, step: int | None, loss: float, grad_norm: float | None
    ) -> None:
        if not self.nan_sentinel or math.isfinite(loss):
            return
        record = {
            "kind": "anomaly",
            "reason": "nonfinite_loss",
            "epoch": epoch,
            "step": step,
            "loss": loss,
            "grad_norm": grad_norm,
        }
        self.metrics.write(record)
        if self.tracer is not None:
            self.tracer.instant("nonfinite_loss", args={"epoch": epoch, "step": step})
        from mpi_pytorch_tpu.utils.logging import run_logger

        where = f"epoch {epoch}" + ("" if step is None else f" step {step}")
        run_logger().error(
            "non-finite loss (%s) at %s — aborting instead of training on "
            "garbage (diagnostic kind='anomaly' record written; disable via "
            "--nan-sentinel false)", loss, where,
        )
        raise NonFiniteLossError(
            f"non-finite loss {loss} at {where}; see the kind='anomaly' "
            "metrics record for diagnostics"
        )
