"""Loss and metric ops.

The reference uses ``nn.CrossEntropyLoss`` (``main.py:56``, applied
``main.py:150``). With a 64 500-class head, materializing one-hot targets
(128×64500 floats per step) would waste HBM bandwidth, so the loss is the
fused integer-label softmax cross-entropy (SURVEY §7 hard-parts). Computed in
float32 regardless of compute dtype — softmax over 64 500 logits is exactly
where bfloat16 accumulates error.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

# Standard weight for the Inception-v3 auxiliary classifier loss — the
# behavior the reference *intends* but gets wrong by never unpacking the
# (logits, aux) train output (``main.py:149-150``; SURVEY §3 quirks).
AUX_LOSS_WEIGHT = 0.4


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean fused softmax CE with integer labels (≙ nn.CrossEntropyLoss).

    Labels < 0 mark padding rows (tail batches are padded to a static shape so
    XLA never recompiles); they contribute nothing to the mean.
    """
    logits = logits.astype(jnp.float32)
    valid = labels >= 0
    per_example = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(labels, 0)
    )
    return jnp.sum(per_example * valid) / jnp.maximum(jnp.sum(valid), 1)


def classification_loss(outputs, labels: jnp.ndarray) -> jnp.ndarray:
    """Total training loss: plain CE, or CE + 0.4·aux-CE for inception's
    train-mode ``(logits, aux_logits)`` output."""
    if isinstance(outputs, tuple):
        logits, aux = outputs
        return cross_entropy(logits, labels) + AUX_LOSS_WEIGHT * cross_entropy(aux, labels)
    return cross_entropy(outputs, labels)


def accuracy_count(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Number of correct top-1 predictions (≙ reference ``main.py:179-182``).
    Padding rows (label < 0) never count as correct."""
    return jnp.sum((jnp.argmax(logits, axis=-1) == labels) & (labels >= 0))


def valid_count(labels: jnp.ndarray) -> jnp.ndarray:
    """Number of non-padding rows in a batch."""
    return jnp.sum((labels >= 0).astype(jnp.int32))
