"""GPipe pipeline parallelism vs the un-pipelined stacked forward on the
8-device CPU mesh — values, gradients, remat agreement, and the shape guards.

The correctness property: streaming M microbatches through S ppermute-linked
stages computes exactly ``stage_S(...stage_1(x))`` per example, and grads
through the schedule equal grads of the plain composition.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.parallel.pipeline import (
    pipeline_forward,
    stack_stage_params,
)

N_STAGES = 8
D = 16


@pytest.fixture(scope="module")
def mesh():
    dev = np.asarray(jax.devices()[:N_STAGES]).reshape(N_STAGES, 1)
    return Mesh(dev, ("pipe", "unused"))


def residual_mlp_stage(params, x):
    """One homogeneous stage: residual two-layer MLP, [mb, D] → [mb, D]."""
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"] + params["b2"]


def _stage_params(seed):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.standard_normal((D, 4 * D)) * 0.1, jnp.float32),
        "b1": jnp.zeros((4 * D,), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((4 * D, D)) * 0.1, jnp.float32),
        "b2": jnp.zeros((D,), jnp.float32),
    }


@pytest.fixture(scope="module")
def stacked():
    return stack_stage_params([_stage_params(s) for s in range(N_STAGES)])


def stacked_reference(stacked_params, x):
    """Un-pipelined composition of all stages on one device."""
    for s in range(N_STAGES):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stacked_params)
        x = residual_mlp_stage(params_s, x)
    return x


def _x(b=32, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, D)), jnp.float32)


@pytest.mark.parametrize("num_micro", [4, 8])
def test_pipeline_matches_stacked_forward(mesh, stacked, num_micro):
    x = _x()
    got = pipeline_forward(
        stacked, x, mesh, stage_fn=residual_mlp_stage, num_microbatches=num_micro
    )
    want = stacked_reference(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_stacked(mesh, stacked):
    x = _x(seed=2)
    y = jnp.asarray(np.random.default_rng(3).standard_normal(x.shape), jnp.float32)

    def loss_pp(params, x_):
        out = pipeline_forward(
            params, x_, mesh, stage_fn=residual_mlp_stage, num_microbatches=8
        )
        return jnp.mean((out - y) ** 2)

    def loss_ref(params, x_):
        return jnp.mean((stacked_reference(params, x_) - y) ** 2)

    gp, gxp = jax.grad(loss_pp, argnums=(0, 1))(stacked, x)
    gr, gxr = jax.grad(loss_ref, argnums=(0, 1))(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gxp), np.asarray(gxr), rtol=5e-5, atol=5e-5)


def test_pipeline_remat_matches_plain(mesh, stacked):
    """remat=True re-derives stage internals in the backward; same numbers."""
    x = _x(seed=4)

    def loss(params, remat):
        out = pipeline_forward(
            params, x, mesh, stage_fn=residual_mlp_stage,
            num_microbatches=8, remat=remat,
        )
        return jnp.sum(out * out)

    g_plain = jax.grad(functools.partial(loss, remat=False))(stacked)
    g_remat = jax.grad(functools.partial(loss, remat=True))(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_pipeline_composes_with_dp():
    """PP×DP on a 4-stage × 2-data mesh: values AND grads equal the
    un-pipelined single-device composition (shard_map's transpose supplies
    the gradient psum over the data axis for the pipe-sharded params)."""
    dev = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh2d = Mesh(dev, ("pipe", "data"))
    stacked4 = stack_stage_params([_stage_params(s) for s in range(4)])

    def ref4(params, x):
        for s in range(4):
            x = residual_mlp_stage(
                jax.tree_util.tree_map(lambda p: p[s], params), x
            )
        return x

    x = _x(b=32, seed=9)
    got = pipeline_forward(
        stacked4, x, mesh2d, stage_fn=residual_mlp_stage,
        num_microbatches=8, data_axis="data",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref4(stacked4, x)), rtol=2e-5, atol=2e-5
    )

    y = jnp.asarray(np.random.default_rng(10).standard_normal(x.shape), jnp.float32)

    def loss_pp(params):
        out = pipeline_forward(
            params, x, mesh2d, stage_fn=residual_mlp_stage,
            num_microbatches=8, data_axis="data",
        )
        return jnp.mean((out - y) ** 2)

    g_pp = jax.grad(loss_pp)(stacked4)
    g_rf = jax.grad(lambda p: jnp.mean((ref4(p, x) - y) ** 2))(stacked4)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_rf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


# --- real-model stages: the ViT encoder block as a pipeline stage ---------

VIT_BLOCK = dict(num_heads=4, mlp_dim=32)
VIT_HIDDEN = 16


def vit_block_stage(params, x):
    """One ViT EncoderBlock as a pipeline stage: [mb, S, hidden] →
    [mb, S, hidden] (the homogeneous-stage property models/vit.py documents)."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    return EncoderBlock(**VIT_BLOCK).apply({"params": params}, x, train=False)


def test_pipeline_runs_vit_encoder_blocks(mesh):
    """An 8-deep ViT encoder split one-block-per-stage over the pipe axis
    equals running the blocks sequentially on one device."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((16, 8, VIT_HIDDEN)), jnp.float32)
    block = EncoderBlock(**VIT_BLOCK)
    per_stage = [
        block.init({"params": jax.random.PRNGKey(s)}, x[:2], train=False)["params"]
        for s in range(N_STAGES)
    ]
    stacked_blocks = stack_stage_params(per_stage)

    got = pipeline_forward(
        stacked_blocks, x, mesh, stage_fn=vit_block_stage, num_microbatches=8
    )
    want = x
    for params in per_stage:
        want = block.apply({"params": params}, want, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pipeline_rejects_bad_shapes(mesh, stacked):
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(
            stacked, _x(b=30), mesh,
            stage_fn=residual_mlp_stage, num_microbatches=7,
        )
    short = jax.tree_util.tree_map(lambda p: p[:4], stacked)
    with pytest.raises(ValueError, match="stage axis"):
        pipeline_forward(
            short, _x(), mesh, stage_fn=residual_mlp_stage, num_microbatches=4
        )
