"""Integration tests (SURVEY §4 item 3): tiny synthetic run — loss decreases,
checkpoint round-trips, resume continues, eval matches a plain forward."""

import os

import jax
import numpy as np
import pytest

from mpi_pytorch_tpu.config import Config
from mpi_pytorch_tpu.train.trainer import train
from mpi_pytorch_tpu.evaluate import evaluate


def _tiny_cfg(tmpdir, **kw) -> Config:
    cfg = Config()
    cfg.debug = True
    cfg.debug_sample_size = 128
    cfg.test_csv = "/root/repo/data/test_sample.csv"
    cfg.train_csv = "/root/repo/data/train_sample.csv"
    cfg.synthetic_data = True
    cfg.model_name = "resnet18"
    cfg.num_classes = 64500  # raw category_id labels, reference head size
    cfg.batch_size = 32
    cfg.width = cfg.height = 32
    cfg.num_epochs = 2
    cfg.compute_dtype = "float32"
    cfg.checkpoint_dir = os.path.join(tmpdir, "ckpt")
    cfg.log_file = os.path.join(tmpdir, "training.log")
    cfg.validate = False
    cfg.loader_workers = 2
    cfg.log_every_steps = 0
    for k, v in kw.items():
        setattr(cfg, k, v)
    cfg.validate_config()
    return cfg


@pytest.mark.slow
@pytest.mark.parametrize("spmd", [False, True])
def test_loss_decreases(tmp_path, spmd):
    cfg = _tiny_cfg(str(tmp_path), num_epochs=3, spmd_mode=spmd,
                    learning_rate=1e-3, num_classes=200)
    summary = train(cfg)
    assert summary.epochs_run == 3
    assert summary.epoch_losses[-1] < summary.epoch_losses[0]
    assert os.path.exists(cfg.log_file)


@pytest.mark.slow
def test_checkpoint_resume(tmp_path):
    # num_classes=200 (not the full 64500) keeps the XLA CPU compile cheap;
    # raw-category-id label handling is covered by test_data.test_labels_fit_head.
    cfg = _tiny_cfg(str(tmp_path), num_epochs=1, num_classes=200)
    s1 = train(cfg)
    assert s1.checkpoint_path and os.path.exists(s1.checkpoint_path)

    # resume: epoch counter continues (helpers.py:10-15 semantics)
    cfg2 = _tiny_cfg(str(tmp_path), num_epochs=2, from_checkpoint=True, num_classes=200)
    s2 = train(cfg2)
    assert s2.epochs_run == 1  # only epoch 1 remains
    assert "00001" in s2.checkpoint_path


@pytest.mark.slow
def test_validation_runs_on_train_split(tmp_path):
    cfg = _tiny_cfg(str(tmp_path), num_epochs=1, validate=True, num_classes=150,
                    debug_sample_size=96)
    summary = train(cfg)
    assert summary.val_accuracy is not None
    assert 0.0 <= summary.val_accuracy <= 1.0


@pytest.mark.slow
def test_eval_pipeline_matches_direct_forward(tmp_path):
    """The collapsed 4-stage pipeline reports the same accuracy a direct
    batched forward gives (SURVEY §4 item 3 'eval pipeline produces the same
    accuracy as a plain batched forward'): one un-sharded, un-padded
    ``model.apply`` over the whole test manifest, accuracy in plain numpy."""
    import jax.numpy as jnp

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.data import DataLoader
    from mpi_pytorch_tpu.evaluate import build_inference

    cfg = _tiny_cfg(str(tmp_path), num_epochs=1, num_classes=200, debug_sample_size=160)
    train(cfg)
    res = evaluate(cfg)
    assert res.num_images == 32  # 20% of 160

    mesh, bundle, state, test_manifest = build_inference(cfg)
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    assert latest is not None
    state, _, _ = ckpt.load_for_eval(latest, state)
    loader = DataLoader(
        test_manifest, batch_size=len(test_manifest), image_size=cfg.image_size,
        shuffle=False, drop_remainder=False, synthetic=True, num_workers=2,
    )
    images, labels = next(iter(loader.epoch(0)))
    logits = state.apply_fn(state.variables, jnp.asarray(images), train=False)
    direct_acc = float(np.mean(np.argmax(np.asarray(logits), axis=-1) == labels))
    assert res.accuracy == pytest.approx(direct_acc, abs=1e-9)


def test_async_checkpointer_roundtrip(tmp_path):
    """AsyncCheckpointer: snapshot-then-background-write lands an atomic,
    loadable checkpoint; the snapshot is decoupled from the live state (the
    train loop donates those buffers into the next step)."""
    import jax.numpy as jnp
    import optax

    from flax import linen as nn

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.train.state import TrainState

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x)

    model = M()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    state = TrainState.create(
        apply_fn=model.apply, variables=variables, tx=optax.adam(1e-3),
        rng=jax.random.PRNGKey(1),
    )
    cp = ckpt.AsyncCheckpointer()
    path = cp.save(str(tmp_path), epoch=3, state=state, loss=1.5, keep=2)
    cp.wait()
    assert path and os.path.exists(path)
    assert ckpt.latest_checkpoint(str(tmp_path)) == path

    template = TrainState.create(
        apply_fn=model.apply,
        variables=model.init(jax.random.PRNGKey(9), jnp.zeros((1, 8))),
        tx=optax.adam(1e-3), rng=jax.random.PRNGKey(2),
    )
    restored, epoch, loss = ckpt.load_checkpoint(path, template)
    assert (epoch, loss) == (3, 1.5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_moments_checkpoint_roundtrip(tmp_path):
    """moments_bf16 snapshots: params restore EXACTLY, big moment tensors
    restore as f32 values quantized to bf16, small/integer optimizer leaves
    (Adam count) stay exact, and the file actually shrinks."""
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.train.state import TrainState

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(nn.Dense(2048)(x))

    model = M()

    def fresh(seed):
        return TrainState.create(
            apply_fn=model.apply,
            variables=model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8))),
            tx=optax.adam(1e-3), rng=jax.random.PRNGKey(seed + 1),
        )

    state = fresh(0)
    # Take one real optimizer step so the moments are non-zero (a zero
    # moment would trivially be bf16-exact and prove nothing).
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(np.random.default_rng(0).normal(size=p.shape), p.dtype),
        state.params,
    )

    def step(st, grads):
        updates, opt_state = st.tx.update(grads, st.opt_state, st.params)
        return st.replace(
            step=st.step + 1,
            params=optax.apply_updates(st.params, updates),
            opt_state=opt_state,
        )

    state = step(state, grads)

    cp = ckpt.AsyncCheckpointer()
    exact = cp.save(str(tmp_path / "exact"), epoch=0, state=state, loss=1.0)
    cp.wait()
    lossy = cp.save(
        str(tmp_path / "bf16"), epoch=0, state=state, loss=1.0, moments_bf16=True
    )
    cp.wait()
    assert os.path.getsize(lossy) < 0.75 * os.path.getsize(exact)

    restored, _, _ = ckpt.load_checkpoint(lossy, fresh(9))
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Restored moments: f32 dtype (the optimizer's), values == bf16(quantized).
    for a, b in zip(
        jax.tree_util.tree_leaves(state.opt_state),
        jax.tree_util.tree_leaves(restored.opt_state),
    ):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, (a.dtype, b.dtype)
        if a.dtype == np.float32 and a.size >= 4096:
            np.testing.assert_array_equal(
                a.astype(jnp.bfloat16).astype(np.float32), b
            )
        else:  # count / small leaves: exact
            np.testing.assert_array_equal(a, b)
    # The restored state steps (dtype-clean for the optimizer).
    step(restored, grads)


def test_dirty_checkpoint_marker_and_resume_warning(tmp_path):
    """A mid-epoch preemption save is marked dirty (sidecar): resume warns
    that the replayed epoch double-applies the partial epoch's updates, a
    clean overwrite of the same epoch clears the marker, and last-k cleanup
    removes markers with their checkpoints."""
    import logging

    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.train.state import TrainState
    from mpi_pytorch_tpu.utils.logging import run_logger

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x)

    model = M()
    state = TrainState.create(
        apply_fn=model.apply,
        variables=model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8))),
        tx=optax.adam(1e-3), rng=jax.random.PRNGKey(1),
    )
    cp = ckpt.AsyncCheckpointer()
    path = cp.save(str(tmp_path), epoch=5, state=state, loss=1.0, dirty=True)
    cp.wait()
    assert os.path.exists(path + ".dirty")

    # Capture from the rank-tagged run logger itself: it is the logger the
    # trainer configures (propagate=False), so the warning must land THERE
    # to be visible in real runs' stream/file handlers.
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = run_logger()
    logger.addHandler(handler)
    try:
        ckpt.load_checkpoint(path, state)
        assert any("DIRTY" in r.getMessage() for r in records)

        # A clean save of the same epoch (the resumed run re-finishing it)
        # clears the marker, and a clean load stays silent.
        cp.save(str(tmp_path), epoch=5, state=state, loss=0.9)
        cp.wait()
        assert not os.path.exists(path + ".dirty")
        records.clear()
        ckpt.load_checkpoint(path, state)
        assert not records
    finally:
        logger.removeHandler(handler)

    # Markers ride last-k retention: evicting the checkpoint evicts its
    # sidecar too.
    p6 = ckpt.save_checkpoint(str(tmp_path), epoch=6, state=state, loss=0.8,
                              dirty=True)
    assert os.path.exists(p6 + ".dirty")
    ckpt.save_checkpoint(str(tmp_path), epoch=7, state=state, loss=0.7, keep=1)
    assert not os.path.exists(p6) and not os.path.exists(p6 + ".dirty")


@pytest.mark.slow
def test_device_cache_matches_streaming(tmp_path):
    """device_cache=True (HBM-resident dataset, on-device index gather) walks
    the data in the same order as the streaming loader and must produce the
    same loss trajectory — including a padded tail step (102 images, batch 32
    → 6-row tail)."""
    cfg_a = _tiny_cfg(
        os.path.join(str(tmp_path), "a"), num_epochs=2, num_classes=200,
        debug_sample_size=128, drop_remainder=False,
    )
    sa = train(cfg_a)
    cfg_b = _tiny_cfg(
        os.path.join(str(tmp_path), "b"), num_epochs=2, num_classes=200,
        debug_sample_size=128, drop_remainder=False, device_cache=True,
    )
    sb = train(cfg_b)
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)


@pytest.mark.slow
def test_device_cache_rows_sharded_not_replicated(tmp_path):
    """The device cache shards rows over the data axis: each of the 8
    devices holds ceil(N/8) rows — per-device HBM ≈ dataset/n, not a full
    replica per chip — and the padded tail rows sit past the real count."""
    from mpi_pytorch_tpu.train.trainer import build_device_cache, build_training

    cfg = _tiny_cfg(str(tmp_path), num_classes=200, debug_sample_size=102,
                    device_cache=True)
    mesh, _, _, (train_manifest, _, loader) = build_training(cfg)
    dataset, labels = build_device_cache(cfg, train_manifest, loader, mesh)
    n = len(train_manifest)
    per_dev = -(-n // 8)
    assert dataset.shape[0] == per_dev * 8  # padded to divisibility
    assert int(labels.shape[0]) == n  # labels stay real-length (and replicated)
    for shard in dataset.addressable_shards:
        assert shard.data.shape[0] == per_dev, shard.data.shape
    # Distinct rows per device (sharded), not 8 copies of everything.
    assert len({shard.index[0].start for shard in dataset.addressable_shards}) == 8


@pytest.mark.slow
def test_host_cache_matches_streaming(tmp_path):
    """host_cache=True (decode the shard once into host RAM, slice epochs)
    must reproduce the streaming loss trajectory and validation accuracy —
    same (seed, epoch) walk, same padding semantics."""
    kw = dict(num_epochs=2, num_classes=200, debug_sample_size=128,
              drop_remainder=False, validate=True)
    sa = train(_tiny_cfg(os.path.join(str(tmp_path), "a"), **kw))
    sb = train(_tiny_cfg(os.path.join(str(tmp_path), "b"), **kw, host_cache=True))
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)
    assert sa.val_accuracy == sb.val_accuracy


def test_host_and_device_cache_exclusive():
    with pytest.raises(ValueError, match="host_cache and device_cache"):
        Config(host_cache=True, device_cache=True).validate_config()


@pytest.mark.slow
def test_scan_epoch_matches_per_step_cache(tmp_path):
    """scan_epoch=True (the whole epoch as ONE compiled lax.scan over the
    device cache) must reproduce the per-step cached trajectory — same
    (seed, epoch) batch order, same padded tail handling, one dispatch."""
    cfg_a = _tiny_cfg(
        os.path.join(str(tmp_path), "a"), num_epochs=2, num_classes=200,
        debug_sample_size=96, drop_remainder=False, device_cache=True,
    )
    sa = train(cfg_a)
    cfg_b = _tiny_cfg(
        os.path.join(str(tmp_path), "b"), num_epochs=2, num_classes=200,
        debug_sample_size=96, drop_remainder=False, device_cache=True,
        scan_epoch=True,
    )
    sb = train(cfg_b)
    # The scan body is compiled (and fused) separately from the unrolled
    # step, so f32 reassociation drifts the trajectory slightly as updates
    # compound across an epoch: first epoch agrees to ~1e-5 relative, later
    # epochs to ~1e-3. Assert trajectory-level equivalence.
    np.testing.assert_allclose(sa.epoch_losses[:1], sb.epoch_losses[:1], rtol=1e-4)
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=5e-3)


def test_scan_epoch_requires_device_cache():
    with pytest.raises(ValueError, match="scan_epoch"):
        Config(scan_epoch=True).validate_config()


def _mlp_state(rng_seed=0, num_classes=11, image=8):
    """A BN-free, dropout-free model so accumulation/remat equivalence can be
    asserted exactly (no per-microbatch stats, no rng-shape dependence)."""
    import flax.linen as nn
    import jax.numpy as jnp
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(num_classes)(x)

    model = MLP()
    variables = model.init(jax.random.PRNGKey(rng_seed), jnp.zeros((1, image, image, 3)))
    return TrainState.create(
        apply_fn=model.apply, variables=variables, tx=make_optimizer(1e-3),
        rng=jax.random.PRNGKey(rng_seed + 1),
    )


def test_grad_accumulation_matches_full_batch():
    """accum_steps=k (count-weighted microbatch grads, one optimizer update)
    must equal the unsplit big-batch step — including when padded (-1) rows
    land unevenly across microbatches."""
    import jax.numpy as jnp
    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh

    mesh = create_mesh(MeshConfig())
    rng = np.random.default_rng(0)
    batch = 32
    images = rng.standard_normal((batch, 8, 8, 3)).astype(np.float32)
    labels = rng.integers(0, 11, size=(batch,)).astype(np.int32)
    labels[5:11] = -1  # padding rows, unevenly placed across 4 microbatches

    outs = {}
    for k in (1, 4):
        state = place_state_on_mesh(_mlp_state(), mesh)
        step = make_train_step(jnp.float32, accum_steps=k, mesh=mesh)
        new_state, m = step(state, shard_batch((images, labels), mesh))
        outs[k] = (new_state.params, m)
    p1, m1 = outs[1]
    p4, m4 = outs[4]
    assert int(m1["count"]) == int(m4["count"]) == batch - 6
    assert int(m1["correct"]) == int(m4["correct"])
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7), p1, p4
    )


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["full", "blocks"])
def test_remat_matches_plain_step(tmp_path, strategy):
    """Rematerialization (whole-forward jax.checkpoint, or per-residual-block
    nn.remat) only changes WHEN activations are computed, not what — the loss
    trajectory must match the plain step."""
    cfg_a = _tiny_cfg(os.path.join(str(tmp_path), "a"), num_epochs=2, num_classes=200)
    sa = train(cfg_a)
    cfg_b = _tiny_cfg(
        os.path.join(str(tmp_path), "b"), num_epochs=2, num_classes=200, remat=strategy
    )
    sb = train(cfg_b)
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)


@pytest.mark.slow
def test_remat_blocks_param_tree_unchanged():
    """nn.remat must not change parameter paths — checkpoints and the
    torchvision converter depend on them."""
    from mpi_pytorch_tpu.models import create_model_bundle

    _, plain = create_model_bundle("resnet18", 10, image_size=32)
    _, blocks = create_model_bundle("resnet18", 10, image_size=32, remat_blocks=True)
    assert jax.tree_util.tree_structure(plain) == jax.tree_util.tree_structure(blocks)


@pytest.mark.slow
def test_cached_eval_matches_streaming_eval(tmp_path):
    """evaluate_cached (HBM-resident val set) must agree with
    evaluate_manifest (streaming decode) — same masking, same accounting."""
    from mpi_pytorch_tpu.train.trainer import (
        build_device_cache,
        build_training,
        evaluate_cached,
        evaluate_manifest,
    )
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    cfg = _tiny_cfg(str(tmp_path), num_classes=200, debug_sample_size=96, batch_size=32)
    mesh, bundle, state, (train_manifest, _, loader) = build_training(cfg)
    state = place_state_on_mesh(state, mesh)
    dataset, labels = build_device_cache(cfg, train_manifest, loader, mesh)
    acc_c, loss_c = evaluate_cached(cfg, state, mesh, dataset, labels)
    acc_s, loss_s = evaluate_manifest(cfg, state, mesh, train_manifest)
    # The two paths compile different HLO; allow one argmax tie-flip of slack
    # (the loss check concedes the same numeric divergence via rtol).
    assert abs(acc_c - acc_s) <= 1.0 / len(train_manifest) + 1e-9
    np.testing.assert_allclose(loss_c, loss_s, rtol=1e-5)


def test_remat_blocks_rejects_non_resnet():
    with pytest.raises(ValueError, match="not implemented for"):
        Config(remat="blocks", model_name="alexnet").validate_config()


@pytest.mark.slow
def test_remat_blocks_densenet_tree_and_forward():
    """densenet block remat: unchanged param tree, same forward output."""
    import jax.numpy as jnp
    from mpi_pytorch_tpu.models import create_model_bundle

    b_plain, v_plain = create_model_bundle("densenet121", 10, image_size=32)
    b_remat, v_remat = create_model_bundle("densenet121", 10, image_size=32, remat_blocks=True)
    assert jax.tree_util.tree_structure(v_plain) == jax.tree_util.tree_structure(v_remat)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    out_plain = b_plain.model.apply(v_plain, x, train=False)
    out_remat = b_remat.model.apply(v_plain, x, train=False)
    np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_remat), atol=1e-5)


def test_accum_config_validation():
    with pytest.raises(ValueError, match="accum_steps"):
        Config(accum_steps=3, batch_size=128).validate_config()
    with pytest.raises(ValueError, match="accum_steps"):
        Config(accum_steps=2, device_cache=True).validate_config()
    with pytest.raises(ValueError, match="accum_steps"):
        Config(accum_steps=0).validate_config()


@pytest.mark.slow
def test_feature_extract_freezes_backbone(tmp_path):
    from mpi_pytorch_tpu.train.trainer import build_training
    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh
    import jax.numpy as jnp

    cfg = _tiny_cfg(str(tmp_path), feature_extract=True, num_classes=200)
    mesh, bundle, state, (_, _, loader) = build_training(cfg)
    state = place_state_on_mesh(state, mesh)
    before = jax.device_get(state.params)
    step = make_train_step(jnp.float32)
    batch = next(iter(loader.epoch(0)))
    state2, _ = step(state, shard_batch(batch, mesh))
    after = jax.device_get(state2.params)

    # backbone unchanged, head moved
    np.testing.assert_array_equal(before["conv1"]["kernel"], after["conv1"]["kernel"])
    assert not np.array_equal(before["head"]["kernel"], after["head"]["kernel"])


def test_make_optimizer_variants_and_schedules():
    """adam|sgd|adamw x constant|cosine|warmup_cosine: each produces finite
    updates, cosine's update magnitude shrinks toward the end of the run,
    and bad names raise."""
    import jax.numpy as jnp

    from mpi_pytorch_tpu.train.state import make_optimizer

    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    for opt in ("adam", "sgd", "adamw"):
        tx = make_optimizer(1e-2, optimizer=opt)
        st = tx.init(params)
        upd, _ = tx.update(grads, st, params)
        assert all(
            np.all(np.isfinite(np.asarray(u))) for u in jax.tree_util.tree_leaves(upd)
        )

    # Cosine: step-100 update is much smaller than step-0 update (lr -> 0).
    tx = make_optimizer(1e-2, optimizer="sgd", lr_schedule="cosine", total_steps=100)
    st = tx.init(params)
    upd0, st = tx.update(grads, st, params)
    for _ in range(98):
        _, st = tx.update(grads, st, params)
    upd_last, _ = tx.update(grads, st, params)
    assert abs(float(upd_last["w"][0, 0])) < 0.05 * abs(float(upd0["w"][0, 0]))

    # Warmup: the first update is (near) zero, the peak is reached later.
    tx = make_optimizer(
        1e-2, optimizer="sgd", lr_schedule="warmup_cosine",
        warmup_steps=10, total_steps=100,
    )
    st = tx.init(params)
    upd0, _ = tx.update(grads, st, params)
    assert abs(float(upd0["w"][0, 0])) < 1e-4

    with pytest.raises(ValueError, match="total_steps"):
        make_optimizer(1e-2, lr_schedule="cosine")
    with pytest.raises(ValueError, match="optimizer"):
        make_optimizer(1e-2, optimizer="rmsprop")
    with pytest.raises(ValueError, match="lr_schedule"):
        make_optimizer(1e-2, lr_schedule="linear")


def test_config_rejects_bad_optimizer_fields():
    from mpi_pytorch_tpu.config import Config

    with pytest.raises(ValueError, match="optimizer"):
        Config(optimizer="rmsprop").validate_config()
    with pytest.raises(ValueError, match="lr_schedule"):
        Config(lr_schedule="linear").validate_config()


def test_config_rejects_ignored_optimizer_combos():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.train.state import make_optimizer

    with pytest.raises(ValueError, match="weight_decay"):
        Config(weight_decay=0.01).validate_config()  # adam ignores it
    with pytest.raises(ValueError, match="warmup_steps"):
        Config(warmup_steps=10, lr_schedule="cosine").validate_config()
    with pytest.raises(ValueError, match="must be <"):
        make_optimizer(
            1e-2, lr_schedule="warmup_cosine", warmup_steps=200, total_steps=100
        )


@pytest.mark.slow
def test_uint8_input_matches_float_input(tmp_path):
    """--input-dtype uint8 (raw pixels to device, normalize on chip) must
    reproduce the float-input loss trajectory on a real-JPEG dataset — the
    pixels are uint8 at the source, so the two paths see identical data."""
    from mpi_pytorch_tpu.data.create_dataset import main as create_main

    out = str(tmp_path / "data")
    create_main(["--synthetic", "96", "--num-classes", "8", "--image-size", "48",
                 "--out", out])
    common = dict(
        debug=True, debug_sample_size=64, synthetic_data=False, num_classes=8,
        validate=True, val_on_train=True,
    )
    cfg_a = _tiny_cfg(os.path.join(str(tmp_path), "a"), **common)
    cfg_b = _tiny_cfg(
        os.path.join(str(tmp_path), "b"), **common, input_dtype="uint8"
    )
    for c in (cfg_a, cfg_b):
        c.train_csv = f"{out}/train_sample.csv"
        c.test_csv = f"{out}/test_sample.csv"
        c.train_img_dir = f"{out}/img/train"
        c.test_img_dir = f"{out}/img/test"
    sa = train(cfg_a)
    sb = train(cfg_b)
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)
    assert sa.val_accuracy == sb.val_accuracy


@pytest.mark.slow
def test_uint8_device_cache_matches_uint8_streaming(tmp_path):
    """input_dtype='uint8' composed with device_cache: the HBM-resident
    dataset is stored as raw uint8 (4x smaller) and normalized on device
    after the index gather — trajectory must match uint8 streaming."""
    kw = dict(num_epochs=2, num_classes=200, debug_sample_size=96,
              drop_remainder=False, input_dtype="uint8")
    sa = train(_tiny_cfg(os.path.join(str(tmp_path), "a"), **kw))
    sb = train(_tiny_cfg(os.path.join(str(tmp_path), "b"), **kw, device_cache=True))
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)


@pytest.mark.slow
def test_track_best_pins_checkpoint_and_eval_uses_it(tmp_path):
    """--track-best: best.json points at the best-validation epoch, retention
    (keep=1) never deletes that file even as newer checkpoints churn past it,
    a resumed run won't demote the stored best, and evaluate --use-best loads
    exactly the marked checkpoint."""
    from mpi_pytorch_tpu import checkpoint as ckpt

    cfg = _tiny_cfg(
        str(tmp_path), num_epochs=4, num_classes=200, validate=True,
        track_best=True, keep_checkpoints=1, learning_rate=1e-3,
    )
    summary = train(cfg)
    marker = ckpt.best_marker(cfg.checkpoint_dir)
    assert marker is not None
    assert marker["accuracy"] == summary.best_accuracy
    best_path = os.path.join(cfg.checkpoint_dir, marker["checkpoint"])
    assert os.path.exists(best_path), "retention must pin the best checkpoint"

    # The marker is the max over epochs: at least as good as the final
    # epoch's accuracy (equality when the last epoch is the best).
    assert summary.best_accuracy >= summary.val_accuracy
    assert marker["epoch"] <= 3

    # A resumed run starting from the stored best must not demote it.
    cfg2 = _tiny_cfg(
        str(tmp_path), num_epochs=5, num_classes=200, validate=True,
        track_best=True, keep_checkpoints=1, from_checkpoint=True,
    )
    train(cfg2)
    marker2 = ckpt.best_marker(cfg.checkpoint_dir)
    assert marker2["accuracy"] >= marker["accuracy"]

    # evaluate --use-best loads the marked file (log records the epoch).
    cfg3 = _tiny_cfg(str(tmp_path), num_classes=200, use_best=True)
    res = evaluate(cfg3)
    assert 0.0 <= res.accuracy <= 1.0


def test_track_best_requires_validation():
    with pytest.raises(ValueError, match="track_best"):
        Config(track_best=True, validate=False).validate_config()


@pytest.mark.slow
def test_full_fast_path_stack_matches_streaming(tmp_path):
    """The whole TPU-first ingest stack composed — offline pack, raw-uint8
    feeding, HBM-resident device cache, one-scan-per-epoch — must reproduce
    the plain f32 streaming trajectory on a real-JPEG dataset (uint8 source,
    so every path sees identical pixels)."""
    from mpi_pytorch_tpu.data.create_dataset import main as create_main
    from mpi_pytorch_tpu.data.packed import main as pack_main

    out = str(tmp_path / "data")
    create_main(["--synthetic", "96", "--num-classes", "8", "--image-size", "48",
                 "--out", out])
    data_args = dict(
        debug=True, debug_sample_size=64, synthetic_data=False, num_classes=8,
    )

    def with_dataset(cfg):
        cfg.train_csv = f"{out}/train_sample.csv"
        cfg.test_csv = f"{out}/test_sample.csv"
        cfg.train_img_dir = f"{out}/img/train"
        cfg.test_img_dir = f"{out}/img/test"
        return cfg

    packed_dir = str(tmp_path / "packed")
    pack_main([
        "--packed-dir", packed_dir, "--debug", "true", "--debug-sample-size", "64",
        "--test-csv", f"{out}/test_sample.csv", "--train-csv", f"{out}/train_sample.csv",
        "--train-img-dir", f"{out}/img/train", "--test-img-dir", f"{out}/img/test",
        "--synthetic-data", "false", "--num-classes", "8",
        "--image-size", "32", "--loader-workers", "2",
    ])

    sa = train(with_dataset(_tiny_cfg(os.path.join(str(tmp_path), "a"), **data_args)))
    sb = train(with_dataset(_tiny_cfg(
        os.path.join(str(tmp_path), "b"), **data_args,
        packed_dir=packed_dir, input_dtype="uint8",
        device_cache=True, scan_epoch=True,
    )))
    np.testing.assert_allclose(sa.epoch_losses, sb.epoch_losses, rtol=1e-4)


@pytest.mark.slow
def test_predictions_file_matches_reported_accuracy(tmp_path):
    """evaluate --predictions-file writes one row per test image in manifest
    order; the fraction of rows whose predicted_category_id equals the true
    category reproduces the reported accuracy exactly — the submission-file
    capability the reference's predictor ranks compute per-image but never
    persist (evaluation_pipeline.py:149-158)."""
    cfg = _tiny_cfg(str(tmp_path), num_epochs=2, num_classes=200,
                    debug_sample_size=160, learning_rate=1e-3)
    train(cfg)
    pred_path = os.path.join(str(tmp_path), "predictions.csv")
    cfg.predictions_file = pred_path
    res = evaluate(cfg)

    from mpi_pytorch_tpu.data import load_manifests

    _, test_m = load_manifests(cfg)
    rows = open(pred_path).read().strip().splitlines()
    assert rows[0] == "file_name,predicted_label,predicted_category_id"
    body = [r.split(",") for r in rows[1:]]
    assert [b[0] for b in body] == list(test_m.filenames)  # manifest order
    correct = sum(
        int(b[2]) == int(c) for b, c in zip(body, test_m.category_ids)
    )
    assert correct / len(body) == pytest.approx(res.accuracy, abs=1e-9)
