"""Microbench: fused stem kernel vs XLA composition, headline shape, on chip.

Default mode: the fused-vs-reference A/B (fwd and fwd+bwd) that produced
the §4d round-5 numbers.

``--levers``: one JSON row per §4d byte-bound lever configuration
(docs/RESULTS.md §4d, round 6) — r5-default, bf16-pool, lanes-256,
idx-int8, c-block-16, and all-four — each correctness-checked against the
XLA reference on chip before timing, so every lever lands in the table as
a measured ship-or-rejection row, never a silent drop. Lever gates are
read from the env at TRACE time (ops/fused_stem.py:_levers), so each
config builds fresh jitted callables.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
import numpy as np

from mpi_pytorch_tpu.ops.fused_stem import stem_affine_relu_pool, _reference_impl

B, H, W, C = 2048, 64, 64, 64

# (label, env) — the §4d lever matrix. Values mirror the MPT_STEM_* gates.
LEVER_CONFIGS = [
    ("r5-default", {}),
    ("bf16-pool", {"MPT_STEM_BF16_POOL": "1"}),
    ("lanes-256", {"MPT_STEM_LANES": "256"}),
    ("idx-int8", {"MPT_STEM_IDX_INT8": "1"}),
    ("c-block-16", {"MPT_STEM_C_BLOCK": "16"}),
    (
        "all-four",
        {
            "MPT_STEM_BF16_POOL": "1",
            "MPT_STEM_LANES": "256",
            "MPT_STEM_IDX_INT8": "1",
            "MPT_STEM_C_BLOCK": "16",
        },
    ),
]


def _data():
    key = jax.random.PRNGKey(0)
    y = jax.random.normal(key, (B, H, W, C), jnp.bfloat16)
    a = jnp.abs(jax.random.normal(key, (C,), jnp.float32)) + 0.5
    b = jax.random.normal(key, (C,), jnp.float32) * 0.1
    co = jax.random.normal(key, (B, H // 2, W // 2, C), jnp.bfloat16)
    return y, a, b, co


def make(fn):
    @jax.jit
    def fwd(y, a, b):
        return fn(y, a, b)

    @jax.jit
    def fwdbwd(y, a, b, co):
        l, grads = jax.value_and_grad(
            lambda y, a, b: jnp.sum((fn(y, a, b) * co).astype(jnp.float32)),
            argnums=(0, 1, 2))(y, a, b)
        return l, grads

    return fwd, fwdbwd


def timeit(f, *args, n=30):
    r = f(*args)
    jax.block_until_ready(r)
    # value-fetch barrier (docs/RESULTS.md 4c: block_until_ready can lie here)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    leaf = jax.tree.leaves(r)[0]
    _ = float(jnp.sum(leaf.astype(jnp.float32)))
    return (time.perf_counter() - t0) / n * 1000


def check(fus_fwd, fus_fb, ref_fwd, ref_fb, y, a, b, co):
    """On-chip correctness gate before any timing ships. bf16 storage
    tolerances (2e-2 values / 3e-1 grad atol) — identical to the round-5
    A/B gate; the bf16-pool lever stays within them because the stored
    output is bf16-rounded either way."""
    rf = ref_fwd(y, a, b)
    ff = fus_fwd(y, a, b)
    np.testing.assert_allclose(
        np.asarray(rf, np.float32), np.asarray(ff, np.float32), rtol=2e-2, atol=2e-2
    )
    _, gr = ref_fb(y, a, b, co)
    _, gf = fus_fb(y, a, b, co)
    for u, v in zip(gr, gf):
        np.testing.assert_allclose(
            np.asarray(u, np.float32), np.asarray(v, np.float32), rtol=3e-2, atol=3e-1
        )


def bench_default(n: int) -> None:
    y, a, b, co = _data()
    ref_fwd, ref_fb = make(lambda y, a, b: _reference_impl(y, a, b))
    fus_fwd, fus_fb = make(lambda y, a, b: stem_affine_relu_pool(y, a, b))
    check(fus_fwd, fus_fb, ref_fwd, ref_fb, y, a, b, co)
    print("on-chip correctness OK")
    print(f"ref  fwd: {timeit(ref_fwd, y, a, b, n=n):8.3f} ms")
    print(f"fused fwd: {timeit(fus_fwd, y, a, b, n=n):8.3f} ms")
    print(f"ref  fwd+bwd: {timeit(ref_fb, y, a, b, co, n=n):8.3f} ms")
    print(f"fused fwd+bwd: {timeit(fus_fb, y, a, b, co, n=n):8.3f} ms")


def bench_levers(n: int) -> None:
    y, a, b, co = _data()
    ref_fwd, ref_fb = make(lambda y, a, b: _reference_impl(y, a, b))
    jax.block_until_ready(ref_fwd(y, a, b))
    # Each row must measure EXACTLY its config: ambient MPT_STEM_* vars
    # (e.g. a lever the operator exported while experimenting) would
    # otherwise contaminate every row including the r5-default baseline.
    # Snapshot them, clear before each config, restore when done.
    gate_keys = sorted({k for _, env in LEVER_CONFIGS for k in env})
    ambient = {k: os.environ.get(k) for k in gate_keys}
    try:
        for label, env in LEVER_CONFIGS:
            for k in gate_keys:
                os.environ.pop(k, None)
            os.environ.update(env)
            try:
                fus_fwd, fus_fb = make(lambda y, a, b: stem_affine_relu_pool(y, a, b))
                check(fus_fwd, fus_fb, ref_fwd, ref_fb, y, a, b, co)
                row = {
                    "metric": f"fused stem ms (B={B}, {H}x{W}x{C}, bf16)",
                    "label": label,
                    "env": env,
                    "fwd_ms": round(timeit(fus_fwd, y, a, b, n=n), 3),
                    "fwdbwd_ms": round(timeit(fus_fb, y, a, b, co, n=n), 3),
                }
            except Exception as e:  # a rejected lever is still a table row
                row = {
                    "metric": f"fused stem ms (B={B}, {H}x{W}x{C}, bf16)",
                    "label": label,
                    "env": env,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            print(json.dumps(row), flush=True)
    finally:
        for k, v in ambient.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--levers", action="store_true",
                    help="one JSON row per §4d byte-bound lever config "
                    "(correctness-gated A/B vs the r5-default kernel)")
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    if args.levers:
        bench_levers(args.steps)
    else:
        bench_default(args.steps)
