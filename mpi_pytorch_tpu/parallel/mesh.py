"""Device mesh construction and sharding rules.

The reference's process model — N MPI ranks, each a full model replica
(``main.py:16-18``) — becomes one global ``jax.sharding.Mesh`` with a
``data`` axis (DP, ≙ MPI ranks) and a ``model`` axis (TP). The reference has
no tensor parallelism (SURVEY §2c), but its 64 500-class head is the one
layer where sharding matters (512×64500 ≈ 33 M params for resnet18, ~25% of
the model): the ``model`` axis column-shards exactly that head, as a config
change (``--mesh.model-parallel N``).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_pytorch_tpu.config import MeshConfig


def create_mesh(cfg: MeshConfig, devices: list | None = None) -> Mesh:
    """Build a (data, model[, pipe]) mesh over all devices (or the given
    ones). The ``pipe`` axis exists only when ``pipe_parallel > 1``
    (--pp-stages), so 2-axis layouts — and everything keyed on
    ``axis_names[0] == data`` / ``axis_names[1] == model`` — are untouched.
    Pipe is the LAST reshape axis: consecutive pipeline stages land on
    adjacent devices, so the stage→stage ``ppermute`` rides neighbor ICI
    links.

    ``pods > 1`` (--mesh-pods, ISSUE 15 / ROADMAP item 5) FACTORS the data
    axis into the nested ``(pod, ici)`` pair instead: the mesh becomes
    ``(pod, ici, model)`` with ``pod`` as the MAJOR reshape axis, so every
    ``ici`` group is a contiguous run of devices — and, multi-host, a
    contiguous run of whole processes — meaning the within-pod collectives
    never cross a pod boundary (ICI stays ICI, and only the ``pod`` axis
    rides the DCN). Flat meshes (pods == 1) are byte-identical to before."""
    from mpi_pytorch_tpu.utils.env import fault_countdown

    if fault_countdown("MPT_FAULT_BACKEND_WEDGE_N"):
        # The wedged-backend-init scenario (bench history: rounds r02/r05,
        # rc=3): deterministic, in-process, absorbed by the resume-side
        # retry loop (train/elastic.with_retries).
        raise RuntimeError(
            "injected fault: backend init wedged (MPT_FAULT_BACKEND_WEDGE_N)"
        )
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    mp, pp = cfg.model_parallel, cfg.pipe_parallel
    if n % (mp * pp) != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallel={mp} x pipe_parallel={pp}"
        )
    dp = cfg.data_parallel if cfg.data_parallel > 0 else n // (mp * pp)
    if dp * mp * pp != n:
        raise ValueError(
            f"data_parallel×model_parallel×pipe_parallel = {dp}×{mp}×{pp} "
            f"!= {n} devices"
        )
    if cfg.pods > 1:
        if pp > 1:
            raise ValueError(
                "mesh pods (hierarchical data axis) does not compose with "
                "pipe_parallel — the pipe axis claims the trailing reshape "
                "position the nested layout needs"
            )
        if dp % cfg.pods != 0:
            raise ValueError(
                f"data-parallel size {dp} not divisible by pods={cfg.pods}; "
                "the data axis factors as pods × ici"
            )
        ici = dp // cfg.pods
        per_pod = ici * mp
        local = jax.local_device_count()
        if jax.process_count() > 1 and per_pod % local != 0:
            raise ValueError(
                f"each pod spans {per_pod} device(s) but processes hold "
                f"{local}; a process may not straddle a pod boundary "
                "(pods are whole hosts on separate DCN domains)"
            )
        arr = np.asarray(devices).reshape(cfg.pods, ici, mp)
        return Mesh(arr, (cfg.pod_axis, cfg.ici_axis, cfg.model_axis))
    if pp == 1:
        arr = np.asarray(devices).reshape(dp, mp)
        return Mesh(arr, (cfg.data_axis, cfg.model_axis))
    arr = np.asarray(devices).reshape(dp, mp, pp)
    return Mesh(arr, (cfg.data_axis, cfg.model_axis, cfg.pipe_axis))


def create_serve_mesh(shard_degree: int, devices: list | None = None) -> Mesh:
    """The nested ``(data, model)`` SERVE mesh (ISSUE 17): ``model`` spans
    ``shard_degree`` chips (one tenant's TP/FSDP split), ``data`` the rest
    (distinct batch rows — and, fleet-wise, distinct tenants — land on
    distinct data-slices). The axis names are FIXED to the trainer defaults
    so every helper below (``data_axis_names``, ``model_axis_name``,
    ``shard_first_divisible``) reads a serve mesh exactly like a flat
    training mesh — PR 15's axis-name discipline, reused rather than
    reinvented. ``shard_degree == 1`` is the degenerate replicated layout
    (``(n, 1)``, identical to ``serve.server.local_replica_mesh``)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    k = int(shard_degree)
    if k < 1:
        raise ValueError(f"serve shard degree must be >= 1, got {shard_degree}")
    if n % k != 0:
        raise ValueError(
            f"{n} device(s) not divisible by serve shard degree {k}; a "
            "sharded tenant occupies exactly K chips per data-slice"
        )
    arr = np.asarray(devices).reshape(n // k, k)
    return Mesh(arr, (SERVE_DATA_AXIS, SERVE_MODEL_AXIS))


def create_pipe_serve_mesh(stages: int, devices: list | None = None) -> Mesh:
    """The nested ``(data, pipe)`` SERVE mesh (ISSUE 20): ``pipe`` spans
    ``stages`` chip groups — stage ``s`` of a pipeline tenant owns column
    ``s`` (``mesh.devices[:, s]``), ``data`` the ``n // stages`` chips
    within each stage group (distinct micro-batch rows). Like the serve
    ``(data, model)`` mesh the axis names are FIXED: residency records and
    the planner's per-chip byte arithmetic key on the literal ``"pipe"``
    axis, which is reserved exactly like ``pod``/``ici`` (MeshConfig
    rejects configurable axes claiming it)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    k = int(stages)
    if k < 2:
        raise ValueError(
            f"pipeline serve mesh needs >= 2 stages, got {stages}"
        )
    if n % k != 0:
        raise ValueError(
            f"{n} device(s) not divisible by pipe stage count {k}; each "
            "stage occupies an equal disjoint chip group"
        )
    arr = np.asarray(devices).reshape(n // k, k)
    return Mesh(arr, (SERVE_DATA_AXIS, SERVE_PIPE_AXIS))


# ---------------------------------------------------------------------------
# Nested (hierarchical) data-axis helpers — the one vocabulary every layer
# keys the pod/ici factoring on, so "is this mesh hierarchical" can never
# drift between the step, the state sharder, and the trainer.
# ---------------------------------------------------------------------------

# Serve-mesh axis names are FIXED like the pod/ici pair (not MeshConfig-
# renameable): residency records, the packing planner's per-chip byte
# arithmetic, and the reshard path all key on them.
SERVE_DATA_AXIS, SERVE_MODEL_AXIS = "data", "model"

# The pipeline-stage axis of the nested (data, pipe) serve mesh (ISSUE 20).
# Reserved: stage chip-group membership, interstage ledger booking, and the
# planner's stage byte arithmetic all key on the literal name.
SERVE_PIPE_AXIS = "pipe"

# The nested data-axis names are FIXED (unlike the flat axis, which
# MeshConfig can rename): the traffic ledger classifies collectives by
# whether they touch "pod", and a renamed pod axis would silently book DCN
# traffic as ICI.
POD_AXIS, ICI_AXIS = "pod", "ici"


def is_hierarchical(mesh: Mesh) -> bool:
    """Whether ``mesh`` carries the nested ``(pod, ici)`` data factoring."""
    return POD_AXIS in mesh.axis_names and ICI_AXIS in mesh.axis_names


def data_axis_names(mesh: Mesh) -> tuple[str, ...]:
    """The batch-sharding axes, major→minor: ``("pod", "ici")`` on a nested
    mesh, ``(axis_names[0],)`` on a flat one. Everything that shards a batch
    dimension (or psums a per-shard scalar globally) reduces over exactly
    this tuple."""
    if is_hierarchical(mesh):
        return (POD_AXIS, ICI_AXIS)
    return (mesh.axis_names[0],)


def data_axis_size(mesh: Mesh) -> int:
    """Total data-parallel shard count (pods × ici on a nested mesh)."""
    size = 1
    for a in data_axis_names(mesh):
        size *= int(mesh.shape[a])
    return size


def pod_shape(mesh: Mesh) -> tuple[int, int]:
    """``(pods, ici)`` — ``(1, data_size)`` on a flat mesh, so flat-mesh
    callers can treat every mesh as one pod."""
    if is_hierarchical(mesh):
        return int(mesh.shape[POD_AXIS]), int(mesh.shape[ICI_AXIS])
    return 1, data_axis_size(mesh)


def zero_shard_axis(mesh: Mesh) -> tuple[str, int]:
    """``(axis_name, n_shards)`` the ZeRO optimizer-state partition keys on:
    the ``ici`` axis on a nested mesh — shards place WITHIN a pod, each pod
    holding a full (pod-replicated) copy, so the param all_gather that
    reassembles full weights every step never touches the DCN — and the
    whole data axis on a flat one."""
    if is_hierarchical(mesh):
        return ICI_AXIS, int(mesh.shape[ICI_AXIS])
    axis = mesh.axis_names[0]
    return axis, int(mesh.shape[axis])


def model_axis_name(mesh: Mesh) -> str:
    """The TP axis: ``axis_names[2]`` on a nested ``(pod, ici, model)``
    mesh, ``axis_names[1]`` otherwise (flat 2-axis and pipe 3-axis alike)."""
    return mesh.axis_names[2] if is_hierarchical(mesh) else mesh.axis_names[1]


def mesh_topology(mesh: Mesh) -> dict:
    """The world shape of ``mesh`` as plain JSON-able data — the vocabulary
    of the checkpoint topology manifest and the ``kind="resume"`` record
    (train/elastic.py): device/process counts plus the per-axis sizes in
    axis order."""
    return {
        "device_count": int(mesh.devices.size),
        "process_count": int(jax.process_count()),
        "mesh_axes": list(mesh.axis_names),
        "mesh_shape": {str(a): int(mesh.shape[a]) for a in mesh.axis_names},
    }


def describe_topology(topo: dict | None) -> str:
    """``"8 devices (data=8, model=1)"`` — the human rendering of a
    ``mesh_topology`` dict for logs and resume records; legacy (None) reads
    as unknown."""
    if not topo:
        return "unknown (legacy checkpoint, no manifest)"
    axes = ", ".join(f"{a}={s}" for a, s in topo.get("mesh_shape", {}).items())
    return f"{topo.get('device_count', '?')} devices ({axes})"


def flat_mesh(mesh: Mesh, axis: str) -> Mesh:
    """A one-axis mesh over the SAME devices as ``mesh``, for the in-model
    SP/EP wrappers (they shard sequence/experts over their own axis name
    while the surrounding step stays batch-sharded over ``data``)."""
    devices = mesh.devices.reshape(-1)
    return Mesh(np.asarray(devices).reshape(len(devices), 1), (axis, "_"))


def is_head_kernel(path_keys: tuple) -> tuple[bool, bool]:
    """(is_head_param, is_kernel) for a param path. Head layers are named
    ``head``/``aux_head`` across the whole zoo (models/common.py)."""
    keys = [str(getattr(k, "key", k)) for k in path_keys]
    is_head = any(k in ("head", "aux_head") for k in keys)
    return is_head, keys[-1] == "kernel"


def shard_first_divisible(shape, axis_name: str, size: int) -> P:
    """The ZeRO shard-selection rule, shared by FSDP param placement and the
    ZeRO-1 moment placement (train/step.py): shard the FIRST dimension that
    divides evenly by the axis size; no divisible dim → replicate."""
    for i, dim in enumerate(shape):
        if dim > 0 and dim % size == 0:
            return P(*([None] * i + [axis_name] + [None] * (len(shape) - i - 1)))
    return P()


def param_specs(params: Any, mesh: Mesh, fsdp: bool = False) -> Any:
    """PartitionSpecs for a param tree: classifier-head kernels column-sharded
    over the ``model`` axis (Megatron-style vocab-parallel classifier), head
    bias sharded likewise, everything else replicated (pure DP).

    ``fsdp`` (ZeRO-3-style, beyond reference parity): every param that would
    be replicated is instead sharded over the ``data`` axis on its first
    evenly-divisible dimension. At rest each device then holds 1/n of the
    weights; inside the jitted step XLA all-gathers each layer's weights just
    before use and reduce-scatters its gradient — the compiler-native form of
    fully-sharded data parallelism. Params with no divisible axis (small
    biases, BN scales) stay replicated."""
    model_axis = model_axis_name(mesh)
    data_axis, data_size = mesh.axis_names[0], mesh.shape[mesh.axis_names[0]]

    def spec(path, leaf):
        is_head, is_kernel = is_head_kernel(path)
        if not is_head or mesh.shape[model_axis] == 1:
            if fsdp and data_size > 1:
                return shard_first_divisible(leaf.shape, data_axis, data_size)
            return P()
        if is_kernel:
            # Dense kernel [in, out] or 1×1-conv kernel [kh, kw, in, out]:
            # shard the output (class) dim, provided it divides evenly.
            if leaf.shape[-1] % mesh.shape[model_axis] == 0:
                return P(*([None] * (leaf.ndim - 1) + [model_axis]))
            return P()
        if leaf.ndim == 1 and leaf.shape[0] % mesh.shape[model_axis] == 0:
            return P(model_axis)  # bias over classes
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def named_shardings(tree_specs: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_batch(batch: tuple, mesh: Mesh) -> tuple:
    """Place a host batch onto the mesh, batch axis over ``data`` — the
    scatter step (``main.py:91``) as a pure device placement.

    Multi-host: each host holds only its own shard of the global batch
    (per-host manifest sharding, trainer.build_training), so the global array
    is assembled from process-local data — no cross-host scatter traffic,
    unlike the reference's rank-0 pickled-dataframe scatter.

    Nested meshes shard the batch over BOTH data factors (``("pod",
    "ici")`` on dim 0) — pod-major, so shard (p, i) holds exactly the rows
    flat shard ``p*ici + i`` would (the property the hierarchical ≡ flat
    parity tests rest on)."""
    data_axis = data_axis_names(mesh)

    def put(x):
        spec = P(data_axis, *([None] * (x.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, batch)
