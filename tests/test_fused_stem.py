"""Pin the fused stem kernel (ops/fused_stem.py) to the unfused XLA
composition it replaces — values AND gradients, via the Pallas interpreter
on CPU (the same kernel code path the TPU compiles).

Reference semantics: ``max_pool3x3s2p1(relu(y·a + b))`` with f32 math
(≙ the torchvision resnet stem tail, reference ``models.py:30-45``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.ops.fused_stem import (
    _reference_impl,
    stem_affine_relu_pool,
)

B, H, W, C = 4, 16, 16, 64


def _inputs(rng, tie_heavy=False, dtype=jnp.float32):
    y = rng.standard_normal((B, H, W, C)).astype(np.float32)
    if tie_heavy:
        # Quantize hard so pool windows tie constantly (and relu produces
        # exact-zero plateaus) — the select-and-scatter tie-break regime.
        y = np.round(y * 2) / 2
    a = (0.5 + rng.random(C)).astype(np.float32)
    b = rng.standard_normal(C).astype(np.float32) * 0.1
    return jnp.asarray(y, dtype), jnp.asarray(a), jnp.asarray(b)


@pytest.mark.parametrize("tie_heavy", [False, True])
def test_forward_matches_reference(rng, tie_heavy):
    y, a, b = _inputs(rng, tie_heavy)
    got = stem_affine_relu_pool(y, a, b, interpret=True)
    want = _reference_impl(y, a, b)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("tie_heavy", [False, True])
def test_gradients_match_reference(rng, tie_heavy):
    y, a, b = _inputs(rng, tie_heavy)
    co = jnp.asarray(rng.standard_normal((B, H // 2, W // 2, C)), jnp.float32)

    def loss(fn):
        return lambda y, a, b: jnp.sum(fn(y, a, b) * co)

    gy, ga, gb = jax.grad(
        loss(lambda y, a, b: stem_affine_relu_pool(y, a, b, interpret=True)),
        argnums=(0, 1, 2),
    )(y, a, b)
    ry, ra, rb = jax.grad(loss(_reference_impl), argnums=(0, 1, 2))(y, a, b)
    np.testing.assert_allclose(gy, ry, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ga, ra, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(gb, rb, rtol=1e-5, atol=1e-4)


def test_bf16_storage_roundtrip(rng):
    """Production dtype: bf16 in/out, f32 compute inside the kernel."""
    y, a, b = _inputs(rng, dtype=jnp.bfloat16)
    got = stem_affine_relu_pool(y, a, b, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _reference_impl(y, a, b)
    np.testing.assert_allclose(
        got.astype(np.float32), want.astype(np.float32), rtol=2e-2, atol=2e-2
    )


def test_shape_guards(rng):
    y, a, b = _inputs(rng)
    with pytest.raises(ValueError):
        stem_affine_relu_pool(y[:, :15], a, b, interpret=True)
    with pytest.raises(ValueError):
        stem_affine_relu_pool(y, a[:3], b, interpret=True)


def test_module_runs_kernel_under_env_gate(rng, monkeypatch):
    """MPT_STEM_INTERPRET routes the module through the REAL kernel code
    path (Pallas interpreter) instead of the XLA fallback — the gate the
    whole-model CPU tests rely on."""
    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    from mpi_pytorch_tpu.models.common import FusedStemBNReluPool

    y = jnp.asarray(rng.standard_normal((B, H, W, C)), jnp.float32)
    m = FusedStemBNReluPool()
    v = m.init(jax.random.PRNGKey(0), y, True)
    out, _ = m.apply(v, y, False, mutable=["batch_stats"])
    monkeypatch.delenv("MPT_STEM_INTERPRET")
    want = m.apply(v, y, False, mutable=["batch_stats"])[0]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_fused_stem_training_matches_unfused(rng, monkeypatch, tmp_path):
    """TWO full sharded training epochs through the REAL kernel code path
    (Pallas interpreter) equal the unfused stem's epochs — the end-to-end
    integration pin: custom-VJP grads, BN stat updates, optimizer steps,
    checkpointing, all through the trainer."""
    import os

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.train.trainer import train

    def cfg(fused, sub):
        c = Config(
            model_name="resnet18", num_classes=200, batch_size=16,
            num_epochs=2, debug=True, debug_sample_size=64,
            synthetic_data=True, compute_dtype="float32",
            width=32, height=32, fused_stem=fused, validate=False,
            loader_workers=2, log_every_steps=0, metrics_file="",
            checkpoint_dir=os.path.join(str(tmp_path), sub),
            log_file=os.path.join(str(tmp_path), sub + ".log"),
        )
        c.validate_config()
        return c

    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    fused = train(cfg(True, "f"))
    monkeypatch.delenv("MPT_STEM_INTERPRET")
    plain = train(cfg(False, "p"))
    # Same data, same init, same seeds. Epoch 1 agrees to float tolerance;
    # later epochs drift at the usual chaotic-amplification rate of
    # correct-but-not-bit-identical op orderings (measured: 1e-6 after
    # epoch 1, 1e-3 after epoch 2) — gradient EXACTNESS is pinned tightly
    # in test_gradients_match_reference; this test pins the integration.
    np.testing.assert_allclose(
        fused.epoch_losses[:1], plain.epoch_losses[:1], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        fused.epoch_losses, plain.epoch_losses, rtol=1e-2, atol=1e-2
    )


def test_module_matches_unfused_stem(rng):
    """FusedStemBNReluPool ≡ batch_norm → relu → max_pool(3,2,1): same
    output, same batch_stats update, same eval-mode behavior, and the
    SAME variable tree (checkpoints interchange)."""
    from flax import linen as nn

    from mpi_pytorch_tpu.models.common import (
        FusedStemBNReluPool,
        batch_norm,
        max_pool,
    )

    class Unfused(nn.Module):
        @nn.compact
        def __call__(self, y, use_running_average):
            z = batch_norm("bn1")(y, use_running_average=use_running_average)
            return max_pool(nn.relu(z), 3, 2, padding=1)

    class Fused(nn.Module):
        @nn.compact
        def __call__(self, y, use_running_average):
            return FusedStemBNReluPool(name="bn1")(y, use_running_average)

    y = jnp.asarray(rng.standard_normal((B, H, W, C)), jnp.float32)
    uf, fu = Unfused(), Fused()
    vu = uf.init(jax.random.PRNGKey(0), y, True)
    vf = fu.init(jax.random.PRNGKey(0), y, True)
    assert jax.tree.structure(vu) == jax.tree.structure(vf)

    # Train mode: same output, same running-stat update (from shared params).
    ou, su = uf.apply(vu, y, False, mutable=["batch_stats"])
    of, sf = fu.apply(vu, y, False, mutable=["batch_stats"])
    np.testing.assert_allclose(ou, of, rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda x, z: np.testing.assert_allclose(x, z, rtol=1e-5, atol=1e-6),
        su["batch_stats"], sf["batch_stats"],
    )

    # Eval mode: running stats drive both identically.
    eu = uf.apply(vu, y, True)
    ef = fu.apply(vu, y, True)
    np.testing.assert_allclose(eu, ef, rtol=1e-5, atol=1e-5)

    # Gradients through the module (params + input) agree.
    def tloss(m):
        def f(params, y):
            out, _ = m.apply(
                {"params": params, "batch_stats": vu["batch_stats"]},
                y, False, mutable=["batch_stats"],
            )
            return jnp.sum(out * out)
        return f

    gu = jax.grad(tloss(uf), argnums=(0, 1))(vu["params"], y)
    gf = jax.grad(tloss(fu), argnums=(0, 1))(vu["params"], y)
    jax.tree.map(
        lambda x, z: np.testing.assert_allclose(x, z, rtol=1e-4, atol=1e-4),
        gu, gf,
    )
