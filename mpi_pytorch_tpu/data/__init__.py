from mpi_pytorch_tpu.data.manifest import (
    Manifest,
    build_label_map,
    load_manifests,
    manifest_fingerprint,
)
from mpi_pytorch_tpu.data.pipeline import DataLoader, decode_image, normalize_image, synthetic_image

__all__ = [
    "Manifest",
    "build_label_map",
    "load_manifests",
    "manifest_fingerprint",
    "DataLoader",
    "decode_image",
    "normalize_image",
    "synthetic_image",
]
