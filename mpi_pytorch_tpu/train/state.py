"""Train state: params + BN running stats + optimizer state + step + rng.

The reference's analogue is the (model, optimizer) pair of torch objects
(``main.py:121-125``) whose state lives implicitly in mutable modules. Here
it is one immutable pytree, which is what makes the whole step jittable and
shardable.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any  # None for BN-free models (alexnet, squeezenet)
    opt_state: Any
    rng: jax.Array
    # static (non-pytree) fields:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, variables: dict, tx, rng: jax.Array) -> "TrainState":
        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats"),
            opt_state=tx.init(params),
            rng=rng,
            apply_fn=apply_fn,
            tx=tx,
        )

    @property
    def variables(self) -> dict:
        v = {"params": self.params}
        if self.batch_stats is not None:
            v["batch_stats"] = self.batch_stats
        return v


# ---------------------------------------------------------------------------
# ZeRO-style optimizer-state sharding over the data axis (spmd mode)
#
# The spmd shard_map step's ``--zero-opt-state`` lever (train/step.py):
# every array leaf of the optimizer state is flatten-pad-reshaped to
# ``[P, chunk]`` (chunk = ceil(size/P)) and placed sharded over the data
# axis — each shard OWNS rows ``[k]``, i.e. a 1/P slice of every moment
# tensor, so per-device optimizer HBM drops from 2×params (adam mu+nu) to
# 2×params/P (PAPERS arXiv 2004.13336). Scalar leaves (Adam's count, lr-
# schedule steps) stay replicated: they are bytes-free and every shard's
# update needs them. The flatten-pad-reshape keeps the optax TREE STRUCTURE
# intact, which is what makes the sliced update exact: adam/adamw/sgd-
# momentum (and multi_transform's frozen-param masking) are elementwise per
# leaf, so updating slice k of every leaf and allgathering the param slices
# reproduces the replicated update bit-for-bit up to reduction order.
# ---------------------------------------------------------------------------


def zero_shard_spec(shape: tuple, n_shards: int) -> tuple[int, int] | None:
    """The ZeRO partition rule for one optimizer-state leaf: ``(chunk,
    padded)`` where ``chunk = ceil(size/P)`` and ``padded = chunk*P`` (the
    flat length after zero-padding), or None for scalars (replicated —
    nothing to shard, and Adam's count must stay exact on every shard)."""
    if not shape:
        return None
    size = 1
    for d in shape:
        size *= d
    chunk = -(-size // n_shards)
    return chunk, chunk * n_shards


# Jitted placement helpers, cached at module level so repeated sharding
# (trainer start, every checkpoint restore, bench cells) reuses ONE
# callable per configuration — a fresh jit closure per leaf would miss the
# jit cache every time and pay one XLA compile per optimizer leaf.


@functools.lru_cache(maxsize=None)
def _zero_reshape_fn(n_shards: int, chunk: int, padded: int, row_sharded):
    def reshape(x):
        flat = jnp.pad(x.reshape(-1), (0, padded - x.size))
        return flat.reshape(n_shards, chunk)

    return jax.jit(reshape, out_shardings=row_sharded)


@functools.lru_cache(maxsize=None)
def _replicated_gather_fn(repl):
    return jax.jit(lambda x: x, out_shardings=repl)


# Host leaves at or above this size take the per-row redistribution path in
# ``zero_shard_opt_state``: each shard's [chunk] slice is device_put onto
# its own device directly, so the peak transient HBM of placing the leaf is
# ONE chunk per device — never the full unsharded leaf the jitted-reshape
# path materializes. 4 MiB mirrors the checkpoint gather's big-leaf bound
# (checkpoint._BIG_LEAF_BYTES): the same leaves that gather alone on save
# redistribute chunked on restore.
_BOUNDED_LEAF_BYTES = 4 * 1024 * 1024


def redistribute_to(host_array, sharding):
    """The bounded-HBM placement core (arXiv 2112.01075's portable
    redistribution, host-staged): place each device's shard of ``sharding``
    directly from the host buffer (``make_array_from_single_device_arrays``)
    — the peak device-side transient is ONE shard, never the full array a
    plain ``device_put`` of the whole leaf would materialize, and each
    process places only its addressable shards (multi-host safe). Shared by
    the ZeRO reshard-on-load path below and the serve-side cross-topology
    residency reshard (``serve/sharding.py``), so "never a gather of the
    full tree" is one code path, not two disciplines."""
    shape = host_array.shape
    arrays = [
        jax.device_put(host_array[idx], dev)
        for dev, idx in sharding.addressable_devices_indices_map(shape).items()
    ]
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


def _row_redistribute(host_leaf, mesh, row_sharded, n_shards: int, chunk: int):
    """Chunked device redistribution of one HOST leaf into the
    ``zero_shard_spec`` ``[P, chunk]`` layout: pad on host, then place each
    data-axis row directly on the devices that own it (``redistribute_to``)
    — no device ever holds more than its own 1/P slice. The source here is
    always checkpoint bytes, so the host hop is already paid."""
    import numpy as np

    flat = np.asarray(host_leaf).reshape(-1)
    padded = np.zeros((n_shards, chunk), flat.dtype)
    padded.reshape(-1)[: flat.size] = flat
    return redistribute_to(padded, row_sharded)


def zero_shard_opt_state(opt_state: Any, mesh, bounded_bytes: int | None = None) -> Any:
    """Partition an optimizer state over ``mesh``'s data axis: array leaves
    become ``[P, chunk]`` jax Arrays sharded on dim 0 (each device holds one
    ``[1, chunk]`` row — 1/P of the leaf), scalars stay replicated. The
    placement runs through a jitted reshape with explicit out_shardings so
    it is multi-host safe (plain device_put of process-local numpy cannot
    target a cross-host sharding); leaves sharing a shape share one
    compiled reshape (mu/nu pairs, BN scale/bias — ``_zero_reshape_fn``).

    HOST leaves above ``bounded_bytes`` (an elastic restore's gathered-on-
    save checkpoint tree; default ``_BOUNDED_LEAF_BYTES``) bypass the jitted
    reshape for ``_row_redistribute``: the jitted path transiently
    materializes the full unsharded leaf on device before the sharded
    output exists, which at 2×params scale is exactly the HBM spike the
    sharding is meant to avoid — the per-row path bounds the transient to
    one chunk per device.

    On a NESTED ``(pod, ici)`` mesh (ISSUE 15) the shard index is the
    position on ``ici`` ONLY (``mesh.zero_shard_axis``): each pod holds a
    full pod-replicated copy of the [ici, chunk] layout, so the per-step
    param all_gather that reassembles full weights runs entirely within the
    pod and never crosses the DCN. Optimizer HBM is 2×params/ici per device
    instead of 2×params/(pods·ici) — the deliberate trade that keeps DCN
    off the critical path of every step."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_pytorch_tpu.parallel.mesh import zero_shard_axis

    data_axis, n_shards = zero_shard_axis(mesh)
    rep = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P(data_axis))
    cap = _BOUNDED_LEAF_BYTES if bounded_bytes is None else bounded_bytes

    def shard(leaf):
        if not hasattr(leaf, "ndim"):
            return leaf
        if leaf.ndim == 0:
            return jax.device_put(leaf, rep)
        chunk, padded = zero_shard_spec(np.shape(leaf), n_shards)
        if not isinstance(leaf, jax.Array) and leaf.size * leaf.dtype.itemsize > cap:
            return _row_redistribute(leaf, mesh, row_sharded, n_shards, chunk)
        return _zero_reshape_fn(n_shards, chunk, padded, row_sharded)(leaf)

    return jax.tree_util.tree_map(shard, opt_state)


def zero_unshard_opt_state(opt_state: Any, template: Any) -> Any:
    """Inverse of ``zero_shard_opt_state``, to HOST numpy: ``[P, chunk]``
    leaves → flat → strip padding → the template leaf's shape. ``template``
    is the unsharded optimizer-state structure (``jax.eval_shape(tx.init,
    params)`` — shapes only, zero device memory), so the result is exactly
    the layout an unsharded run checkpoints: gather-on-save keeps the
    on-disk format unchanged, and legacy checkpoints restore into either
    layout. Gathers one leaf at a time (the checkpoint memory discipline:
    peak transient cost is one leaf, never the whole 2×params state)."""
    import numpy as np

    def gather(leaf):
        # Multi-host: a data-sharded leaf is not process-addressable in
        # full; one tiny jitted replicated-gather makes it so. Single
        # process assembles directly from the addressable shards.
        if (
            isinstance(leaf, jax.Array)
            and not leaf.sharding.is_fully_replicated
            and jax.process_count() > 1
        ):
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(leaf.sharding.mesh, P())
            leaf = _replicated_gather_fn(repl)(leaf)
        return np.asarray(jax.device_get(leaf))

    def unshard(leaf, tmpl):
        if not hasattr(tmpl, "shape") or not hasattr(leaf, "ndim"):
            return leaf
        host = gather(leaf)
        if len(tmpl.shape) == 0:
            return host.reshape(())
        size = int(np.prod(tmpl.shape))
        return host.reshape(-1)[:size].reshape(tmpl.shape)

    return jax.tree_util.tree_map(unshard, opt_state, template)


def make_optimizer(
    learning_rate: float,
    trainable_mask: Any | None = None,
    *,
    optimizer: str = "adam",
    lr_schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int | None = None,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Optimizer factory. Defaults reproduce the reference exactly:
    Adam(lr) with a constant rate (≙ ``main.py:125``). Beyond parity:

    - ``optimizer``: ``adam`` | ``sgd`` (momentum 0.9) | ``adamw``
      (decoupled ``weight_decay``);
    - ``lr_schedule``: ``constant`` | ``cosine`` (decay to 0 over
      ``total_steps``) | ``warmup_cosine`` (linear warmup over
      ``warmup_steps`` then cosine) — schedules are optax schedule
      functions, evaluated inside the jitted step from the optimizer
      state's own step counter;
    - ``feature_extract``: with ``trainable_mask``, non-head params get
      zero updates — the optax expression of ``requires_grad=False``
      (reference ``models.py:5-13``).
    """
    if lr_schedule == "constant":
        lr: Any = learning_rate
    elif lr_schedule in ("cosine", "warmup_cosine"):
        if not total_steps or total_steps <= 0:
            raise ValueError(f"lr_schedule={lr_schedule!r} requires total_steps > 0")
        warmup = warmup_steps if lr_schedule == "warmup_cosine" else 0
        if warmup < 0:
            raise ValueError(f"warmup_steps must be >= 0, got {warmup}")
        if warmup >= total_steps:
            raise ValueError(
                f"warmup_steps ({warmup}) must be < the run's total step "
                f"count ({total_steps}); shorten the warmup or train longer"
            )
        if warmup > 0:
            lr = optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=learning_rate,
                warmup_steps=warmup, decay_steps=total_steps,
            )
        else:
            lr = optax.cosine_decay_schedule(learning_rate, decay_steps=total_steps)
    else:
        raise ValueError(
            f"lr_schedule must be constant|cosine|warmup_cosine, got {lr_schedule!r}"
        )

    if optimizer == "adam":
        tx = optax.adam(lr)
    elif optimizer == "sgd":
        tx = optax.sgd(lr, momentum=0.9)
    elif optimizer == "adamw":
        tx = optax.adamw(lr, weight_decay=weight_decay)
    else:
        raise ValueError(f"optimizer must be adam|sgd|adamw, got {optimizer!r}")

    if trainable_mask is None:
        return tx
    labels = jax.tree_util.tree_map(lambda t: "train" if t else "freeze", trainable_mask)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )
