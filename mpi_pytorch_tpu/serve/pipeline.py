"""Pipeline-parallel serving: stage-split executables with micro-batched
inter-stage handoff (ISSUE 20, ROADMAP item 2's stretch).

The source paper's second half is a 4-stage MPI inference pipeline — rank 0
reads, ranks resize/normalize, the rest run model replicas
(``evaluation_pipeline.py:162-199``). Its modern resurrection puts MODEL
stages on different chips: the ``pipe:K`` residency splits a zoo CNN at
registry-derived cut points into K stages (stem / trunk blocks / head — the
fused head kernel stays the last stage), lowers each stage as its own
per-bucket AOT executable on a disjoint chip group of the nested
``(data, pipe)`` serve mesh (``parallel.mesh.create_pipe_serve_mesh``), and
executes a flush as M micro-batches streamed through the stages: stage i
runs micro-batch m while stage i+1 runs m−1, so steady-state throughput is
bounded by the SLOWEST stage rather than the whole model.

Stage derivation is generic, not per-arch tables: a recording flax method
interceptor (``nn.intercept_methods``) traces the model once under
``jax.eval_shape`` and names every top-level submodule ``__call__`` in
execution order — every zoo arch presents a clean once-called chain ending
in the unit ``"head"``. Cut points balance cumulative param bytes across
the trunk stages; the head unit is always its own last stage, so the
64.5k-class logits slab (and the fused head kernel) only ever lives on the
head stage's chips. ``PIPE_CUT_OVERRIDES`` is the escape hatch for an arch
whose traced chain ever stops being linear.

Stage executables are carved from the SAME traced forward the single-chip
oracle runs: stage s's program re-traces the full ``apply_fn`` with an
inject interceptor replacing the previous stage's boundary unit (its output
becomes the stage input argument — everything upstream is dead code XLA
removes) and a capture interceptor returning this stage's boundary output.
Foreign param leaves are rebuilt as in-trace zeros constants, so each
compiled stage's argument bytes are exactly its own stage's params —
verified by compiled-executable arg-byte inspection, with bit-exact parity
against the unsplit forward.

The fill/drain bubble: with S stages and M micro-batches of equal stage
time, utilization is M/(M+S−1), i.e. a bubble fraction of (S−1)/(M+S−1)
(``pipeline_bubble_fraction`` — the GPipe arithmetic, arXiv 1811.06965;
the measure-then-overlap discipline of arXiv 1810.11112). Each flush stamps
the MEASURED bubble from per-stage dispatch walls, so a slow stage
(``MPT_FAULT_STAGE_DELAY_MS``) visibly inflates it, and per-stage tracing
spans let critical-path attribution name the bottleneck stage.

Inter-stage activation handoff is booked in the PR 15 traffic LEDGER at
build time (per-bucket, per-hop, one micro-batch's bytes — the book-at-
trace-time discipline), and every flush stamps the flowed total
(``interstage_bytes`` = Σ hop bytes × M) on its serve records.
"""

from __future__ import annotations

import time

import numpy as np

from mpi_pytorch_tpu.serve.batcher import parse_buckets

# Explicit per-arch stage plans: arch name → list of K unit-name lists.
# EMPTY by design — every current zoo arch derives a clean linear chain
# from the traced forward (tests pin this); an override only exists so a
# future non-linear arch fails toward an explicit table instead of a
# wrong generic cut.
PIPE_CUT_OVERRIDES: dict[str, list[list[str]]] = {}


def pipeline_bubble_fraction(stages: int, microbatches: int) -> float:
    """The GPipe fill/drain bubble under EQUAL stage times: S−1 of the
    M+S−1 schedule ticks are ramp, so the idle fraction is
    (S−1)/(M+S−1). M=1 degenerates to fully sequential (bubble
    (S−1)/S — each stage idles while the others run); M→∞ amortizes the
    ramp to zero. The measured per-flush stamp generalizes this to
    unequal stage times (see ``PipelineExecutables.__call__``)."""
    s, m = int(stages), int(microbatches)
    if s < 1 or m < 1:
        raise ValueError(f"need stages >= 1 and microbatches >= 1, got {stages}/{microbatches}")
    return (s - 1) / (m + s - 1)


def _key_name(entry) -> str | None:
    """The string key of one tree-path entry (DictKey/GetAttrKey), or None
    for positional entries (sequences, flattened indices)."""
    key = getattr(entry, "key", getattr(entry, "name", None))
    return key if isinstance(key, str) else None


def trace_units(apply_fn, variables, img_aval):
    """Name every top-level submodule in execution order, with its output
    aval, by abstractly tracing one forward under a recording interceptor.

    The two filters are load-bearing: ``method_name == "__call__"`` drops
    helper-method invocations (inception's Mixed blocks call branch
    helpers that would otherwise read as duplicate units), and
    ``len(path) == 1`` keeps only direct children of the top module. The
    result is the cut-point vocabulary: each unit's output is a legal
    stage boundary."""
    import jax
    from flax import linen as flax_nn

    units: list[tuple[str, object]] = []

    def record(next_fn, args, kwargs, context):
        out = next_fn(*args, **kwargs)
        if (
            context.method_name == "__call__"
            and len(context.module.path) == 1
            and hasattr(out, "shape")
        ):
            units.append(
                (context.module.path[0],
                 jax.ShapeDtypeStruct(tuple(out.shape), out.dtype))
            )
        return out

    def run(v, x):
        with flax_nn.intercept_methods(record):
            return apply_fn(v, x, train=False)

    jax.eval_shape(run, variables, img_aval)
    names = [n for n, _ in units]
    if len(set(names)) != len(names):
        raise ValueError(
            "top-level unit chain is not once-called "
            f"(duplicates in {names}); add a PIPE_CUT_OVERRIDES entry"
        )
    return units


def plan_stages(
    unit_names: list[str], unit_bytes: dict[str, int], stages: int,
    *, arch: str = "",
) -> list[list[str]]:
    """Split the ordered unit chain into ``stages`` contiguous groups.

    The final unit must be ``"head"`` and always becomes the last stage
    alone — the fused head kernel (and the [B, num_classes] logits slab)
    lives only on the head stage's chips. The remaining trunk units are
    balanced into the first K−1 stages by cumulative param bytes (greedy
    at the mean-bytes boundary, never leaving a later stage empty)."""
    if arch and arch in PIPE_CUT_OVERRIDES:
        plan = PIPE_CUT_OVERRIDES[arch]
        flat = [u for g in plan for u in g]
        if len(plan) != stages or flat != list(unit_names):
            raise ValueError(
                f"PIPE_CUT_OVERRIDES[{arch!r}] does not cover the traced "
                f"unit chain for {stages} stages"
            )
        return [list(g) for g in plan]
    k = int(stages)
    if k < 2:
        raise ValueError(f"a pipeline needs >= 2 stages, got {stages}")
    if not unit_names or unit_names[-1] != "head":
        raise ValueError(
            f"traced unit chain does not end in 'head' ({unit_names[-3:]}); "
            "add a PIPE_CUT_OVERRIDES entry for this arch"
        )
    trunk = list(unit_names[:-1])
    if len(trunk) < k - 1:
        raise ValueError(
            f"{len(unit_names)} top-level unit(s) cannot split into "
            f"{k} stages (each stage needs at least one unit)"
        )
    total = sum(unit_bytes.get(u, 0) for u in trunk) or 1
    target = total / (k - 1)
    plan: list[list[str]] = []
    group: list[str] = []
    gbytes = 0.0
    for i, u in enumerate(trunk):
        group.append(u)
        gbytes += unit_bytes.get(u, 0)
        left_units = len(trunk) - i - 1
        left_groups = (k - 1) - len(plan) - 1
        if left_groups > 0 and (gbytes >= target or left_units == left_groups):
            plan.append(group)
            group, gbytes = [], 0.0
    plan.append(group)
    plan.append([unit_names[-1]])
    return plan


def _capture(name: str, box: list):
    def interceptor(next_fn, args, kwargs, context):
        out = next_fn(*args, **kwargs)
        if context.method_name == "__call__" and context.module.path == (name,):
            box.append(out)
        return out

    return interceptor


def _inject(name: str, value):
    def interceptor(next_fn, args, kwargs, context):
        if context.method_name == "__call__" and context.module.path == (name,):
            # The boundary unit's output IS the stage input; next_fn is
            # never called, so everything feeding it is dead code.
            return value
        return next_fn(*args, **kwargs)

    return interceptor


class _BucketPlan:
    """Everything one bucket's flush needs, AOT-compiled at build time."""

    __slots__ = (
        "m_eff", "micro_rows", "in_shardings", "stage_exes", "concat",
        "hop_bytes",
    )

    def __init__(self):
        self.stage_exes = []
        self.in_shardings = []
        self.hop_bytes = []
        self.concat = None
        self.m_eff = 1
        self.micro_rows = 0


class PipelineExecutables:
    """Per-bucket pipeline-stage AOT executables over a stage-placed state.

    Duck-typed to ``BucketExecutables`` (the server/pool/parity surfaces:
    ``place``/``__call__``/``warmup``/``host_rows``/``compiles_since_
    warmup``/``rebaseline``/``reshard_stats``), plus the pipeline-only
    observability: ``last_flush()`` returns the just-executed flush's
    ``pipe_stages``/``microbatches``/``bubble_frac``/``interstage_bytes``/
    per-stage wall windows, and ``set_obs`` wires the metrics writer (the
    slow-stage fault gate's announce-once record) and the tracer (per-hop
    handoff instants).

    ``host_rows(bucket) == bucket``: micro-batch rows shard over the
    stage group's ``data`` chips when divisible and run replicated within
    the group otherwise — there is no degree padding, because a stage
    group serves whole micro-batch rows, never column-sharded params."""

    def __init__(
        self, cfg, state, mesh, *, logger=None, precision: str = "bf16",
        residency=None, prequantized: bool = False, microbatches=None,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_pytorch_tpu.evaluate import _make_predict_step, _row_sharding
        from mpi_pytorch_tpu.obs import compile_count, ensure_compile_listener
        from mpi_pytorch_tpu.ops.quantize import fused_head_gate
        from mpi_pytorch_tpu.parallel.collectives import LEDGER
        from mpi_pytorch_tpu.parallel.mesh import SERVE_PIPE_AXIS
        from mpi_pytorch_tpu.serve import sharding as shd

        if precision not in ("bf16", "int8"):
            raise ValueError(
                f"precision must be 'bf16' or 'int8', got {precision!r}"
            )
        if SERVE_PIPE_AXIS not in mesh.axis_names:
            raise ValueError(
                f"pipeline serving needs the nested (data, pipe) serve mesh "
                f"(create_pipe_serve_mesh), got axes {mesh.axis_names}"
            )
        n_stages = int(mesh.shape[SERVE_PIPE_AXIS])
        if residency is None:
            residency = shd.Residency("pipe", n_stages)
        if residency.kind != "pipe" or residency.degree != n_stages:
            raise ValueError(
                f"residency {residency} does not match the mesh pipe axis "
                f"(pipe={n_stages}); build the mesh with "
                f"create_pipe_serve_mesh({residency.degree})"
            )

        self.precision = precision
        self._mesh = mesh
        self.stages = n_stages
        self.residency = residency
        self.buckets = parse_buckets(cfg.parsed_serve_buckets())
        self.topk = int(cfg.serve_topk)
        self.fused_head = fused_head_gate(cfg)
        if self.fused_head and self.topk > 1:
            if logger is not None:
                logger.warning(
                    "--fused-head-eval streams argmax only: serving top-1 "
                    "instead of the requested serve_topk=%d", self.topk,
                )
            self.topk = 1
        compute_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
            cfg.compute_dtype
        ]
        if cfg.input_dtype == "bfloat16":
            import ml_dtypes

            self.image_dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            self.image_dtype = np.dtype(cfg.input_dtype)
        self.microbatches = int(
            microbatches if microbatches is not None
            else getattr(cfg, "serve_pipe_microbatches", 4)
        )
        if self.microbatches < 1:
            raise ValueError(
                f"microbatches must be >= 1, got {self.microbatches}"
            )

        if precision == "int8" and not prequantized:
            # Quantize the FULL state before splitting (same scales as the
            # unsplit int8 set — the shared seeded calibration batch), so
            # pipe:K int8 and replicated int8 can never disagree.
            from mpi_pytorch_tpu.ops import quantize as qz

            act_scale = (
                qz.calibrate_head_act_scale(
                    state, qz.calibration_batch(cfg), compute_dtype
                )
                if self.fused_head else 1.0
            )
            state = qz.quantize_state(
                state, keep_head_int8=self.fused_head, act_scale=act_scale
            )

        # --- stage submeshes: column s of the (data, pipe) device grid.
        # Built 2-D as ("data", "model") with model=1 so every mesh helper
        # (_row_sharding, model_axis_name, _make_predict_step) reads a
        # stage group exactly like a replicated serve mesh.
        devs = np.asarray(mesh.devices)
        from jax.sharding import Mesh

        self._stage_meshes = [
            Mesh(devs[:, s].reshape(-1, 1), ("data", "model"))
            for s in range(n_stages)
        ]
        group_chips = int(devs.shape[0])

        # --- cut plan, from one abstract trace of the model's own forward.
        self._image_hw = h, w = cfg.image_size
        img_probe = jax.ShapeDtypeStruct((1, h, w, 3), compute_dtype)
        units = trace_units(state.apply_fn, state.variables, img_probe)
        unit_names = [name for name, _ in units]
        params = state.variables.get("params", {})
        bstats = state.variables.get("batch_stats") or {}

        def _tree_bytes(tree) -> int:
            return sum(
                int(np.prod(np.shape(leaf))) * np.dtype(
                    getattr(leaf, "dtype", np.float32)
                ).itemsize
                for leaf in jax.tree_util.tree_leaves(tree)
            )

        unit_bytes = {
            u: _tree_bytes(params.get(u)) + _tree_bytes(bstats.get(u))
            for u in unit_names
        }
        self.stage_units = plan_stages(
            unit_names, unit_bytes, n_stages, arch=cfg.model_name
        )
        self._boundaries = [g[-1] for g in self.stage_units[:-1]]
        unit_to_stage = {
            u: s for s, g in enumerate(self.stage_units) for u in g
        }

        # --- leaf → stage partition + placement. Params/batch_stats keys
        # follow their unit's stage; a top-level DIRECT param leaf (e.g.
        # vit's pos_embed, read by inter-unit glue code whose stage is not
        # statically knowable) replicates on EVERY stage group; an
        # UNCALLED submodule subtree (inception's AuxLogits — eval-dead)
        # and the non-variable leaves (step/rng/opt_state) park on stage 0.
        leaves_p, treedef = jax.tree_util.tree_flatten_with_path(state)
        self._treedef = treedef

        def leaf_stage(path):
            keys = [_key_name(e) for e in path]
            for j, kname in enumerate(keys):
                if kname in ("params", "batch_stats"):
                    rest = keys[j + 1:]
                    if not rest:
                        return 0
                    if rest[0] in unit_to_stage:
                        return unit_to_stage[rest[0]]
                    if len(rest) == 1:
                        return "all"
                    return 0
            return 0

        stats = shd.ReshardStats(residency=str(residency))
        self._leaf_avals = []
        placed = []
        stage_arg_idx: list[list[int]] = [[] for _ in range(n_stages)]
        stage_args: list[list] = [[] for _ in range(n_stages)]
        is_variable = []

        def _place(leaf, sharding):
            if isinstance(leaf, jax.Array) and leaf.sharding == sharding:
                return leaf
            host = np.asarray(jax.device_get(leaf))
            stats.bytes_moved += host.nbytes * int(sharding.mesh.devices.size)
            stats.peak_chunk_bytes = max(stats.peak_chunk_bytes, host.nbytes)
            return jax.device_put(host, sharding)

        for i, (path, leaf) in enumerate(leaves_p):
            if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
                self._leaf_avals.append(leaf)
                placed.append(leaf)
                is_variable.append(False)
                continue
            self._leaf_avals.append(
                jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
            )
            stats.leaves += 1
            stats.sharded_leaves += 1
            s = leaf_stage(path)
            keys = [_key_name(e) for e in path]
            in_vars = any(k in ("params", "batch_stats") for k in keys)
            is_variable.append(in_vars)
            if s == "all":
                copies = [
                    _place(leaf, NamedSharding(m, P()))
                    for m in self._stage_meshes
                ]
                placed.append(copies[0])
                if in_vars:
                    for t in range(n_stages):
                        stage_arg_idx[t].append(i)
                        stage_args[t].append(copies[t])
            else:
                arr = _place(
                    leaf, NamedSharding(self._stage_meshes[s], P())
                )
                placed.append(arr)
                if in_vars:
                    stage_arg_idx[s].append(i)
                    stage_args[s].append(arr)
        self.reshard_stats = stats
        self._state = jax.tree_util.tree_unflatten(treedef, placed)
        self._stage_args = stage_args
        self._stage_arg_idx = stage_arg_idx

        # --- per-bucket stage executables + the preds-assembly concat,
        # all AOT. One activation trace per distinct micro-row count.
        int8_head = precision == "int8" and self.fused_head
        options = cfg.parsed_compiler_options()
        from flax import linen as flax_nn

        from mpi_pytorch_tpu.train.step import ingest_images

        def make_rebuild(arg_idx):
            avals = self._leaf_avals

            def rebuild(args):
                leaves = []
                it = iter(args)
                idx = set(arg_idx)
                for i, a in enumerate(avals):
                    if i in idx:
                        leaves.append(next(it))
                    elif isinstance(a, jax.ShapeDtypeStruct):
                        # Foreign leaf: an in-trace zeros constant XLA
                        # dead-code-eliminates — the compiled stage's arg
                        # bytes are exactly its own stage's params.
                        leaves.append(jnp.zeros(a.shape, a.dtype))
                    else:
                        leaves.append(a)
                return jax.tree_util.tree_unflatten(treedef, leaves)

            return rebuild

        rebuilds = [make_rebuild(stage_arg_idx[s]) for s in range(n_stages)]

        def act_avals_for(rows: int) -> dict[str, object]:
            probe = jax.ShapeDtypeStruct((rows, h, w, 3), compute_dtype)
            traced = trace_units(state.apply_fn, state.variables, probe)
            return dict(traced)

        def make_stage_fn(s: int, rows: int):
            rebuild = rebuilds[s]
            bound_in = self._boundaries[s - 1] if s > 0 else None
            bound_out = (
                self._boundaries[s] if s < n_stages - 1 else None
            )
            if s == n_stages - 1:
                predict = _make_predict_step(
                    self._stage_meshes[s], compute_dtype,
                    fused_head=self.fused_head, topk=self.topk,
                    int8_head=int8_head,
                )
                # Call the UNWRAPPED predict body. _make_predict_step
                # returns an @jax.jit function whose inner trace cache is
                # keyed on (identity, avals) — identical across buckets
                # with equal micro rows — so calling the wrapper inside
                # our per-bucket lowering would let bucket N reuse a
                # jaxpr traced under bucket 1's inject interceptor, with
                # that bucket's boundary tracer baked in as a constant.
                predict = getattr(predict, "__wrapped__", predict)

                def fn(args, a_in):
                    state2 = rebuild(args)
                    images = jnp.zeros((rows, h, w, 3), self.image_dtype)
                    labels = jnp.full((rows,), -1, jnp.int32)
                    with flax_nn.intercept_methods(_inject(bound_in, a_in)):
                        _, preds = predict(state2, (images, labels))
                    return preds

                return fn
            if s == 0:

                def fn(args, images):
                    state2 = rebuild(args)
                    x = ingest_images(images, compute_dtype)
                    box: list = []
                    with flax_nn.intercept_methods(_capture(bound_out, box)):
                        state2.apply_fn(state2.variables, x, train=False)
                    return box[0]

                return fn

            def fn(args, a_in):
                state2 = rebuild(args)
                x = jnp.zeros((rows, h, w, 3), compute_dtype)
                box: list = []
                with flax_nn.intercept_methods(_inject(bound_in, a_in)):
                    with flax_nn.intercept_methods(_capture(bound_out, box)):
                        state2.apply_fn(state2.variables, x, train=False)
                return box[0]

            return fn

        self._plans: dict[int, _BucketPlan] = {}
        act_cache: dict[int, dict] = {}
        for bucket in self.buckets:
            plan = _BucketPlan()
            m = max(
                (d for d in range(1, self.microbatches + 1) if bucket % d == 0),
                default=1,
            )
            plan.m_eff = m
            rows = plan.micro_rows = bucket // m
            if rows not in act_cache:
                act_cache[rows] = act_avals_for(rows)
            acts = act_cache[rows]
            for s in range(n_stages):
                stage_mesh = self._stage_meshes[s]
                row_sh = _row_sharding(stage_mesh, rows)
                arg_avals = [
                    jax.ShapeDtypeStruct(
                        a.shape, a.dtype,
                        sharding=NamedSharding(stage_mesh, P()),
                    )
                    for a in (self._leaf_avals[i] for i in stage_arg_idx[s])
                ]
                if s == 0:
                    in_aval = jax.ShapeDtypeStruct(
                        (rows, h, w, 3), self.image_dtype, sharding=row_sh
                    )
                else:
                    b_aval = acts[self._boundaries[s - 1]]
                    in_aval = jax.ShapeDtypeStruct(
                        b_aval.shape, b_aval.dtype, sharding=row_sh
                    )
                    hop = int(np.prod(b_aval.shape)) * np.dtype(
                        b_aval.dtype
                    ).itemsize
                    if len(plan.hop_bytes) < s:
                        plan.hop_bytes.append(hop)
                        # Book the hop at build time (the PR 15 trace-time
                        # discipline): one micro-batch's activation bytes
                        # ride the within-pod fabric per handoff.
                        LEDGER.add("ici", "pipe_handoff", hop)
                plan.in_shardings.append(in_aval.sharding)
                fn = make_stage_fn(s, rows)
                plan.stage_exes.append(
                    jax.jit(fn)
                    .lower(arg_avals, in_aval)
                    .compile(compiler_options=options)
                )
            # Preds assembly compiles AT BUILD TIME too (the zero-steady-
            # state-compile invariant covers the concat): its input avals
            # carry the head-stage executable's OWN output sharding, so
            # the compiled concat accepts the stage output verbatim.
            preds_sh = plan.stage_exes[-1].output_shardings
            micro_pred = jax.eval_shape(
                make_stage_fn(n_stages - 1, rows),
                [self._leaf_avals[i] for i in stage_arg_idx[n_stages - 1]],
                jax.ShapeDtypeStruct(
                    acts[self._boundaries[-1]].shape,
                    acts[self._boundaries[-1]].dtype,
                ),
            )
            concat_avals = [
                jax.ShapeDtypeStruct(
                    micro_pred.shape, micro_pred.dtype, sharding=preds_sh
                )
            ] * m
            plan.concat = (
                jax.jit(lambda xs: jnp.concatenate(xs, axis=0))
                .lower(concat_avals)
                .compile(compiler_options=options)
            )
            self._plans[bucket] = plan
        self._group_chips = group_chips

        self._metrics = None
        self._tracer = None
        self._fault_announced = False
        self._last = None
        ensure_compile_listener()
        self._compile_count = compile_count
        self._baseline = compile_count()
        self._warm = False

    # --- BucketExecutables duck-type surface -------------------------------

    @property
    def shard_degree(self) -> int:
        """Chips one copy of this set's params spans — the K stage groups
        jointly hold one copy, so the pipe degree."""
        return self.residency.degree

    def host_rows(self, bucket: int) -> int:
        return bucket

    def interstage_bytes_per_flush(self) -> int:
        """Worst-case (max over buckets) inter-stage activation bytes one
        flush moves: Σ hop_bytes × its bucket's micro-batch count — what a
        retune record quotes as the conversion's steady-state traffic
        price."""
        return max(
            (
                int(sum(p.hop_bytes)) * p.m_eff
                for p in self._plans.values()
            ),
            default=0,
        )

    def set_obs(self, *, metrics=None, tracer=None) -> None:
        """Wire the serve observability surfaces: ``metrics`` receives the
        slow-stage fault gate's announce-once record, ``tracer`` the
        per-hop handoff instants."""
        if metrics is not None:
            self._metrics = metrics
        if tracer is not None:
            self._tracer = tracer

    def place(self, images: np.ndarray, labels: np.ndarray):
        """Host batch → M micro-batches on the stage-0 group (async
        device_puts; labels are unused — the predict step runs on
        constant −1 labels and serving discards the metrics)."""
        import jax

        plan = self._plans[images.shape[0]]
        imgs = images.astype(self.image_dtype, copy=False)
        r = plan.micro_rows
        return [
            jax.device_put(imgs[i * r:(i + 1) * r], plan.in_shardings[0])
            for i in range(plan.m_eff)
        ]

    def _announce_fault(self, delay_ms: int, stage: int) -> None:
        if self._fault_announced:
            return
        self._fault_announced = True
        if self._metrics is not None:
            self._metrics.write({
                "kind": "fault",
                "reason": "injected_stage_delay",
                "detail": (
                    f"sleeping {delay_ms}ms in pipeline stage {stage}'s "
                    f"dispatch window every flush "
                    f"(MPT_FAULT_STAGE_DELAY_MS)"
                ),
            })

    def __call__(self, bucket: int, device_batch):
        """Stream the flush's M micro-batches through the S stages in
        schedule-tick order — stage s dispatches micro m at tick s+m, all
        dispatches async, each hop an async ``device_put`` onto the next
        stage's input sharding. Returns the AOT-concatenated preds array.

        The flush stamp: per-stage dispatch walls t_s feed the measured
        generalization of the GPipe bubble — T = Σt_s + (M−1)·max t_s,
        busy = M·Σt_s, bubble = 1 − busy/(S·T) — which reduces exactly to
        ``pipeline_bubble_fraction`` under equal stage times and grows
        when one stage lags (the slow-stage drill's observable)."""
        import jax

        from mpi_pytorch_tpu.utils.env import env_int

        plan = self._plans[bucket]
        S = self.stages
        M = plan.m_eff
        delay_ms = env_int("MPT_FAULT_STAGE_DELAY_MS", 0)
        target = env_int("MPT_FAULT_STAGE_DELAY_STAGE", -1)
        if target < 0 or target >= S:
            target = S - 1
        delayed = False
        outs = [[None] * M for _ in range(S)]
        stage_s = [0.0] * S
        windows: list[list] = [[None, None] for _ in range(S)]
        for tick in range(M + S - 1):
            for s in range(min(tick, S - 1), -1, -1):
                m = tick - s
                if m < 0 or m >= M:
                    continue
                t0 = time.monotonic()
                if delay_ms > 0 and s == target and not delayed:
                    delayed = True
                    self._announce_fault(delay_ms, s)
                    time.sleep(delay_ms / 1000.0)
                inp = device_batch[m] if s == 0 else outs[s - 1][m]
                out = plan.stage_exes[s](self._stage_args[s], inp)
                if s < S - 1:
                    out = jax.device_put(out, plan.in_shardings[s + 1])
                    if self._tracer is not None:
                        self._tracer.instant(
                            "serve/pipe_handoff",
                            args={
                                "hop": s, "micro": m,
                                "bytes": plan.hop_bytes[s],
                            },
                        )
                outs[s][m] = out
                t1 = time.monotonic()
                stage_s[s] += t1 - t0
                if windows[s][0] is None:
                    windows[s][0] = t0
                windows[s][1] = t1
        preds = plan.concat(outs[S - 1])
        total = sum(stage_s)
        t_max = max(stage_s)
        span = total + (M - 1) * t_max
        bubble = 1.0 - (M * total) / (S * span) if span > 0 else 0.0
        self._last = {
            "pipe_stages": S,
            "microbatches": M,
            "bubble_frac": round(max(0.0, bubble), 6),
            "interstage_bytes": int(sum(plan.hop_bytes)) * M,
            "stage_ms": [round(t * 1000.0, 3) for t in stage_s],
            "stage_windows": [tuple(wnd) for wnd in windows],
        }
        return preds

    def last_flush(self) -> dict | None:
        """The most recent flush's pipeline stamp (None before traffic):
        ``pipe_stages``/``microbatches``/``bubble_frac``/
        ``interstage_bytes`` plus per-stage dispatch-wall windows in
        ``time.monotonic`` seconds (the server converts them to its span
        clock for the per-stage trace spans)."""
        return self._last

    def warmup(self) -> None:
        import jax

        h, w = self._image_hw
        for bucket in self.buckets:
            images = np.zeros((bucket, h, w, 3), self.image_dtype)
            labels = np.full((bucket,), -1, np.int32)
            preds = self(bucket, self.place(images, labels))
            jax.block_until_ready(preds)
        self._baseline = self._compile_count()
        self._warm = True

    @property
    def warm(self) -> bool:
        return self._warm

    def compiles_since_warmup(self) -> int:
        return self._compile_count() - self._baseline

    def rebaseline(self) -> None:
        self._baseline = self._compile_count()
