"""Elastic training: mesh-shape-change resume, preemption watchdog, and the
in-process halves of the fault-injection harness (ISSUE 7 / ROADMAP item 4).

Production fleets lose and gain chips — this repo's own bench history shows
it (rounds r02 and r05 died on a wedged TPU backend). The reference handles
every failure the same way: a human restarts ``main.py`` with
``FROM_CHECKPOINT=True`` onto the SAME MPI world (``main.py:127-130``).
This module generalizes that into a self-healing loop:

- **Topology manifest** — every checkpoint is stamped with the writer's
  world shape (device/process counts, dp×mp mesh shape, the ZeRO
  ``[P, chunk]`` shard layout per optimizer leaf, payload schema version)
  as a JSON sidecar (``checkpoint.write_manifest``), so a restore knows
  what it is resharding FROM without trusting the payload.

- **Reshard-on-load** (``restore_latest``) — a checkpoint written on mesh
  shape A restores onto mesh shape B. The on-disk payload is always the
  gathered (unsharded) host layout (``zero_unshard_opt_state``
  gather-on-save), so resharding is a placement problem: replicated leaves
  are re-placed, sharded leaves re-split for the new axis sizes, and ZeRO
  opt-state leaves re-flattened/re-padded/re-chunked for the new P
  (``zero_shard_opt_state`` — including the P→1 and 1→P degenerate cases).
  Small leaves batch through one jitted reshape; leaves past the bounded-
  HBM cap take the chunked per-row redistribution
  (``state._row_redistribute``) so no device ever transiently holds a full
  unsharded moment tensor. A corrupt/truncated newest checkpoint logs a
  ``kind="anomaly"`` record and falls back to the previous one.

- **Preemption watchdog** (``PreemptionWatchdog``) — generalizes the
  SIGTERM-only ``PreemptionGuard``: a sentinel file (``MPT_PREEMPT_FILE``,
  the cluster-scheduler preemption-notice pattern) or repeated health
  signals (straggler-beat / non-finite-grad streaks from ``obs/``) trigger
  the same safe-boundary save + clean exit, each writing a ``kind="fault"``
  record naming the reason.

- **Bounded retry+backoff** (``with_retries``) — the resume side retries
  backend init and state placement a bounded number of times with
  deterministic exponential backoff, absorbing transient wedges instead of
  dying on the first one.

- **Fault injection** (``FaultInjector`` + the ``MPT_FAULT_*`` gates in
  ``utils/env.py``, driven by ``tools/inject_faults.py``) — deterministic
  mid-step kills and fake stragglers, so the recovery paths above are
  testable end to end on a CPU mesh.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any

import jax

from mpi_pytorch_tpu import checkpoint as ckpt
from mpi_pytorch_tpu.parallel.mesh import (
    describe_topology,
    mesh_topology,
    zero_shard_axis,
)
from mpi_pytorch_tpu.train.state import _BOUNDED_LEAF_BYTES, zero_shard_spec
from mpi_pytorch_tpu.train.step import place_state_on_mesh
from mpi_pytorch_tpu.utils.env import env_int, fault_countdown
from mpi_pytorch_tpu.utils.logging import process_index, run_logger

MANIFEST_VERSION = 1


# ---------------------------------------------------------------------------
# Topology manifest
# ---------------------------------------------------------------------------


def zero_shard_layout(opt_template: Any, n_shards: int) -> dict:
    """Per-leaf ZeRO partition table for the manifest: key-path →
    ``[chunk, padded]`` (``zero_shard_spec``), or None for replicated
    scalars. ``opt_template`` is the unsharded optimizer layout
    (``jax.eval_shape(tx.init, params)``)."""
    layout = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(opt_template)
    for path, leaf in flat:
        if not hasattr(leaf, "shape"):
            continue
        layout[jax.tree_util.keystr(path)] = zero_shard_spec(tuple(leaf.shape), n_shards)
    return layout


def topology_manifest(
    mesh,
    *,
    zero_opt_state: bool = False,
    spmd_mode: bool = False,
    opt_template: Any = None,
) -> dict:
    """The JSON-able topology stamp every checkpoint of this run carries
    (``checkpoint.write_manifest`` sidecar): world shape, payload schema,
    and — for ZeRO runs — the writer's per-leaf shard layout, so a restore
    can state exactly what it resharded from P_old to P_new."""
    manifest = {
        "manifest_version": MANIFEST_VERSION,
        "payload_schema": ckpt.PAYLOAD_SCHEMA,
        **mesh_topology(mesh),
        "zero_opt_state": bool(zero_opt_state),
        "spmd_mode": bool(spmd_mode),
    }
    if zero_opt_state:
        # The ZeRO partition axis: within-pod (ici) on a nested mesh —
        # matches what zero_shard_opt_state actually chunked to, so a
        # restore states the true P_old (parallel/mesh.zero_shard_axis).
        _, n_shards = zero_shard_axis(mesh)
        manifest["zero_shards"] = n_shards
        if opt_template is not None:
            manifest["zero_shard_layout"] = zero_shard_layout(opt_template, n_shards)
    return manifest


# ---------------------------------------------------------------------------
# Reshard-on-load restore with corruption fallback
# ---------------------------------------------------------------------------


def restore_latest(
    ckpt_dir: str,
    state: Any,
    mesh,
    *,
    metrics=None,
    logger=None,
    zero_shards_to: int = 0,
):
    """Restore the newest LOADABLE checkpoint in ``ckpt_dir`` against
    ``state``'s templates, walking back past corrupt files, and write the
    ``kind="resume"`` record describing the topology change.

    Returns ``(state, epoch, loss, info)`` or None when no loadable
    checkpoint exists (fresh start). ``info`` carries the path, the
    writer's manifest (None for legacy files), and how many corrupt
    checkpoints were skipped. The caller still places the returned host
    state onto ``mesh`` (``checked_place`` + ``zero_shard_opt_state``) —
    this function only decides WHAT to restore and records the topology
    delta; ``zero_shards_to`` is the data-axis size the caller will
    reshard the ZeRO opt-state to (0 = replicated, no ZeRO)."""
    log = logger or run_logger()
    corrupt = 0
    paths = ckpt.checkpoint_paths(ckpt_dir)
    for path in reversed(paths):
        try:
            restored, epoch, loss = ckpt.load_checkpoint(path, state)
        except ckpt.CheckpointCorruptError as e:
            corrupt += 1
            log.error(
                "corrupt checkpoint %s (%s) — falling back to the previous one",
                path, e,
            )
            if metrics is not None:
                file_epoch = ckpt.checkpoint_epoch(path)
                metrics.write(
                    {
                        "kind": "anomaly",
                        "reason": "corrupt_checkpoint",
                        "epoch": file_epoch if file_epoch is not None else -1,
                        "path": path,
                    }
                )
            continue
        manifest = ckpt.read_manifest(path)
        _write_resume_record(
            metrics, epoch, path, manifest, mesh, zero_shards_to, corrupt, restored
        )
        if manifest is not None and manifest.get("payload_schema", 1) > ckpt.PAYLOAD_SCHEMA:
            log.warning(
                "checkpoint %s was written by a NEWER payload schema (%s > %s); "
                "restore proceeded but fields beyond this build's schema are lost",
                path, manifest.get("payload_schema"), ckpt.PAYLOAD_SCHEMA,
            )
        from_topo = describe_topology(manifest)
        to_topo = describe_topology(mesh_topology(mesh))
        if manifest is None or manifest.get("mesh_shape") != mesh_topology(mesh)["mesh_shape"]:
            log.info(
                "elastic resume: checkpoint topology %s → current %s "
                "(reshard-on-load%s)",
                from_topo, to_topo,
                f"; ZeRO opt-state re-chunked to P={zero_shards_to}"
                if zero_shards_to else "",
            )
        return restored, epoch, loss, {
            "path": path, "manifest": manifest, "corrupt_skipped": corrupt,
        }
    if corrupt:
        # Checkpoints existed but NONE restored. Real on-disk corruption
        # hits one file; every file failing the same way is the signature
        # of a template mismatch (changed model/optimizer config on
        # resume). Silently fresh-starting here would exit 0 AND let
        # retention delete the — probably fine — checkpoints as new epochs
        # save: abort loudly instead, and let the operator fix the config
        # or clear the dir deliberately.
        raise ckpt.CheckpointCorruptError(
            f"all {len(paths)} checkpoint(s) in {ckpt_dir} failed to "
            "restore — refusing to fresh-start over them (a changed "
            "model/optimizer config on resume fails exactly like this; "
            "fix the config, or clear the checkpoint dir / drop "
            "--from-checkpoint to deliberately start over)"
        )
    return None


def _write_resume_record(
    metrics, epoch: int, path: str, manifest: dict | None, mesh,
    zero_shards_to: int, corrupt: int, restored: Any,
) -> None:
    if metrics is None:
        return
    topo = mesh_topology(mesh)
    record: dict = {
        "kind": "resume",
        "epoch": epoch,
        "to_devices": topo["device_count"],
        "to_mesh": ",".join(f"{a}={s}" for a, s in topo["mesh_shape"].items()),
        "path": path,
        "corrupt_skipped": corrupt,
        "strategy": _reshard_strategy(restored, zero_shards_to),
    }
    if manifest is not None:
        record["from_devices"] = int(manifest.get("device_count", 0))
        record["from_mesh"] = ",".join(
            f"{a}={s}" for a, s in manifest.get("mesh_shape", {}).items()
        )
        record["zero_shards_from"] = int(manifest.get("zero_shards", 0))
        cursor = manifest.get("data_cursor")
        if isinstance(cursor, dict):
            # Schema v6: the exact-step data cursor the writer stamped —
            # where the run continues if the trainer validates it
            # (train/trainer.py; a mismatch falls back to epoch replay).
            record["cursor_epoch"] = int(cursor.get("epoch", 0))
            record["cursor_step"] = int(cursor.get("step_in_epoch", 0))
    if zero_shards_to:
        record["zero_shards_to"] = int(zero_shards_to)
    metrics.write(record)


def _reshard_strategy(restored: Any, zero_shards_to: int) -> str:
    """Which placement path the restored opt-state will take: replicate
    (no ZeRO), one jitted host reshard, or the chunked per-row
    redistribution once any leaf exceeds the bounded-HBM cap."""
    if not zero_shards_to:
        return "replicate"
    big = any(
        getattr(leaf, "nbytes", 0) > _BOUNDED_LEAF_BYTES
        for leaf in jax.tree_util.tree_leaves(restored.opt_state)
    )
    return "chunked-redistribute" if big else "host-reshard"


# ---------------------------------------------------------------------------
# Bounded retry + backoff (resume side)
# ---------------------------------------------------------------------------


def with_retries(fn, *, what: str, retries: int = 3, backoff_s: float = 0.5, logger=None):
    """Run ``fn`` with up to ``retries`` retries on Exception, sleeping a
    deterministic exponential backoff (``backoff_s * 2^attempt``) between
    attempts — the resume-side absorber for transiently wedged backend init
    and device placement. The final failure re-raises unchanged."""
    log = logger or run_logger()
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            log.warning(
                "%s failed (attempt %d/%d: %s) — retrying in %.1f s",
                what, attempt + 1, retries + 1, e, delay,
            )
            time.sleep(delay)


def checked_place(state: Any, mesh, *, zero_optimizer: bool = False, fsdp: bool = False):
    """``place_state_on_mesh`` behind the ``MPT_FAULT_DEVICE_PUT_N`` gate —
    the injectable placement the resume path retries through
    ``with_retries`` (placement is idempotent: a retried device_put simply
    re-places the same host arrays)."""
    if fault_countdown("MPT_FAULT_DEVICE_PUT_N"):
        raise RuntimeError("injected fault: device_put failed (MPT_FAULT_DEVICE_PUT_N)")
    return place_state_on_mesh(state, mesh, zero_optimizer=zero_optimizer, fsdp=fsdp)


# ---------------------------------------------------------------------------
# Preemption watchdog
# ---------------------------------------------------------------------------


class PreemptionWatchdog:
    """The trainer's unified stop-signal poll: SIGTERM/SIGINT (via the
    ``PreemptionGuard``), the ``MPT_PREEMPT_FILE`` sentinel, and repeated
    health signals from ``obs/`` (straggler-beat streaks, non-finite-grad
    streaks). The first observed reason writes ONE ``kind="fault"`` record
    and latches — the trainer then stops at the next safe boundary exactly
    like a SIGTERM preemption (save, clean exit, auto-resume).

    Streak thresholds of 0 disable that trigger (the loss sentinel already
    aborts hard on a NaN loss; opting a run into preempt-on-streak is a
    fleet-policy decision, not a default)."""

    def __init__(
        self,
        guard,
        *,
        preempt_file: str = "",
        straggler_beats: int = 0,
        nonfinite_steps: int = 0,
        heartbeat=None,
        health=None,
        metrics=None,
        logger=None,
        injector=None,
    ):
        self.guard = guard
        self.preempt_file = preempt_file or os.environ.get("MPT_PREEMPT_FILE", "")
        self.straggler_beats = int(straggler_beats)
        self.nonfinite_steps = int(nonfinite_steps)
        self.heartbeat = heartbeat
        self.health = health
        self.metrics = metrics
        self.log = logger or run_logger()
        self.injector = injector  # FaultInjector (MPT_FAULT_PREEMPT_AT_STEP)
        self.fired_reason: str | None = None
        self.fired_detail: str = ""
        self.fired_streak: int | None = None

    def _poll(self) -> tuple[str, str, int | None] | None:
        if self.guard is not None and self.guard.triggered:
            return "sigterm", "preemption signal received", None
        if self.preempt_file and os.path.exists(self.preempt_file):
            return "preempt_file", f"sentinel {self.preempt_file} exists", None
        if self.injector is not None and self.injector.preempt_fired:
            return (
                "injected_preempt",
                f"MPT_FAULT_PREEMPT_AT_STEP={self.injector.preempt_at_step}",
                None,
            )
        if (
            self.straggler_beats > 0
            and self.heartbeat is not None
            and getattr(self.heartbeat, "straggler_streak", 0) >= self.straggler_beats
        ):
            return (
                "straggler_streak",
                f"{self.heartbeat.straggler_streak} consecutive straggler beats",
                self.heartbeat.straggler_streak,
            )
        if (
            self.nonfinite_steps > 0
            and self.health is not None
            and getattr(self.health, "nonfinite_grad_streak", 0) >= self.nonfinite_steps
        ):
            return (
                "nonfinite_grads",
                f"{self.health.nonfinite_grad_streak} consecutive non-finite grad norms",
                self.health.nonfinite_grad_streak,
            )
        return None

    def should_stop(self, epoch: int | None = None, step: int | None = None) -> bool:
        """Poll every trigger; latch, record, and warn on the first firing.
        Cheap when nothing fires: a flag read plus (with a sentinel
        configured) one stat()."""
        if self.fired_reason is not None:
            return True
        hit = self._poll()
        if hit is None:
            return False
        self.fired_reason, self.fired_detail, self.fired_streak = hit
        record: dict = {"kind": "fault", "reason": self.fired_reason, "detail": self.fired_detail}
        if epoch is not None:
            record["epoch"] = epoch
        if step is not None:
            record["step"] = step
        if self.fired_streak is not None:
            record["streak"] = self.fired_streak
        if self.metrics is not None:
            self.metrics.write(record)
        self.log.warning(
            "preemption watchdog: %s (%s) — stopping at the next safe "
            "boundary, saving, and exiting cleanly for auto-resume",
            self.fired_reason, self.fired_detail,
        )
        return True


# ---------------------------------------------------------------------------
# Bad-step rollback policy (ISSUE 10: --bad-step-policy rollback)
# ---------------------------------------------------------------------------


class RollbackLimitError(RuntimeError):
    """More in-process rollbacks than ``--max-rollbacks`` allows — the run
    is not converging past the bad region, so it aborts loudly with the
    full ``kind="rollback"`` trail in the metrics stream."""


class RollbackPolicy:
    """Host-side governor deciding WHEN ``--bad-step-policy rollback``
    restores the last good checkpoint (the trainer does the restoring,
    in-process, via ``restore_latest`` — no process death).

    Two triggers, both computed from globally-reduced per-step values
    (the count-weighted global loss and the all-parameter grad norm), so
    every host reaches the identical verdict at the identical step:

    - ``nonfinite_steps`` CONSECUTIVE steps with a non-finite loss/grad
      norm (a diverged update poisons the params, so every later step
      stays non-finite — the streak is the detection delay, not a retry);
    - ``loss_drift`` > 0: the loss exceeds ``loss_drift`` × the run's own
      warmup baseline (the mean of the first ``drift_warmup`` finite
      losses) — the same warmup-baseline semantics as the SLO monitor's
      ``drift:`` rules (obs/monitor.py), catching a spike that never goes
      NaN but has clearly left the run's normal.
    """

    def __init__(
        self,
        *,
        nonfinite_steps: int = 2,
        loss_drift: float = 0.0,
        drift_warmup: int = 5,
    ):
        self.nonfinite_steps = max(1, int(nonfinite_steps))
        self.loss_drift = float(loss_drift)
        self.drift_warmup = max(1, int(drift_warmup))
        self.nonfinite_streak = 0
        self.baseline: list[float] = []

    def observe(self, loss: float, grad_norm: float | None) -> str | None:
        """Feed one step's host-read metrics; returns the trigger reason
        (``"nonfinite_streak"`` / ``"loss_drift"``) or None."""
        import math

        finite = math.isfinite(loss) and (
            grad_norm is None or math.isfinite(grad_norm)
        )
        if not finite:
            self.nonfinite_streak += 1
            if self.nonfinite_streak >= self.nonfinite_steps:
                return "nonfinite_streak"
            return None
        self.nonfinite_streak = 0
        if self.loss_drift > 0:
            if len(self.baseline) < self.drift_warmup:
                # The first observations ARE the baseline (SLO drift
                # semantics): the policy only judges once the run has
                # defined "normal".
                self.baseline.append(loss)
                return None
            base = sum(self.baseline) / len(self.baseline)
            if base > 0 and loss / base > self.loss_drift:
                return "loss_drift"
        return None

    def after_rollback(self) -> None:
        """Re-arm after a restore: the streak resets (the restored state
        is good); the warmup baseline is KEPT — it describes the run's
        normal, which a rollback does not change."""
        self.nonfinite_streak = 0


# ---------------------------------------------------------------------------
# In-process fault injection (the trainer-side half of tools/inject_faults.py)
# ---------------------------------------------------------------------------


class FaultInjector:
    """Deterministic in-process chaos, armed by the ``MPT_FAULT_*`` env
    gates (``utils/env.py FAULT_GATES``), inert otherwise:

    - ``MPT_FAULT_KILL_AT_STEP=n``: SIGKILL this process right after its
      n-th completed train step — a hard crash with the async checkpoint
      writer possibly mid-write, exactly what the atomic tmp+rename
      discipline must survive;
    - ``MPT_FAULT_DELAY_STEP_MS=m`` (+ ``MPT_FAULT_DELAY_PROCESS=k``,
      ``MPT_FAULT_DELAY_AFTER_STEP=j``): sleep m ms inside every timed
      step (on process k only, if set; only after the first j clean steps,
      if set) — a fake straggler the heartbeat/watchdog stack must flag,
      appearing mid-run when j > 0 so the SLO monitor's warmup-baseline
      drift rules (obs/monitor.py) see a clean "normal" first.
    - ``MPT_FAULT_NONFINITE_AT_STEP=n``: poison the n-th train batch
      (1-based, counted across epochs) with NaN pixels so that step's
      loss/grad norm go non-finite — announced with a ``kind="fault"``
      record BEFORE the step runs, so the ``--bad-step-policy``
      skip/rollback paths are testable without a hand-tuned poisoned
      learning rate. Streaming float-input path only (uint8 batches
      cannot carry a NaN; the device-cache path feeds indices).
    - ``MPT_FAULT_PREEMPT_AT_STEP=n``: behave as if a preemption notice
      arrived right after the n-th completed train step — a deterministic
      mid-epoch stop (the watchdog polls ``preempt_fired``) exercising
      the dirty-save + exact-step-resume path without racing a signal.
    """

    def __init__(self, metrics=None):
        self.kill_at_step = env_int("MPT_FAULT_KILL_AT_STEP", 0)
        self.delay_ms = env_int("MPT_FAULT_DELAY_STEP_MS", 0)
        self.delay_process = env_int("MPT_FAULT_DELAY_PROCESS", -1)
        self.delay_after = env_int("MPT_FAULT_DELAY_AFTER_STEP", 0)
        self.dcn_delay_ms = env_int("MPT_FAULT_DCN_DELAY_MS", 0)
        self.nonfinite_at_step = env_int("MPT_FAULT_NONFINITE_AT_STEP", 0)
        self.preempt_at_step = env_int("MPT_FAULT_PREEMPT_AT_STEP", 0)
        self.preempt_fired = False
        self.metrics = metrics
        self._steps = 0
        self._delay_calls = 0
        self._batches = 0

    @property
    def active(self) -> bool:
        return bool(
            self.kill_at_step or self.delay_ms or self.dcn_delay_ms
            or self.nonfinite_at_step or self.preempt_at_step
        )

    def poison_batches(self, batches, epoch: int | None = None):
        """Wrap a host-batch iterator, NaN-poisoning the images of the
        armed batch (1-based, counted across epochs — the injector
        instance carries the count between epochs). The fault record is
        written BEFORE the poisoned batch is yielded, so the stream always
        shows the injection ahead of its non-finite step records."""
        import numpy as np

        for images, labels in batches:
            self._batches += 1
            if self._batches == self.nonfinite_at_step:
                if self.metrics is not None:
                    self.metrics.write(
                        {
                            "kind": "fault",
                            "reason": "injected_nonfinite",
                            "detail": (
                                f"MPT_FAULT_NONFINITE_AT_STEP="
                                f"{self.nonfinite_at_step}"
                            ),
                            **({"epoch": epoch} if epoch is not None else {}),
                        }
                    )
                run_logger().warning(
                    "fault injection: NaN-poisoning train batch %d "
                    "(MPT_FAULT_NONFINITE_AT_STEP)", self._batches,
                )
                images = np.full_like(images, np.nan)
            yield images, labels

    def after_step(self, epoch: int, step: int) -> None:
        """Count completed steps; fire whichever step-count gate is armed.
        The kill gate announces itself (the metrics stream is
        line-buffered, so the record lands) and SIGKILLs — no cleanup, no
        drain: this is the crash, not a shutdown. The preempt gate only
        latches a flag the watchdog polls at the next step boundary."""
        if not (self.kill_at_step or self.preempt_at_step):
            return
        self._steps += 1
        if (
            self.preempt_at_step
            and not self.preempt_fired
            and self._steps >= self.preempt_at_step
        ):
            self.preempt_fired = True
            run_logger().warning(
                "fault injection: simulated preemption notice after train "
                "step %d (epoch %d step %d)", self._steps, epoch, step,
            )
        if not self.kill_at_step or self._steps < self.kill_at_step:
            return
        if self.metrics is not None:
            self.metrics.write(
                {
                    "kind": "fault",
                    "reason": "injected_kill",
                    "epoch": epoch,
                    "step": step,
                    "detail": f"MPT_FAULT_KILL_AT_STEP={self.kill_at_step}",
                }
            )
        run_logger().warning(
            "fault injection: SIGKILL at train step %d (epoch %d step %d)",
            self._steps, epoch, step,
        )
        os.kill(os.getpid(), signal.SIGKILL)

    def maybe_delay(self) -> None:
        """The straggler fake — called inside the step's timed region so
        heartbeats attribute the delay to this host's step time. With
        ``MPT_FAULT_DELAY_AFTER_STEP`` the first j steps stay clean."""
        if self.delay_ms <= 0:
            return
        self._delay_calls += 1
        if self._delay_calls <= self.delay_after:
            return
        if self.delay_process < 0 or process_index() == self.delay_process:
            time.sleep(self.delay_ms / 1e3)

    def maybe_dcn_delay(self, hierarchical: bool) -> None:
        """``MPT_FAULT_DCN_DELAY_MS`` — the slow-DCN-link fake (ISSUE 15):
        stretch every step by the injected cross-pod latency, but ONLY on
        hierarchical (pods > 1) runs — a flat mesh has no DCN phase, so
        the gate correctly does nothing there (the property the overlap
        chaos test pins). Host-side stand-in: the device step is one fused
        program, so the delay lands in the timed region like a real slow
        second-stage reduction would, and heartbeats/step records carry
        it."""
        if self.dcn_delay_ms <= 0 or not hierarchical:
            return
        time.sleep(self.dcn_delay_ms / 1e3)
