"""Fault injection (SURVEY §5 failure-detection row): SIGKILL a live training
process mid-run and verify the atomic-checkpoint discipline (tmp+rename) left
only loadable checkpoints with auto-resume continuing the epoch count; SIGTERM
one and verify graceful preemption (stop at a safe boundary, save, exit 0) —
the crash-recovery story the reference handles by manual restart with
FROM_CHECKPOINT=True (``main.py:127-130``)."""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trainer_args(tmp_path, **overrides) -> list[str]:
    """The shared CLI recipe for a small CPU-mesh training subprocess."""
    defaults = {
        "--debug": "true", "--debug-sample-size": "128", "--num-classes": "200",
        "--batch-size": "32", "--width": "32", "--height": "32",
        "--num-epochs": "50", "--synthetic-data": "true", "--validate": "false",
        "--compute-dtype": "float32", "--loader-workers": "2",
        "--log-every-steps": "0",
        "--checkpoint-dir": str(tmp_path / "ckpt"),
        "--log-file": str(tmp_path / "training.log"),
        "--metrics-file": "",
    }
    defaults.update(overrides)
    return [tok for pair in defaults.items() for tok in pair]


def _launch_training(args: list[str], device_count: int = 8) -> subprocess.Popen:
    """Spawn the CLI trainer on a ``device_count``-virtual-device CPU world."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPT_PLATFORM"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={device_count}"]
    )
    return subprocess.Popen(
        [sys.executable, "-m", "mpi_pytorch_tpu.train", *args],
        env=env, cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _await(proc: subprocess.Popen, condition, what: str, deadline_s: float = 300):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if condition():
            return
        if proc.poll() is not None:
            pytest.fail(f"training exited early with rc={proc.returncode}")
        time.sleep(0.2)
    pytest.fail(f"{what} within the deadline")


@pytest.mark.slow
def test_sigkill_mid_training_then_resume(tmp_path):
    args = _trainer_args(tmp_path)
    ckpt_dir = str(tmp_path / "ckpt")
    proc = _launch_training(args)
    try:
        # Wait until at least two checkpoints exist, then SIGKILL with the
        # run (and possibly an async write) in flight.
        _await(
            proc,
            lambda: os.path.isdir(ckpt_dir)
            and sum(n.endswith(".msgpack") for n in os.listdir(ckpt_dir)) >= 2,
            "no checkpoints appeared",
        )
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.train.trainer import train

    latest = ckpt.latest_checkpoint(ckpt_dir)
    assert latest is not None and latest.endswith(".msgpack")
    killed_epoch = int(ckpt._CKPT_RE.search(os.path.basename(latest)).group(1))

    # Auto-resume from whatever the crash left behind and run to completion.
    cfg = parse_config(
        args + ["--from-checkpoint", "true", "--num-epochs", str(killed_epoch + 3)]
    )
    summary = train(cfg)
    assert summary.epochs_run == 2  # epochs killed+1 .. killed+2
    assert summary.checkpoint_path and os.path.exists(summary.checkpoint_path)
    resumed_epoch = int(
        ckpt._CKPT_RE.search(os.path.basename(summary.checkpoint_path)).group(1)
    )
    assert resumed_epoch == killed_epoch + 2


def test_preemption_guard_flag_and_restore():
    """First signal sets the flag without raising; handlers are restored on
    exit."""
    from mpi_pytorch_tpu.train.trainer import PreemptionGuard

    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert not guard.triggered
        signal.raise_signal(signal.SIGTERM)
        assert guard.triggered  # first signal: flag only, no exception
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_second_signal_escape_hatch():
    """A second signal defers to the prior handler — for SIGINT, Python's
    default handler, which raises KeyboardInterrupt (the escape hatch when
    the graceful drain itself wedges)."""
    from mpi_pytorch_tpu.train.trainer import PreemptionGuard

    before = signal.getsignal(signal.SIGINT)
    with pytest.raises(KeyboardInterrupt):
        with PreemptionGuard() as guard:
            signal.raise_signal(signal.SIGINT)
            assert guard.triggered
            signal.raise_signal(signal.SIGINT)  # second: prior handler raises
            pytest.fail("second SIGINT must re-raise through the prior handler")
    assert signal.getsignal(signal.SIGINT) is before


@pytest.mark.slow
def test_sigterm_graceful_preemption_then_resume(tmp_path):
    """SIGTERM mid-run → the trainer stops at a safe boundary, saves the last
    COMPLETED epoch even though the periodic save (every 3 epochs) isn't due,
    exits 0, and auto-resume continues from exactly that epoch."""
    args = _trainer_args(
        tmp_path,
        **{
            "--debug-sample-size": "512", "--num-classes": "600",
            "--num-epochs": "500", "--checkpoint-every-epochs": "3",
        },
    )
    ckpt_dir = str(tmp_path / "ckpt")
    log_file = str(tmp_path / "training.log")
    proc = _launch_training(args)
    try:
        _await(
            proc,
            lambda: os.path.exists(log_file) and "Epoch: 1," in open(log_file).read(),
            "epoch 1 never completed",
        )
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    assert rc == 0, f"graceful preemption must exit 0, got {rc}"
    log = open(log_file).read()
    assert "preemption signal" in log
    completed = max(
        int(line.split("Epoch: ")[1].split(",")[0])
        for line in log.splitlines()
        if "Epoch: " in line
    )

    from mpi_pytorch_tpu import checkpoint as ckpt
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.train.trainer import train

    latest = ckpt.latest_checkpoint(ckpt_dir)
    assert latest is not None, "preemption must leave a checkpoint"
    saved_epoch = int(ckpt._CKPT_RE.search(os.path.basename(latest)).group(1))
    assert saved_epoch == completed  # the preemption save, not just every-3rd

    cfg = parse_config(
        args + ["--from-checkpoint", "true", "--num-epochs", str(saved_epoch + 3)]
    )
    summary = train(cfg)
    assert summary.epochs_run == 2 and not summary.preempted


@pytest.mark.slow
def test_resume_on_different_world_size(tmp_path):
    """Checkpoints are world-size independent: a run on 8 devices (ZeRO-
    sharded moments included) resumes cleanly on a 4-device world — the
    shrunk-fleet restart a preemptible environment needs. The snapshot
    gather stores replicated arrays, and restore re-shards onto whatever
    mesh exists."""
    args = _trainer_args(
        tmp_path, **{"--num-epochs": "2", "--zero-optimizer": "true"}
    )
    log_file = str(tmp_path / "training.log")
    proc = _launch_training(args, device_count=8)
    assert proc.wait(timeout=300) == 0

    proc = _launch_training(
        args + ["--from-checkpoint", "true", "--num-epochs", "4"],
        device_count=4,
    )
    assert proc.wait(timeout=300) == 0
    log = open(log_file).read()
    assert "resumed from" in log
    assert "8 device(s)" in log and "4 device(s)" in log
    completed = [
        int(line.split("Epoch: ")[1].split(",")[0])
        for line in log.splitlines()
        if "Epoch: " in line
    ]
    assert completed == [0, 1, 2, 3]  # epochs 2-3 ran on the 4-device world
