"""Input/execution-mode benchmark: the round-2 feeding features on a chip.

VERDICT r2 flagged that uint8 feeding, the device cache, and scan-epoch had
only virtual-CPU-mesh verification. This sweeps the HEADLINE workload
(resnet18, 64 500 classes, 128px) through each mode with the same timing
discipline as bench.py/bench_zoo.py and prints one JSON line per mode:

    stream-f32    — host batches as float32 (reference-parity numerics)
    stream-bf16   — host batches as bfloat16 (half the H2D bytes)
    stream-uint8  — raw pixels + on-device normalize (1/4 the H2D bytes)
    cached        — HBM-resident dataset, per-step index gather
    cached-scan   — HBM-resident dataset, whole epoch as one lax.scan

Plus the TRAINING-HALF LEVER sweep (ISSUE 6 / ROADMAP item 2) over the spmd
shard_map step — ``--levers`` runs the staged A/B in one command:

    spmd-base         — fused single-pmean baseline (reference parity)
    spmd-zero         — ZeRO optimizer-state sharding (--zero-opt-state)
    spmd-buckets      — bucketed grad-sync overlap (--grad-sync-buckets)
    spmd-zero-buckets — both: buckets become reduce_scatters

Lever rows add per-chip HBM high-water, optimizer-state MB/chip, MFU, the
static overlap_frac of the bucket plan, and compiles_after_warmup (must be
0 — the zero-steady-state-compile invariant, re-checked per row).

``--mesh-pods P`` (ISSUE 15 / ROADMAP item 5) runs the spmd lever cells on
the NESTED (pod, ici) mesh — the two-level ICI/DCN hierarchical sync —
keyed ``mode-pP-bN`` with the per-axis byte-ledger columns
(``ici_bytes_per_step`` / ``dcn_bytes_per_step``), ``dcn_overlap_frac``,
and a ``mesh`` topology stamp ("p2xi4") the regression gate keys into the
training trend-line identity (tools/check_regression.py).

Streaming modes re-shard a fresh host batch EVERY step (device_put inside
the timed loop), so they carry the real H2D cost the dtype modes differ by;
the cached modes send only [B] int32 indices (and the scan, one dispatch per
epoch). Run: ``python tools/bench_modes.py [--steps 20] [--out path]``
(``--levers`` for the A/B; ``--partial-out``/``--resume-from`` give cell-
granular durability across a wedged backend — see bench.py). The
packed-mmap path is host-side decode (no chip leg) — its numbers live in
docs/RESULTS.md §4 host-ingest table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMG_PER_SEC_PER_WORKER = 4.4  # BASELINE.md
MODEL, NUM_CLASSES, IMAGE = "resnet18", 64500, 128
CACHE_ROWS = 8192  # HBM-resident rows for the cached modes (~400 MB f32)


def _setup(pods: int = 1):
    """Identical model/state for every mode — the dtype distinction lives
    entirely in the host batch (`_host_batch`) and the ingest cast.
    ``pods > 1`` nests the data axis (``--mesh-pods``, ISSUE 15) for the
    hierarchical lever cells."""
    import optax  # noqa: F401  (state factory pulls it in)

    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.parallel.mesh import create_mesh
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    mesh = create_mesh(MeshConfig(pods=pods))
    bundle, variables = create_model_bundle(
        MODEL, NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=IMAGE,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(4e-4), rng=jax.random.PRNGKey(1),
    )
    return mesh, place_state_on_mesh(state, mesh)


def _host_batch(batch: int, input_dtype: str):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, NUM_CLASSES, size=(batch,)).astype(np.int32)
    if input_dtype == "uint8":
        images = rng.integers(0, 256, size=(batch, IMAGE, IMAGE, 3)).astype(np.uint8)
    else:
        images = rng.standard_normal((batch, IMAGE, IMAGE, 3)).astype(np.float32)
        if input_dtype == "bfloat16":
            images = images.astype(jnp.bfloat16)
    return images, labels


def bench_streaming(input_dtype: str, batch_per_chip: int, steps: int, warmup: int):
    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.train.step import make_train_step

    mesh, state = _setup()
    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    images, labels = _host_batch(batch, input_dtype)
    step = make_train_step(jnp.bfloat16)
    compiled = step.lower(state, shard_batch((images, labels), mesh)).compile()

    for _ in range(warmup):
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        # The device_put is INSIDE the timed loop on purpose: the H2D
        # transfer is the thing the input dtypes differ by.
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    return dt, steps * batch, n_chips, {}


def _hbm_high_water():
    """Per-chip HBM high-water mark (bytes), or None where the backend has
    no memory_stats (CPU) — the column carries null, not a fake zero."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use")))
    except Exception:
        pass
    return None


def bench_spmd(
    zero: bool, bucket_mb: float, batch_per_chip: int, steps: int, warmup: int,
    pods: int = 1,
):
    """One training-half-lever cell: the spmd shard_map step with ZeRO
    opt-state sharding and/or bucketed grad sync. Same timing discipline as
    the streaming modes (fresh device_put per step), plus the lever
    telemetry columns: optimizer-state MB actually resident per chip, the
    bucket plan's static overlap_frac, HBM high-water, and a
    compiles-after-warmup recheck of the zero-steady-state invariant.

    ``pods > 1`` (the ``--mesh-pods`` hierarchical cells, ISSUE 15): the
    same levers on the nested (pod, ici) mesh, with the per-axis byte
    ledger's ICI/DCN traffic and the DCN overlap estimate on the row —
    the columns a chip A/B of the two-level sync is judged by."""
    from mpi_pytorch_tpu.obs.health import compile_count, ensure_compile_listener
    from mpi_pytorch_tpu.parallel.collectives import LEDGER
    from mpi_pytorch_tpu.parallel.mesh import is_hierarchical, pod_shape, shard_batch
    from mpi_pytorch_tpu.train.state import zero_shard_opt_state
    from mpi_pytorch_tpu.train.step import (
        bucket_overlap_frac,
        grad_bucket_plan,
        hier_dcn_overlap_frac,
        make_spmd_train_step,
    )
    from mpi_pytorch_tpu.utils.hardware import peak_bf16_tflops, step_flops

    mesh, state = _setup(pods)
    if zero:
        state = state.replace(opt_state=zero_shard_opt_state(state.opt_state, mesh))
    opt_bytes_per_chip = sum(
        leaf.addressable_shards[0].data.nbytes
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "addressable_shards") and leaf.ndim > 0
    )
    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    images, labels = _host_batch(batch, "float32")
    step = make_spmd_train_step(
        mesh, jnp.bfloat16, zero_opt_state=zero, grad_bucket_mb=bucket_mb
    )
    # Per-axis traffic is booked at trace time: reset + one lower = one
    # step's ICI-vs-DCN bytes (parallel/collectives.LEDGER).
    LEDGER.reset()
    compiled = step.lower(state, shard_batch((images, labels), mesh)).compile()
    traffic = LEDGER.snapshot()
    flops = step_flops(compiled)

    ensure_compile_listener()
    for _ in range(warmup):
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    base_compiles = compile_count()
    t0 = time.perf_counter()
    for _ in range(steps):
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    high_water = _hbm_high_water()
    extra = {
        "zero_opt_state": zero,
        "grad_sync_buckets_mb": bucket_mb,
        "opt_state_mb_per_chip": round(opt_bytes_per_chip / 1e6, 1),
        "hbm_high_water_mb": round(high_water / 1e6, 1) if high_water else None,
        "compiles_after_warmup": compile_count() - base_compiles,
        "ici_bytes_per_step": traffic["ici"]["bytes"],
        "dcn_bytes_per_step": traffic["dcn"]["bytes"],
    }
    if is_hierarchical(mesh):
        n_pods, ici = pod_shape(mesh)
        extra["mesh"] = f"p{n_pods}xi{ici}"
    if bucket_mb > 0:
        plan = grad_bucket_plan(state.params, bucket_mb)
        extra["buckets"] = len(plan)
        extra["overlap_frac"] = bucket_overlap_frac(state.params, plan)
        if is_hierarchical(mesh):
            extra["dcn_overlap_frac"] = hier_dcn_overlap_frac(state.params, plan)
    peak = peak_bf16_tflops(jax.devices()[0])
    if peak and flops > 0:
        extra["mfu_pct"] = round(100.0 * flops * steps / dt / 1e12 / peak, 1)
    return dt, steps * batch, n_chips, extra


def bench_cached(scan: bool, batch_per_chip: int, steps: int, warmup: int):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_pytorch_tpu.train.step import (
        make_cached_train_step,
        make_scanned_epoch,
    )

    mesh, state = _setup()
    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    n_data = mesh.shape[mesh.axis_names[0]]
    rows = -(-CACHE_ROWS // n_data) * n_data
    rng = np.random.default_rng(0)
    dataset = jax.device_put(
        rng.standard_normal((rows, IMAGE, IMAGE, 3)).astype(np.float32),
        NamedSharding(mesh, P(mesh.axis_names[0])),
    )
    labels_all = jax.device_put(
        rng.integers(0, NUM_CLASSES, size=(rows,)).astype(np.int32),
        NamedSharding(mesh, P()),
    )
    idx = rng.integers(0, rows, size=(steps + warmup, batch)).astype(np.int32)
    valid = np.ones((steps + warmup, batch), bool)

    if scan:
        epoch_fn = make_scanned_epoch(mesh, jnp.bfloat16)
        compiled = epoch_fn.lower(
            state, dataset, labels_all, idx[:steps], valid[:steps]
        ).compile()
        state, _ = compiled(state, dataset, labels_all, idx[:steps], valid[:steps])
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        state, _ = compiled(state, dataset, labels_all, idx[:steps], valid[:steps])
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        return dt, steps * batch, n_chips, {}

    step = make_cached_train_step(mesh, jnp.bfloat16)
    compiled = step.lower(state, dataset, labels_all, idx[0], valid[0]).compile()
    for i in range(warmup):
        state, _ = compiled(state, dataset, labels_all, idx[i], valid[i])
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for i in range(steps):
        state, _ = compiled(state, dataset, labels_all, idx[warmup + i], valid[warmup + i])
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    return dt, steps * batch, n_chips, {}


MODES = {
    "stream-f32": lambda b, s, w, mb, p: bench_streaming("float32", b, s, w),
    "stream-bf16": lambda b, s, w, mb, p: bench_streaming("bfloat16", b, s, w),
    "stream-uint8": lambda b, s, w, mb, p: bench_streaming("uint8", b, s, w),
    "cached": lambda b, s, w, mb, p: bench_cached(False, b, s, w),
    "cached-scan": lambda b, s, w, mb, p: bench_cached(True, b, s, w),
    # Training-half levers (spmd shard_map step; ROADMAP items 2 + 5 —
    # --mesh-pods > 1 runs the same levers hierarchically):
    "spmd-base": lambda b, s, w, mb, p: bench_spmd(False, 0.0, b, s, w, p),
    "spmd-zero": lambda b, s, w, mb, p: bench_spmd(True, 0.0, b, s, w, p),
    "spmd-buckets": lambda b, s, w, mb, p: bench_spmd(False, mb, b, s, w, p),
    "spmd-zero-buckets": lambda b, s, w, mb, p: bench_spmd(True, mb, b, s, w, p),
}

# Modes the --mesh-pods axis applies to (the hierarchical cells are
# spmd-lever cells; the ingest modes run the auto-jit step, which a nested
# mesh cannot change).
POD_MODES = ("spmd-base", "spmd-zero", "spmd-buckets", "spmd-zero-buckets")

LEVER_MODES = "spmd-base,spmd-zero,spmd-buckets,spmd-zero-buckets"
# The documented default run stays the five INGEST modes — the lever cells
# are the opt-in --levers A/B, not a silent doubling of a plain round's
# backend time (and of the rows existing bench_modes artifacts expect).
INGEST_MODES = "stream-f32,stream-bf16,stream-uint8,cached,cached-scan"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=2048, help="per chip")
    ap.add_argument("--modes", default=INGEST_MODES)
    ap.add_argument(
        "--levers", action="store_true",
        help=f"the staged training-half A/B in one command: --modes {LEVER_MODES}",
    )
    ap.add_argument(
        "--bucket-mb", type=float, default=25.0,
        help="grad-sync bucket size (MiB) for the spmd-*buckets modes",
    )
    ap.add_argument(
        "--mesh-pods", type=int, default=1,
        help="factor the data axis into this many nested pods for the "
             "spmd lever cells (hierarchical ICI/DCN sync, ISSUE 15); "
             "cells key mode-p<P>-b<batch> and rows carry per-axis bytes",
    )
    ap.add_argument("--out", default="")
    ap.add_argument(
        "--partial-out", default="",
        help="append each completed row to this *.partial.json as it lands "
             "(cell-granular durability across a wedged backend; bench.py)",
    )
    ap.add_argument(
        "--resume-from", default="",
        help="skip cells this partial file already holds (reprinted as-is)",
    )
    args = ap.parse_args()
    if args.levers:
        args.modes = LEVER_MODES

    from bench import append_partial_row, load_partial  # repo root on sys.path above

    done = load_partial(args.resume_from)
    records = []
    for mode in (m.strip() for m in args.modes.split(",") if m.strip()):
        pods = args.mesh_pods if mode in POD_MODES else 1
        # Hierarchical cells key their pod factoring (mode-pP-bN) so a
        # partial-file resume — and the trend-line identity downstream —
        # never conflates them with flat cells of the same mode.
        cell = (
            f"{mode}-p{pods}-b{args.batch}" if pods > 1 else f"{mode}-b{args.batch}"
        )
        if cell in done:
            rec = done[cell]
            records.append(rec)
            print(json.dumps(rec), flush=True)
            continue
        try:
            dt, images, n_chips, extra = MODES[mode](
                args.batch, args.steps, args.warmup, args.bucket_mb, pods
            )
            rec = {
                "mode": mode,
                "batch_per_chip": args.batch,
                "images_per_sec_per_chip": round(images / dt / n_chips, 1),
                "vs_baseline": round(
                    images / dt / n_chips / REFERENCE_IMG_PER_SEC_PER_WORKER, 1
                ),
                **extra,
            }
            if args.partial_out:
                append_partial_row(args.partial_out, cell, rec)
        except Exception as e:
            rec = {"mode": mode, "error": f"{type(e).__name__}: {e}"[:300]}
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
