"""Mapping between this zoo's Flax param trees and torchvision state_dicts.

This is the substance of the ``use_pretrained`` capability (reference
``models.py:16-101`` downloads torchvision ImageNet weights; this environment
has neither torchvision nor egress, so weights are converted offline by
``tools/convert_torchvision.py`` using these rules and loaded from disk by
``models/pretrained.py``).

Layout conventions converted here:
- conv kernels:  torch OIHW  → flax HWIO
- dense kernels: torch [out, in] → flax [in, out]
- the first dense after a flatten: torch flattens CHW, this zoo flattens HWC
  (NHWC layout), so the input axis is additionally permuted
- BatchNorm: torch ``weight``/``bias``/``running_mean``/``running_var`` →
  flax ``scale``/``bias`` (params) + ``mean``/``var`` (batch_stats)

Classifier heads (``head``/``aux_head``) are never mapped: the reference
replaces them with fresh ``num_classes`` layers (``models.py:36,44,53,62,70,
80,90-94``), and so does this framework.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from mpi_pytorch_tpu.models.common import head_filter

# ---------------------------------------------------------------------------
# tensor layout transforms (torch-side array → flax-side array)
# ---------------------------------------------------------------------------


def conv_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))  # OIHW → HWIO


def dense_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (1, 0))  # [out, in] → [in, out]


def flatten_dense_kernel(c: int, h: int, wd: int) -> Callable[[np.ndarray], np.ndarray]:
    """Dense right after flatten: permute the input axis CHW → HWC."""

    def t(w: np.ndarray) -> np.ndarray:
        out = w.shape[0]
        return w.reshape(out, c, h, wd).transpose(0, 2, 3, 1).reshape(out, -1).T

    return t


def identity(w: np.ndarray) -> np.ndarray:
    return w


# ---------------------------------------------------------------------------
# per-architecture module-prefix maps: flax module path → torchvision prefix
# ---------------------------------------------------------------------------

# AlexNet/VGG11-BN/SqueezeNet are nn.Sequential in torchvision; the numeric
# indices below are the fixed positions of the parameterized layers.
_ALEXNET = {
    "conv1": "features.0", "conv2": "features.3", "conv3": "features.6",
    "conv4": "features.8", "conv5": "features.10",
    "fc1": "classifier.1", "fc2": "classifier.4",
}
_VGG11 = {
    **{f"conv{i}": f"features.{n}" for i, n in enumerate((0, 4, 8, 11, 15, 18, 22, 25))},
    **{f"bn{i}": f"features.{n}" for i, n in enumerate((1, 5, 9, 12, 16, 19, 23, 26))},
    "fc1": "classifier.0", "fc2": "classifier.3",
}
_SQUEEZENET = {
    "conv1": "features.0",
    **{f"fire{i + 2}": f"features.{n}" for i, n in enumerate((3, 4, 5, 7, 8, 9, 10, 12))},
}

# Dense layers fed by a flatten, with the (C, H, W) the torch side flattened.
_FLATTEN_DENSE = {
    ("alexnet", "fc1"): (256, 6, 6),
    ("vgg11_bn", "fc1"): (512, 7, 7),
}


def _module_prefix(arch: str, module_path: tuple[str, ...]) -> str:
    """torchvision prefix for a flax module path (everything but the leaf)."""
    if arch in ("resnet18", "resnet34"):
        out = []
        for p in module_path:
            if p.startswith("layer") and "_" in p:
                stage, block = p.split("_")
                out.append(f"{stage}.{block}")
            elif p == "downsample_conv":
                out.append("downsample.0")
            elif p == "downsample_bn":
                out.append("downsample.1")
            else:
                out.append(p)
        return ".".join(out)
    if arch == "alexnet":
        return ".".join(_ALEXNET.get(p, p) for p in module_path)
    if arch == "vgg11_bn":
        return ".".join(_VGG11.get(p, p) for p in module_path)
    if arch == "squeezenet1_0":
        return ".".join(_SQUEEZENET.get(p, p) for p in module_path)
    if arch == "densenet121":
        out = []
        for p in module_path:
            if p.startswith("denseblock") and "_" in p:
                block, layer = p.split("_")
                n = block.removeprefix("denseblock")
                out.append(f"features.{block}.denselayer{layer.removeprefix('layer')}")
                continue
            if p.startswith("transition") or p in ("conv0", "norm0", "norm5"):
                out.append(f"features.{p}")
                continue
            out.append(p)
        return ".".join(out)
    if arch == "inception_v3":
        # module names were chosen to match torchvision exactly
        # (Conv2d_1a_3x3, Mixed_5b…, AuxLogits, conv/bn, branch names).
        return ".".join(module_path)
    if arch == "mobilenet_v2":
        # torchvision: features.0 = stem ConvBNActivation, features.1..17 =
        # InvertedResidual (whose .conv Sequential has one fewer stage when
        # expand_ratio == 1 — exactly our block0), features.18 = head conv.
        if module_path and module_path[0].startswith("block"):
            i = int(module_path[0].removeprefix("block"))
            sub = module_path[1]
            stages = (
                {"depthwise": "conv.0.0", "depthwise_bn": "conv.0.1",
                 "project": "conv.1", "project_bn": "conv.2"}
                if i == 0
                else {"expand": "conv.0.0", "expand_bn": "conv.0.1",
                      "depthwise": "conv.1.0", "depthwise_bn": "conv.1.1",
                      "project": "conv.2", "project_bn": "conv.3"}
            )
            return f"features.{i + 1}.{stages[sub]}"
        flat = {"stem": "features.0.0", "stem_bn": "features.0.1",
                "head_conv": "features.18.0", "head_bn": "features.18.1"}
        return ".".join(flat.get(p, p) for p in module_path)
    if arch == "efficientnet_b0":
        # torchvision: features.0 = stem Conv2dNormActivation, features.1..7
        # = the seven MBConv stages (block-in-stage nesting vs this zoo's
        # flat global block index), features.8 = head conv. Within an MBConv
        # the .block Sequential has one fewer stage when expand_ratio == 1
        # (exactly our block0), and the SE convs are fc1 (reduce) / fc2
        # (expand).
        if module_path and module_path[0].startswith("block"):
            rem = int(module_path[0].removeprefix("block"))
            stage = 1
            for n in (1, 2, 2, 3, 3, 4, 1):  # blocks per stage (_SETTINGS)
                if rem < n:
                    break
                rem -= n
                stage += 1
            expand_less = stage == 1  # expand_ratio == 1: no expand stage
            if module_path[1] == "se":
                se = "block.1" if expand_less else "block.2"
                fc = {"reduce": "fc1", "expand": "fc2"}[module_path[2]]
                return f"features.{stage}.{rem}.{se}.{fc}"
            stages = (
                {"depthwise": "block.0.0", "depthwise_bn": "block.0.1",
                 "project": "block.2.0", "project_bn": "block.2.1"}
                if expand_less
                else {"expand": "block.0.0", "expand_bn": "block.0.1",
                      "depthwise": "block.1.0", "depthwise_bn": "block.1.1",
                      "project": "block.3.0", "project_bn": "block.3.1"}
            )
            return f"features.{stage}.{rem}.{stages[module_path[1]]}"
        flat = {"stem": "features.0.0", "stem_bn": "features.0.1",
                "head_conv": "features.8.0", "head_bn": "features.8.1"}
        return ".".join(flat.get(p, p) for p in module_path)
    raise ValueError(f"no torchvision mapping for {arch!r}")


def tv_entries(
    arch: str, collection: str, path: tuple[str, ...], shape: tuple[int, ...]
) -> tuple[str, Callable[[np.ndarray], np.ndarray]] | None:
    """(torchvision key, transform) for one flax leaf, or None if the leaf is
    a classifier-head param (kept fresh) with no torchvision counterpart.

    ``collection`` is "params" or "batch_stats"; ``path`` is the flax tree
    path as strings, e.g. ("layer2_0", "bn1", "scale").
    """
    if head_filter(path):
        return None
    *module_path, leaf = path
    prefix = _module_prefix(arch, tuple(module_path))

    if collection == "batch_stats":
        return f"{prefix}.running_{'mean' if leaf == 'mean' else 'var'}", identity

    if leaf == "scale":  # BatchNorm scale
        return f"{prefix}.weight", identity
    if leaf == "bias":
        return f"{prefix}.bias", identity
    if leaf == "kernel":
        if len(shape) == 4:
            return f"{prefix}.weight", conv_kernel
        key = (arch, module_path[-1] if module_path else "")
        if key in _FLATTEN_DENSE:
            return f"{prefix}.weight", flatten_dense_kernel(*_FLATTEN_DENSE[key])
        return f"{prefix}.weight", dense_kernel
    raise ValueError(f"unrecognized param leaf {leaf!r} at {path}")


def convert_state_dict(arch: str, variables: dict, state_dict: dict) -> dict:
    """Overlay a torchvision ``state_dict`` (str → numpy array) onto freshly
    initialized flax ``variables``. Heads keep their fresh init; every other
    leaf must find its counterpart (missing keys raise, so a silent partial
    load can't masquerade as pretrained)."""
    import jax

    def build(collection: str, tree):
        flat = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path_keys, leaf in flat[0]:
            path = tuple(str(getattr(k, "key", k)) for k in path_keys)
            entry = tv_entries(arch, collection, path, tuple(leaf.shape))
            if entry is None:
                out.append(leaf)  # head: keep fresh init
                continue
            key, transform = entry
            if key not in state_dict:
                raise KeyError(
                    f"{arch}: torchvision state_dict is missing {key!r} "
                    f"(needed for flax param {'/'.join(path)})"
                )
            arr = transform(np.asarray(state_dict[key]))
            if tuple(arr.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"{arch}: shape mismatch for {key!r}: torchvision "
                    f"{arr.shape} vs flax {leaf.shape} at {'/'.join(path)}"
                )
            out.append(arr.astype(np.asarray(leaf).dtype))
        return jax.tree_util.tree_unflatten(flat[1], out)

    result = dict(variables)
    result["params"] = build("params", variables["params"])
    if "batch_stats" in variables:
        result["batch_stats"] = build("batch_stats", variables["batch_stats"])
    return result
