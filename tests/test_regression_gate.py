"""Perf regression gate (tools/check_regression.py) — the tier-1 wrapper
(the check_results_artifacts pattern) plus unit coverage: regression
detection, tolerance, metric-string isolation, wedged-round (rc!=0) and
null-cell tolerance, empty histories, and serve p99/img-s baseline pairs."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_regression  # noqa: E402


def _bench(path, rnd, value, metric="m train img/s", rc=0, parsed=True):
    cell = {"metric": metric, "value": value} if parsed else None
    with open(os.path.join(path, f"BENCH_r{rnd:02d}.json"), "w") as f:
        json.dump({"n": rnd, "rc": rc, "parsed": cell}, f)


def test_committed_history_passes():
    """THE gate: the repo's own bench trajectory must be regression-free
    (r02/r05 are rc=3 wedged rounds and must be tolerated, not failed)."""
    assert check_regression.main([]) == 0


def test_detects_throughput_regression(tmp_path, capsys):
    _bench(tmp_path, 1, 1000.0)
    _bench(tmp_path, 2, 850.0)  # -15%
    rc = check_regression.main(["--root", str(tmp_path), "--tolerance-pct", "10"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "regressed" in out and "r01" in out and "15.0%" in out


def test_tolerance_and_improvements_pass(tmp_path):
    _bench(tmp_path, 1, 1000.0)
    _bench(tmp_path, 2, 950.0)  # -5%: inside the 10% noise floor
    _bench(tmp_path, 3, 1200.0)  # improvement
    assert check_regression.main(["--root", str(tmp_path)]) == 0


def test_only_the_newest_pair_is_judged(tmp_path):
    """A historical dip that later recovered must not fail CI forever —
    the artifacts are immutable, so the gate protects only the CURRENT
    claim (newest cell vs its predecessor)."""
    _bench(tmp_path, 1, 1000.0)
    _bench(tmp_path, 2, 700.0)  # a real historical dip...
    _bench(tmp_path, 3, 1050.0)  # ...since recovered
    assert check_regression.main(["--root", str(tmp_path)]) == 0
    _bench(tmp_path, 4, 700.0)  # the NEWEST cell regressing still fails
    assert check_regression.main(["--root", str(tmp_path)]) == 1


def test_compares_latest_against_most_recent_comparable(tmp_path):
    """A wedged round between two good ones must not break the pairing:
    r03 compares against r01, the most recent round with the same metric."""
    _bench(tmp_path, 1, 1000.0)
    _bench(tmp_path, 2, 0.0, rc=3)  # lost to a wedged backend
    _bench(tmp_path, 3, 600.0)
    assert check_regression.main(["--root", str(tmp_path)]) == 1


def test_different_metric_strings_are_separate_trends(tmp_path):
    """A config change (batch size in the metric string) starts a NEW trend
    line — a smaller absolute number is not a regression."""
    _bench(tmp_path, 1, 1000.0, metric="m (batch 512)")
    _bench(tmp_path, 2, 400.0, metric="m (batch 2048)")
    assert check_regression.main(["--root", str(tmp_path)]) == 0


def test_tolerates_empty_and_null_history(tmp_path):
    assert check_regression.main(["--root", str(tmp_path)]) == 0  # no files
    _bench(tmp_path, 1, 0.0, rc=3, parsed=False)  # null cell
    _bench(tmp_path, 2, 500.0)  # first good round: no pair yet
    assert check_regression.main(["--root", str(tmp_path)]) == 0


def _serve_row(mode="closed", p99=40.0, ips=300.0, **kw):
    return {
        "kind": "serve_bench", "ts": 1.0, "mode": mode, "buckets": "1,8",
        "max_wait_ms": 2.0, "offered_rps": None, "requests": 48,
        "p50_ms": 10.0, "p95_ms": 30.0, "p99_ms": p99,
        "images_per_sec": ips, **kw,
    }


def _write_rows(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_serve_p99_and_throughput_regressions(tmp_path, capsys):
    base, new = str(tmp_path / "base.json"), str(tmp_path / "new.json")
    _write_rows(base, [_serve_row(), _serve_row(mode="open", offered_rps=400.0)])
    _write_rows(new, [
        _serve_row(p99=60.0),  # +50% p99
        _serve_row(mode="open", offered_rps=400.0, ips=200.0),  # -33% img/s
    ])
    rc = check_regression.main([
        "--root", str(tmp_path), "--serve", new, "--serve-baseline", base,
    ])
    assert rc == 1
    out = capsys.readouterr().out
    assert "p99" in out and "img/s" in out


def test_serve_empty_history_and_null_cells_pass(tmp_path):
    new = str(tmp_path / "new.json")
    _write_rows(new, [_serve_row()])
    # No baseline file: the empty-history case of the current trajectory.
    assert check_regression.main([
        "--root", str(tmp_path), "--serve", new,
        "--serve-baseline", str(tmp_path / "missing.json"),
    ]) == 0
    # Staged/null chip cells skip the comparison, not the run.
    base = str(tmp_path / "base.json")
    _write_rows(base, [_serve_row(p99=None, ips=None)])
    assert check_regression.main([
        "--root", str(tmp_path), "--serve", new, "--serve-baseline", base,
    ]) == 0


def test_serve_within_tolerance_passes(tmp_path):
    base, new = str(tmp_path / "base.json"), str(tmp_path / "new.json")
    _write_rows(base, [_serve_row(p99=40.0, ips=300.0)])
    _write_rows(new, [_serve_row(p99=42.0, ips=290.0)])  # +5% / -3%
    assert check_regression.main([
        "--root", str(tmp_path), "--serve", new, "--serve-baseline", base,
    ]) == 0
