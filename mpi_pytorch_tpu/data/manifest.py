"""Manifest (CSV) loading and deterministic sharding.

Capability parity with the reference's rank-0 CSV read + scatter
(``main.py:73-91``): rank 0 reads the manifest, ``np.array_split``s it across
ranks, and ``comm.scatter``s pickled dataframes. Here every process
deterministically computes its own shard from the same seed — no coordinator,
no pickle over the wire; the "scatter" is a pure function of
(manifest, num_shards, shard_index), which is the idiomatic per-host sharding
under ``jax.distributed``.

DEBUG sampling semantics are preserved exactly (``main.py:77-79``): sample
``debug_sample_size`` rows from the *test* CSV with seed 0, then an 80/20
train/test split.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np
import pandas as pd

from mpi_pytorch_tpu.config import Config


@dataclasses.dataclass(frozen=True)
class Manifest:
    """An image-classification manifest: filenames + integer labels."""

    filenames: tuple[str, ...]
    labels: np.ndarray  # int32 [N] — contiguous class ids
    category_ids: np.ndarray  # int64 [N] — raw Herbarium category_id column
    img_dir: str

    def __len__(self) -> int:
        return len(self.filenames)

    def shard(self, num_shards: int, shard_index: int) -> "Manifest":
        """Deterministic contiguous shard p of num_shards — the scatter
        equivalent (``main.py:84-91``). Uses np.array_split semantics so shard
        sizes match the reference exactly (first shards get the remainder)."""
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} out of range for {num_shards} shards")
        idx = np.array_split(np.arange(len(self.filenames)), num_shards)[shard_index]
        return Manifest(
            filenames=tuple(self.filenames[i] for i in idx),
            labels=self.labels[idx],
            category_ids=self.category_ids[idx],
            img_dir=self.img_dir,
        )

    def select(self, idx: Sequence[int] | np.ndarray) -> "Manifest":
        idx = np.asarray(idx)
        return Manifest(
            filenames=tuple(self.filenames[i] for i in idx),
            labels=self.labels[idx],
            category_ids=self.category_ids[idx],
            img_dir=self.img_dir,
        )


def manifest_fingerprint(manifest: Manifest) -> str:
    """Stable digest of a manifest's identity (filenames + labels + size) —
    the exact-step resume cursor (train/trainer.py) stamps it into the
    checkpoint's topology sidecar so a resume can PROVE the saved
    ``epoch_order`` offset still refers to the same dataset walk before
    fast-forwarding past it. Order-sensitive by design: a reordered CSV is
    a different walk."""
    import hashlib

    h = hashlib.sha1()
    h.update(str(len(manifest)).encode())
    for name in manifest.filenames:
        h.update(name.encode())
        h.update(b"\0")
    h.update(np.ascontiguousarray(manifest.labels).tobytes())
    return h.hexdigest()[:16]


def _to_manifest(df: pd.DataFrame, img_dir: str, label_map: dict[int, int]) -> Manifest:
    cats = df["category_id"].to_numpy(dtype=np.int64)
    labels = np.asarray([label_map[c] for c in cats], dtype=np.int32)
    return Manifest(
        filenames=tuple(df["file_name"].tolist()),
        labels=labels,
        category_ids=cats,
        img_dir=img_dir,
    )


def build_label_map(*dfs: pd.DataFrame) -> dict[int, int]:
    """Map raw Herbarium category_id → contiguous [0, num_classes) label.

    The reference feeds raw ``category_id`` straight into CrossEntropyLoss
    against a 64 500-way head (``main.py:150``, ``utils.py:39``) — valid only
    because ids happen to be < 64500. We keep that behavior when ids fit the
    head, and this explicit map is used by tests and small-vocabulary runs.
    """
    cats = np.unique(np.concatenate([df["category_id"].to_numpy(dtype=np.int64) for df in dfs]))
    return {int(c): i for i, c in enumerate(cats)}


def load_manifests(cfg: Config) -> tuple[Manifest, Manifest]:
    """Load (train, test) manifests with the reference's DEBUG semantics.

    DEBUG=True (``main.py:77-79``): read test_sample.csv, sample
    ``debug_sample_size`` rows with seed 0, 80/20 train_test_split.
    DEBUG=False (``main.py:81-82``): full train_sample.csv + test_sample.csv.
    """
    if cfg.debug:
        df = pd.read_csv(cfg.test_csv)
        df = df.sample(n=min(cfg.debug_sample_size, len(df)), random_state=cfg.seed)
        n_train = int(len(df) * 0.8)
        # sklearn's train_test_split(shuffle default) ≙ sample + positional split
        # (the sample above already shuffled with the same seed discipline).
        train_df, test_df = df.iloc[:n_train], df.iloc[n_train:]
        img_train, img_test = cfg.test_img_dir, cfg.test_img_dir
    else:
        train_df = pd.read_csv(cfg.train_csv)
        test_df = pd.read_csv(cfg.test_csv)
        img_train, img_test = cfg.train_img_dir, cfg.test_img_dir

    if cfg.num_classes >= int(max(train_df["category_id"].max(), test_df["category_id"].max())) + 1:
        # Reference behavior: raw category_id used directly as the label
        # (main.py:150 feeds category_id into CrossEntropyLoss unmapped).
        lm = {c: c for c in build_label_map(train_df, test_df)}
    else:
        lm = build_label_map(train_df, test_df)
        if len(lm) > cfg.num_classes:
            raise ValueError(
                f"{len(lm)} distinct classes in manifests exceed num_classes={cfg.num_classes}"
            )
    return _to_manifest(train_df, img_train, lm), _to_manifest(test_df, img_test, lm)
