"""MobileNetV2 in Flax (NHWC, TPU-native) — beyond-parity zoo member.

The reference zoo stops at its seven torchvision CNNs (``models.py:16-101``).
MobileNetV2 adds the inverted-residual/depthwise-separable family — the op
class the rest of the zoo lacks (depthwise 3×3s run on the VPU rather than
the MXU, so this is also the zoo's bandwidth-bound probe). Architecture per
the public MobileNetV2 paper: expand 1×1 → depthwise 3×3 → linear project
1×1, residual when stride 1 and channels match, ReLU6 activations, width
settings [(1,16,1,1), (6,24,2,2), (6,32,3,2), (6,64,4,2), (6,96,3,1),
(6,160,3,2), (6,320,1,1)], 1280-wide head conv. Parameter count matches
torchvision's mobilenet_v2 (3,504,872 at 1000 classes; asserted in
tests/test_mobilenet.py).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import batch_norm, global_avg_pool

# (expansion t, out channels c, repeats n, first stride s)
_SETTINGS = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def relu6(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.minimum(nn.relu(x), 6.0)


class InvertedResidual(nn.Module):
    features: int
    stride: int
    expand: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        bn = lambda name: batch_norm(name, dtype=self.dtype, axis_name=self.bn_axis_name)
        y = x
        if self.expand != 1:
            y = nn.Conv(
                hidden, (1, 1), use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name="expand",
            )(y)
            y = relu6(bn("expand_bn")(y, use_running_average=not train))
        # Depthwise 3x3: feature_group_count == channels puts one filter per
        # channel (VPU work on TPU — no MXU contraction dimension).
        y = nn.Conv(
            hidden, (3, 3), strides=(self.stride, self.stride), padding=1,
            feature_group_count=hidden, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="depthwise",
        )(y)
        y = relu6(bn("depthwise_bn")(y, use_running_average=not train))
        # Linear bottleneck: no activation after the projection.
        y = nn.Conv(
            self.features, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="project",
        )(y)
        y = bn("project_bn")(y, use_running_average=not train)
        if self.stride == 1 and in_ch == self.features:
            y = x + y
        return y


class MobileNetV2(nn.Module):
    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        bn = lambda name: batch_norm(name, dtype=self.dtype, axis_name=self.bn_axis_name)
        x = nn.Conv(
            32, (3, 3), strides=(2, 2), padding=1, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="stem",
        )(x)
        x = relu6(bn("stem_bn")(x, use_running_average=not train))

        block = 0
        for t, c, n, s in _SETTINGS:
            for i in range(n):
                x = InvertedResidual(
                    features=c, stride=s if i == 0 else 1, expand=t,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    bn_axis_name=self.bn_axis_name, name=f"block{block}",
                )(x, train)
                block += 1

        x = nn.Conv(
            1280, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="head_conv",
        )(x)
        x = relu6(bn("head_bn")(x, use_running_average=not train))
        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            name="head",
        )(x)


def mobilenet_v2(num_classes: int, **kw: Any) -> MobileNetV2:
    return MobileNetV2(num_classes=num_classes, **kw)
