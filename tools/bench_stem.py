"""Microbench: fused stem kernel vs XLA composition, headline shape, on chip."""
import time, functools
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
import numpy as np

from mpi_pytorch_tpu.ops.fused_stem import stem_affine_relu_pool, _reference_impl

B, H, W, C = 2048, 64, 64, 64
key = jax.random.PRNGKey(0)
y = jax.random.normal(key, (B, H, W, C), jnp.bfloat16)
a = jnp.abs(jax.random.normal(key, (C,), jnp.float32)) + 0.5
b = jax.random.normal(key, (C,), jnp.float32) * 0.1
co = jax.random.normal(key, (B, H//2, W//2, C), jnp.bfloat16)

def make(fn):
    @jax.jit
    def fwd(y, a, b):
        return fn(y, a, b)
    @jax.jit
    def fwdbwd(y, a, b, co):
        l, grads = jax.value_and_grad(
            lambda y, a, b: jnp.sum((fn(y, a, b) * co).astype(jnp.float32)),
            argnums=(0, 1, 2))(y, a, b)
        return l, grads
    return fwd, fwdbwd

def timeit(f, *args, n=30):
    r = f(*args)
    jax.block_until_ready(r)
    # value-fetch barrier (docs/RESULTS.md 4c: block_until_ready can lie here)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    leaf = jax.tree.leaves(r)[0]
    _ = float(jnp.sum(leaf.astype(jnp.float32)))
    return (time.perf_counter() - t0) / n * 1000

ref_fwd, ref_fb = make(lambda y,a,b: _reference_impl(y,a,b))
fus_fwd, fus_fb = make(lambda y,a,b: stem_affine_relu_pool(y,a,b))

# correctness on chip first
rf = ref_fwd(y,a,b); ff = fus_fwd(y,a,b)
np.testing.assert_allclose(np.asarray(rf, np.float32), np.asarray(ff, np.float32), rtol=2e-2, atol=2e-2)
_, gr = ref_fb(y,a,b,co); _, gf = fus_fb(y,a,b,co)
for u, v, name in [(gr[0], gf[0], "dy"), (gr[1], gf[1], "da"), (gr[2], gf[2], "db")]:
    np.testing.assert_allclose(np.asarray(u, np.float32), np.asarray(v, np.float32), rtol=3e-2, atol=3e-1)
print("on-chip correctness OK")

print(f"ref  fwd: {timeit(ref_fwd, y, a, b):8.3f} ms")
print(f"fused fwd: {timeit(fus_fwd, y, a, b):8.3f} ms")
print(f"ref  fwd+bwd: {timeit(ref_fb, y, a, b, co):8.3f} ms")
print(f"fused fwd+bwd: {timeit(fus_fb, y, a, b, co):8.3f} ms")
