import numpy as np
import pytest

from mpi_pytorch_tpu.config import Config
from mpi_pytorch_tpu.data import DataLoader, load_manifests, normalize_image, synthetic_image
from mpi_pytorch_tpu.data.manifest import Manifest


@pytest.fixture(scope="module")
def cfg():
    c = Config()
    c.test_csv = "/root/repo/data/test_sample.csv"
    c.train_csv = "/root/repo/data/train_sample.csv"
    c.debug = True
    return c


@pytest.fixture(scope="module")
def manifests(cfg):
    return load_manifests(cfg)


def test_debug_sampling_semantics(manifests):
    # main.py:77-79: 1000-row sample seed 0, 80/20 split
    train, test = manifests
    assert len(train) == 800
    assert len(test) == 200


def test_sharding_matches_array_split(manifests):
    train, _ = manifests
    shards = [train.shard(3, i) for i in range(3)]
    sizes = [len(s) for s in shards]
    expected = [len(a) for a in np.array_split(np.arange(len(train)), 3)]
    assert sizes == expected
    # shards partition the manifest without overlap
    all_files = [f for s in shards for f in s.filenames]
    assert all_files == list(train.filenames)


def test_labels_fit_head(manifests):
    train, test = manifests
    assert train.labels.max() < 64500  # utils.py:39 head size
    assert train.labels.min() >= 0


def test_normalize_matches_torch_semantics():
    # transforms.Normalize((0.485,...),(0.229,...)) — main.py:65
    img = np.full((4, 4, 3), 0.5, dtype=np.float32)
    out = normalize_image(img)
    expected = (0.5 - np.array([0.485, 0.456, 0.406])) / np.array([0.229, 0.224, 0.225])
    np.testing.assert_allclose(out[0, 0], expected, rtol=1e-5)


def test_synthetic_deterministic():
    a = synthetic_image(7, (16, 16))
    b = synthetic_image(7, (16, 16))
    c = synthetic_image(8, (16, 16))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.shape == (16, 16, 3)
    assert a.min() >= 0.0 and a.max() <= 1.0


def _tiny_manifest(n=20, classes=4):
    labels = np.arange(n, dtype=np.int32) % classes
    return Manifest(
        filenames=tuple(f"img_{i}.jpg" for i in range(n)),
        labels=labels,
        category_ids=labels.astype(np.int64),
        img_dir="unused",
    )


def test_loader_shapes_and_determinism():
    m = _tiny_manifest()
    dl = DataLoader(m, batch_size=8, image_size=(32, 32), synthetic=True, seed=3)
    batches = list(dl.epoch(0))
    assert len(batches) == 2  # drop_remainder: 20 // 8
    imgs, labels = batches[0]
    assert imgs.shape == (8, 32, 32, 3) and imgs.dtype == np.float32
    assert labels.shape == (8,) and labels.dtype == np.int32
    # same (seed, epoch) → same order; different epoch → different order
    again = list(dl.epoch(0))
    np.testing.assert_array_equal(batches[0][1], again[0][1])
    other = list(dl.epoch(1))
    assert not all(np.array_equal(b[1], o[1]) for b, o in zip(batches, other))


def test_loader_no_drop_remainder():
    m = _tiny_manifest(n=10)
    dl = DataLoader(m, batch_size=8, image_size=(8, 8), synthetic=True, drop_remainder=False,
                    shuffle=False)
    batches = list(dl.epoch(0))
    assert [b[0].shape[0] for b in batches] == [8, 2]
