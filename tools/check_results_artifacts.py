"""Lint docs/RESULTS.md (claims → artifacts) AND the committed metrics
artifacts themselves (``docs/*_metrics.jsonl`` → the obs record schema).

Claims lint: every numeric perf claim must cite a committed
machine-readable artifact — or be explicitly marked staged/pending/rejected.

Why (VERDICT r5 #9 / weak #1-2): the round-5 headline lived only in prose
(no raw A/B JSON, ``docs/bench_latest.json`` stale two rounds), and a
corrupt 242.4%-MFU row shipped un-annotated. The repo's brand is
measurement honesty; this linter makes claim→artifact drift a CI failure
instead of a reviewer catch (``tests/test_results_artifacts.py`` is the
tier-1 wrapper).

Contract (deliberately section-granular — prose moves, headings don't):

- The doc is split into sections at markdown headings (``#``..``####``).
- A section CLAIMS perf when any line matches a perf-number pattern
  (img/s, ms, MFU %, TFLOP/s, GB/s — the units this repo measures in).
- A claiming section PASSES when it contains at least one citation of a
  committed machine-readable artifact: a backtick-quoted token ending in
  .json/.jsonl/.log/.txt/.csv that resolves to an existing file (tried
  as-given from the repo root, then under docs/, then at the root), OR an
  explicit status marker (``staged``, ``pending``, ``rejected``,
  ``withdrawn``, ``stale``, ``not driver-confirmed``) telling the reader
  the number is not artifact-backed yet — the staleness-ledger idiom.
- Anything else fails with the section heading and the offending lines.

Metrics lint: every committed ``docs/*_metrics.jsonl`` must parse line-by-
line against the shared record schema (``mpi_pytorch_tpu/obs/schema.py``) —
a truncated write or a hand-edited record fails tier-1 instead of silently
rendering wrong in ``tools/report_run.py``.

Run: ``python tools/check_results_artifacts.py [--file docs/RESULTS.md]``
Exit 0 = every claim maps and every metrics artifact is schema-clean;
1 = violations (printed).
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The units this repo states measurements in (docs/RESULTS.md §§1-5).
PERF_CLAIM = re.compile(
    r"\d[\d\s,.]*\s*(img/s|images?/sec|ms\b|%?\s*MFU|MFU\b|TFLOP|GB/s)",
    re.IGNORECASE,
)

# Backtick-quoted machine-readable artifact path.
ARTIFACT_CITE = re.compile(r"`([^`\s]+\.(?:json|jsonl|log|txt|csv))`")

# The explicit not-yet-measured / no-longer-claimed markers (the staleness
# ledger idiom: a number may ship unbacked ONLY when the prose says so).
STATUS_MARKER = re.compile(
    r"staged|pending|rejected|withdrawn|stale|not driver-confirmed",
    re.IGNORECASE,
)

HEADING = re.compile(r"^#{1,4}\s")


def artifact_exists(path: str) -> bool:
    for cand in (path, os.path.join("docs", path), os.path.basename(path)):
        if os.path.isfile(os.path.join(REPO, cand)):
            return True
    return False


def split_sections(text: str) -> list[tuple[str, list[str]]]:
    sections: list[tuple[str, list[str]]] = [("(preamble)", [])]
    for line in text.splitlines():
        if HEADING.match(line):
            sections.append((line.strip(), []))
        else:
            sections[-1][1].append(line)
    return sections


def check(path: str) -> list[str]:
    with open(path) as f:
        text = f.read()
    violations = []
    for heading, lines in split_sections(text):
        body = "\n".join(lines)
        claims = [ln for ln in lines if PERF_CLAIM.search(ln)]
        if not claims:
            continue
        cites = [m for m in ARTIFACT_CITE.findall(heading + "\n" + body)]
        live = [c for c in cites if artifact_exists(c)]
        dead = [c for c in cites if not artifact_exists(c)]
        if live or STATUS_MARKER.search(body):
            if dead:
                violations.append(
                    f"{heading}: cites missing artifact(s): {', '.join(sorted(set(dead)))}"
                )
            continue
        sample = "; ".join(c.strip()[:80] for c in claims[:3])
        violations.append(
            f"{heading}: {len(claims)} perf-claim line(s) with no committed "
            f"artifact citation and no staged/pending marker — e.g. {sample}"
        )
    return violations


def check_metrics_artifacts(docs_dir: str | None = None) -> list[str]:
    """Schema violations across every committed ``*_metrics.jsonl`` artifact
    (the obs record schema is the contract ``report_run.py`` renders by),
    plus ``serve_bench.json`` — the serve load driver's rows are obs
    records too (``kind="serve_bench"``), so a truncated or hand-edited
    latency row fails tier-1 like any other metrics artifact."""
    docs_dir = docs_dir or os.path.join(REPO, "docs")
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    paths = sorted(glob.glob(os.path.join(docs_dir, "*_metrics.jsonl")))
    serve_bench = os.path.join(docs_dir, "serve_bench.json")
    if os.path.isfile(serve_bench):
        paths.append(serve_bench)
    violations = []
    for path in paths:
        rel = os.path.relpath(path, REPO)
        violations.extend(f"{rel}: {p}" for p in validate_jsonl(path))
    return violations


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", default=os.path.join(REPO, "docs", "RESULTS.md"))
    args = ap.parse_args()
    claim_violations = check(args.file)
    metrics_violations = check_metrics_artifacts()
    if claim_violations:
        print(f"{len(claim_violations)} claim violation(s) in {args.file}:")
        for v in claim_violations:
            print(" -", v)
    if metrics_violations:
        print(f"{len(metrics_violations)} metrics-artifact schema "
              "violation(s) (paths below are the offending files):")
        for v in metrics_violations:
            print(" -", v)
    if claim_violations or metrics_violations:
        return 1
    print(f"ok: every perf-claiming section of {args.file} cites a committed "
          "artifact or carries an explicit staged/pending marker, and every "
          "docs/*_metrics.jsonl record matches the obs schema")
    return 0


if __name__ == "__main__":
    sys.exit(main())
