"""AlexNet in Flax (NHWC). Parity with the reference's torchvision alexnet
factory (``models.py:47-54``): five-conv feature stack, adaptive 6×6 pool,
4096-4096-num_classes classifier with dropout."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import adaptive_avg_pool, max_pool


class AlexNet(nn.Module):
    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=p,
            dtype=self.dtype, param_dtype=self.param_dtype, name=name,
        )
        x = nn.relu(conv(64, 11, 4, 2, "conv1")(x))
        x = max_pool(x, 3, 2)
        x = nn.relu(conv(192, 5, 1, 2, "conv2")(x))
        x = max_pool(x, 3, 2)
        x = nn.relu(conv(384, 3, 1, 1, "conv3")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv4")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv5")(x))
        x = max_pool(x, 3, 2)

        x = adaptive_avg_pool(x, (6, 6))
        x = x.reshape(x.shape[0], -1)

        dense = lambda f, name: nn.Dense(
            f, dtype=self.dtype, param_dtype=self.param_dtype, name=name
        )
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc2")(x))
        # Head matmul in compute dtype (bf16 rides the MXU; measured 2.38 vs
        # 2.96 ms fwd+bwd at B=512/V=64500 on v5e); the loss re-casts logits
        # to float32 for a stable softmax (ops/losses.py).
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)


def alexnet(num_classes: int, **kw: Any) -> AlexNet:
    return AlexNet(num_classes=num_classes, **kw)
