"""Checkpoint save/restore — parity with ``helpers.py`` + its call sites.

Reference semantics preserved:
- epoch-granular save of ``{epoch, state_dict, optimizer, loss}``
  (``main.py:162-171``, ``helpers.py:4-7``) → here
  ``{epoch, params, batch_stats, opt_state, loss, step, config}``;
- rank-0-only writes (``main.py:162``) → process-0-only writes;
- ``FROM_CHECKPOINT`` resume restoring model+optimizer and returning the
  epoch (``main.py:127-130``, ``helpers.py:10-15``);
- post-restore broadcast (``sync_params``, ``main.py:131``) → restored
  arrays are ``device_put`` replicated/sharded onto the mesh.

Improvements the reference lacks (SURVEY §5 failure-detection row): the file
is written atomically (tmp+rename, so a crash mid-write can't corrupt the
resume path — the reference overwrites its single fixed path in place,
``helpers.py:6-7``), the last-k checkpoints are kept, and ``latest`` resolves
automatically for auto-resume.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import numpy as np
from flax import serialization

from mpi_pytorch_tpu.utils.logging import process_index

_CKPT_RE = re.compile(r"ckpt_(\d+)\.msgpack$")


def _ckpt_path(ckpt_dir: str, epoch: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_{epoch:05d}.msgpack")


def _payload(state: Any, epoch: int = 0, loss: float = 0.0) -> dict:
    """The single checkpoint schema, used both as the save payload and as the
    restore template so the two can never drift apart."""
    return {
        "epoch": epoch,
        "step": np.asarray(state.step),
        "loss": np.asarray(loss, np.float32),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats)
        if state.batch_stats is not None
        else {},
        "opt_state": jax.device_get(state.opt_state),
        "rng": jax.device_get(state.rng),
    }


def save_checkpoint(
    ckpt_dir: str,
    *,
    epoch: int,
    state: Any,
    loss: float,
    keep: int = 3,
) -> str | None:
    """Write checkpoint (process 0 only); returns the path written."""
    if process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    payload = _payload(state, epoch, loss)
    path = _ckpt_path(ckpt_dir, epoch)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    os.replace(tmp, path)  # atomic on POSIX
    _cleanup(ckpt_dir, keep)
    return path


def _cleanup(ckpt_dir: str, keep: int) -> None:
    ckpts = sorted(
        (m.group(1), name)
        for name in os.listdir(ckpt_dir)
        if (m := _CKPT_RE.search(name))
    )
    for _, name in ckpts[:-keep] if keep > 0 else []:
        os.remove(os.path.join(ckpt_dir, name))


def latest_checkpoint(ckpt_dir: str) -> str | None:
    if not os.path.isdir(ckpt_dir):
        return None
    ckpts = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(ckpt_dir)
        if (m := _CKPT_RE.search(name))
    )
    return os.path.join(ckpt_dir, ckpts[-1][1]) if ckpts else None


def load_checkpoint(path: str, state: Any) -> tuple[Any, int, float]:
    """Restore (state, epoch, loss) from a checkpoint file (≙
    ``load_checkpoint``, helpers.py:10-15 — which returns the epoch so the
    driver can continue the epoch loop, main.py:127-129)."""
    with open(path, "rb") as f:
        data = f.read()
    restored = serialization.from_bytes(_payload(state), data)
    new_state = state.replace(
        step=jax.numpy.asarray(restored["step"]),
        params=restored["params"],
        batch_stats=restored["batch_stats"] if state.batch_stats is not None else None,
        opt_state=restored["opt_state"],
        rng=jax.numpy.asarray(restored["rng"]),
    )
    return new_state, int(restored["epoch"]), float(restored["loss"])


def load_for_eval(path: str, state: Any) -> tuple[Any, int, float]:
    """Restore params + batch_stats only — the inference path (≙ predictor
    ranks loading just the ``state_dict``, ``evaluation_pipeline.py:142-144``).
    No optimizer template is needed, so eval never materializes Adam moments."""
    with open(path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    params = serialization.from_state_dict(jax.device_get(state.params), raw["params"])
    batch_stats = None
    if state.batch_stats is not None:
        batch_stats = serialization.from_state_dict(
            jax.device_get(state.batch_stats), raw["batch_stats"]
        )
    new_state = state.replace(params=params, batch_stats=batch_stats)
    return new_state, int(raw["epoch"]), float(raw["loss"])
