"""Headline benchmark: resnet18 training throughput, images/sec/chip.

Mirrors the reference's north-star workload (``main.py``: resnet18, 64 500
classes, batch 128, Adam 4e-4, 128×128 inputs) as one jitted DP train step
over all available chips, bfloat16 compute. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

``vs_baseline`` is value ÷ the reference's best *per-worker* throughput
(≈4.4 img/s/worker — 800 imgs / 45.4 s over 4 MPI ranks, derived from
``training.log:1268-1275``; see BASELINE.md).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMG_PER_SEC_PER_WORKER = 4.4  # BASELINE.md, training.log:1268-1275

MODEL = "resnet18"
NUM_CLASSES = 64500  # utils.py:39
IMAGE = 128          # utils.py:33-34
GLOBAL_BATCH = 128   # utils.py:40
WARMUP_STEPS = 5
MEASURE_STEPS = 30


def main() -> None:
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh

    n_chips = jax.device_count()
    # Per-chip batch 128 (so one chip runs the reference's exact global batch;
    # more chips scale the global batch like adding MPI ranks does).
    batch = GLOBAL_BATCH * n_chips

    mesh = create_mesh(Config().mesh)
    bundle, variables = create_model_bundle(
        MODEL, NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=IMAGE,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(4e-4), rng=jax.random.PRNGKey(1),
    )
    state = place_state_on_mesh(state, mesh)
    step = make_train_step(jnp.bfloat16)

    rng = np.random.default_rng(0)
    images = rng.standard_normal((batch, IMAGE, IMAGE, 3), np.float32)
    labels = rng.integers(0, NUM_CLASSES, size=(batch,), dtype=np.int64).astype(np.int32)
    device_batch = shard_batch((images, labels), mesh)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, device_batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, device_batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    ips = MEASURE_STEPS * batch / dt
    ips_per_chip = ips / n_chips
    print(json.dumps({
        "metric": f"{MODEL} train images/sec/chip (bf16, {NUM_CLASSES} classes, batch {GLOBAL_BATCH}/chip)",
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / REFERENCE_IMG_PER_SEC_PER_WORKER, 2),
    }))


if __name__ == "__main__":
    main()
