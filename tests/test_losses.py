"""Loss-op parity with the reference's actual loss (``nn.CrossEntropyLoss``,
``main.py:56,150``), checked against real torch on CPU, plus the padding-mask
and inception-aux semantics the framework adds."""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_pytorch_tpu.ops.losses import (
    AUX_LOSS_WEIGHT,
    accuracy_count,
    classification_loss,
    cross_entropy,
    valid_count,
)

torch = pytest.importorskip("torch")


def _rand(b=16, c=50, seed=0):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(b, c)).astype(np.float32)
    labels = rng.integers(0, c, size=(b,)).astype(np.int32)
    return logits, labels


def test_cross_entropy_matches_torch():
    logits, labels = _rand()
    ours = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    theirs = float(
        torch.nn.CrossEntropyLoss()(
            torch.from_numpy(logits), torch.from_numpy(labels.astype(np.int64))
        )
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-6)


def test_cross_entropy_big_head_matches_torch():
    # The reference's actual head size: softmax over 64 500 logits in f32.
    logits, labels = _rand(b=4, c=64500, seed=1)
    ours = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    theirs = float(
        torch.nn.CrossEntropyLoss()(
            torch.from_numpy(logits), torch.from_numpy(labels.astype(np.int64))
        )
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-5)


def test_padding_rows_are_masked():
    logits, labels = _rand()
    padded_labels = labels.copy()
    padded_labels[10:] = -1  # padding marker
    ours = float(cross_entropy(jnp.asarray(logits), jnp.asarray(padded_labels)))
    # torch's own masking convention (ignore_index) must agree
    theirs = float(
        torch.nn.CrossEntropyLoss(ignore_index=-1)(
            torch.from_numpy(logits), torch.from_numpy(padded_labels.astype(np.int64))
        )
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-6)
    assert int(valid_count(jnp.asarray(padded_labels))) == 10


def test_all_padding_batch_is_zero_loss_not_nan():
    logits, _ = _rand()
    labels = np.full(16, -1, np.int32)
    assert float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels))) == 0.0
    assert int(valid_count(jnp.asarray(labels))) == 0
    assert int(accuracy_count(jnp.asarray(logits), jnp.asarray(labels))) == 0


def test_inception_aux_weighting():
    logits, labels = _rand(seed=2)
    aux, _ = _rand(seed=3)
    total = float(classification_loss((jnp.asarray(logits), jnp.asarray(aux)), jnp.asarray(labels)))
    main = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    auxl = float(cross_entropy(jnp.asarray(aux), jnp.asarray(labels)))
    np.testing.assert_allclose(total, main + AUX_LOSS_WEIGHT * auxl, rtol=1e-6)


def test_accuracy_count_matches_manual():
    logits, labels = _rand(seed=4)
    labels[3] = -1
    manual = int(np.sum((np.argmax(logits, axis=-1) == labels) & (labels >= 0)))
    assert int(accuracy_count(jnp.asarray(logits), jnp.asarray(labels))) == manual
