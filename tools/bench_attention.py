"""Attention microbench: full (materialized S×S) vs flash (Pallas) vs the
fused tiny-S kernel, on chip.

Default mode sweeps long sequences — the flash kernel's domain:

    python tools/bench_attention.py [--seqs 512,1024,2048,4096] [--out f]

``--fused-small`` is the tiny-S staged A/B (docs/RESULTS.md §4, the
vit_s16 candidate): S ∈ {64, 65, 50, 128} at a (batch·head) count big enough
to fill the grid, one JSON row per (impl, S) plus one per
``MPT_ATTN_BH_BLOCK`` lever value for the fused kernel — each fused row
CORRECTNESS-GATED against full attention on chip before any timing ships,
and the ambient ``MPT_ATTN_*`` environment snapshotted/cleared/restored
around the sweep so an operator's exported lever cannot contaminate a row
(the same env-hygiene guard as ``bench_stem --levers``). A rejected
config still lands as an error row, never a silent drop.

On non-TPU backends the flash and fused-small paths fall back to full
attention (their module gating), so chip runs are the meaningful ones;
the battery stages this after the zoo sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

H, D = 6, 64  # vit_s16-shaped heads
DEFAULT_BATCH = 4          # long-S mode: S×S dominates, tiny B suffices
FUSED_SMALL_BATCH = 256    # tiny-S mode: enough (b·h) tiles to fill the grid

# (label, env) — the tiny-S bh-grouping lever matrix (MPT_ATTN_BH_BLOCK;
# ops/fused_attention_small.py _bh_block). "auto" is the kernel default.
FUSED_SMALL_CONFIGS = [
    ("auto", {}),
    ("bh1", {"MPT_ATTN_BH_BLOCK": "1"}),
    ("bh2", {"MPT_ATTN_BH_BLOCK": "2"}),
    ("bh4", {"MPT_ATTN_BH_BLOCK": "4"}),
]


def _impl_fn(impl: str):
    from mpi_pytorch_tpu.ops.flash_attention import flash_attention
    from mpi_pytorch_tpu.ops.fused_attention_small import fused_attention_small
    from mpi_pytorch_tpu.ops.ring_attention import full_attention

    return {
        "full": lambda q, k, v: full_attention(q, k, v),
        "flash": lambda q, k, v: flash_attention(q, k, v),
        "fused-small": lambda q, k, v: fused_attention_small(q, k, v),
    }[impl]


def _check_vs_full(fn, q, k, v):
    """On-chip correctness gate before any timing ships (the bench_stem
    --levers discipline): values AND all three gradients — the timed row
    is fwd+bwd, and the fused kernel's recompute backward is its own
    Mosaic program, so a chip-only backward miscompile (the class of bug
    the flash lse block spec hit on hardware, docs/RESULTS.md §4c) must
    fail the gate, not ship inside a timing row. bf16 storage tolerances —
    identical math, bf16 quantization on in/out."""
    from mpi_pytorch_tpu.ops.ring_attention import full_attention

    got = jax.jit(fn)(q, k, v)
    want = jax.jit(lambda q, k, v: full_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )

    def grads(f):
        loss = lambda q_, k_, v_: jnp.sum(f(q_, k_, v_).astype(jnp.float32) ** 2)
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    for g_got, g_want in zip(grads(fn),
                             grads(lambda q, k, v: full_attention(q, k, v))):
        np.testing.assert_allclose(
            np.asarray(g_got, np.float32), np.asarray(g_want, np.float32),
            rtol=5e-2, atol=5e-1,
        )


def bench_one(impl: str, seq: int, steps: int, warmup: int, batch: int,
              check: bool = False, label: str | None = None,
              env: dict | None = None) -> dict:
    fn = _impl_fn(impl)

    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, seq, H, D)), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    if check:
        _check_vs_full(fn, q, k, v)

    # The inputs are DONATED and each step consumes the previous step's
    # outputs (a true dependency chain), and the timing barrier is a VALUE
    # FETCH of a scalar computed from the final state — measured live on
    # this relay: ``block_until_ready`` returns in ~0.03 ms/step while the
    # actual chained work takes ~170 ms/step (the relay acks readiness
    # without execution). A fetched value cannot be fabricated, so the
    # fetch is the only trustworthy barrier for short programs; its one
    # round-trip is amortized over ``steps``.
    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(q, k, v):
        def loss(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_).astype(jnp.float32) ** 2)

        _, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        eps = jnp.asarray(1e-3, q.dtype)  # tiny axpy: negligible vs attention
        return q - eps * grads[0], k - eps * grads[1], v - eps * grads[2]

    compiled = step.lower(q, k, v).compile()
    mem = None
    try:
        ma = compiled.memory_analysis()
        mem = int(getattr(ma, "temp_size_in_bytes", 0))
    except Exception:
        pass

    def sync(x):  # true execution barrier (see note above)
        return float(jnp.sum(x.astype(jnp.float32)))

    for _ in range(warmup):
        q, k, v = compiled(q, k, v)
    sync(q)
    t0 = time.perf_counter()
    for _ in range(steps):
        q, k, v = compiled(q, k, v)
    sync(q)
    dt = (time.perf_counter() - t0) / steps

    rec = {
        "impl": impl, "seq": seq, "batch": batch, "heads": H, "head_dim": D,
        "fwd_bwd_ms": round(dt * 1e3, 3),
    }
    if label is not None:
        rec["label"] = label
    if env:
        rec["env"] = env
    if mem is not None:
        rec["temp_hbm_mb"] = round(mem / 1e6, 1)
    return rec


def sweep_long(args) -> list[dict]:
    records = []
    for seq in (int(s) for s in args.seqs.split(",") if s):
        for impl in ("full", "flash"):
            try:
                rec = bench_one(impl, seq, args.steps, args.warmup, args.batch)
            except Exception as e:
                rec = {"impl": impl, "seq": seq,
                       "error": f"{type(e).__name__}: {e}"[:300]}
            records.append(rec)
            print(json.dumps(rec), flush=True)
    return records


def sweep_fused_small(args) -> list[dict]:
    """The tiny-S staged A/B: full / flash baselines + the fused kernel per
    bh-grouping lever, correctness-gated, env-hygienic."""
    records = []
    # Every row must measure EXACTLY its config: ambient MPT_ATTN_* vars
    # (e.g. a lever the operator exported while experimenting) would
    # otherwise contaminate every row including the baselines. Snapshot
    # them, clear before each config, restore when done (the bench_stem
    # --levers guard).
    gate_keys = sorted(
        {k for _, env in FUSED_SMALL_CONFIGS for k in env}
        | {k for k in os.environ if k.startswith("MPT_ATTN_")}
    )
    ambient = {k: os.environ.get(k) for k in gate_keys}
    try:
        for seq in (int(s) for s in args.seqs.split(",") if s):
            for impl, label, env in (
                [("full", None, {}), ("flash", None, {})]
                + [("fused-small", lbl, env) for lbl, env in FUSED_SMALL_CONFIGS]
            ):
                for k in gate_keys:
                    os.environ.pop(k, None)
                os.environ.update(env)
                try:
                    rec = bench_one(
                        impl, seq, args.steps, args.warmup, args.batch,
                        check=(impl == "fused-small"), label=label, env=env,
                    )
                except Exception as e:  # a rejected config is still a row
                    rec = {"impl": impl, "seq": seq, "label": label,
                           "env": env,
                           "error": f"{type(e).__name__}: {e}"[:300]}
                records.append(rec)
                print(json.dumps(rec), flush=True)
    finally:
        for k, v in ambient.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return records


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default=None,
                    help="comma-separated sequence lengths "
                    "(default 512,1024,2048,4096; 64,50,128 with --fused-small)")
    ap.add_argument("--batch", type=int, default=None,
                    help=f"batch size (default {DEFAULT_BATCH}; "
                    f"{FUSED_SMALL_BATCH} with --fused-small)")
    ap.add_argument("--fused-small", action="store_true",
                    help="tiny-S staged A/B: full/flash vs the fused tiny-S "
                    "kernel per MPT_ATTN_BH_BLOCK lever (correctness-gated, "
                    "ambient MPT_ATTN_* cleared per row)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    if args.seqs is None:
        # 64 = the vit_s16 token count (GAP head, S == patch count); 65 =
        # the class-token variant (odd S → padded rows + bh-group G=1, a
        # different tiling); 50 = heavy padding; 128 = the envelope edge.
        args.seqs = "64,65,50,128" if args.fused_small else "512,1024,2048,4096"
    if args.batch is None:
        args.batch = FUSED_SMALL_BATCH if args.fused_small else DEFAULT_BATCH

    records = sweep_fused_small(args) if args.fused_small else sweep_long(args)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
