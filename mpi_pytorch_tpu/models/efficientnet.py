"""EfficientNet-B0 in Flax (NHWC, TPU-native) — beyond-parity zoo member.

The reference zoo stops at its seven torchvision CNNs (``models.py:16-101``).
EfficientNet-B0 adds the compound-scaled MBConv family: squeeze-excitation
(the zoo's only channel-attention op), SiLU activations, per-sample
stochastic depth, and 5×5 depthwise kernels. Architecture per the public
EfficientNet paper / torchvision's ``efficientnet_b0``: stem 3×3 s2 → 32ch,
MBConv settings [(1,16,1,1,3), (6,24,2,2,3), (6,40,2,2,5), (6,80,3,2,3),
(6,112,3,1,5), (6,192,4,2,5), (6,320,1,1,3)] (expand, channels, repeats,
stride, kernel), SE squeeze = input_channels/4, head conv 1280, dropout 0.2,
BN eps 1e-3, stochastic-depth rate 0.2 scaled linearly over block depth.
Parameter count matches torchvision's efficientnet_b0 (5,288,548 at 1000
classes; asserted in tests/test_efficientnet.py).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import batch_norm, global_avg_pool

_BN_EPS = 1e-3  # efficientnet's BN epsilon (torch default is 1e-5)

# (expansion t, out channels c, repeats n, first stride s, kernel k)
_SETTINGS = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)
_DROP_PATH_RATE = 0.2  # final stochastic-depth rate; scaled by block index


class SqueezeExcite(nn.Module):
    """SE channel attention: global pool → reduce 1×1 → SiLU → expand 1×1 →
    sigmoid gate. Squeeze width comes from the BLOCK INPUT channels (÷4),
    not the expanded width — the efficientnet convention."""

    squeeze: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(
            self.squeeze, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype,
            name="reduce",
        )(s)
        s = nn.silu(s)
        s = nn.Conv(
            x.shape[-1], (1, 1), dtype=self.dtype, param_dtype=self.param_dtype,
            name="expand",
        )(s)
        return x * nn.sigmoid(s)


class MBConv(nn.Module):
    features: int
    stride: int
    expand: int
    kernel: int
    se_squeeze: int
    drop_rate: float
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        bn = lambda name: batch_norm(
            name, dtype=self.dtype, axis_name=self.bn_axis_name, eps=_BN_EPS
        )
        y = x
        if self.expand != 1:
            y = nn.Conv(
                hidden, (1, 1), use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name="expand",
            )(y)
            y = nn.silu(bn("expand_bn")(y, use_running_average=not train))
        y = nn.Conv(
            hidden, (self.kernel, self.kernel),
            strides=(self.stride, self.stride), padding=self.kernel // 2,
            feature_group_count=hidden, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="depthwise",
        )(y)
        y = nn.silu(bn("depthwise_bn")(y, use_running_average=not train))
        y = SqueezeExcite(
            self.se_squeeze, dtype=self.dtype, param_dtype=self.param_dtype,
            name="se",
        )(y)
        y = nn.Conv(
            self.features, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="project",
        )(y)
        y = bn("project_bn")(y, use_running_average=not train)
        if self.stride == 1 and in_ch == self.features:
            if train and self.drop_rate > 0.0:
                # Per-sample stochastic depth ("row" mode): drop the whole
                # residual branch for a fraction of the batch, scale the rest.
                keep = 1.0 - self.drop_rate
                mask = jax.random.bernoulli(
                    self.make_rng("dropout"), keep, shape=(y.shape[0], 1, 1, 1)
                )
                y = jnp.where(mask, y / keep, jnp.zeros_like(y))
            y = x + y
        return y


class EfficientNetB0(nn.Module):
    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        bn = lambda name: batch_norm(
            name, dtype=self.dtype, axis_name=self.bn_axis_name, eps=_BN_EPS
        )
        x = nn.Conv(
            32, (3, 3), strides=(2, 2), padding=1, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="stem",
        )(x)
        x = nn.silu(bn("stem_bn")(x, use_running_average=not train))

        total_blocks = sum(n for _, _, n, _, _ in _SETTINGS)
        block = 0
        for t, c, n, s, k in _SETTINGS:
            for i in range(n):
                in_ch = x.shape[-1]
                x = MBConv(
                    features=c, stride=s if i == 0 else 1, expand=t, kernel=k,
                    se_squeeze=max(1, in_ch // 4),
                    drop_rate=_DROP_PATH_RATE * block / total_blocks,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    bn_axis_name=self.bn_axis_name, name=f"block{block}",
                )(x, train)
                block += 1

        x = nn.Conv(
            1280, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name="head_conv",
        )(x)
        x = nn.silu(bn("head_bn")(x, use_running_average=not train))
        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            name="head",
        )(x)


def efficientnet_b0(num_classes: int, **kw: Any) -> EfficientNetB0:
    return EfficientNetB0(num_classes=num_classes, **kw)
