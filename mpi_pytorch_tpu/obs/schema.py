"""THE metrics-record schema — one definition, three consumers.

``MetricsWriter`` streams are consumed by ``tools/report_run.py`` (render),
``tools/check_results_artifacts.py`` (CI lint over the committed
``docs/*_metrics.jsonl`` artifacts), and ad-hoc analysis; all three validate
through here so the record shapes cannot drift between writer and readers.

Deliberately dependency-free (no jax, no numpy): the tools import this
module without initializing a backend.

Record kinds (every record also carries ``ts``, the epoch-seconds stamp
``MetricsWriter`` adds, and ``kind``):

| kind      | required                                            | optional |
|-----------|-----------------------------------------------------|----------|
| epoch     | epoch, loss, time_s, images_per_sec                 | tflops, mfu_pct |
| val       | epoch, accuracy, loss                               |          |
| eval      | accuracy, loss, images, time_s                      |          |
| step      | epoch, step, loss                                   | grad_norm, data_wait_ms, step_ms, recompiles, hbm_bytes, sync_ms, overlap_frac, dcn_overlap_frac, skipped, steps_skipped |
| heartbeat | epoch, step, step_ms, median_step_ms, stragglers, threshold | images_per_sec |
| anomaly   | reason, epoch                                       | step, loss, grad_norm, path, detail |
| serve     | bucket, requests, queue_depth, fill_ratio, queue_wait_ms, device_ms | preprocess_ms, total_ms, precision, model |
| serve_bench | mode, buckets, max_wait_ms, requests, p50_ms, p95_ms, p99_ms, images_per_sec | model, offered_rps, rejected, mean_fill_ratio, compiles_after_warmup, chips, precision, parity_top1, load_shape |
| quant_parity | precision, top1_agree, samples                   | top5_agree, max_logit_drift, model |
| resume    | epoch, to_devices                                   | from_devices, from_mesh, to_mesh, path, zero_shards_from, zero_shards_to, corrupt_skipped, strategy, cursor_epoch, cursor_step |
| fault     | reason                                              | epoch, step, detail, streak |
| rollback  | epoch, reason                                       | step, restored_epoch, rollbacks, lr_scale, path, detail |
| metrics   | counters, gauges, histograms                        | merged_hosts |
| alert     | rule, severity                                      | metric, value, threshold, streak, action, detail, epoch, step |
| route     | host, requests                                      | share, score, queue_depth, inflight, window_s, transport, trace_ids, models |
| fleet     | event                                               | host, detail, redispatched, spare, max_wait_ms_from/to, buckets_from/to, p99_ms, target_p99_ms, compiles_after_warmup, hosts_from/to, reason, reject_rate, queue_depth, restarts, transport, model, resident, plan |
| timeline  | host, metric, points                                | window_s, clock_offset_ms, resets |
| hedge     | winner, loser                                       | cancelled, deadline_ms, trace_id |
| canary    | model, event                                        | agreement_top1, agreement_topk, rank_drift, probes, verdict, mutation, reason, detail |

``serve`` is the per-flush record the online inference server writes
(serve/server.py: one coalesced batch dispatched to a bucket executable);
``serve_bench`` is a latency/throughput summary row from the load driver
(tools/bench_serve.py — the committed ``docs/serve_bench.json`` rows).
``resume`` is written once per elastic restore (train/elastic.py): the
checkpoint's topology-manifest shape vs the mesh actually resumed onto;
``fault`` is written when a preemption/fault signal is observed (the
watchdog's SIGTERM / sentinel-file / streak triggers, and the
fault-injection gates of ``tools/inject_faults.py`` announcing themselves
before they strike).

Optional fields may be ``null`` (unknown on this backend — e.g. HBM bytes
on CPU, per-step host timing in scan-epoch mode); required fields may not.
Unknown EXTRA keys are allowed (forward compatibility); unknown KINDS are
not (a typo'd kind is exactly the malformed record this schema exists to
catch).
"""

from __future__ import annotations

import json
from typing import Any, Mapping

# Schema generations (additive only — readers accept every prior version's
# records, and optional fields never become required):
#   1: epoch/val/eval/step/heartbeat/anomaly (+serve, serve_bench in PR 4)
#   2: step records may carry the grad-sync fields ``sync_ms`` (measured
#      per-step gradient-sync milliseconds, where a tool measured one) and
#      ``overlap_frac`` (the static bucket-plan overlap estimate the
#      spmd --grad-sync-buckets trainer stamps; train/step.py
#      bucket_overlap_frac) — ISSUE 6 / ROADMAP item 2.
#   3: the elastic-training kinds ``resume`` (topology of an elastic
#      restore) and ``fault`` (an observed preemption/fault signal), plus
#      the ``serve`` record's optional ``preprocess_failures`` /
#      ``worker_respawns`` counts — ISSUE 7 / ROADMAP item 4.
#   4: the live-telemetry kinds ``metrics`` (a point-in-time snapshot of
#      the in-process metrics registry, ``obs/metrics.py`` — counters,
#      gauges, and histogram summaries with sketch-derived p50/p95/p99)
#      and ``alert`` (one SLO-rule breach from the monitor,
#      ``obs/monitor.py``: the rule that fired, the observed value vs its
#      threshold, and the action(s) taken) — ISSUE 8.
#   5: the fleet-serving kinds ``route`` (one per-host routing window from
#      the fleet router, ``serve/fleet/router.py``: requests dispatched to
#      that host in the window, its EWMA load score, queue depth) and
#      ``fleet`` (one fleet lifecycle event: a failover — host drained,
#      in-flight requests re-dispatched, warm spare promoted — or a
#      controller retune of ``max_wait_ms`` / the active bucket set,
#      ``serve/fleet/controller.py``), plus the ``serve_bench`` row's
#      optional ``fleet_hosts`` / ``per_host`` breakdown
#      (``tools/bench_serve.py --fleet N``) — ISSUE 9 / ROADMAP item 1.
#   6: the self-healing-training fields (ISSUE 10): the ``rollback`` kind
#      (one in-process bad-step rollback — the trigger, the checkpoint
#      restored, the rollback count and LR scale), the ``step`` record's
#      optional ``skipped``/``steps_skipped`` fields (--bad-step-policy
#      skip: this step's update was discarded / cumulative discards), the
#      ``resume`` record's optional ``cursor_epoch``/``cursor_step``
#      (the exact-step data cursor stamped in the checkpoint's topology
#      sidecar), and the ``anomaly`` record's optional ``path``/``detail``
#      (``reason=bad_sample`` quarantines name the undecodable file).
#   7: the quantized-serving fields (ISSUE 11): ``precision`` on ``serve``
#      flushes (which startup-compiled executable set ran the batch —
#      stamped when a server holds multiple sets or serves non-bf16) and
#      on ``serve_bench`` rows (plus ``parity_top1``, the int8-vs-bf16
#      startup agreement, on int8 rows); ``precision_from``/
#      ``precision_to`` + ``parity_top1`` on ``fleet`` retune records
#      (the controller's precision axis, with the measured top-1 parity
#      delta on the record); and the ``quant_parity`` kind — one offline
#      int8-vs-bf16 parity report from ``evaluate --quantize-eval``
#      (top-1/top-5 agreement + max logit drift on a fixed sample).
#   8: the remote-fleet generation (ISSUE 12): ``fleet`` records grow the
#      autoscaler/supervisor events ``scale_up``/``scale_down``/
#      ``restart`` with their evidence fields (``hosts_from``/``hosts_to``
#      host counts, ``reason``, the front-door ``reject_rate`` rejects/s,
#      the summed ``queue_depth``, the supervisor's cumulative
#      ``restarts``); and ``route``/``fleet``/``serve_bench`` records may
#      carry ``transport`` ("http" when the row came from real serving
#      processes over the wire — stamped only when the axis is live, so
#      in-process streams stay byte-identical to prior generations, and
#      ``check_regression`` keys it into the serve trend-line identity).
#   9: the distributed-tracing generation (ISSUE 13): the ``timeline``
#      kind — one per-(host, metric) time-series window from the fleet
#      collector (``obs/collector.py``: gauge samples / counter RATES as
#      ``points`` [[ts, value], ...], the host's probe-RTT clock-offset
#      estimate, and how many counter RESETS — host restarts — the
#      collector absorbed instead of booking negative rates); optional
#      ``trace_ids`` on ``serve`` flushes and ``route`` windows (the
#      W3C-traceparent-style trace ids of the TRACED requests they
#      carried — absent on untraced traffic, so tracing-off streams stay
#      byte-identical to v8); optional ``trace_id`` on ``fault`` records
#      (a fault gate firing inside a traced request names its victim
#      trace, so chaos evidence joins the exact waterfall it disrupted);
#      and optional ``per_phase`` on ``serve_bench`` rows (the
#      collector-derived queue/preprocess/device/wire p50/p99 breakdown
#      per sweep point).
#  10: the multi-model-tenancy generation (ISSUE 14): ``serve`` flushes
#      may carry ``model`` (the tenant the single-tenant-by-construction
#      flush served), ``route`` windows may carry ``models`` (per-tenant
#      dispatch counts of the window), ``fleet`` records grow the zoo
#      lifecycle events ``swap_in``/``evict`` (the cold-model swap-in /
#      LRU-or-operator eviction, with ``model``, the ``resident`` tenant
#      list after the change, and — on swap-ins — the explainable
#      packing ``plan`` the decision rested on), controller ``retune``
#      and autoscaler ``scale_up``/``scale_down`` records may carry
#      ``model`` (the tenant retuned / the pressured tenant), ``alert``
#      records may carry ``model`` (the SLO monitor's tenant label), and
#      ``serve_bench`` rows may carry ``load_shape`` (the multi-tenant
#      sweep's traffic shape, e.g. "uniform" / "hot:resnet18"). All
#      absent on untenanted serving — streams stay byte-identical to v9.
#  11: the cross-pod hierarchical-training generation (ISSUE 15 / ROADMAP
#      item 5): ``step`` records may carry ``dcn_overlap_frac`` (the
#      static estimate of how much of the two-level grad sync's CROSS-POD
#      (DCN) traffic is issued before the final reverse-topo bucket —
#      stamped only on ``--mesh-pods > 1`` runs, so flat-mesh streams stay
#      byte-identical to v10; the within-pod twin is v2's
#      ``overlap_frac``). The checkpoint topology manifest and ``resume``
#      records carry the pod factoring implicitly via their mesh-shape
#      strings (``pod=2,ici=4,model=1``) — no new fields.
#  12: the tail-at-scale data-plane generation (ISSUE 16): the ``hedge``
#      kind — one per hedged request that raced (router-level request
#      hedging over the framed wire, ``serve/fleet/router.py``: which
#      host won, which lost, whether the loser was revoked in flight,
#      and the p99-derived deadline that fired the hedge; ``trace_id``
#      when the request was traced); ``serve_bench`` rows may carry
#      ``hedged`` (how many requests of the sweep point hedged) and
#      ``copies_per_request`` (the zero-copy dispatch assertion: input
#      bytes touched exactly once between wire and ``device_put``);
#      ``transport`` values grow "framed" / "framed+hedge" (the binary
#      framed wire of ``serve/wire.py`` — check_regression already keys
#      transport into the serve trend-line identity). All absent on
#      HTTP/in-process serving — streams stay byte-identical to v11.
#  13: the model-parallel-residency generation (ISSUE 17): ``serve``
#      flushes and ``serve_bench`` rows may carry ``shard_degree`` (how
#      many chips one copy of the serving params spans — absent on
#      replicated tenants, so pre-sharding streams stay byte-identical
#      to v12); ``fleet`` swap_in/retune records may carry ``residency``
#      (the tenant's weight layout after the event — "replicated" /
#      "tp:K" / "fsdp:K"), ``reshard_bytes`` (total bytes the bounded
#      per-leaf cross-topology reshard moved), and ``shard_degree``.
#  14: the trace-replay generation (ISSUE 18): fleet-trace ROOT spans
#      (``route/request``) carry ``model``/``bucket``/``rows``/
#      ``precision`` attrs (joined from the winning ``serve/request``
#      span at collector finalize — trace files are spans, not metrics
#      records, so this is documented here rather than type-checked;
#      pre-v14 traces replay with documented defaults). ``serve_bench``
#      rows may carry ``workload`` (the 16-hex content fingerprint of
#      the replayed workload artifact — check_regression keys it so a
#      replay row never compares against a synthetic-Poisson baseline),
#      ``speed`` (the replay time-warp factor, absent at 1.0), and
#      ``replay_diff`` (the recorded-vs-replayed differential report:
#      per-phase p50/p99 both sides + reject-rate/throughput deltas).
#      New ``whatif`` kind — one offline planner run (tools/whatif.py):
#      the workload fingerprint, the ranked candidate plan, and the
#      model's stamped calibration error. All absent on non-replay
#      serving — streams stay byte-identical to v13.
#  15: the quality-observability generation (ISSUE 19): the ``canary``
#      kind — one golden-set canary event per tenant (``obs/canary.py``:
#      ``event`` is "pin" — references pinned from the healthy tenant's
#      answers, "probe" — one shadow probe cycle scored against them
#      (top-1/top-k agreement, ``rank_drift`` — the max-logit-drift
#      stand-in for an index-only prediction contract), or "blocked" —
#      a fleet mutation refused on a FAIL verdict, naming the mutation);
#      ``alert`` records may carry ``source`` ("drift" = a
#      baseline-relative breach from ``obs/drift.py``, with its
#      ``psi``/``chi2`` evidence, window/baseline sizes, and — for
#      CUSUM change-points over collector rings — the ``host``);
#      ``fleet`` swap_in/retune records may carry ``canary_verdict``
#      (the gate's verdict stamped on every ALLOWED mutation);
#      ``serve`` flushes may carry ``shadow_requests`` (how many of the
#      flush's requests were tagged canary probes — excluded from the
#      served/requests counters, so billing stays honest); and
#      ``serve_bench`` rows may carry ``agreement_top1`` (the canary
#      agreement measured during the sweep point — trends like img/s in
#      check_regression, a >2-point absolute drop fails) and
#      ``residency`` (keyed into the trend-line identity alongside
#      precision). All absent when the canary/drift knobs are off —
#      streams stay byte-identical to v14.
# v16: pipeline-parallel serving (serve/pipeline.py, ISSUE 20 — additive):
#      ``serve`` flushes on a ``pipe:K`` tenant carry ``pipe_stages``,
#      ``bubble_frac`` (the MEASURED fill/drain bubble of that flush's
#      micro-batch schedule), and ``interstage_bytes`` (the ledger-booked
#      inter-stage activation traffic the flush moved); ``serve_bench``
#      rows from ``--serve-pipe-stages`` sweeps carry ``pipe_stages``
#      (keyed into the trend-line identity) and ``bubble_frac``;
#      ``fleet`` retune records for conversions TO pipe carry
#      ``pipe_stages`` + ``interstage_bytes``. Traced pipe requests gain
#      per-stage ``serve/stage{i}`` child spans under ``serve/device``.
#      All absent off the pipe path — streams stay byte-identical to v15.
SCHEMA_VERSION = 16

_NUM = (int, float)
_INT = (int,)

# kind -> {field: allowed types}. bool is an int subclass in Python; it is
# never a valid metrics value, so the checker rejects it explicitly.
REQUIRED: dict[str, dict[str, tuple]] = {
    "epoch": {
        "epoch": _INT, "loss": _NUM, "time_s": _NUM, "images_per_sec": _NUM,
    },
    "val": {"epoch": _INT, "accuracy": _NUM, "loss": _NUM},
    "eval": {"accuracy": _NUM, "loss": _NUM, "images": _INT, "time_s": _NUM},
    "step": {"epoch": _INT, "step": _INT, "loss": _NUM},
    "heartbeat": {
        "epoch": _INT, "step": _INT, "step_ms": (list,),
        "median_step_ms": _NUM, "stragglers": (list,), "threshold": _NUM,
    },
    "anomaly": {"reason": (str,), "epoch": _INT},
    "serve": {
        "bucket": _INT, "requests": _INT, "queue_depth": _INT,
        "fill_ratio": _NUM, "queue_wait_ms": _NUM, "device_ms": _NUM,
    },
    "serve_bench": {
        "mode": (str,), "buckets": (str,), "max_wait_ms": _NUM,
        "requests": _INT, "p50_ms": _NUM, "p95_ms": _NUM, "p99_ms": _NUM,
        "images_per_sec": _NUM,
    },
    "resume": {"epoch": _INT, "to_devices": _INT},
    "fault": {"reason": (str,)},
    # v4: live-telemetry snapshot (the three registry sections; each a
    # name → value/summary object) and SLO alerts.
    "metrics": {"counters": (dict,), "gauges": (dict,), "histograms": (dict,)},
    "alert": {"rule": (str,), "severity": (str,)},
    # v5: fleet serving — one routing window per host (router) and one
    # lifecycle event (failover/retune/…) per occurrence.
    "route": {"host": (str,), "requests": _INT},
    "fleet": {"event": (str,)},
    # v6: one in-process bad-step rollback (train/trainer.py,
    # --bad-step-policy rollback): where it triggered and why.
    "rollback": {"epoch": _INT, "reason": (str,)},
    # v7: one offline int8-vs-bf16 parity report (evaluate --quantize-eval
    # — the serve-side parity gates' reusable oracle).
    "quant_parity": {
        "precision": (str,), "top1_agree": _NUM, "samples": _INT,
    },
    # v9: one per-(host, metric) time-series window from the fleet
    # collector (obs/collector.py) — points are [[wall_ts, value], ...].
    "timeline": {"host": (str,), "metric": (str,), "points": (list,)},
    # v12: one hedged-request race (serve/fleet/router.py): the host
    # whose completion won and the host whose attempt was revoked.
    "hedge": {"winner": (str,), "loser": (str,)},
    # v14: one offline what-if planner run (tools/whatif.py): which
    # workload it planned against and the ranked candidate list.
    "whatif": {"workload": (str,), "ranked": (list,)},
    # v15: one golden-set canary event per tenant (obs/canary.py):
    # references pinned, a probe cycle scored, or a mutation blocked.
    "canary": {"model": (str,), "event": (str,)},
}

OPTIONAL: dict[str, dict[str, tuple]] = {
    "epoch": {"tflops": _NUM, "mfu_pct": _NUM},
    "val": {},
    "eval": {},
    "step": {
        "grad_norm": _NUM, "data_wait_ms": _NUM, "step_ms": _NUM,
        "recompiles": _INT, "hbm_bytes": _INT,
        # v2 grad-sync fields (spmd --grad-sync-buckets; absent on v1
        # records and on lever-less runs):
        "sync_ms": _NUM, "overlap_frac": _NUM,
        # v11: hierarchical (--mesh-pods > 1) runs only — the cross-pod
        # (DCN) overlap estimate of the two-level bucket plan.
        "dcn_overlap_frac": _NUM,
        # v6 bad-step-policy fields (--bad-step-policy skip only): whether
        # THIS step's update was discarded on a non-finite grad norm
        # (0/1), and the run's cumulative discard count.
        "skipped": _INT, "steps_skipped": _INT,
    },
    "heartbeat": {"images_per_sec": _NUM},
    # v6: bad_sample quarantines (data/pipeline.py) carry the undecodable
    # file's path and the decode error; cursor_mismatch fallbacks carry
    # the mismatch reason in detail.
    "anomaly": {
        "step": _INT, "loss": _NUM, "grad_norm": _NUM,
        "path": (str,), "detail": (str,),
    },
    "serve": {
        "preprocess_ms": _NUM, "total_ms": _NUM,
        # v3: requests of this flush dropped at preprocess (typed
        # PreprocessError to their callers) and cumulative worker-pool
        # respawns — absent on clean flushes.
        "preprocess_failures": _INT, "worker_respawns": _INT,
        # v7: which startup-compiled executable set ran this flush —
        # stamped when the server holds multiple precision sets or serves
        # non-bf16 (pure-bf16 servers keep v6-identical records).
        "precision": (str,),
        # v9: the trace ids of the TRACED requests this flush carried —
        # absent on untraced traffic (tracing-off streams stay
        # byte-identical to v8; the no-hot-path-cost invariant's record
        # half).
        "trace_ids": (list,),
        # v10: the tenant this flush served (flushes are single-tenant
        # by construction — serve/zoo/) — absent on untenanted servers.
        "model": (str,),
        # v13: chips one copy of the params spans (model-parallel
        # tenants only — absent on replicated serving).
        "shard_degree": _INT,
        # v15: how many of the flush's requests were tagged canary
        # shadow probes (obs/canary.py) — they ride the batch but are
        # excluded from the served/requests counters; absent on flushes
        # that carried none, so canary-off streams stay byte-identical.
        "shadow_requests": _INT,
        # v16: pipeline flush facts (pipe:K tenants only): stage count,
        # the measured fill/drain bubble fraction of the micro-batch
        # schedule, and the ledger-booked inter-stage activation bytes
        # moved. Absent on non-pipeline serving.
        "pipe_stages": _INT, "bubble_frac": _NUM, "interstage_bytes": _INT,
    },
    "serve_bench": {
        "model": (str,), "offered_rps": _NUM, "rejected": _INT,
        "mean_fill_ratio": _NUM, "compiles_after_warmup": _INT, "chips": _INT,
        # v5: rows from the --fleet N mode — how many serving hosts the
        # router spread the sweep over, and the per-host breakdown (host
        # name → {requests, fill_pct, mean_ms}, all deltas over THIS
        # sweep point; per-point tail percentiles live on the row itself).
        "fleet_hosts": _INT, "per_host": (dict,),
        # v7: the --precision sweep axis; int8 rows also carry the
        # startup int8-vs-bf16 top-1 agreement the accuracy claim rests
        # on (a throughput row without its parity stamp is half a row).
        "precision": (str,), "parity_top1": _NUM,
        # v8: which transport served the row ("http" = real serving
        # processes over the wire) — a remote row is a different trend
        # line than an in-process one (check_regression keys it).
        "transport": (str,),
        # v9: the collector-derived per-phase latency breakdown for this
        # sweep point (span name → {count, p50_ms, p99_ms} — the
        # queue/preprocess/device/wire attribution; absent without a
        # collector, so pre-v9 rows compare unchanged).
        "per_phase": (dict,),
        # v10: the multi-tenant sweep's traffic shape ("uniform" /
        # "hot:<model>") — keyed into the regression trend-line identity
        # alongside model, so a skewed-load row never compares against a
        # uniform baseline.
        "load_shape": (str,),
        # v12: how many requests of this sweep point hedged (framed wire
        # with --hedge only), and the zero-copy dispatch assertion —
        # input copies per served request (1.0 = bytes touched exactly
        # once between the wire and device_put). Absent elsewhere.
        "hedged": _INT, "copies_per_request": _NUM,
        # v13: the --serve-shard-degree axis — a sharded row is a
        # different trend line than a replicated one
        # (check_regression keys it).
        "shard_degree": _INT,
        # v14: trace-replay rows (bench_serve --replay): the workload
        # artifact's content fingerprint (keyed into the regression
        # trend-line identity — replayed load never compares against
        # synthetic Poisson), the time-warp factor (absent at 1.0), and
        # the recorded-vs-replayed differential report. Absent on
        # synthetic-load rows — streams stay byte-identical to v13.
        "workload": (str,), "speed": _NUM, "replay_diff": (dict,),
        # v15: the quality axes — the canary top-1 agreement measured
        # during this sweep point (trends like img/s: a >2-point
        # absolute drop fails check_regression), and the tenant's weight
        # residency, keyed into the trend-line identity so a sharded/
        # int8 row never compares against a replicated/bf16 baseline.
        "agreement_top1": _NUM, "residency": (str,),
        # v16: the --serve-pipe-stages axis — a pipelined row is its own
        # trend line (check_regression keys pipe_stages) and carries the
        # mean measured bubble fraction over the sweep point.
        "pipe_stages": _INT, "bubble_frac": _NUM,
    },
    "resume": {
        "from_devices": _INT, "from_mesh": (str,), "to_mesh": (str,),
        "path": (str,), "zero_shards_from": _INT, "zero_shards_to": _INT,
        "corrupt_skipped": _INT, "strategy": (str,),
        # v6: the exact-step data cursor stamped in the restored
        # checkpoint's topology sidecar (train/trainer.py): the epoch and
        # step-in-epoch the run continues at when the cursor validates.
        "cursor_epoch": _INT, "cursor_step": _INT,
    },
    # v9 trace_id: a fault gate that fired INSIDE a traced request (the
    # router's kill gate striking a traced dispatch, a preprocess crash
    # taking a traced flush) stamps the victim's trace id, so the chaos
    # evidence links to the exact waterfall it disrupted.
    "fault": {
        "epoch": _INT, "step": _INT, "detail": (str,), "streak": _INT,
        "trace_id": (str,),
    },
    # v5: fleet routing/lifecycle fields. ``route`` is a per-host window:
    # requests dispatched there since the last record, the router's
    # smoothed load score and the host's queue/in-flight state when the
    # window closed. ``fleet`` events: "failover" carries the drained
    # host, how many in-flight requests were re-dispatched, and the
    # promoted spare; "retune" carries the controller's max_wait/bucket
    # change and the p99-vs-target evidence it acted on.
    "route": {
        "share": _NUM, "score": _NUM, "queue_depth": _INT, "inflight": _INT,
        "window_s": _NUM,
        # v8: the host's transport ("http" = a real serving process over
        # the wire; absent = in-process LocalHost, streams unchanged).
        "transport": (str,),
        # v9: the traced requests dispatched to this host in the window
        # (bounded; absent when tracing is off — streams unchanged).
        "trace_ids": (list,),
        # v10: per-tenant dispatch counts of this window (multi-model
        # fleets only — absent otherwise, streams unchanged).
        "models": (dict,),
    },
    "fleet": {
        "host": (str,), "detail": (str,), "redispatched": _INT,
        "spare": (str,), "max_wait_ms_from": _NUM, "max_wait_ms_to": _NUM,
        "buckets_from": (str,), "buckets_to": (str,), "p99_ms": _NUM,
        "target_p99_ms": _NUM, "compiles_after_warmup": _INT,
        # v10: the multi-model axis — the tenant a retune/scale acted on
        # (or the swap_in/evict subject), the resident set after a zoo
        # residency change, and the packing plan a swap-in rested on.
        "model": (str,), "resident": (list,), "plan": (dict,),
        # v7: the controller's precision retune axis — which executable
        # set the host left/entered, and the measured int8-vs-bf16 top-1
        # agreement stamped as the retune's accuracy evidence.
        "precision_from": (str,), "precision_to": (str,),
        "parity_top1": _NUM,
        # v8: the autoscaler/supervisor events (scale_up / scale_down /
        # restart): host counts before/after, the policy's reason, the
        # front-door reject rate and summed queue depth it acted on, the
        # supervisor's cumulative restart count, and the transport.
        "hosts_from": _INT, "hosts_to": _INT, "reason": (str,),
        "reject_rate": _NUM, "queue_depth": _INT, "restarts": _INT,
        "transport": (str,),
        # v13: the model-parallel residency axis — the tenant's weight
        # layout after a swap_in/retune ("replicated"/"tp:K"/"fsdp:K"),
        # the bytes the bounded cross-topology reshard moved getting
        # there, and the chip span (absent on replicated events).
        "residency": (str,), "reshard_bytes": _INT, "shard_degree": _INT,
        # v15: the canary gate's verdict stamped on every ALLOWED
        # mutation (swap_in / retune / conversion) when a gate is
        # present — "pass", or "none" for a tenant never probed. Absent
        # on canary-off fleets (streams stay byte-identical to v14);
        # refused mutations write kind="canary" event="blocked" instead.
        "canary_verdict": (str,),
        # v16: a retune converting a tenant TO pipe:K says how it was cut
        # and the per-flush inter-stage traffic price (absent elsewhere).
        "pipe_stages": _INT, "interstage_bytes": _INT,
    },
    # v6: which step the rollback triggered at, what it restored (the
    # checkpoint's filed epoch + path), how many rollbacks this run has
    # taken, and the cumulative --rollback-lr-backoff scale in effect.
    "rollback": {
        "step": _INT, "restored_epoch": _INT, "rollbacks": _INT,
        "lr_scale": _NUM, "path": (str,), "detail": (str,),
    },
    "metrics": {
        # How many hosts' registries were merged into this snapshot
        # (absent on single-host runs — the local registry IS the merge).
        "merged_hosts": _INT,
    },
    "alert": {
        "metric": (str,), "value": _NUM, "threshold": _NUM, "streak": _INT,
        "action": (str,), "detail": (str,), "epoch": _INT, "step": _INT,
        # v10: the SLO monitor's tenant label (a zoo tenant's rules fire
        # with its model stamped) — absent on untenanted monitors.
        "model": (str,),
        # v15: baseline-relative drift alerts (obs/drift.py) carry
        # source="drift" (the collector pins in-flight traces on them),
        # the PSI / reduced-chi2 evidence with window/baseline sizes,
        # and — for CUSUM change-points over collector rings — which
        # host's series moved. Absent on threshold-DSL SLO alerts.
        "source": (str,), "psi": _NUM, "chi2": _NUM,
        "window_n": _INT, "baseline_n": _INT, "host": (str,),
    },
    # v7: top5_agree is null for fused (argmax-only) contracts.
    "quant_parity": {
        "top5_agree": _NUM, "max_logit_drift": _NUM, "model": (str,),
    },
    # v9: window span of the points, the host's probe-RTT clock-offset
    # estimate (ms — what skew-corrects its span timestamps), and how
    # many counter resets (host restarts) the collector absorbed.
    "timeline": {
        "window_s": _NUM, "clock_offset_ms": _NUM, "resets": _INT,
    },
    # v12: whether the loser was revoked while still in flight (a CANCEL
    # frame / Future.cancel() landed before it resolved), the deadline
    # that fired the hedge, and the traced request's id.
    "hedge": {
        "cancelled": _INT, "deadline_ms": _NUM, "trace_id": (str,),
    },
    # v14: the winning candidate config (first ranked entry, repeated for
    # direct access), the fitted model summary with its stamped
    # calibration error, and — when --validate replayed the winner — the
    # validated row's p99 and whether prediction landed inside the
    # calibration bound.
    "whatif": {
        "winner": (dict,), "model": (dict,), "candidates": _INT,
        "validated_p99_ms": _NUM, "within_calibration": _INT,
        "calibration_error_pct": _NUM,
    },
    # v15: probe-cycle scores (event="probe"), the pinned set size
    # (event="pin"), the latched verdict, and — on event="blocked" —
    # which mutation the FAIL verdict refused and why. rank_drift is the
    # mean displacement of the reference top-1 within the probed top-k
    # (the logit-drift stand-in for an index-only serve contract).
    "canary": {
        "agreement_top1": _NUM, "agreement_topk": _NUM, "rank_drift": _NUM,
        "probes": _INT, "verdict": (str,), "mutation": (str,),
        "reason": (str,), "detail": (str,),
    },
}


def _type_ok(value: Any, types: tuple) -> bool:
    return isinstance(value, types) and not isinstance(value, bool)


def validate_record(rec: Any) -> list[str]:
    """Problems with one parsed record ([] = valid)."""
    if not isinstance(rec, Mapping):
        return [f"record is {type(rec).__name__}, not an object"]
    problems = []
    kind = rec.get("kind")
    if not isinstance(kind, str) or kind not in REQUIRED:
        return [f"unknown kind {kind!r} (expected one of {sorted(REQUIRED)})"]
    if not _type_ok(rec.get("ts"), _NUM):
        problems.append("missing/non-numeric 'ts'")
    for field, types in REQUIRED[kind].items():
        if field not in rec:
            problems.append(f"{kind}: missing required field {field!r}")
        elif not _type_ok(rec[field], types):
            problems.append(
                f"{kind}: field {field!r} has type "
                f"{type(rec[field]).__name__}, expected "
                f"{'/'.join(t.__name__ for t in types)}"
            )
    for field, types in OPTIONAL[kind].items():
        if field in rec and rec[field] is not None and not _type_ok(rec[field], types):
            problems.append(
                f"{kind}: optional field {field!r} has type "
                f"{type(rec[field]).__name__}, expected "
                f"{'/'.join(t.__name__ for t in types)} or null"
            )
    return problems


def validate_jsonl(path: str) -> list[str]:
    """Problems across a metrics JSONL file, tagged ``line N:`` ([] = valid)."""
    problems = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                problems.append(f"line {lineno}: not JSON ({e})")
                continue
            problems.extend(f"line {lineno}: {p}" for p in validate_record(rec))
    return problems


def load_records(path: str) -> list[dict]:
    """Parse a metrics JSONL (no validation — pair with ``validate_jsonl``)."""
    records = []
    with open(path) as f:
        for line in f:
            if line.strip():
                records.append(json.loads(line))
    return records
