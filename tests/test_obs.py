"""Run-telemetry subsystem (mpi_pytorch_tpu/obs/): span tracer output
format and nesting, per-step health record schema, the NaN-sentinel abort
path, straggler flagging with a faked slow host, the report tool against
both a live dryrun and the committed artifacts, and the grad-norm metric
every train-step flavor now carries."""

import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.obs import (
    Heartbeat,
    NonFiniteLossError,
    StepHealth,
    Tracer,
    flag_stragglers,
)
from mpi_pytorch_tpu.obs.schema import validate_jsonl, validate_record
from mpi_pytorch_tpu.utils.logging import MetricsWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import report_run  # noqa: E402


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_tracer_span_nesting_and_chrome_format(tmp_path):
    """Spans emit Chrome 'X' (complete) events whose ts/dur nest correctly,
    args round-trip, and close() writes one valid JSON object."""
    path = str(tmp_path / "trace.json")
    tracer = Tracer(path)
    with tracer.span("outer"):
        with tracer.span("inner", args={"step": 3}):
            pass
    tracer.instant("marker", args={"why": "test"})
    out = tracer.close()
    assert out == path

    data = json.load(open(path))
    events = {e["name"]: e for e in data["traceEvents"]}
    assert set(events) == {"outer", "inner", "marker"}
    outer, inner = events["outer"], events["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert events["marker"]["ph"] == "i"
    # inner completes first (events append at span END), and sits inside
    # outer's [ts, ts+dur) window — the property Chrome renders as nesting.
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert inner["args"] == {"step": 3}
    assert outer["pid"] == 0  # single-process test env


def test_tracer_disabled_is_inert(tmp_path):
    tracer = Tracer("")
    with tracer.span("anything"):
        pass
    assert tracer.close() is None
    assert list(tmp_path.iterdir()) == []


def test_tracer_close_idempotent_and_creates_dirs(tmp_path):
    path = str(tmp_path / "deep" / "dir" / "t.json")
    tracer = Tracer(path)
    with tracer.span("s"):
        pass
    assert tracer.close() == path
    assert tracer.close() is None  # second close: no rewrite
    assert json.load(open(path))["traceEvents"]


def test_trace_path_per_process_suffix():
    from mpi_pytorch_tpu.obs.trace import trace_path

    assert trace_path("run.json", 0, 1) == "run.json"
    assert trace_path("run.json", 2, 4) == "run.p2.json"
    assert trace_path("run", 1, 2) == "run.p1.json"


# ---------------------------------------------------------------------------
# per-step health records + NaN sentinel
# ---------------------------------------------------------------------------


def test_step_health_record_matches_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    writer = MetricsWriter(path)
    health = StepHealth(writer, step_metrics=True)
    health.start_epoch()
    health.on_step(0, 0, {"loss": 1.5, "grad_norm": 2.25}, 0.012, 0.345)
    writer.close()

    assert validate_jsonl(path) == []
    (rec,) = [json.loads(line) for line in open(path)]
    assert rec["kind"] == "step"
    assert rec["loss"] == 1.5 and rec["grad_norm"] == 2.25
    assert rec["data_wait_ms"] == 12.0 and rec["step_ms"] == 345.0
    assert isinstance(rec["recompiles"], int)
    assert rec["hbm_bytes"] is None  # CPU test env has no memory_stats


def test_step_health_disabled_never_syncs(tmp_path):
    """With step_metrics off, on_step must not touch the metrics values at
    all (reading them would force a per-step device sync in real runs)."""
    writer = MetricsWriter(str(tmp_path / "m.jsonl"))

    class Exploding:
        def __getitem__(self, key):  # pragma: no cover - must not be hit
            raise AssertionError("on_step read a metric while disabled")

        def __contains__(self, key):
            raise AssertionError("on_step probed a metric while disabled")

    health = StepHealth(writer, step_metrics=False)
    health.on_step(0, 0, Exploding(), 0.0, 0.0)  # must be a silent no-op
    writer.close()


def test_nan_sentinel_writes_diagnostic_and_aborts(tmp_path):
    path = str(tmp_path / "m.jsonl")
    writer = MetricsWriter(path)
    health = StepHealth(writer, step_metrics=True)
    with pytest.raises(NonFiniteLossError, match="epoch 1 step 4"):
        health.on_step(1, 4, {"loss": float("nan"), "grad_norm": 7.0}, 0.0, 0.1)
    writer.close()

    records = [json.loads(line) for line in open(path)]
    # The poisoned step record lands first, then the diagnostic.
    assert [r["kind"] for r in records] == ["step", "anomaly"]
    anomaly = records[-1]
    assert anomaly["reason"] == "nonfinite_loss"
    assert (anomaly["epoch"], anomaly["step"]) == (1, 4)
    assert math.isnan(anomaly["loss"]) and anomaly["grad_norm"] == 7.0
    assert validate_jsonl(path) == []


def test_nan_sentinel_epoch_check_and_opt_out(tmp_path):
    writer = MetricsWriter(str(tmp_path / "m.jsonl"))
    health = StepHealth(writer, step_metrics=False)  # default run shape
    health.check_epoch(2, 1.25)  # finite: fine
    with pytest.raises(NonFiniteLossError):
        health.check_epoch(2, float("inf"))
    writer.close()

    off = StepHealth(MetricsWriter(None), step_metrics=False, nan_sentinel=False)
    off.check_epoch(0, float("nan"))  # explicitly disabled: keep going


def test_scan_epoch_records_and_sentinel(tmp_path):
    path = str(tmp_path / "m.jsonl")
    writer = MetricsWriter(path)
    health = StepHealth(writer, step_metrics=True)
    m = {"loss": np.asarray([1.0, 2.0]), "grad_norm": np.asarray([3.0, 4.0])}
    health.on_scan_epoch(0, m)
    poisoned = {"loss": np.asarray([1.0, float("nan")])}
    with pytest.raises(NonFiniteLossError):
        health.on_scan_epoch(1, poisoned)
    writer.close()

    records = [json.loads(line) for line in open(path)]
    steps = [r for r in records if r["kind"] == "step"]
    # 2 clean + 2 poisoned-epoch records (the NaN step IS recorded), 1 anomaly.
    assert len(steps) == 4 and records[-1]["kind"] == "anomaly"
    assert steps[0]["step_ms"] is None  # scan mode: no per-step host timing
    assert steps[1]["grad_norm"] == 4.0
    assert validate_jsonl(path) == []


# ---------------------------------------------------------------------------
# heartbeat / straggler flagging
# ---------------------------------------------------------------------------


def test_flag_stragglers_policy():
    assert flag_stragglers([100.0, 101.0, 99.0, 400.0], 1.5) == [3]
    assert flag_stragglers([100.0, 100.0, 100.0, 100.0], 1.5) == []
    assert flag_stragglers([100.0], 1.5) == []  # one host: no baseline
    # Two slow hosts don't hide each other (median, not mean).
    assert flag_stragglers([100.0, 104.0, 98.0, 101.0, 300.0, 280.0], 1.5) == [4, 5]


def test_heartbeat_flags_faked_slow_host(tmp_path):
    """A 4-host heartbeat with one faked 4x-slower process: the record
    carries per-host rows, the straggler index, and the schema holds."""
    path = str(tmp_path / "m.jsonl")
    writer = MetricsWriter(path)
    calls = []

    def fake_gather(local):  # process 3 is wedged on a slow disk
        calls.append(np.asarray(local))
        return np.asarray([[100.0], [102.0], [98.0], [400.0]], np.float32)

    hb = Heartbeat(
        writer, every_steps=2, threshold=1.5, batch_images=128,
        gather=fake_gather,
    )
    hb.on_step(0, 0, 0.1)
    assert calls == []  # not at the beat boundary yet
    hb.on_step(0, 1, 0.1)
    assert len(calls) == 1
    np.testing.assert_allclose(calls[0], [100.0])  # local mean, ms
    writer.close()

    (rec,) = [json.loads(line) for line in open(path)]
    assert rec["kind"] == "heartbeat"
    assert rec["step_ms"] == [100.0, 102.0, 98.0, 400.0]
    assert rec["stragglers"] == [3]
    assert rec["median_step_ms"] == 101.0
    # Steps are collective: the slowest host sets the global pace.
    assert rec["images_per_sec"] == pytest.approx(128 / 0.4, rel=1e-6)
    assert validate_record(rec) == []


def test_heartbeat_uniform_hosts_flag_nothing(tmp_path):
    path = str(tmp_path / "m.jsonl")
    writer = MetricsWriter(path)
    hb = Heartbeat(
        writer, every_steps=1, threshold=1.5,
        gather=lambda v: np.asarray([[100.0], [101.0]], np.float32),
    )
    hb.on_step(0, 0, 0.1)
    writer.close()
    (rec,) = [json.loads(line) for line in open(path)]
    assert rec["stragglers"] == []


def test_host_allgather_single_process_identity():
    from mpi_pytorch_tpu.parallel.collectives import host_allgather

    out = host_allgather(np.asarray([1.5, 2.5], np.float32))
    assert out.shape == (1, 2)
    np.testing.assert_allclose(out[0], [1.5, 2.5])


# ---------------------------------------------------------------------------
# grad-norm metric in the train steps
# ---------------------------------------------------------------------------


def test_train_step_metrics_include_global_grad_norm():
    """Every step flavor now reports the global gradient L2 norm — checked
    here on the streaming auto step against an explicit value_and_grad."""
    import flax.linen as nn
    import optax

    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.ops.losses import classification_loss
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(11)(nn.relu(nn.Dense(16)(x)))

    model = MLP()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    state = TrainState.create(
        apply_fn=model.apply, variables=variables, tx=make_optimizer(1e-3),
        rng=jax.random.PRNGKey(1),
    )
    mesh = create_mesh(MeshConfig())
    state = place_state_on_mesh(state, mesh)
    rng = np.random.default_rng(0)
    images = rng.standard_normal((16, 8, 8, 3)).astype(np.float32)
    labels = rng.integers(0, 11, size=(16,)).astype(np.int32)

    params_before = jax.device_get(state.params)  # the step donates `state`
    step = make_train_step(jnp.float32)
    _, m = step(state, shard_batch((images, labels), mesh))
    got = float(m["grad_norm"])
    assert math.isfinite(got) and got > 0

    def loss_fn(params):
        return classification_loss(
            model.apply({"params": params}, jnp.asarray(images), train=False),
            jnp.asarray(labels),
        )

    grads = jax.grad(loss_fn)(params_before)
    np.testing.assert_allclose(got, float(optax.global_norm(grads)), rtol=1e-4)


def test_step_sync_fields_schema_and_render(tmp_path, capsys):
    """Schema v2 (grad-sync levers): step records MAY carry sync_ms /
    overlap_frac — v1 records without them stay valid, mistyped values fail
    validation, and report_run renders the grad-sync phase row + overlap
    line only when the fields are present (satellite: backward-compatible
    rendering)."""
    v2 = {"ts": 2.0, "kind": "step", "epoch": 0, "step": 1, "loss": 0.9,
          "sync_ms": 3.2, "overlap_frac": 0.87}
    v1 = {"ts": 1.0, "kind": "step", "epoch": 0, "step": 0, "loss": 1.0}
    assert validate_record(v1) == [] and validate_record(v2) == []
    assert validate_record({**v2, "overlap_frac": "high"}) != []
    assert validate_record({**v2, "sync_ms": True}) != []

    both = tmp_path / "levers_metrics.jsonl"
    both.write_text(json.dumps(v1) + "\n" + json.dumps(v2) + "\n")
    assert validate_jsonl(str(both)) == []
    assert report_run.main([str(both)]) == 0
    out = capsys.readouterr().out
    assert "grad-sync" in out and "overlap-eligible" in out

    old = tmp_path / "old_metrics.jsonl"
    old.write_text(json.dumps(v1) + "\n")
    assert report_run.main([str(old)]) == 0
    assert "grad-sync" not in capsys.readouterr().out


def test_report_run_renders_committed_levers_artifact(capsys):
    """The committed §4e dryrun artifact (spmd --zero-opt-state
    --grad-sync-buckets, 8-device CPU mesh) renders with the overlap line
    and zero recompiles — the artifact CI schema-checks via
    check_results_artifacts."""
    path = os.path.join(REPO, "docs", "levers_dryrun_metrics.jsonl")
    assert report_run.main([path]) == 0
    out = capsys.readouterr().out
    assert "overlap-eligible" in out
    assert "recompiles (max per record): 0" in out


# ---------------------------------------------------------------------------
# end-to-end: telemetry-enabled dryrun + the report tool
# ---------------------------------------------------------------------------


def _telemetry_cfg(tmpdir, **kw):
    from mpi_pytorch_tpu.config import Config

    cfg = Config()
    cfg.debug = True
    cfg.debug_sample_size = 48
    cfg.train_csv = os.path.join(REPO, "data", "train_sample.csv")
    cfg.test_csv = os.path.join(REPO, "data", "test_sample.csv")
    cfg.synthetic_data = True
    cfg.model_name = "resnet18"
    cfg.num_classes = 200
    cfg.batch_size = 16
    cfg.width = cfg.height = 16
    cfg.num_epochs = 2
    cfg.compute_dtype = "float32"
    cfg.checkpoint_dir = os.path.join(tmpdir, "ckpt")
    cfg.log_file = os.path.join(tmpdir, "training.log")
    cfg.metrics_file = os.path.join(tmpdir, "metrics.jsonl")
    cfg.trace_file = os.path.join(tmpdir, "trace.json")
    cfg.validate = False
    cfg.loader_workers = 2
    cfg.log_every_steps = 0
    cfg.step_metrics = True
    cfg.heartbeat_every_steps = 2
    for k, v in kw.items():
        setattr(cfg, k, v)
    cfg.validate_config()
    return cfg


def test_dryrun_telemetry_end_to_end(tmp_path, capsys):
    """THE acceptance path: a CPU dryrun with telemetry on produces a valid
    Chrome-trace JSON plus per-step records (data-wait, grad-norm,
    recompile count) that report_run.py accepts."""
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _telemetry_cfg(str(tmp_path))
    summary = train(cfg)
    assert summary.epochs_run == 2

    # Chrome trace: valid JSON, the documented span names, nested step spans.
    trace = json.load(open(cfg.trace_file))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"build", "compile", "ingest", "step", "checkpoint"} <= names
    assert all("ts" in e and "pid" in e for e in trace["traceEvents"])

    # Metrics stream: schema-clean; step records carry the health fields.
    assert validate_jsonl(cfg.metrics_file) == []
    records = [json.loads(line) for line in open(cfg.metrics_file)]
    kinds = {r["kind"] for r in records}
    assert {"epoch", "step", "heartbeat"} <= kinds
    steps = [r for r in records if r["kind"] == "step"]
    # 48 sampled images -> 38-image train split -> 2 steps/epoch x 2 epochs.
    assert len(steps) == 4
    for rec in steps:
        assert math.isfinite(rec["loss"]) and rec["grad_norm"] > 0
        assert rec["data_wait_ms"] >= 0 and rec["step_ms"] > 0
        assert rec["recompiles"] == 0  # AOT step: no silent recompiles
    beats = [r for r in records if r["kind"] == "heartbeat"]
    assert beats and all(b["stragglers"] == [] for b in beats)

    # The report tool renders it (exit 0) with the phase breakdown.
    assert report_run.main([cfg.metrics_file]) == 0
    out = capsys.readouterr().out
    assert "data-wait" in out and "grad norm" in out and "heartbeats" in out


def test_poisoned_loss_aborts_cleanly(tmp_path):
    """THE sentinel acceptance: a diverging run (lr=1e38 NaNs the loss
    within two steps) aborts with NonFiniteLossError, writes the anomaly
    diagnostic, and still flushes the trace on the failure path."""
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _telemetry_cfg(str(tmp_path), learning_rate=1e38, num_epochs=3)
    with pytest.raises(NonFiniteLossError):
        train(cfg)

    records = [json.loads(line) for line in open(cfg.metrics_file)]
    anomalies = [r for r in records if r["kind"] == "anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["reason"] == "nonfinite_loss"
    assert not math.isfinite(anomalies[0]["loss"])
    assert validate_jsonl(cfg.metrics_file) == []
    # Failure path still writes the trace the diagnostics need.
    assert {"build", "step"} <= {
        e["name"] for e in json.load(open(cfg.trace_file))["traceEvents"]
    }


def test_report_run_renders_committed_artifact(capsys):
    """Acceptance: the committed chip artifact renders into a summary."""
    path = os.path.join(REPO, "docs", "chip_train_metrics.jsonl")
    assert report_run.main([path]) == 0
    out = capsys.readouterr().out
    assert "epochs:" in out and "throughput" in out
    assert "MFU" in out


def test_report_run_json_mode(capsys):
    path = os.path.join(REPO, "docs", "decode_metrics.jsonl")
    assert report_run.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["kinds"] == {"epoch": 10, "eval": 1, "val": 10}
    assert summary["val"]["best_accuracy"] == 1.0


def test_report_run_rejects_malformed(tmp_path, capsys):
    bad = tmp_path / "bad_metrics.jsonl"
    bad.write_text(
        '{"ts": 1.0, "kind": "epoch", "epoch": 0}\n'  # missing required fields
        '{"ts": 1.0, "kind": "bogus"}\n'  # unknown kind
        "not json\n"
    )
    assert report_run.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "schema violation" in out and "bogus" in out


def test_schema_rejects_wrong_types():
    assert validate_record(
        {"ts": 1.0, "kind": "epoch", "epoch": "zero", "loss": 1.0,
         "time_s": 1.0, "images_per_sec": 1.0}
    ) != []
    assert validate_record({"kind": "val", "epoch": 0, "accuracy": 0.5,
                            "loss": 1.0}) != []  # missing ts
    assert validate_record(
        {"ts": 1.0, "kind": "step", "epoch": 0, "step": 0, "loss": 1.0,
         "grad_norm": None, "hbm_bytes": None}
    ) == []  # optional fields may be null


def test_heartbeat_window_resets_at_epoch_boundary(tmp_path):
    """Leftover step samples (n_steps % every != 0) must not leak into the
    next epoch's first beat — beats never average across epoch boundaries."""
    locals_sent = []

    def gather(v):
        locals_sent.append(round(float(np.asarray(v)[0]), 3))
        return np.asarray(v, np.float32)[None]

    writer = MetricsWriter(str(tmp_path / "m.jsonl"))
    hb = Heartbeat(writer, every_steps=2, gather=gather)
    hb.start_epoch()
    hb.on_step(0, 0, 1.0)
    hb.on_step(0, 1, 1.0)      # beat: mean 1000 ms
    hb.on_step(0, 2, 9.0)      # tail sample, no beat — must be dropped
    hb.start_epoch()
    hb.on_step(1, 0, 0.1)
    hb.on_step(1, 1, 0.1)      # beat: mean 100 ms, NOT polluted by the 9 s tail
    writer.close()
    assert locals_sent == [1000.0, 100.0]


# ---------------------------------------------------------------------------
# live telemetry: registry + SLO monitor + flight recorder, end to end
# ---------------------------------------------------------------------------


def test_slo_straggler_alert_preempts_run(tmp_path, monkeypatch):
    """ISSUE 8's acceptance chain, in-process: a fake straggler appears
    mid-run (MPT_FAULT_DELAY_STEP_MS after MPT_FAULT_DELAY_AFTER_STEP
    clean steps), the drift SLO rule fires ONE kind="alert" record, its
    preempt action writes the sentinel, the watchdog observes it
    (kind="fault" reason=preempt_file) and stops the run cleanly, the
    flight recorder dumps schema-clean evidence, and periodic
    kind="metrics" snapshots land in the stream."""
    from mpi_pytorch_tpu.train.trainer import train

    sentinel = str(tmp_path / "preempt.sentinel")
    # The delay must dominate the noisy natural CPU step time so the 2x
    # drift ratio is unambiguous — the run preempts ~2 delayed steps in,
    # so the extra wall cost stays at a few seconds. Natural steps on a
    # loaded single-core box reach ~2 s, which put 1500 ms under the 2x
    # ratio; 6 s keeps the ratio >= 3-4x on any hardware.
    monkeypatch.setenv("MPT_FAULT_DELAY_STEP_MS", "6000")
    monkeypatch.setenv("MPT_FAULT_DELAY_AFTER_STEP", "4")
    cfg = _telemetry_cfg(
        str(tmp_path),
        num_epochs=8,
        heartbeat_every_steps=0,
        slo_rules=(
            "drift:train/step_ms_last > 2.0 warmup=3 "
            "action=log,metric,preempt name=straggler_step_drift"
        ),
        metrics_every_steps=2,
        flight_dir=str(tmp_path / "flight"),
        preempt_file=sentinel,
    )
    summary = train(cfg)
    assert summary.preempted, "the SLO breach never stopped the run"
    assert os.path.exists(sentinel)

    assert validate_jsonl(cfg.metrics_file) == []
    records = [json.loads(line) for line in open(cfg.metrics_file)]
    alerts = [r for r in records if r["kind"] == "alert"]
    assert [a["rule"] for a in alerts] == ["straggler_step_drift"]
    assert alerts[0]["value"] > 2.0 and alerts[0]["action"] == "log,metric,preempt"
    faults = [r for r in records if r["kind"] == "fault"]
    assert any(f["reason"] == "preempt_file" for f in faults), faults
    snaps = [r for r in records if r["kind"] == "metrics"]
    assert snaps, "no kind='metrics' snapshots on the cadence"
    last = snaps[-1]
    assert last["counters"]["obs/alerts_fired"] == 1.0
    assert last["histograms"]["train/step_ms"]["count"] > 0
    assert last["gauges"]["train/step_ms_last"] > 0

    dumps = sorted(os.listdir(cfg.flight_dir))
    alert_dumps = [d for d in dumps if "alert_straggler_step_drift" in d]
    assert alert_dumps, dumps
    dumped = json.load(open(os.path.join(cfg.flight_dir, alert_dumps[0])))
    assert dumped["records"][-1]["kind"] == "alert"
    from mpi_pytorch_tpu.obs.schema import validate_record as _vr
    for rec in dumped["records"]:
        assert _vr(rec) == [], rec

    # The report tool renders the new kinds.
    assert report_run.main([cfg.metrics_file]) == 0


def test_registry_snapshots_without_rules(tmp_path):
    """--metrics-every-steps alone (no SLO rules) still publishes the
    registry cadence: step-time histograms/gauges with no alert machinery,
    and the stream stays schema-clean."""
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _telemetry_cfg(
        str(tmp_path), metrics_every_steps=2, heartbeat_every_steps=0,
    )
    summary = train(cfg)
    assert summary.epochs_run == 2
    assert validate_jsonl(cfg.metrics_file) == []
    records = [json.loads(line) for line in open(cfg.metrics_file)]
    snaps = [r for r in records if r["kind"] == "metrics"]
    # 2 steps/epoch x 2 epochs at every-2 cadence = 2 periodic + 1 final.
    assert len(snaps) == 3
    for s in snaps:
        assert set(s["histograms"]) >= {"train/step_ms", "train/data_wait_ms"}
    assert snaps[-1]["gauges"]["train/images_per_sec"] > 0
    assert not [r for r in records if r["kind"] == "alert"]
