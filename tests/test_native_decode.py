"""Native (C++) batched JPEG ingest: build, parity vs the PIL path, fallback.

The native library (mpi_pytorch_tpu/native/decode.cpp) is the TPU-host
equivalent of the reference's parallel-ingest machinery (torch DataLoader
workers, ``data_loader.py:29-39``; MPI preprocessing ranks,
``evaluation_pipeline.py:53-129``). These tests pin its contract:

- decode parity: same libjpeg, so exact-size decode is bit-identical to PIL
- resize parity: the separable triangle filter matches PIL's BILINEAR within
  fixed-point rounding (<1.5/255 per pixel)
- DCT prescale modes trade PIL-exactness for IDCT work, with bounded deviation
- corrupt / non-JPEG items fall back to PIL one at a time
"""

from __future__ import annotations

import os

import numpy as np
import pytest
from PIL import Image

from mpi_pytorch_tpu import native
from mpi_pytorch_tpu.config import IMAGENET_MEAN, IMAGENET_STD
from mpi_pytorch_tpu.data.manifest import Manifest
from mpi_pytorch_tpu.data.pipeline import (
    DataLoader,
    decode_image,
    normalize_image,
    synthetic_image,
)

MEAN = np.asarray(IMAGENET_MEAN, dtype=np.float32)
STD = np.asarray(IMAGENET_STD, dtype=np.float32)

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native decode unavailable: {native.build_error()}"
)


def _write_jpeg(path, img_u8, quality=95):
    Image.fromarray(img_u8).save(path, quality=quality)


def _pil(path, size=(128, 128)):
    return normalize_image(decode_image(str(path), size))


def _pixel_diff(a, b):
    """Max |a-b| in uint8 pixel units (undo the ImageNet normalization)."""
    return float((np.abs(a - b) * STD).max() * 255)


def test_exact_size_decode_is_bit_parity_with_pil(tmp_path):
    img = (synthetic_image(3, (128, 128)) * 255).astype(np.uint8)
    p = tmp_path / "a.jpg"
    _write_jpeg(p, img)
    out = native.decode_batch([str(p)], (128, 128), MEAN, STD)
    assert _pixel_diff(out[0], _pil(p)) < 0.01  # same libjpeg: f32 rounding only


def test_resize_matches_pil_bilinear(tmp_path):
    # 140->128 stays below any prescale threshold: pure resize comparison.
    img = (synthetic_image(3, (140, 140)) * 255).astype(np.uint8)
    p = tmp_path / "a.jpg"
    _write_jpeg(p, img)
    out = native.decode_batch([str(p)], (128, 128), MEAN, STD, prescale_margin=0)
    # PIL computes the same triangle filter in 8.22 fixed point; we use f32.
    assert _pixel_diff(out[0], _pil(p)) < 1.5


def test_upscale_matches_pil(tmp_path):
    img = (synthetic_image(5, (100, 90)) * 255).astype(np.uint8)
    p = tmp_path / "a.jpg"
    _write_jpeg(p, img)
    out = native.decode_batch([str(p)], (128, 128), MEAN, STD)
    assert _pixel_diff(out[0], _pil(p)) < 1.5


def test_prescale_margin0_full_parity_on_large_source(tmp_path):
    img = (synthetic_image(7, (1000, 800)) * 255).astype(np.uint8)
    p = tmp_path / "big.jpg"
    _write_jpeg(p, img)
    out = native.decode_batch([str(p)], (128, 128), MEAN, STD, prescale_margin=0)
    assert _pixel_diff(out[0], _pil(p)) < 1.5


def test_prescale_deviation_is_bounded(tmp_path):
    # Scaled IDCT is a different low-pass than full-decode+resize; the default
    # 2x-margin mode must stay close to PIL in the mean (documented contract).
    img = (synthetic_image(7, (1000, 800)) * 255).astype(np.uint8)
    p = tmp_path / "big.jpg"
    _write_jpeg(p, img)
    ref = _pil(p)
    for margin, mean_tol in ((2, 3.0), (1, 6.0)):
        out = native.decode_batch([str(p)], (128, 128), MEAN, STD, prescale_margin=margin)
        mean_diff = float((np.abs(out[0] - ref) * STD).mean() * 255)
        assert mean_diff < mean_tol, (margin, mean_diff)


def test_grayscale_jpeg_expands_to_rgb(tmp_path):
    gray = (synthetic_image(2, (150, 150))[:, :, 0] * 255).astype(np.uint8)
    p = tmp_path / "gray.jpg"
    Image.fromarray(gray, mode="L").save(p, quality=95)
    out = native.decode_batch([str(p)], (128, 128), MEAN, STD, prescale_margin=0)
    assert out.shape == (1, 128, 128, 3)
    # PIL path applies .convert("RGB") — the grayscale fix the reference lacks.
    assert _pixel_diff(out[0], _pil(p)) < 1.5


def test_corrupt_item_falls_back_per_item(tmp_path):
    good = tmp_path / "good.jpg"
    _write_jpeg(good, (synthetic_image(1, (128, 128)) * 255).astype(np.uint8))
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"this is not a jpeg")
    calls = []

    def fallback(path):
        calls.append(path)
        return np.zeros((128, 128, 3), np.float32)

    out = native.decode_batch(
        [str(good), str(bad)], (128, 128), MEAN, STD, fallback=fallback
    )
    assert calls == [str(bad)]
    assert np.all(out[1] == 0)
    assert _pixel_diff(out[0], _pil(good)) < 0.01


def test_missing_file_raises_without_fallback(tmp_path):
    with pytest.raises(RuntimeError, match="native decode failed"):
        native.decode_batch([str(tmp_path / "nope.jpg")], (128, 128), MEAN, STD)


def _jpeg_manifest(tmp_path, n=12):
    img_dir = tmp_path / "img"
    img_dir.mkdir()
    names, labels = [], []
    for i in range(n):
        name = f"im_{i}.jpg"
        _write_jpeg(img_dir / name, (synthetic_image(i % 3, (160, 140)) * 255).astype(np.uint8))
        names.append(name)
        labels.append(i % 3)
    return Manifest(
        filenames=tuple(names),
        labels=np.array(labels, np.int32),
        category_ids=np.array(labels, np.int64),
        img_dir=str(img_dir),
    )


def test_loader_native_path_matches_pil_path(tmp_path):
    m = _jpeg_manifest(tmp_path)
    kw = dict(batch_size=4, image_size=(128, 128), shuffle=False, drop_remainder=False)
    native_batches = list(
        DataLoader(m, **kw, native_decode=True, decode_prescale=0).epoch(0)
    )
    pil_batches = list(DataLoader(m, **kw, native_decode=False).epoch(0))
    assert len(native_batches) == len(pil_batches) == 3
    for (ni, nl), (pi, pl) in zip(native_batches, pil_batches):
        np.testing.assert_array_equal(nl, pl)
        assert _pixel_diff(ni, pi) < 1.5


def test_loader_host_cache_matches_direct_decode(tmp_path):
    """host_cache composed with native decode: identical batches to direct
    per-epoch decode, and repeat epochs serve from the cache byte-for-byte."""
    m = _jpeg_manifest(tmp_path)
    kw = dict(batch_size=4, image_size=(128, 128), shuffle=True, seed=3,
              drop_remainder=False, native_decode=True, decode_prescale=0)
    direct = list(DataLoader(m, **kw).epoch(1))
    cached_loader = DataLoader(m, **kw, host_cache=True)
    first = list(cached_loader.epoch(1))
    again = list(cached_loader.epoch(1))
    assert len(direct) == len(first) == len(again) == 3
    for (di, dl), (fi, fl), (ai, al) in zip(direct, first, again):
        np.testing.assert_array_equal(dl, fl)
        np.testing.assert_array_equal(di, fi)
        np.testing.assert_array_equal(fi, ai)
        np.testing.assert_array_equal(fl, al)


def test_env_kill_switch():
    # The switch is latched at first load(), and this process has already
    # loaded the library — exercise it in a fresh interpreter.
    import subprocess
    import sys

    probe = (
        "from mpi_pytorch_tpu import native; "
        "assert native.load() is None, 'kill switch ignored'; "
        "assert not native.available(); print('disabled-ok')"
    )
    out = subprocess.run(
        [sys.executable, "-c", probe],
        env={**os.environ, "MPT_DISABLE_NATIVE": "1", "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "disabled-ok" in out.stdout
