"""Tests for trace-replay workloads + the fitted per-phase latency model
(ISSUE 18): golden-trace extraction round-trip and fingerprint identity,
deterministic replay under an injected fake clock (exact arrival fidelity,
latency measured from the intended arrival, duck-typed rejection
classification), warp/trim producing new workload identities, typed
rejection of malformed/truncated trace rows, model fit/predict against
synthetic spans with KNOWN phase costs (device exact, unseen-bucket
linear-in-rows scaling, saturation flagging), stamped calibration-error
bounds, what-if ranking sanity (a strictly-worse config never outranks a
better one), the differential report + render lines, and the v14
workload axis in the regression gate's serve trend-line identity.

Everything here is jax-free — the replay/model layer is pure obs code,
and the real-fleet record→replay→plan chain is the driver's
``_dryrun_replay`` leg.
"""

import json
import os
import sys
from concurrent.futures import Future

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from mpi_pytorch_tpu.obs.model import (  # noqa: E402
    SATURATED_MS,
    ModelError,
    PhaseLatencyModel,
)
from mpi_pytorch_tpu.obs.replay import (  # noqa: E402
    Workload,
    WorkloadError,
    WorkloadRequest,
    differential_report,
    extract_workload,
    load_workload,
    render_diff,
    replay_workload,
)


# ------------------------------------------------------------ trace builder


def _span(name, t0, t1, trace="t0", span_id="s0", parent=None, attrs=None):
    s = {"name": name, "t0": t0, "t1": t1, "trace": trace, "span": span_id,
         "pid": 1}
    if parent is not None:
        s["parent"] = parent
    if attrs is not None:
        s["attrs"] = attrs
    return s


def _golden_trace(path, n=8, gap_s=0.5, device_ms=20.0, prep_ms=1.0,
                  queue_ms=2.0, bucket=4, rows=4, precision="bf16"):
    """A synthetic fleet trace with KNOWN phase costs: n completed
    requests, one every gap_s, each with a route/request root (v14
    attrs), a serve/request child, and queue/preprocess/device
    grandchildren of exact durations."""
    spans = []
    for i in range(n):
        t0 = 100.0 + i * gap_s
        total = (queue_ms + prep_ms + device_ms) / 1e3
        trace = f"tr{i}"
        spans.append(_span(
            "route/request", t0, t0 + total, trace=trace, span_id=f"r{i}",
            attrs={"status": "ok", "bucket": bucket, "rows": rows,
                   "precision": precision}))
        spans.append(_span(
            "serve/request", t0, t0 + total, trace=trace, span_id=f"q{i}",
            parent=f"r{i}",
            attrs={"status": "ok", "bucket": bucket, "rows": rows,
                   "precision": precision}))
        t = t0
        for ph, dur in (("serve/queue", queue_ms),
                        ("serve/preprocess", prep_ms),
                        ("serve/device", device_ms)):
            spans.append(_span(ph, t, t + dur / 1e3, trace=trace,
                               span_id=f"{ph[-3:]}{i}", parent=f"q{i}"))
            t += dur / 1e3
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")
    return spans


# --------------------------------------------------- extraction round-trip


def test_golden_trace_roundtrip(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    _golden_trace(trace, n=8, gap_s=0.5)
    wl = extract_workload(trace)
    assert len(wl.requests) == 8
    assert wl.accepted == 8 and wl.rejected == 0
    assert wl.defaults_applied == 0
    # Offsets normalized to t=0 at the recorded gaps.
    assert wl.requests[0].offset_s == 0.0
    assert wl.requests[3].offset_s == pytest.approx(1.5)
    assert wl.duration_s == pytest.approx(3.5)
    r = wl.requests[0]
    assert (r.model, r.bucket, r.rows, r.precision) == (None, 4, 4, "bf16")
    # Recorded per-phase summary carries the known costs exactly.
    pp = wl.recorded["per_phase"]
    assert pp["serve/device"]["p99_ms"] == pytest.approx(20.0, abs=1e-3)
    assert pp["serve/preprocess"]["p50_ms"] == pytest.approx(1.0, abs=1e-3)
    # Artifact round-trip: save → load preserves identity and content.
    art = str(tmp_path / "workload.json")
    wl.save(art)
    back = load_workload(art)
    assert back.fingerprint == wl.fingerprint
    assert back.requests == wl.requests
    assert back.recorded == wl.recorded
    # load_workload on the raw trace extracts the same workload.
    assert load_workload(trace).fingerprint == wl.fingerprint


def test_fingerprint_deterministic_and_transform_sensitive(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    _golden_trace(trace, n=6)
    a, b = extract_workload(trace), extract_workload(trace)
    assert a.fingerprint == b.fingerprint  # content-derived, no clock
    # Derived stats are excluded from identity: warp/trim are NEW loads.
    warped = a.warp(2.0)
    assert warped.fingerprint != a.fingerprint
    assert warped.duration_s == pytest.approx(a.duration_s / 2)
    assert a.warp(1.0) is a  # identity warp is a no-op, same fingerprint
    trimmed = a.trim(1.0)
    assert trimmed.fingerprint != a.fingerprint
    assert trimmed.requests[0].offset_s == 0.0  # re-zeroed to window start
    assert len(trimmed.requests) < len(a.requests)
    with pytest.raises(WorkloadError):
        a.trim(99.0)  # empty window is a typed refusal
    with pytest.raises(WorkloadError):
        a.warp(0.0)


def test_pre_v14_roots_replay_with_documented_defaults(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    spans = [
        _span("route/request", 100.0, 100.1, trace=f"t{i}", span_id=f"r{i}",
              attrs={"status": "ok"})  # no bucket/rows/precision: pre-v14
        for i in range(3)
    ]
    with open(trace, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")
    wl = extract_workload(trace)
    assert wl.defaults_applied == 3
    assert all(r.bucket is None and r.rows == 1 and r.precision is None
               for r in wl.requests)


# ------------------------------------------------------- typed rejections


@pytest.mark.parametrize("line", [
    '{"name": "route/request", "t0": 1.0',           # truncated JSON
    '[1, 2]',                                         # not an object
    '{"name": "route/request", "t1": 2.0}',           # missing t0
    '{"name": 7, "t0": 1.0, "t1": 2.0}',              # wrong name type
    '{"name": "x", "t0": true, "t1": 2.0}',           # bool is not a time
    '{"name": "x", "t0": 2.0, "t1": 1.0}',            # ends before it starts
])
def test_malformed_trace_rows_rejected_typed(tmp_path, line):
    trace = str(tmp_path / "trace.jsonl")
    good = json.dumps(_span("route/request", 1.0, 2.0,
                            attrs={"status": "ok"}))
    with open(trace, "w") as fh:
        fh.write(good + "\n" + line + "\n")
    with pytest.raises(WorkloadError) as ei:
        extract_workload(trace)
    assert "line 2" in str(ei.value)  # points at the offending row


def test_trace_without_roots_rejected(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    with open(trace, "w") as fh:
        fh.write(json.dumps(_span("serve/device", 1.0, 2.0)) + "\n")
    with pytest.raises(WorkloadError, match="route/request"):
        extract_workload(trace)


def test_bad_workload_artifact_rejected(tmp_path):
    art = str(tmp_path / "workload.json")
    with open(art, "w") as fh:
        fh.write('{"kind": "workload", "requests": [{"bogus": 1}]}\n')
    with pytest.raises(WorkloadError, match="malformed workload request"):
        load_workload(art)


# --------------------------------------------------------- fake-clock replay


class _FakeClock:
    """Deterministic time: sleep() IS the only thing that advances it, so
    replay lands every arrival at exactly its recorded offset."""

    def __init__(self, start=50.0):
        self.t = start

    def clock(self):
        return self.t

    def sleep(self, dt):
        assert dt >= 0
        self.t += dt


def _workload(offsets, outcome="ok"):
    return Workload(requests=[
        WorkloadRequest(offset_s=o, model=None, bucket=4, rows=4,
                        precision="bf16", outcome=outcome)
        for o in offsets
    ])


def test_replay_fake_clock_exact_arrivals_and_latency():
    fc = _FakeClock()
    wl = _workload([0.0, 0.25, 1.0, 1.5])
    seen = []

    def submit(i, req):
        seen.append((i, fc.clock(), req.offset_s))
        fc.sleep(0.005)  # 5 ms synchronous service
        fut = Future()
        fut.set_result("ok")
        return fut

    res = replay_workload(submit, wl, clock=fc.clock, sleep=fc.sleep)
    # Every arrival re-driven in order at exactly its recorded offset.
    assert [i for i, _, _ in seen] == [0, 1, 2, 3]
    t0 = seen[0][1]
    for i, t, off in seen:
        assert t - t0 == pytest.approx(off, abs=1e-9)
    assert res["submitted"] == 4 and res["accepted"] == 4
    assert res["rejected"] == 0 and res["failed"] == 0
    assert res["max_arrival_skew_ms"] == pytest.approx(0.0, abs=1e-6)
    # Latency measured from the INTENDED arrival: exactly the 5 ms service.
    assert res["p99_ms"] == pytest.approx(5.0, abs=1e-6)
    # Wall = last arrival offset + the last request's synchronous 5 ms
    # (earlier service times are absorbed by the sleep-to-target).
    assert res["wall_s"] == pytest.approx(1.505, abs=1e-6)


def test_replay_speed_warps_arrivals():
    fc = _FakeClock()
    wl = _workload([0.0, 1.0, 2.0])
    times = []

    def submit(i, req):
        times.append(fc.clock())
        fut = Future()
        fut.set_result("ok")
        return fut

    replay_workload(submit, wl, speed=2.0, clock=fc.clock, sleep=fc.sleep)
    assert times[2] - times[0] == pytest.approx(1.0)  # 2 s replayed in 1 s


def test_replay_rejection_classified_by_duck_type():
    fc = _FakeClock()
    wl = _workload([0.0, 0.1, 0.2, 0.3])

    class QueueFullError(Exception):  # serve's name, NOT serve's class
        pass

    class Backoff(Exception):
        retry_after_ms = 5.0

    def submit(i, req):
        if i == 0:
            raise QueueFullError()
        if i == 1:
            raise Backoff()  # rejection by attribute, any type name
        if i == 2:
            raise ValueError("boom")  # a real failure, not admission
        fut = Future()
        fut.set_result("ok")
        return fut

    res = replay_workload(submit, wl, clock=fc.clock, sleep=fc.sleep)
    assert res["rejected"] == 2
    assert res["failed"] == 1
    assert res["accepted"] == 1


def test_replay_is_deterministic_under_fake_clock():
    wl = _workload([0.0, 0.5, 1.0])

    def run():
        fc = _FakeClock()

        def submit(i, req):
            fc.sleep(0.002 * (i + 1))
            fut = Future()
            fut.set_result("ok")
            return fut

        return replay_workload(submit, wl, clock=fc.clock, sleep=fc.sleep)

    assert run() == run()  # same workload + same server = same point


# --------------------------------------------------------------- the model


def _fitted(tmp_path, **kw):
    trace = str(tmp_path / "fit.jsonl")
    _golden_trace(trace, **kw)
    model = PhaseLatencyModel()
    assert model.fit_trace(trace) == kw.get("n", 8)
    return model


def test_model_predicts_known_phase_costs_exactly(tmp_path):
    model = _fitted(tmp_path, n=8, device_ms=20.0, prep_ms=1.0, bucket=4)
    wl = _workload([i * 0.5 for i in range(8)])
    pred = model.predict(
        {"buckets": [4], "max_wait_ms": 2.0, "hosts": 2,
         "precision": "bf16"}, wl)
    # Fitted phases reproduce the synthetic costs exactly.
    assert pred["per_phase"]["serve/device"] == pytest.approx(20.0, abs=1e-3)
    assert pred["per_phase"]["serve/preprocess"] == pytest.approx(
        1.0, abs=1e-3)
    # Queue = the chosen batching window + a small congestion term.
    assert pred["per_phase"]["serve/queue"] >= 2.0
    assert not pred["saturated"] and pred["rho"] < 1.0
    assert pred["bucket"] == 4
    assert pred["p99_ms"] == pytest.approx(
        sum(pred["per_phase"].values()), abs=1e-3)


def test_model_unseen_bucket_scales_linearly_with_note(tmp_path):
    model = _fitted(tmp_path, n=8, device_ms=20.0, bucket=4)
    wl = _workload([i * 0.5 for i in range(8)])
    pred = model.predict(
        {"buckets": [8], "max_wait_ms": 2.0, "hosts": 2,
         "precision": "bf16"}, wl)
    # bucket 8 never fitted: borrowed from bucket 4, scaled 2x in rows.
    assert pred["per_phase"]["serve/device"] == pytest.approx(40.0, abs=1e-3)
    assert any("unseen" in n for n in pred["notes"])


def test_model_saturation_flagged_and_ranks_by_hosts(tmp_path):
    model = _fitted(tmp_path, n=8, device_ms=200.0, bucket=4)
    # 100 rps against ~20 rows/s/host capacity: saturated either way,
    # but the finite-burst backlog-drain term must still rank more hosts
    # strictly better (a flat sentinel could not).
    wl = _workload([i * 0.01 for i in range(200)])
    p1 = model.predict({"buckets": [4], "max_wait_ms": 2.0, "hosts": 1,
                        "precision": "bf16"}, wl)
    p4 = model.predict({"buckets": [4], "max_wait_ms": 2.0, "hosts": 4,
                        "precision": "bf16"}, wl)
    assert p1["saturated"] and p4["saturated"]
    assert p4["rho"] < p1["rho"]
    assert p4["p99_ms"] < p1["p99_ms"]
    assert p1["per_phase"]["serve/queue"] <= 2.0 + SATURATED_MS


def test_model_typed_errors(tmp_path):
    model = _fitted(tmp_path)
    wl = _workload([0.0, 0.5])
    with pytest.raises(ModelError, match="nothing fitted"):
        model.predict({"buckets": [4], "max_wait_ms": 2.0, "hosts": 1,
                       "precision": "int8"}, wl)
    with pytest.raises(ModelError, match="malformed candidate"):
        model.predict({"buckets": [], "max_wait_ms": 2.0, "hosts": 1}, wl)
    with pytest.raises(ModelError, match="malformed candidate"):
        model.predict({"hosts": 1}, wl)
    # Pre-v14 recording: serve roots carry no bucket attr — typed refusal.
    trace = str(tmp_path / "prev14.jsonl")
    with open(trace, "w") as fh:
        fh.write(json.dumps(_span("serve/request", 1.0, 2.0,
                                  attrs={"status": "ok"})) + "\n")
    with pytest.raises(ModelError, match="cannot fit"):
        PhaseLatencyModel().fit_trace(trace)


def test_model_calibration_error_bounds(tmp_path):
    model = _fitted(tmp_path, n=8, device_ms=20.0, prep_ms=1.0, bucket=4)
    wl = _workload([i * 0.5 for i in range(8)])
    cfg = {"buckets": [4], "max_wait_ms": 2.0, "hosts": 2,
           "precision": "bf16"}
    pred = model.predict(cfg, wl)
    assert pred["calibration_error_pct"] is None  # unstamped until measured
    # Replayed end-to-end p99 exactly matches the prediction: 0% error.
    exact = {"route/request": {"p50_ms": 1.0, "p99_ms": pred["p99_ms"]}}
    assert model.calibrate(pred, exact) == pytest.approx(0.0)
    # Measured DOUBLE the prediction: |pred - meas| / meas = 50%.
    double = {"route/request": {"p50_ms": 1.0,
                                "p99_ms": 2.0 * pred["p99_ms"]}}
    assert model.calibrate(pred, double) == pytest.approx(50.0)
    assert model.calibration_window == "holdout"
    # The stamp rides every later prediction and the explain lines.
    assert model.predict(cfg, wl)["calibration_error_pct"] == 50.0
    assert any("calibration" in ln for ln in model.explain())
    rec = model.to_record()
    assert rec["calibration_error_pct"] == 50.0
    # Fallback: no route/request measurement → sum of phase p99s.
    phases_only = {ph: {"p50_ms": 1.0, "p99_ms": v}
                   for ph, v in pred["per_phase"].items()}
    assert model.calibrate(pred, phases_only) == pytest.approx(0.0)
    with pytest.raises(ModelError):
        model.calibrate(pred, {})


# ------------------------------------------------------------ what-if plan


def test_whatif_ranking_sanity(tmp_path):
    from whatif import explain_plan, rank_candidates

    model = _fitted(tmp_path, n=8, device_ms=20.0, bucket=4)
    wl = _workload([i * 0.5 for i in range(8)])
    ranked = rank_candidates(
        model, wl, bucket_sets=["4"], precisions=["bf16"],
        hosts=[1, 2], waits=[2.0, 200.0], budgets=[0])
    assert [c["rank"] for c in ranked] == [1, 2, 3, 4]
    p99s = [c["predicted"]["p99_ms"] for c in ranked]
    assert p99s == sorted(p99s)  # best first
    # A strictly-worse config (same everything, 100x the batching window)
    # must never outrank the smaller window: queue = wait + congestion.
    best_by_wait = {}
    for c in ranked:
        key = c["config"]["hosts"]
        best_by_wait.setdefault(key, {})[c["config"]["max_wait_ms"]] = (
            c["rank"])
    for by_wait in best_by_wait.values():
        assert by_wait[2.0] < by_wait[200.0]
    # Unpriceable candidates are reported, not dropped.
    ranked2 = rank_candidates(
        model, wl, bucket_sets=["4"], precisions=["bf16", "int8"],
        hosts=[1], waits=[2.0], budgets=[0])
    errs = [c for c in ranked2 if "error" in c]
    assert len(errs) == 1 and "int8" in errs[0]["error"]
    lines = explain_plan(ranked2, wl, model)
    assert any("#1" in ln for ln in lines)
    assert any("UNPRICEABLE" in ln for ln in lines)
    assert wl.fingerprint in lines[0]


# --------------------------------------------------- differential + gating


def test_differential_report_and_render(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    _golden_trace(trace, n=8, device_ms=20.0)
    wl = extract_workload(trace)
    replayed = {"submitted": 8, "rejected": 2, "images_per_sec": 10.0}
    rep_phases = {"serve/device": {"p50_ms": 25.0, "p99_ms": 30.0}}
    diff = differential_report(wl, replayed, rep_phases)
    assert diff["workload"] == wl.fingerprint
    ent = diff["phases"]["serve/device"]
    assert ent["recorded_p99_ms"] == pytest.approx(20.0, abs=1e-3)
    assert ent["replayed_p99_ms"] == 30.0
    assert ent["delta_p99_pct"] == pytest.approx(50.0, abs=0.1)
    assert diff["replayed_reject_rate"] == pytest.approx(0.25)
    lines = render_diff(diff)
    assert wl.fingerprint in lines[0]
    assert any("serve/device" in ln and "+50.0%" in ln for ln in lines)


def test_serve_trend_line_keys_on_workload_fingerprint():
    from check_regression import _serve_key

    poisson = {"kind": "serve_bench", "mode": "open", "buckets": "1,4",
               "max_wait_ms": 2.0, "offered_rps": 400.0}
    replay = dict(poisson, mode="replay", workload="b764999_deadbeef")
    # A replayed-load row never compares against a synthetic-Poisson
    # baseline, and two replays only compare on the IDENTICAL workload.
    assert _serve_key(poisson) != _serve_key(replay)
    assert _serve_key(replay) != _serve_key(
        dict(replay, workload="other_fingerprint"))
    assert _serve_key(dict(replay)) == _serve_key(dict(replay))
    # Pre-v14 rows key None on both sides — prior baselines unchanged.
    assert _serve_key(poisson)[-1] is None
