"""GPipe pipeline parallelism vs the un-pipelined stacked forward on the
8-device CPU mesh — values, gradients, remat agreement, and the shape guards.

The correctness property: streaming M microbatches through S ppermute-linked
stages computes exactly ``stage_S(...stage_1(x))`` per example, and grads
through the schedule equal grads of the plain composition.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.parallel.pipeline import (
    pipeline_forward,
    stack_stage_params,
)

N_STAGES = 8
D = 16


@pytest.fixture(scope="module")
def mesh():
    dev = np.asarray(jax.devices()[:N_STAGES]).reshape(N_STAGES, 1)
    return Mesh(dev, ("pipe", "unused"))


def residual_mlp_stage(params, x):
    """One homogeneous stage: residual two-layer MLP, [mb, D] → [mb, D]."""
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"] + params["b2"]


def _stage_params(seed):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.standard_normal((D, 4 * D)) * 0.1, jnp.float32),
        "b1": jnp.zeros((4 * D,), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((4 * D, D)) * 0.1, jnp.float32),
        "b2": jnp.zeros((D,), jnp.float32),
    }


@pytest.fixture(scope="module")
def stacked():
    return stack_stage_params([_stage_params(s) for s in range(N_STAGES)])


def stacked_reference(stacked_params, x):
    """Un-pipelined composition of all stages on one device."""
    for s in range(N_STAGES):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stacked_params)
        x = residual_mlp_stage(params_s, x)
    return x


def _x(b=32, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, D)), jnp.float32)


@pytest.mark.parametrize("num_micro", [4, 8])
def test_pipeline_matches_stacked_forward(mesh, stacked, num_micro):
    x = _x()
    got = pipeline_forward(
        stacked, x, mesh, stage_fn=residual_mlp_stage, num_microbatches=num_micro
    )
    want = stacked_reference(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pipeline_grads_match_stacked(mesh, stacked):
    x = _x(seed=2)
    y = jnp.asarray(np.random.default_rng(3).standard_normal(x.shape), jnp.float32)

    def loss_pp(params, x_):
        out = pipeline_forward(
            params, x_, mesh, stage_fn=residual_mlp_stage, num_microbatches=8
        )
        return jnp.mean((out - y) ** 2)

    def loss_ref(params, x_):
        return jnp.mean((stacked_reference(params, x_) - y) ** 2)

    gp, gxp = jax.grad(loss_pp, argnums=(0, 1))(stacked, x)
    gr, gxr = jax.grad(loss_ref, argnums=(0, 1))(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gxp), np.asarray(gxr), rtol=5e-5, atol=5e-5)


def test_pipeline_remat_matches_plain(mesh, stacked):
    """remat=True re-derives stage internals in the backward; same numbers."""
    x = _x(seed=4)

    def loss(params, remat):
        out = pipeline_forward(
            params, x, mesh, stage_fn=residual_mlp_stage,
            num_microbatches=8, remat=remat,
        )
        return jnp.sum(out * out)

    g_plain = jax.grad(functools.partial(loss, remat=False))(stacked)
    g_remat = jax.grad(functools.partial(loss, remat=True))(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_pipeline_composes_with_dp():
    """PP×DP on a 4-stage × 2-data mesh: values AND grads equal the
    un-pipelined single-device composition (shard_map's transpose supplies
    the gradient psum over the data axis for the pipe-sharded params)."""
    dev = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh2d = Mesh(dev, ("pipe", "data"))
    stacked4 = stack_stage_params([_stage_params(s) for s in range(4)])

    def ref4(params, x):
        for s in range(4):
            x = residual_mlp_stage(
                jax.tree_util.tree_map(lambda p: p[s], params), x
            )
        return x

    x = _x(b=32, seed=9)
    got = pipeline_forward(
        stacked4, x, mesh2d, stage_fn=residual_mlp_stage,
        num_microbatches=8, data_axis="data",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref4(stacked4, x)), rtol=2e-5, atol=2e-5
    )

    y = jnp.asarray(np.random.default_rng(10).standard_normal(x.shape), jnp.float32)

    def loss_pp(params):
        out = pipeline_forward(
            params, x, mesh2d, stage_fn=residual_mlp_stage,
            num_microbatches=8, data_axis="data",
        )
        return jnp.mean((out - y) ** 2)

    g_pp = jax.grad(loss_pp)(stacked4)
    g_rf = jax.grad(lambda p: jnp.mean((ref4(p, x) - y) ** 2))(stacked4)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_rf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


# --- real-model stages: the ViT encoder block as a pipeline stage ---------

VIT_BLOCK = dict(num_heads=4, mlp_dim=32)
VIT_HIDDEN = 16


def vit_block_stage(params, x):
    """One ViT EncoderBlock as a pipeline stage: [mb, S, hidden] →
    [mb, S, hidden] (the homogeneous-stage property models/vit.py documents)."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    return EncoderBlock(**VIT_BLOCK).apply({"params": params}, x, train=False)


@pytest.mark.slow
def test_pipeline_runs_vit_encoder_blocks(mesh):
    """An 8-deep ViT encoder split one-block-per-stage over the pipe axis
    equals running the blocks sequentially on one device."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((16, 8, VIT_HIDDEN)), jnp.float32)
    block = EncoderBlock(**VIT_BLOCK)
    per_stage = [
        block.init({"params": jax.random.PRNGKey(s)}, x[:2], train=False)["params"]
        for s in range(N_STAGES)
    ]
    stacked_blocks = stack_stage_params(per_stage)

    got = pipeline_forward(
        stacked_blocks, x, mesh, stage_fn=vit_block_stage, num_microbatches=8
    )
    want = x
    for params in per_stage:
        want = block.apply({"params": params}, want, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# --- PP as a trainer capability (--pp-stages): parallel/pp_vit.py ---------


def _tiny_vit(num_classes=7, depth=4, **kw):
    from mpi_pytorch_tpu.models.vit import VisionTransformer

    return VisionTransformer(
        num_classes=num_classes, patch_size=4, hidden=16, depth=depth,
        num_heads=2, mlp_dim=32, dtype=jnp.float32, param_dtype=jnp.float32,
        **kw,
    )


def _pp_mesh(stages=4):
    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.parallel.mesh import create_mesh

    return create_mesh(MeshConfig(pipe_parallel=stages))


@pytest.mark.slow
def test_pp_apply_matches_model_apply():
    """make_pp_apply over the UNCHANGED param tree reproduces model.apply
    exactly: logits and per-param grads — pipelining is an execution
    strategy, not a different model."""
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply

    model = _tiny_vit()
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((16, 16, 16, 3)), jnp.float32
    )
    variables = model.init({"params": jax.random.PRNGKey(0)}, x[:2], train=False)
    mesh = _pp_mesh(4)
    pp_apply = make_pp_apply(model, mesh, num_microbatches=8)

    got = pp_apply(variables, x, train=False)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    labels = jnp.asarray(np.random.default_rng(1).integers(0, 7, 16), jnp.int32)

    def ce(apply_fn):
        def loss(params):
            logits = apply_fn({"params": params}, x, train=False)
            import optax

            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            )

        return jax.grad(loss)(variables["params"])

    g_pp, g_ref = ce(pp_apply), ce(model.apply)
    assert jax.tree_util.tree_structure(g_pp) == jax.tree_util.tree_structure(g_ref)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_pp_train_step_matches_unpipelined():
    """The FULL jitted train step (loss, grads, Adam update) with the PP
    apply_fn produces the same updated params as the unpipelined step —
    the --pp-stages ≡ unpipelined trajectory property, two steps deep."""
    import optax

    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply
    from mpi_pytorch_tpu.train.state import TrainState
    from mpi_pytorch_tpu.train.step import make_train_step

    model = _tiny_vit()
    mesh = _pp_mesh(4)
    rng = np.random.default_rng(2)
    x = np.asarray(rng.standard_normal((16, 16, 16, 3)), np.float32)
    labels = np.asarray(rng.integers(0, 7, 16), np.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(3)}, jnp.asarray(x[:2]), train=False
    )

    def run(apply_fn):
        # Fresh buffers per run: the jitted step donates the state, so the
        # two runs must not share the init arrays. SGD, not Adam: Adam's
        # m/sqrt(v) normalization amplifies noise-level grad differences on
        # zero-grad params into O(lr) update differences, which would force
        # a vacuous tolerance — SGD keeps the comparison linear in grads.
        fresh = jax.tree_util.tree_map(jnp.array, variables)
        state = TrainState.create(
            apply_fn=apply_fn, variables=fresh, tx=optax.sgd(1e-2),
            rng=jax.random.PRNGKey(4),
        )
        step = make_train_step(compute_dtype=jnp.float32)
        batch = shard_batch((jnp.asarray(x), jnp.asarray(labels)), mesh)
        metrics = None
        for _ in range(2):
            state, metrics = step(state, batch)
        return state, metrics

    s_pp, m_pp = run(make_pp_apply(model, mesh, num_microbatches=8))
    s_ref, m_ref = run(model.apply)
    np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_pp.params), jax.tree_util.tree_leaves(s_ref.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_pp_apply_guards():
    """make_pp_apply rejects the configurations whose semantics would
    silently differ: MoE blocks, SP attention, dropout, indivisible depth."""
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply

    mesh = _pp_mesh(4)
    with pytest.raises(ValueError, match="dense encoder blocks"):
        make_pp_apply(_tiny_vit(moe_every=2), mesh, num_microbatches=8)
    with pytest.raises(ValueError, match="dropout"):
        make_pp_apply(_tiny_vit(dropout=0.1), mesh, num_microbatches=8)
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_apply(_tiny_vit(depth=6), mesh, num_microbatches=8)


def test_build_inference_wires_pp(tmp_path):
    """--pp-stages reaches the EVAL driver through the same apply_fn seam as
    the trainer (no silently-ignored flag)."""
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.evaluate import build_inference

    cfg = parse_config([
        "--model-name", "vit_s16", "--pp-stages", "4", "--image-size", "32",
        "--num-classes", "1000", "--synthetic-data", "true",
    ])
    mesh, bundle, state, _ = build_inference(cfg)
    assert mesh.shape.get("pipe") == 4
    assert state.apply_fn is not bundle.model.apply  # the PP swap happened


@pytest.mark.slow
def test_pp_stages_config_trains_vit(tmp_path):
    """--pp-stages 4 end to end through parse_config/build_training/train on
    the 8-device mesh (pipe=4 × data=2): the PIPELINED multi-epoch loss
    trajectory matches the unpipelined trainer's on the identical config
    (SURVEY §2c's PP "Done =" criterion), and the checkpoint it writes
    restores into an UNPIPELINED run (PP-degree-independent checkpoints)."""
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.train.trainer import train

    common = [
        "--debug", "true", "--debug-sample-size", "64",
        "--image-size", "32", "--batch-size", "16", "--num-classes", "1000",
        "--num-epochs", "2", "--synthetic-data", "true", "--validate", "false",
        "--compute-dtype", "float32",  # tight trajectory comparison
        "--log-file", str(tmp_path / "training.log"),
        "--metrics-file", str(tmp_path / "metrics.jsonl"),
    ]
    args = ["--model-name", "vit_s16", "--pp-stages", "4",
            "--checkpoint-dir", str(tmp_path / "ckpt")] + common
    cfg = parse_config(args)
    assert cfg.mesh.pipe_parallel == 4
    summary = train(cfg)
    assert summary.epochs_run == 2
    assert np.isfinite(summary.final_loss)

    # Same config WITHOUT pipelining: the per-epoch losses must match —
    # PP is an execution strategy, not a different trajectory.
    cfg_ref = parse_config(
        ["--model-name", "vit_s16",
         "--checkpoint-dir", str(tmp_path / "ckpt_ref")] + common
    )
    summary_ref = train(cfg_ref)
    np.testing.assert_allclose(
        summary.epoch_losses, summary_ref.epoch_losses, rtol=1e-4
    )

    # Resume the PP checkpoint WITHOUT pipelining: same param tree.
    cfg2 = parse_config(
        ["--model-name", "vit_s16",
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--from-checkpoint", "true"] + common + ["--num-epochs", "3"]
    )
    assert cfg2.pp_stages == 1
    summary2 = train(cfg2)
    assert summary2.epochs_run == 1
    assert np.isfinite(summary2.final_loss)


def test_pipeline_rejects_bad_shapes(mesh, stacked):
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(
            stacked, _x(b=30), mesh,
            stage_fn=residual_mlp_stage, num_microbatches=7,
        )
    short = jax.tree_util.tree_map(lambda p: p[:4], stacked)
    with pytest.raises(ValueError, match="stage axis"):
        pipeline_forward(
            short, _x(), mesh, stage_fn=residual_mlp_stage, num_microbatches=4
        )


# ==========================================================================
# Serving: the pipe:K residency — stage-split per-bucket AOT executables
# with micro-batched inter-stage handoff (serve/pipeline.py, ISSUE 20).
# ==========================================================================


def _serve_cfg(num_classes=64, buckets="1,4"):
    from mpi_pytorch_tpu.config import Config

    cfg = Config(
        model_name="resnet18", num_classes=num_classes, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets=buckets, serve_topk=3,
        metrics_file="", log_file="", eval_log_file="",
    )
    cfg.validate_config()
    return cfg


@pytest.fixture(scope="module")
def pipe_serving():
    """The module's one expensive build: pipe:2 stage-split executables on
    the nested (data, pipe) CPU mesh, plus the single-chip oracle over the
    SAME state, plus deterministic inputs and the oracle's predictions at
    every bucket. The compile listener is process-global, so the pipe set
    is rebaselined AFTER the oracle's warmup."""
    from mpi_pytorch_tpu.parallel.collectives import LEDGER
    from mpi_pytorch_tpu.parallel.mesh import create_pipe_serve_mesh
    from mpi_pytorch_tpu.serve.executables import BucketExecutables
    from mpi_pytorch_tpu.serve.pipeline import PipelineExecutables
    from mpi_pytorch_tpu.serve.server import InferenceServer

    cfg = _serve_cfg()
    state = InferenceServer._build_state(cfg, None, False)
    booked_before = LEDGER.snapshot()["ici"]["by_op"].get("pipe_handoff", 0)
    exe = PipelineExecutables(
        cfg, state, create_pipe_serve_mesh(2), microbatches=4
    )
    booked = (
        LEDGER.snapshot()["ici"]["by_op"].get("pipe_handoff", 0)
        - booked_before
    )
    exe.warmup()
    oracle_mesh = Mesh(
        np.asarray(jax.devices()[:1]).reshape(1, 1), ("data", "model")
    )
    oracle = BucketExecutables(cfg, state, oracle_mesh)
    oracle.warmup()
    exe.rebaseline()

    rng = np.random.default_rng(11)
    inputs, want = {}, {}
    for bucket in (1, 4):
        imgs = rng.normal(size=(bucket, 32, 32, 3)).astype(np.float32)
        inputs[bucket] = imgs
        rows = oracle.host_rows(bucket)
        oi = np.zeros((rows, 32, 32, 3), np.float32)
        oi[:bucket] = imgs
        ol = np.full((rows,), -1, np.int32)
        preds = np.asarray(jax.device_get(oracle(bucket, oracle.place(oi, ol))))
        want[bucket] = preds[:bucket]
    return {
        "cfg": cfg, "state": state, "exe": exe, "booked": booked,
        "inputs": inputs, "want": want,
    }


def _pipe_flush(exe, imgs):
    bucket = imgs.shape[0]
    labels = np.full((bucket,), -1, np.int32)
    return np.asarray(jax.device_get(exe(bucket, exe.place(imgs, labels))))


def test_pipe_cut_points_every_zoo_arch():
    """The generic cut derivation holds for EVERY servable architecture:
    the traced top-level chain is once-called and ends in "head", and
    plan_stages covers it contiguously in order with the head alone on the
    last stage — no per-arch table needed (PIPE_CUT_OVERRIDES stays empty,
    and this test is what turns a future non-linear arch into a loud
    failure instead of a wrong generic cut)."""
    from mpi_pytorch_tpu.config import SUPPORTED_MODELS
    from mpi_pytorch_tpu.models import initialize_model
    from mpi_pytorch_tpu.serve.pipeline import (
        PIPE_CUT_OVERRIDES, plan_stages, trace_units,
    )

    assert PIPE_CUT_OVERRIDES == {}
    for arch in SUPPORTED_MODELS:
        size = 299 if arch == "inception_v3" else 32
        model, _ = initialize_model(arch, 10)
        dummy = jax.ShapeDtypeStruct((1, size, size, 3), jnp.float32)
        rngs = {
            "params": jax.ShapeDtypeStruct((2,), jnp.uint32),
            "dropout": jax.ShapeDtypeStruct((2,), jnp.uint32),
        }
        shapes = jax.eval_shape(
            lambda r, x, m=model: m.init(r, x, train=True), rngs, dummy
        )
        units = trace_units(model.apply, shapes, dummy)
        names = [n for n, _ in units]
        assert names[-1] == "head", (arch, names[-3:])
        assert len(set(names)) == len(names), (arch, names)
        unit_bytes = {n: 1 for n in names}
        for k in (2, 3):
            if len(names) - 1 < k - 1:
                continue
            plan = plan_stages(names, unit_bytes, k, arch=arch)
            assert len(plan) == k, (arch, k, plan)
            assert [u for g in plan for u in g] == names, (arch, plan)
            assert plan[-1] == ["head"], (arch, plan)
            assert all(g for g in plan), (arch, plan)


def test_pipe_parity_with_single_chip_oracle(pipe_serving):
    """The tentpole's correctness core: the stage-split flush reproduces
    the unsplit single-chip forward bit-exactly at EVERY bucket, with zero
    compiles after warmup (per-bucket AOT — no steady-state tracing)."""
    exe = pipe_serving["exe"]
    for bucket in (1, 4):
        got = _pipe_flush(exe, pipe_serving["inputs"][bucket])
        assert np.array_equal(got, pipe_serving["want"][bucket]), bucket
    assert exe.compiles_since_warmup() == 0


def test_pipe_flush_stamp_and_bubble(pipe_serving):
    """Every flush stamps the measured pipeline facts: S/M as built (M_eff
    is the largest divisor of the bucket ≤ configured M — bucket 1
    degenerates to sequential M=1), bubble_frac in [0, 1), interstage
    bytes = Σ hop bytes × M, and monotonic per-stage wall windows in
    schedule order."""
    exe = pipe_serving["exe"]
    for bucket, m_want in ((1, 1), (4, 4)):
        _pipe_flush(exe, pipe_serving["inputs"][bucket])
        lf = exe.last_flush()
        assert lf["pipe_stages"] == 2
        assert lf["microbatches"] == m_want
        assert 0.0 <= lf["bubble_frac"] < 1.0
        plan = exe._plans[bucket]
        assert lf["interstage_bytes"] == sum(plan.hop_bytes) * m_want
        assert len(lf["stage_ms"]) == 2
        windows = lf["stage_windows"]
        assert len(windows) == 2
        for t0, t1 in windows:
            assert t0 <= t1
        # stage 1 cannot START before stage 0 dispatched its first micro.
        assert windows[1][0] >= windows[0][0]


def test_pipe_bubble_fraction_arithmetic():
    """The GPipe fill/drain arithmetic: (S−1)/(M+S−1), with the M=1 fully
    sequential and M→∞ amortized limits, and loud rejection of degenerate
    S/M."""
    from mpi_pytorch_tpu.serve.pipeline import pipeline_bubble_fraction

    assert pipeline_bubble_fraction(2, 4) == pytest.approx(0.2)
    assert pipeline_bubble_fraction(2, 1) == pytest.approx(0.5)
    assert pipeline_bubble_fraction(4, 1) == pytest.approx(0.75)
    assert pipeline_bubble_fraction(1, 8) == 0.0
    assert pipeline_bubble_fraction(4, 1000) < 0.003
    with pytest.raises(ValueError, match="stages >= 1"):
        pipeline_bubble_fraction(0, 4)
    with pytest.raises(ValueError, match="stages >= 1"):
        pipeline_bubble_fraction(2, 0)


def test_pipe_ledger_books_handoff_at_build(pipe_serving):
    """Inter-stage handoff is booked in the traffic LEDGER at build time
    (book-at-trace, PR 15): one micro-batch's boundary bytes per hop per
    bucket — and the flush-time ``interstage_bytes_per_flush`` quote is
    the max-bucket flow (Σ hop bytes × its M)."""
    exe = pipe_serving["exe"]
    per_hop = {
        b: sum(exe._plans[b].hop_bytes) for b in (1, 4)
    }
    assert all(v > 0 for v in per_hop.values())
    assert pipe_serving["booked"] == sum(per_hop.values())
    assert exe.interstage_bytes_per_flush() == max(
        per_hop[b] * exe._plans[b].m_eff for b in (1, 4)
    )


def test_pipe_microbatch_sweep_parity(pipe_serving):
    """M is a throughput knob, never a numerics knob: M=1 (fully
    sequential) and M=3 (non-divisor → M_eff=2) reproduce the oracle
    exactly at bucket 4, and the non-divisor request visibly degrades to
    the largest divisor in the flush stamp."""
    from mpi_pytorch_tpu.parallel.mesh import create_pipe_serve_mesh
    from mpi_pytorch_tpu.serve.pipeline import PipelineExecutables

    cfg = _serve_cfg(buckets="4")
    for m, m_eff in ((1, 1), (3, 2)):
        exe = PipelineExecutables(
            cfg, pipe_serving["state"], create_pipe_serve_mesh(2),
            microbatches=m,
        )
        exe.warmup()
        exe.rebaseline()
        got = _pipe_flush(exe, pipe_serving["inputs"][4])
        assert np.array_equal(got, pipe_serving["want"][4]), m
        lf = exe.last_flush()
        assert lf["microbatches"] == m_eff, (m, lf)
        assert exe.compiles_since_warmup() == 0
    # These builds moved the process-global compile counter past the
    # shared set's baseline — restore its zero-compile invariant.
    pipe_serving["exe"].rebaseline()


def test_pipe_slow_stage_gate_inflates_measured_bubble(pipe_serving):
    """The slow-stage drill: MPT_FAULT_STAGE_DELAY_MS stalls the target
    stage's dispatch window, the MEASURED bubble rises above the healthy
    flush's at the same bucket, the announce-once kind="fault" record is
    written exactly once, and numerics stay bit-identical."""
    import os

    exe = pipe_serving["exe"]
    _pipe_flush(exe, pipe_serving["inputs"][4])
    healthy = exe.last_flush()["bubble_frac"]

    written = []

    class _Sink:
        def write(self, record):
            written.append(record)

    exe.set_obs(metrics=_Sink())
    os.environ["MPT_FAULT_STAGE_DELAY_MS"] = "30"
    os.environ["MPT_FAULT_STAGE_DELAY_STAGE"] = "0"
    try:
        got = _pipe_flush(exe, pipe_serving["inputs"][4])
        stalled = exe.last_flush()["bubble_frac"]
        _pipe_flush(exe, pipe_serving["inputs"][4])
    finally:
        del os.environ["MPT_FAULT_STAGE_DELAY_MS"]
        del os.environ["MPT_FAULT_STAGE_DELAY_STAGE"]
    assert np.array_equal(got, pipe_serving["want"][4])
    assert stalled > healthy, (healthy, stalled)
    faults = [r for r in written if r.get("kind") == "fault"]
    assert len(faults) == 1, written  # announce-once, two stalled flushes
    assert faults[0]["reason"] == "injected_stage_delay"


def test_pipe_zoo_live_conversion_round_trip(tmp_path):
    """convert_residency replicated → pipe:2 → replicated on a live
    tenant: predictions bit-identical at both buckets through BOTH
    conversions, zero steady-state compiles, and each retune record labels
    its residency — the pipe one additionally carrying pipe_stages and
    the flush's interstage-byte price (schema v16)."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.serve.zoo import ZooServer

    cfg = Config(
        model_name="resnet18", num_classes=16, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=2.0, serve_topk=3,
        serve_models="alpha=resnet18",
        metrics_file=str(tmp_path / "metrics.jsonl"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    zoo = ZooServer(cfg, load_checkpoint=False)
    rng = np.random.default_rng(3)
    images = [rng.random((32, 32, 3)).astype(np.float32) for _ in range(4)]
    base4 = np.asarray(zoo.predict_batch(images, model="alpha"))
    base1 = np.asarray(zoo.predict_batch(images[:1], model="alpha"))

    zoo.convert_residency("alpha", "pipe:2", reason="test")
    assert zoo.pool.residency("alpha") == "pipe:2"
    assert np.array_equal(
        np.asarray(zoo.predict_batch(images, model="alpha")), base4
    )
    assert np.array_equal(
        np.asarray(zoo.predict_batch(images[:1], model="alpha")), base1
    )
    zoo.convert_residency("alpha", "replicated", reason="test")
    assert zoo.pool.residency("alpha") == "replicated"
    assert np.array_equal(
        np.asarray(zoo.predict_batch(images, model="alpha")), base4
    )
    assert zoo.compiles_after_warmup() == 0
    zoo.close()

    assert validate_jsonl(cfg.metrics_file) == []
    retunes = [
        r for r in load_records(cfg.metrics_file)
        if r["kind"] == "fleet" and r.get("event") == "retune"
        and r.get("residency")
    ]
    assert [r["residency"] for r in retunes] == ["pipe:2", "replicated"]
    pipe_rec = retunes[0]
    assert pipe_rec["pipe_stages"] == 2
    assert pipe_rec["interstage_bytes"] > 0
    assert pipe_rec["reshard_bytes"] > 0
    assert pipe_rec["compiles_after_warmup"] == 0
    assert "pipe_stages" not in retunes[1]


def test_pipe_planner_prices_fourth_residency():
    """estimate_model_bytes under pipe:K: per-chip bytes = the BOTTLENECK
    stage (params + activation high-water), the 64.5k-class logits slab
    lands ONLY on the head stage, and the pipe estimate undercuts the
    replicated one — the planner's reason to ever pick the fourth
    option."""
    from mpi_pytorch_tpu.serve.sharding import parse_residency
    from mpi_pytorch_tpu.serve.zoo.registry import estimate_model_bytes

    est = estimate_model_bytes(
        "resnet18", 64500, 32, (1, 4), "bf16",
        residency=parse_residency("pipe:2"), n_devices=8,
    )
    assert est["residency"] == "pipe:2"
    assert est["pipe_stages"] == 2
    assert est["data_degree"] == 4
    stage_params = est["stage_params_bytes"]
    assert len(stage_params) == 2
    # At 64.5k classes the head stage (logits slab) dominates the trunk.
    assert stage_params[1] > stage_params[0]
    assert est["params_bytes"] == max(stage_params)
    assert est["total_bytes"] == est["params_bytes"] + max(
        est["per_bucket_bytes"].values()
    )
    assert est["total_bytes"] < est["replicated_total_bytes"]
    # Indivisible chip counts are a loud error, not a silent round-down.
    with pytest.raises(ValueError, match="does not divide"):
        estimate_model_bytes(
            "resnet18", 64500, 32, (1, 4), "bf16",
            residency=parse_residency("pipe:3"), n_devices=8,
        )


def test_pipe_config_and_mesh_validation():
    """The pipe knobs fail loudly: degenerate stage/micro counts, the
    zoo/shard mutual exclusions, the reserved "pipe" axis name, the
    indivisible serve mesh, and the no-PartitionSpec rule for pipe
    residency."""
    from mpi_pytorch_tpu.config import Config, MeshConfig
    from mpi_pytorch_tpu.parallel.mesh import create_pipe_serve_mesh
    from mpi_pytorch_tpu.serve.sharding import (
        parse_residency, serve_param_specs,
    )

    with pytest.raises(ValueError, match="serve_pipe_stages must be >= 1"):
        Config(serve_pipe_stages=0).validate_config()
    with pytest.raises(ValueError, match="serve_pipe_microbatches"):
        Config(serve_pipe_microbatches=0).validate_config()
    with pytest.raises(ValueError, match="single-model pipeline knob"):
        Config(
            serve_pipe_stages=2, serve_models="a=resnet18"
        ).validate_config()
    with pytest.raises(ValueError, match="mutually"):
        Config(
            serve_pipe_stages=2, serve_shard_degree=2
        ).validate_config()
    with pytest.raises(ValueError, match="reserved for the pipeline-stage"):
        MeshConfig(data_axis="pipe").validate()
    with pytest.raises(ValueError, match="not divisible by pipe stage"):
        create_pipe_serve_mesh(3)  # 8 CPU devices
    with pytest.raises(ValueError, match=">= 2 stages"):
        create_pipe_serve_mesh(1)

    res = parse_residency("pipe:2")
    assert (res.kind, res.degree, str(res)) == ("pipe", 2, "pipe:2")
    with pytest.raises(ValueError, match="degree >= 2"):
        parse_residency("pipe:1")
    with pytest.raises(ValueError, match="PipelineExecutables instead"):
        serve_param_specs({}, None, res)
