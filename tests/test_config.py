import pytest

from mpi_pytorch_tpu.config import Config, parse_config


def test_defaults_mirror_reference_utils():
    # reference utils.py:4-45
    cfg = Config()
    assert cfg.model_name == "resnet18"
    assert cfg.num_classes == 64500
    assert cfg.batch_size == 128
    assert cfg.learning_rate == 4e-4
    assert cfg.num_epochs == 10
    assert cfg.width == cfg.height == 128
    assert cfg.debug is True
    assert cfg.validate is True
    assert cfg.from_checkpoint is False
    assert cfg.feature_extract is False


def test_cli_overrides():
    cfg = parse_config(["--model-name", "resnet34", "--batch-size", "32", "--debug", "false"])
    assert cfg.model_name == "resnet34"
    assert cfg.batch_size == 32
    assert cfg.debug is False


def test_invalid_model_raises():
    # reference models.py:97-99 calls exit(); we raise instead
    with pytest.raises(ValueError, match="unsupported model"):
        parse_config(["--model-name", "resnet50"])


def test_env_override(monkeypatch):
    monkeypatch.setenv("MPT_BATCH_SIZE", "16")
    assert parse_config([]).batch_size == 16


def test_inception_image_size():
    cfg = parse_config(["--model-name", "inception_v3"])
    assert cfg.image_size == (299, 299)
    assert parse_config([]).image_size == (128, 128)


def test_mesh_override():
    cfg = parse_config(["--mesh.model-parallel", "4"])
    assert cfg.mesh.model_parallel == 4


def test_debug_nans_flag_wires_jax_config():
    import jax

    from mpi_pytorch_tpu.config import apply_runtime_flags

    assert parse_config([]).debug_nans is False
    cfg = parse_config(["--debug-nans", "true"])
    assert cfg.debug_nans is True
    try:
        apply_runtime_flags(cfg)
        assert jax.config.jax_debug_nans is True
    finally:
        jax.config.update("jax_debug_nans", False)


def test_unknown_flag_errors_instead_of_silently_dropping():
    """A typo'd flag must NOT train with defaults: argparse exits with an
    'unrecognized arguments' error (strict parse_args, not parse_known_args)."""
    with pytest.raises(SystemExit):
        parse_config(["--batchsize", "64"])


def test_image_size_alias_sets_both_dims():
    cfg = parse_config(["--image-size", "64"])
    assert (cfg.width, cfg.height) == (64, 64)
    # explicit --width/--height still win over the alias
    cfg = parse_config(["--image-size", "64", "--width", "96"])
    assert (cfg.width, cfg.height) == (96, 64)


def test_image_size_env_alias(monkeypatch):
    monkeypatch.setenv("MPT_IMAGE_SIZE", "64")
    cfg = parse_config([])
    assert (cfg.width, cfg.height) == (64, 64)


def test_inception_rejects_explicit_image_size():
    with pytest.raises(ValueError, match="299"):
        parse_config(["--model-name", "inception_v3", "--image-size", "64"])
    # untouched default and explicit 299 both fine
    assert parse_config(["--model-name", "inception_v3"]).image_size == (299, 299)
    assert parse_config(
        ["--model-name", "inception_v3", "--image-size", "299"]
    ).image_size == (299, 299)


def test_env_image_size_respects_per_dim_env(monkeypatch):
    monkeypatch.setenv("MPT_IMAGE_SIZE", "64")
    monkeypatch.setenv("MPT_WIDTH", "96")
    cfg = parse_config([])
    assert (cfg.width, cfg.height) == (96, 64)


def test_inception_rejects_explicit_128_too():
    with pytest.raises(ValueError, match="299"):
        parse_config(["--model-name", "inception_v3", "--image-size", "128"])


def test_supported_models_matches_registry():
    """config.SUPPORTED_MODELS (CLI validation) and the model registry must
    list exactly the same architectures — they live in separate modules to
    avoid an import cycle, so this is the drift guard."""
    from mpi_pytorch_tpu.config import SUPPORTED_MODELS
    from mpi_pytorch_tpu.models.registry import available_models

    assert tuple(SUPPORTED_MODELS) == tuple(available_models())


def test_pp_stages_validation():
    """--pp-stages gates: pipeline-shaped models only, auto mode only, no
    SP/EP/accum nesting, batch divisibility — and pp_stages drives the
    mesh's pipe axis."""
    ok = parse_config(["--model-name", "vit_s16", "--pp-stages", "4"])
    assert ok.pp_stages == 4 and ok.mesh.pipe_parallel == 4

    with pytest.raises(ValueError, match="pipeline-shaped"):
        parse_config(["--pp-stages", "4"])  # default resnet18
    with pytest.raises(ValueError, match="pipeline-shaped"):
        parse_config(["--model-name", "vit_moe_s16", "--pp-stages", "4"])
    with pytest.raises(ValueError, match="auto-partitioned"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--spmd-mode", "true"])
    with pytest.raises(ValueError, match="sp-strategy|SP attention"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--sp-strategy", "ring"])
    with pytest.raises(ValueError, match="expert"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--expert-parallel", "true"])
    with pytest.raises(ValueError, match="microbatches"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--accum-steps", "2"])
    with pytest.raises(ValueError, match="not divisible"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--batch-size", "130"])
    with pytest.raises(ValueError, match="fsdp"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--fsdp", "true"])
    with pytest.raises(ValueError, match="zero"):
        parse_config(["--model-name", "vit_s16", "--pp-stages", "4",
                      "--zero-optimizer", "true"])
    with pytest.raises(ValueError, match="pp_microbatches only applies"):
        parse_config(["--model-name", "vit_s16", "--pp-microbatches", "8"])


def test_parsed_compiler_options_coercion():
    """XLA's option setter needs real types (a "true" string is rejected at
    compile time — observed live), so the parser must coerce."""
    from mpi_pytorch_tpu.config import parse_config

    cfg = parse_config([
        "--compiler-options",
        "xla_tpu_scoped_vmem_limit_kib=65536 "
        "--xla_tpu_enable_latency_hiding_scheduler=true flag_c=false "
        "bare_flag name=text",
    ])
    assert cfg.parsed_compiler_options() == {
        "xla_tpu_scoped_vmem_limit_kib": 65536,
        "xla_tpu_enable_latency_hiding_scheduler": True,
        "flag_c": False,
        "bare_flag": True,
        "name": "text",
    }
    assert parse_config([]).parsed_compiler_options() is None


def test_env_flag_falsy_spellings(monkeypatch):
    """ONE definition of env truthiness (utils/env.py): any case of
    ''/'0'/'false'/'no'/'off' disables — advisor r5 found 'False'/'no'
    silently enabling MPT_FUSED_STEM in the bench harnesses."""
    from mpi_pytorch_tpu.utils.env import env_flag

    for val in ("", "0", "false", "False", "FALSE", "no", "No", "off", "OFF"):
        monkeypatch.setenv("MPT_TEST_FLAG", val)
        assert env_flag("MPT_TEST_FLAG", default=True) is False, repr(val)
    for val in ("1", "true", "True", "yes", "on"):
        monkeypatch.setenv("MPT_TEST_FLAG", val)
        assert env_flag("MPT_TEST_FLAG", default=False) is True, repr(val)
    monkeypatch.delenv("MPT_TEST_FLAG")
    assert env_flag("MPT_TEST_FLAG", default=True) is True
    assert env_flag("MPT_TEST_FLAG", default=False) is False
