"""Golden-set quality canary: seeded per-tenant probes through the real
front door, scored against pinned reference fingerprints, gating every
fleet mutation (ISSUE 19 tentpole 1+3).

PR 11's ``parity_top1`` is a one-shot startup stamp; ROADMAP item 1's
live weight rollout needs the continuous version — "is this tenant still
answering like the weights we registered?" — as a fleet-wide, per-tenant
signal every mutation path can consult. Three pieces:

- **Golden set** (``golden_inputs``): a small deterministic probe set
  per tenant, minted with the ``measure_parity_top1`` input idiom
  (seeded ``default_rng``, uint8 images in the serve path's submit
  shape) — the seed keys on (run seed, tenant name) via crc32 so every
  process, every restart, and every re-pin regenerates byte-identical
  probes.
- **Gate** (``CanaryGate``): holds each tenant's pinned reference
  fingerprints (the top-k index vectors the healthy tenant returned at
  registration) and the latched verdict. ``score()`` compares a probe
  cycle's answers against the references — top-1 agreement, top-k set
  agreement, and ``rank_drift`` (mean displacement of the reference
  top-1 within the probed top-k; the logit-drift stand-in for an
  index-only prediction contract) — writes a ``kind="canary"`` probe
  record, and drives the verdict with hysteresis (``fail_after``
  consecutive failing cycles to trip, ``pass_after`` passing cycles to
  recover). ``check()`` is the mutation hook: a FAIL verdict writes the
  refusal record and raises ``CanaryBlockedError``; the zoo's swap-in /
  ``set_precision`` / ``convert_residency`` and the controller's retunes
  all consult it, and allowed mutations stamp ``canary_verdict`` on
  their fleet records.
- **Prober** (``CanaryProber``): drives the probe cycle through the REAL
  front door as tagged SHADOW requests (``router.submit(...,
  shadow=True)``) — they ride real queues, real batches, real executables
  and appear in traces, but are excluded from SLO/admission/billing
  counters (a canary must never page the on-call about its own traffic).
  The first cycle per tenant self-pins the references (``event="pin"``);
  later cycles score. References survive eviction/re-swap-in — a
  corrupted re-load is exactly what the pinned fingerprints catch.

Scores land three ways: ``kind="canary"`` records on the fleet stream,
gauges on an attached ``MetricsRegistry``, and points pushed into the
collector's per-(host, metric) rings (``canary/<model>/...`` under the
synthetic host ``"fleet"``) where the CUSUM scanner (``drift.py``)
watches them like any other series.

jax-free (numpy only): unit-testable against fixture index vectors.
"""

from __future__ import annotations

import threading
import zlib

import numpy as np

__all__ = [
    "CanaryBlockedError",
    "CanaryGate",
    "CanaryProber",
    "golden_inputs",
    "score_probes",
]


class CanaryBlockedError(RuntimeError):
    """A fleet mutation was refused because the tenant's canary verdict
    is FAIL — mutating a tenant that is answering wrong hides the
    evidence (the mutation becomes the alibi). Clear the fault or wait
    for the canary to recover, then retry."""

    def __init__(self, message: str, model: str | None = None,
                 agreement_top1: float | None = None):
        super().__init__(message)
        self.model = model
        self.agreement_top1 = agreement_top1


def golden_inputs(
    n: int, image_size: int, *, model: str = "", seed: int = 0,
    channels: int = 3,
) -> list[np.ndarray]:
    """The tenant's deterministic probe set: ``n`` uint8 images in the
    front door's submit shape, seeded on (seed, crc32(model)) — NOT
    ``hash()``, which is salted per process and would mint a different
    golden set on every restart."""
    rng = np.random.default_rng([int(seed), zlib.crc32(model.encode())])
    return [
        rng.integers(0, 256, size=(image_size, image_size, channels))
        .astype(np.uint8)
        for _ in range(max(1, int(n)))
    ]


def score_probes(refs, results) -> dict:
    """Agreement of one probe cycle's top-k index vectors against the
    pinned references: ``agreement_top1`` (fraction of probes whose top-1
    matches), ``agreement_topk`` (mean Jaccard-style overlap of the top-k
    sets), and ``rank_drift`` (mean displacement of the reference top-1
    within the probed top-k; a probe that lost the reference top-1
    entirely counts the full k — the max-logit-drift stand-in when the
    serve contract carries indices, not scores)."""
    if len(refs) != len(results):
        raise ValueError(
            f"probe cycle returned {len(results)} results for "
            f"{len(refs)} references"
        )
    top1 = topk = drift = 0.0
    for ref, got in zip(refs, results):
        ref = np.asarray(ref).reshape(-1)
        got = np.asarray(got).reshape(-1)
        k = max(len(ref), 1)
        top1 += float(ref[0] == got[0]) if len(got) else 0.0
        topk += len(set(ref.tolist()) & set(got.tolist())) / k
        where = np.nonzero(got == ref[0])[0]
        drift += float(where[0]) if len(where) else float(k)
    n = max(len(refs), 1)
    return {
        "agreement_top1": round(top1 / n, 6),
        "agreement_topk": round(topk / n, 6),
        "rank_drift": round(drift / n, 6),
        "probes": len(refs),
    }


class _TenantCanary:
    __slots__ = ("refs", "verdict", "fail_streak", "pass_streak", "last")

    def __init__(self):
        self.refs: list[np.ndarray] | None = None
        self.verdict = "none"  # none -> pass/fail; "none" never blocks
        self.fail_streak = 0
        self.pass_streak = 0
        self.last: dict | None = None


class CanaryGate:
    """Pinned references + latched per-tenant verdicts + the mutation
    hook. Thread-safe: probers score on their own thread while mutation
    paths consult verdicts from operator/controller threads."""

    def __init__(
        self,
        *,
        min_top1: float = 0.95,
        fail_after: int = 2,
        pass_after: int = 2,
        metrics=None,
        registry=None,
        collector=None,
        logger=None,
    ):
        if not 0.0 < min_top1 <= 1.0:
            raise ValueError(f"min_top1 must be in (0, 1], got {min_top1}")
        self.min_top1 = float(min_top1)
        self.fail_after = max(1, int(fail_after))
        self.pass_after = max(1, int(pass_after))
        self._metrics = metrics
        self._registry = registry
        self._collector = collector
        self._logger = logger
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantCanary] = {}
        self.stats = {"probes": 0, "pins": 0, "trips": 0, "recoveries": 0,
                      "blocked": 0}

    def _tenant(self, model: str) -> _TenantCanary:
        st = self._tenants.get(model)
        if st is None:
            st = self._tenants[model] = _TenantCanary()
        return st

    def _write(self, record: dict) -> None:
        if self._metrics is not None:
            self._metrics.write(record)

    # ------------------------------------------------------------------ pin

    def pin(self, model: str, results) -> None:
        """Pin ``results`` (the HEALTHY tenant's top-k answers to its
        golden set) as the reference fingerprints — normally the prober's
        first cycle, right after registration/warm-probe. Re-pinning is
        an explicit ``clear()`` first: an intentional weight push changes
        the reference; silence never does."""
        refs = [np.asarray(r).reshape(-1).copy() for r in results]
        with self._lock:
            st = self._tenant(model)
            if st.refs is not None:
                raise ValueError(
                    f"canary references for {model!r} already pinned "
                    "(clear() first — re-pinning must be deliberate)"
                )
            st.refs = refs
            self.stats["pins"] += 1
        self._write({
            "kind": "canary", "model": model, "event": "pin",
            "probes": len(refs),
        })

    def pinned(self, model: str) -> bool:
        with self._lock:
            st = self._tenants.get(model)
            return st is not None and st.refs is not None

    def clear(self, model: str | None = None) -> None:
        """Forget references + verdict for ``model`` (all tenants when
        None) — the deliberate re-pin path after an intentional weight
        rollout."""
        with self._lock:
            if model is None:
                self._tenants.clear()
            else:
                self._tenants.pop(model, None)

    def references(self, model: str) -> list[np.ndarray] | None:
        with self._lock:
            st = self._tenants.get(model)
            return None if st is None or st.refs is None else list(st.refs)

    # ---------------------------------------------------------------- score

    def score(self, model: str, results) -> dict:
        """Score one probe cycle against the pinned references, advance
        the latched verdict, and emit the ``kind="canary"`` probe record
        + gauges/ring points."""
        with self._lock:
            st = self._tenants.get(model)
            if st is None or st.refs is None:
                raise KeyError(f"no canary references pinned for {model!r}")
            scores = score_probes(st.refs, results)
            ok = scores["agreement_top1"] >= self.min_top1
            if ok:
                st.pass_streak += 1
                st.fail_streak = 0
            else:
                st.fail_streak += 1
                st.pass_streak = 0
            tripped = recovered = False
            if st.verdict != "fail" and st.fail_streak >= self.fail_after:
                st.verdict = "fail"
                tripped = True
                self.stats["trips"] += 1
            elif st.verdict == "fail" and st.pass_streak >= self.pass_after:
                st.verdict = "pass"
                recovered = True
                self.stats["recoveries"] += 1
            elif st.verdict == "none" and ok:
                st.verdict = "pass"
            st.last = dict(scores)
            verdict = st.verdict
            self.stats["probes"] += 1
        if tripped and self._logger is not None:
            self._logger.warning(
                "canary: tenant %s TRIPPED (top-1 agreement %.3f < %.3f, "
                "%d consecutive failing cycles)", model,
                scores["agreement_top1"], self.min_top1, self.fail_after,
            )
        if recovered and self._logger is not None:
            self._logger.info("canary: tenant %s recovered", model)
        self._write({
            "kind": "canary", "model": model, "event": "probe",
            "verdict": verdict, **scores,
        })
        if self._registry is not None:
            self._registry.gauge(
                f"canary/agreement_top1/{model}"
            ).set(scores["agreement_top1"])
            self._registry.gauge(
                f"canary/verdict_ok/{model}"
            ).set(0.0 if verdict == "fail" else 1.0)
        if self._collector is not None:
            self._collector.ingest_point(
                "fleet", f"canary/{model}/agreement_top1",
                scores["agreement_top1"],
            )
            self._collector.ingest_point(
                "fleet", f"canary/{model}/rank_drift", scores["rank_drift"],
            )
        return {**scores, "verdict": verdict}

    # -------------------------------------------------------------- verdict

    def verdict(self, model: str) -> str:
        """"pass" / "fail" / "none" (never probed — a fresh fleet must
        not be frozen by a canary that has not run yet)."""
        with self._lock:
            st = self._tenants.get(model)
            return "none" if st is None else st.verdict

    def last_scores(self, model: str) -> dict | None:
        with self._lock:
            st = self._tenants.get(model)
            return None if st is None or st.last is None else dict(st.last)

    def check(self, model: str | None, mutation: str) -> str:
        """The mutation hook: raise ``CanaryBlockedError`` (and write the
        ``event="blocked"`` refusal record) when ``model``'s verdict is
        FAIL; otherwise return the verdict for the caller to stamp as
        ``canary_verdict`` on its fleet record. ``model=None``
        (untenanted path) always passes."""
        if model is None:
            return "none"
        v = self.verdict(model)
        if v != "fail":
            return v
        last = self.last_scores(model) or {}
        with self._lock:
            self.stats["blocked"] += 1
        self._write({
            "kind": "canary", "model": model, "event": "blocked",
            "verdict": "fail", "mutation": mutation,
            "reason": (
                f"top-1 agreement {last.get('agreement_top1')} below "
                f"{self.min_top1}"
            ),
            "agreement_top1": last.get("agreement_top1"),
            "rank_drift": last.get("rank_drift"),
        })
        raise CanaryBlockedError(
            f"canary verdict FAIL for tenant {model!r}: refusing "
            f"{mutation} (last top-1 agreement "
            f"{last.get('agreement_top1')}, threshold {self.min_top1})",
            model=model, agreement_top1=last.get("agreement_top1"),
        )


class CanaryProber:
    """Background probe driver: every cycle, each tenant's golden set
    goes through the REAL front door as shadow requests; the first cycle
    pins, later cycles score. Optionally drives the drift monitor's
    CUSUM scan (the two quality detectors share a heartbeat)."""

    def __init__(
        self,
        submit_fn,
        models_fn,
        gate: CanaryGate,
        *,
        image_size: int,
        probes: int = 8,
        seed: int = 0,
        interval_s: float = 0.0,
        timeout_s: float = 60.0,
        drift=None,
        collector=None,
        logger=None,
    ):
        self._submit = submit_fn  # (image, model) -> Future[topk indices]
        self._models_fn = models_fn
        self._gate = gate
        self._image_size = int(image_size)
        self._probes = max(1, int(probes))
        self._seed = int(seed)
        self._interval_s = float(interval_s)
        self._timeout_s = float(timeout_s)
        self._drift = drift
        self._collector = collector
        self._logger = logger
        self._inputs: dict[str, list[np.ndarray]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"cycles": 0, "probe_errors": 0, "skipped_tenants": 0}

    def _golden(self, model: str) -> list[np.ndarray]:
        imgs = self._inputs.get(model)
        if imgs is None:
            imgs = self._inputs[model] = golden_inputs(
                self._probes, self._image_size, model=model, seed=self._seed,
            )
        return imgs

    def probe_once(self) -> dict[str, dict]:
        """One full probe cycle over every tenant. A tenant whose probes
        cannot complete (front door shedding, host down) is SKIPPED, not
        scored — an unreachable tenant is an availability problem with
        its own alerts; scoring it would fail the QUALITY canary on
        missing evidence."""
        out: dict[str, dict] = {}
        for model in list(self._models_fn() or ()):
            imgs = self._golden(model)
            try:
                futures = [self._submit(img, model) for img in imgs]
                results = [f.result(self._timeout_s) for f in futures]
            except Exception as e:  # noqa: BLE001 — skip, never crash the loop
                self.stats["probe_errors"] += 1
                self.stats["skipped_tenants"] += 1
                if self._logger is not None:
                    self._logger.warning(
                        "canary: probe cycle for %s skipped (%s)", model, e,
                    )
                continue
            if not self._gate.pinned(model):
                self._gate.pin(model, results)
                out[model] = {"event": "pin", "probes": len(results)}
            else:
                out[model] = self._gate.score(model, results)
        self.stats["cycles"] += 1
        if self._drift is not None and self._collector is not None:
            try:
                self._drift.scan(self._collector)
            except Exception:  # noqa: BLE001 — scanning must not kill probing
                pass
        return out

    # ----------------------------------------------------------- background

    def start(self) -> None:
        if self._interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="canary-prober", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.probe_once()
            except Exception as e:  # noqa: BLE001
                if self._logger is not None:
                    self._logger.warning("canary prober cycle failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self._timeout_s, 10.0))
            self._thread = None

    def close(self) -> None:
        self.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
