from mpi_pytorch_tpu.parallel import collectives
from mpi_pytorch_tpu.parallel.mesh import (
    create_mesh,
    named_shardings,
    param_specs,
    shard_batch,
)

__all__ = [
    "collectives",
    "create_mesh",
    "named_shardings",
    "param_specs",
    "shard_batch",
]
