"""TPU-compiler-option sweep for the headline benchmark (MFU lever hunting).

Runs ``bench.py`` in a fresh child interpreter per option set, parses each
run's one-line JSON, and prints a ranked table. Options travel as
PER-COMPILE ``compiler_options`` (via the ``MPT_COMPILER_OPTIONS`` env JSON
that bench.py/bench_zoo.py read at ``.compile()`` time) — NOT ``XLA_FLAGS``:
under the device relay the client-side XLA build parses ``XLA_FLAGS`` and
fatally rejects TPU-only flags (``Unknown flag in XLA_FLAGS``, observed
live); the TPU compiler that actually honors them lives server-side, and
PJRT compile options are the channel that reaches it. The sets below are
the standard TPU levers worth checking for a conv workload; add more on the
command line:

    python tools/bench_flags.py                       # sweep the builtin sets
    python tools/bench_flags.py --flags "xla_tpu_scoped_vmem_limit_kib=65536"

Each child inherits ``MPT_BENCH_BACKEND_TIMEOUT_S`` (default 600), so a
wedged device relay produces an error row rather than a hang.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, compiler_options dict). Baseline first; each candidate is one lever.
SWEEP: list[tuple[str, dict]] = [
    ("baseline", {}),
    # Latency-hiding scheduler: overlaps async copies/collectives with
    # compute; mostly a multi-chip lever but can reorder HBM prefetches.
    ("latency-hiding", {"xla_tpu_enable_latency_hiding_scheduler": True}),
    # More VMEM for fusion scratch: lets XLA form larger fusions before
    # spilling to HBM (default is model-dependent).
    ("vmem-64M", {"xla_tpu_scoped_vmem_limit_kib": 65536}),
    ("vmem-128M", {"xla_tpu_scoped_vmem_limit_kib": 131072}),
    # Aggressive while-loop/all-reduce fusion knobs.
    ("fusion-aggr", {"xla_tpu_enable_aggressive_loop_fusion": True}),
]


def _parse_flag_set(text: str) -> dict:
    """CLI "k=v k2=v2" → compiler_options dict — the shared parser behind
    the trainer's --compiler-options (single source of truth for the
    bool/int coercion XLA's option setter requires)."""
    sys.path.insert(0, REPO)
    from mpi_pytorch_tpu.config import parse_compiler_options

    return parse_compiler_options(text) or {}


def run_one(label: str, options: dict, model: str = "") -> dict:
    env = dict(os.environ)
    env["MPT_COMPILER_OPTIONS"] = json.dumps(options)
    # Default: the headline bench.py (resnet18). --model X instead sweeps the
    # flags over any zoo member via a single-model bench_zoo child — the
    # instrument for attacking the bandwidth-bound members (densenet121
    # 16.3%, squeezenet 30.7% MFU, docs/RESULTS.md §3b).
    cmd = (
        [sys.executable, os.path.join(REPO, "bench.py")]
        if not model
        else [
            sys.executable, os.path.join(REPO, "tools", "bench_zoo.py"),
            "--in-process", "--models", model,
        ]
    )
    try:
        proc = subprocess.run(
            cmd, env=env, cwd=REPO, capture_output=True, text=True, timeout=1800,
        )
    except subprocess.TimeoutExpired:
        # One wedged flag set must not discard the completed results.
        return {
            "value": 0.0, "error": "child exceeded 1800s (hung past backend init)",
            "label": label, "flags": options,
        }
    line = ""
    for out_line in (proc.stdout or "").splitlines()[::-1]:
        if out_line.startswith("{"):
            line = out_line
            break
    try:
        rec = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        stderr_tail = (proc.stderr or "").strip().splitlines()[-3:]
        rec = {
            "value": 0.0,
            "error": f"no JSON (rc={proc.returncode}): " + " | ".join(stderr_tail),
        }
    if model and "value" not in rec:
        # bench_zoo rows key throughput differently from bench.py's one-liner.
        rec["value"] = rec.get("images_per_sec_per_chip", 0.0)
    rec["label"] = label
    rec["flags"] = options
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--flags", action="append", default=[],
        help="extra flag set to sweep (repeatable); label = the flags string",
    )
    ap.add_argument(
        "--sets", default=None,
        help="comma-separated subset of builtin set labels to run",
    )
    ap.add_argument(
        "--model", default="",
        help="sweep this zoo model (bench_zoo child) instead of bench.py",
    )
    args = ap.parse_args()
    # --sets filters only the BUILTIN sets; explicit --flags always run.
    sweep = SWEEP
    if args.sets is not None:
        wanted = set(args.sets.split(","))
        known = {s[0] for s in SWEEP}
        unknown = wanted - known
        if unknown:
            ap.error(
                f"unknown --sets label(s) {sorted(unknown)}; "
                f"builtin sets: {sorted(known)}"
            )
        sweep = [s for s in sweep if s[0] in wanted]
    sweep = sweep + [(f, _parse_flag_set(f)) for f in args.flags]

    results = []
    for label, flags in sweep:
        print(f"== {label}: {flags or '(none)'}", file=sys.stderr, flush=True)
        results.append(run_one(label, flags, model=args.model))
        r = results[-1]
        print(
            f"   -> {r.get('value', 0.0):.0f} img/s  mfu={r.get('mfu_pct', '?')}%"
            + (f"  ERROR: {r['error']}" if "error" in r else ""),
            file=sys.stderr, flush=True,
        )

    results.sort(key=lambda r: -float(r.get("value", 0.0)))
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
