"""The zoo executable pool: per-(model, bucket[, precision]) AOT sets,
built lazily, shared across a host fleet, cold-swappable.

``BucketExecutables`` (PR 4/11) is one model's per-bucket[, per-precision]
executable set; this pool generalizes the axis to the whole zoo — the
tentpole's "``_exe_sets{precision}`` discipline with model identity in
the key". The fleet cost model carries over from ``FleetServer``: N
in-process hosts share ONE pool, so a local zoo fleet pays one warmup
compile set per (model, precision), not N.

Cold swap-in is the state machine ISSUE 14 names::

    load (build state + compile per-bucket sets — persistent-cache hits
          on a warm cache, so the wall clock is placement + warmup)
      → warm-probe (execute every bucket of every set once, REBASELINE
          the compile counters, then probe each bucket AGAIN and assert
          zero compiles — a set that would compile under traffic never
          activates)
      → activate (the caller — ``ZooServer`` — stands the tenant's
          batcher/server over the warmed sets and bumps its facts
          generation)

Byte accounting is measured, not guessed, once a state exists: the
placed state's leaf sizes (PR 6's accounting) replace the registry's
abstract-shape estimate in every later packing plan.
"""

from __future__ import annotations

import threading

from mpi_pytorch_tpu.serve.batcher import ServeError


def state_resident_bytes(state) -> int:
    """Leaf-size accounting over a (possibly quantized, possibly SHARDED)
    serving state — the measured half of the packing plan's arithmetic.
    PER-CHIP bytes: a sharded leaf counts one shard (``shard_shape``), a
    replicated leaf its full size — so a tenant's measurement is directly
    comparable against the per-chip packing budget regardless of
    residency (ISSUE 17 satellite 1)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        shape = getattr(leaf, "shape", None)
        if sharding is not None and shape is not None:
            try:
                shard = sharding.shard_shape(tuple(shape))
                size = 1
                for d in shard:
                    size *= int(d)
            except Exception:
                size = int(getattr(leaf, "size"))
        total += int(size) * int(np.dtype(dtype).itemsize)
    return total


class ColdSwapError(ServeError):
    """A cold swap-in failed its warm probe (the freshly built sets
    would compile under traffic) — the tenant never activates."""


class ZooExecutablePool:
    """model → {precision: warmed ``BucketExecutables``}, built on first
    use, refcounted across the hosts that hold the tenant resident."""

    def __init__(
        self, cfg, registry, *, mesh=None, load_checkpoint: bool = True,
        logger=None, build_fn=None,
    ):
        from mpi_pytorch_tpu.utils.logging import run_logger

        self.cfg = cfg
        self.registry = registry
        self._logger = logger or run_logger()
        self._load_checkpoint = load_checkpoint
        # build_fn (tenant_cfg, mesh) -> {precision: UNWARMED set} is the
        # test seam: packing/LRU/warm-probe logic is drivable without
        # paying a compile per test.
        self._build_fn = build_fn
        self._lock = threading.Lock()
        self._sets: dict[str, dict] = {}
        self._bytes: dict[str, int] = {}
        self._refs: dict[str, int] = {}
        # model → residency string ("replicated"/"tp:K"/"fsdp:K"). Kept
        # alongside _bytes even after eviction: a measurement is only
        # valid at the residency it was taken at, and the planner gates
        # on exactly that (registry._plan_entry).
        self._residency: dict[str, str] = {}
        self._mesh = mesh
        # degree → (data, model) mesh; ("pipe", K) → (data, pipe) mesh.
        self._serve_meshes: dict[object, object] = {}

    @property
    def mesh(self):
        if self._mesh is None:
            import jax

            from mpi_pytorch_tpu.parallel.mesh import create_mesh
            from mpi_pytorch_tpu.serve.batcher import ServeError as _SE

            if jax.process_count() > 1:
                raise _SE(
                    "the in-process zoo pool is single-process; on a "
                    "multi-process world run one zoo host per process over "
                    "serve.local_replica_mesh()"
                )
            self._mesh = create_mesh(self.cfg.mesh)
        return self._mesh

    def serve_mesh(self, degree: int):
        """The nested ``(data, model)`` mesh a ``shard:K`` tenant compiles
        over — built from the pool's OWN device set (so a local-replica
        pool stays on its replica) and cached per degree; degree 1 is the
        flat mesh."""
        if degree <= 1:
            return self.mesh
        cached = self._serve_meshes.get(degree)
        if cached is None:
            from mpi_pytorch_tpu.parallel.mesh import create_serve_mesh

            devices = list(self.mesh.devices.flatten())
            cached = create_serve_mesh(degree, devices=devices)
            self._serve_meshes[degree] = cached
        return cached

    def pipe_mesh(self, stages: int):
        """The nested ``(data, pipe)`` mesh a ``pipe:K`` tenant's stages
        split over — built from the pool's own device set and cached per
        stage count, exactly like ``serve_mesh``."""
        key = ("pipe", stages)
        cached = self._serve_meshes.get(key)
        if cached is None:
            from mpi_pytorch_tpu.parallel.mesh import create_pipe_serve_mesh

            devices = list(self.mesh.devices.flatten())
            cached = create_pipe_serve_mesh(stages, devices=devices)
            self._serve_meshes[key] = cached
        return cached

    def resident(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._sets))

    def measured_bytes(self) -> dict[str, int]:
        """model → measured resident bytes for every BUILT tenant — the
        packing planner's override of its abstract estimates."""
        with self._lock:
            return dict(self._bytes)

    def residency(self, model: str) -> str:
        with self._lock:
            return self._residency.get(model, "replicated")

    def residencies(self) -> dict[str, str]:
        """model → residency string for every tenant that has ever been
        built — paired with ``measured_bytes`` so the planner knows WHICH
        layout each measurement belongs to."""
        with self._lock:
            return dict(self._residency)

    def compiles_after_warmup(self) -> int:
        with self._lock:
            sets = [e for m in self._sets.values() for e in m.values()]
        return sum(e.compiles_since_warmup() for e in sets)

    # ------------------------------------------------------------ build

    def _build(self, model: str, residency=None) -> tuple[dict, int, str]:
        """Load: per-tenant state + one UNWARMED set per precision, built
        at ``residency`` (defaults to the spec's ``shard=`` option; the
        planner may override via ``ensure``)."""
        from mpi_pytorch_tpu.serve.sharding import parse_residency

        tenant_cfg = self.registry.tenant_cfg(model)
        if residency is None:
            residency = parse_residency(self.registry.spec(model).shard)
        if self._build_fn is not None:
            # The test seam builds replicated fakes; residency is
            # recorded as requested so planner plumbing stays testable.
            sets = self._build_fn(tenant_cfg, self.mesh)
            return sets, sum(
                state_resident_bytes(getattr(e, "_state", ()))
                for e in sets.values()
            ), str(residency)
        from mpi_pytorch_tpu.serve.executables import BucketExecutables
        from mpi_pytorch_tpu.serve.server import InferenceServer
        from mpi_pytorch_tpu.train.step import place_state_on_mesh

        if residency.kind == "pipe":
            # Pipeline build (ISSUE 20): per-stage executables over the
            # nested (data, pipe) mesh. State is built unplaced — the cut
            # planner places each leaf on ITS stage's chip group itself.
            from mpi_pytorch_tpu.serve.pipeline import PipelineExecutables

            mesh = self.pipe_mesh(residency.degree)
            state = InferenceServer._build_state(
                tenant_cfg, None, self._load_checkpoint
            )
            sets = {
                p: PipelineExecutables(
                    tenant_cfg, state, mesh, logger=self._logger,
                    precision=p, residency=residency,
                )
                for p in tenant_cfg.parsed_serve_precisions()
            }
            measured = sum(
                state_resident_bytes(e._state) for e in sets.values()
            )
            return sets, measured, str(residency)
        if residency.sharded:
            # Sharded build: compile over the nested (data, model) mesh
            # and let BucketExecutables reshard post-quantization.
            # place_state_on_mesh is deliberately BYPASSED — the trainer's
            # param_specs would TP the head over the nested mesh's model
            # axis before the serve specs get a say.
            mesh = self.serve_mesh(residency.degree)
            state = InferenceServer._build_state(
                tenant_cfg, mesh, self._load_checkpoint
            )
            build_residency = residency
        else:
            mesh = self.mesh
            state = InferenceServer._build_state(
                tenant_cfg, mesh, self._load_checkpoint
            )
            state = place_state_on_mesh(state, mesh)
            build_residency = None
        sets = {
            p: BucketExecutables(
                tenant_cfg, state, mesh, logger=self._logger,
                precision=p, residency=build_residency,
            )
            for p in tenant_cfg.parsed_serve_precisions()
        }
        # Measured resident bytes: each set holds ITS state (int8 sets a
        # quantized copy) — sum over sets, PR 6's leaf accounting,
        # per-chip under sharding (state_resident_bytes).
        measured = sum(
            state_resident_bytes(e._state) for e in sets.values()
        )
        return sets, measured, str(residency)

    def ensure(self, model: str, residency=None) -> dict:
        """The tenant's warmed sets — building, warming, and PROBING them
        on first use (the cold swap-in's load + warm-probe halves).
        ``residency`` overrides the spec's layout for a FRESH build (the
        packing planner's ``shard:K`` pick); a tenant already resident is
        returned as-is — converting a live tenant is ``reshard``'s job.
        Idempotent; refcounted per ``release``."""
        self.registry.spec(model)  # unknown tenant raises typed, early
        with self._lock:
            ready = self._sets.get(model)
            if ready is not None:
                self._refs[model] += 1
                return ready
        # Build OUTSIDE the lock: a cold swap-in compiling for seconds
        # must not block another tenant's lookup.
        try:
            sets, measured, res_str = self._build(model, residency)
            # Warm EVERY set, then rebaseline ALL (the compile listener
            # is process-global — InferenceServer.__init__'s
            # discipline), then the warm PROBE: run each bucket once
            # more and demand zero compiles before the tenant may
            # activate.
            for exe in sets.values():
                if not exe.warm:
                    exe.warmup()
            for exe in sets.values():
                exe.rebaseline()
            self.warm_probe(sets, model)
        finally:
            # The compile listener is PROCESS-GLOBAL: this swap-in's
            # cold compiles landed on every already-resident set's
            # counter too — on the FAILURE path as much as the success
            # path (a refused swap-in must not leave phantom compiles on
            # healthy tenants, which would fail their zero-steady-state
            # assertions and the supervisor's re-admission gate).
            # Re-baseline them all; the swap-in is a known, announced
            # compile event, and steady state stays zero-compile for
            # every tenant from here on.
            with self._lock:
                others = [
                    e for sets_ in self._sets.values()
                    for e in sets_.values()
                ]
            for exe in others:
                exe.rebaseline()
        with self._lock:
            if model not in self._sets:  # lost builds are discarded, loudly
                self._sets[model] = sets
                self._bytes[model] = measured
                self._residency[model] = res_str
                self._refs[model] = 0
            else:
                self._logger.warning(
                    "zoo pool: concurrent build of %s discarded (another "
                    "host won the race)", model,
                )
            self._refs[model] += 1
            return self._sets[model]

    def reshard(self, model: str, residency) -> tuple[dict, int]:
        """Convert a RESIDENT tenant's sets to a new residency IN PLACE —
        the cross-topology half of the ISSUE 17 tentpole. Each precision's
        already-quantized state moves through the bounded per-leaf path
        (``prequantized=True`` so int8 scales are never re-derived), new
        executables compile over the target mesh, and the full warm →
        rebaseline → warm-probe gate runs before the swap: a conversion
        that would compile under traffic raises ``ColdSwapError`` and the
        OLD sets stay live and zero-compile (the rebaseline-in-finally
        discipline covers both exits). Returns the new sets plus the total
        ``reshard_bytes`` actually moved."""
        from mpi_pytorch_tpu.serve.executables import BucketExecutables
        from mpi_pytorch_tpu.serve.sharding import parse_residency

        if isinstance(residency, str):
            residency = parse_residency(residency)
        with self._lock:
            old_sets = self._sets.get(model)
            if old_sets is None:
                raise ServeError(
                    f"cannot reshard {model!r}: not resident in the pool"
                )
            if self._residency.get(model, "replicated") == str(residency):
                return old_sets, 0
        tenant_cfg = self.registry.tenant_cfg(model)
        if residency.kind == "pipe":
            mesh = self.pipe_mesh(residency.degree)
        else:
            mesh = self.serve_mesh(
                residency.degree if residency.sharded else 1
            )
        try:
            new_sets = {}
            moved = 0
            for p, exe in old_sets.items():
                if residency.kind == "pipe":
                    # Conversion TO pipe: the stage planner re-places the
                    # already-quantized state leaf-by-leaf onto its stage
                    # groups (prequantized so int8 scales never re-derive).
                    from mpi_pytorch_tpu.serve.pipeline import (
                        PipelineExecutables,
                    )

                    ns = PipelineExecutables(
                        tenant_cfg, exe._state, mesh, logger=self._logger,
                        precision=p, residency=residency, prequantized=True,
                    )
                else:
                    ns = BucketExecutables(
                        tenant_cfg, exe._state, mesh, logger=self._logger,
                        precision=p, residency=residency, prequantized=True,
                    )
                if ns.reshard_stats is not None:
                    moved += ns.reshard_stats.bytes_moved
                new_sets[p] = ns
            for exe in new_sets.values():
                if not exe.warm:
                    exe.warmup()
            for exe in new_sets.values():
                exe.rebaseline()
            self.warm_probe(new_sets, model)
        finally:
            # Same process-global-listener discipline as ensure(): the
            # conversion's compiles landed on every OTHER resident set's
            # counter (and, on the failure path, on this tenant's still-
            # live old sets) — rebaseline them all so a failed reshard
            # leaves every resident tenant's zero-compile assertion
            # intact.
            with self._lock:
                others = [
                    e for m, sets_ in self._sets.items()
                    for e in sets_.values()
                ]
            for exe in others:
                exe.rebaseline()
        with self._lock:
            self._sets[model] = new_sets
            self._bytes[model] = sum(
                state_resident_bytes(e._state) for e in new_sets.values()
            )
            self._residency[model] = str(residency)
        return new_sets, int(moved)

    @staticmethod
    def warm_probe(sets: dict, model: str) -> None:
        """The activation gate: every bucket of every set executes once
        AFTER the rebaseline, and any compile fails the swap-in — a
        tenant that would compile under traffic never enters rotation
        (the supervisor's re-admission handshake, generalized to
        models)."""
        import numpy as np

        for exe in sets.values():
            h, w = exe._image_hw
            for bucket in exe.buckets:
                # Sharded sets pad buckets to the data degree — probe at
                # the HOST rows the server will actually ship.
                rows = (
                    exe.host_rows(bucket)
                    if hasattr(exe, "host_rows") else bucket
                )
                images = np.zeros((rows, h, w, 3), exe.image_dtype)
                labels = np.full((rows,), -1, np.int32)
                exe(bucket, exe.place(images, labels))
        compiles = sum(e.compiles_since_warmup() for e in sets.values())
        if compiles != 0:
            raise ColdSwapError(
                f"cold swap-in of {model!r} failed its warm probe: "
                f"{compiles} steady-state compile(s) after warmup — the "
                "set must not activate"
            )

    def release(self, model: str) -> None:
        """One host evicted the tenant; the last reference drops the
        sets (the executable and state arrays free with them)."""
        with self._lock:
            if model not in self._sets:
                return
            self._refs[model] -= 1
            if self._refs[model] <= 0:
                del self._sets[model]
                del self._refs[model]
                # Measured bytes stay cached: a re-swap-in plans with the
                # measurement, not the estimate.
