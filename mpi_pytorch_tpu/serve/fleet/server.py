"""FleetServer: N dynamic-batching hosts behind the router, one handle.

The in-process fleet harness — N ``InferenceServer`` replicas (threads)
plus an optional warm spare, fronted by ``FleetRouter`` and optionally
retuned by ``FleetController``. This is the shape ``tools/bench_serve.py
--fleet N``, the ``_dryrun_fleet`` CI leg, and the tests drive; in a real
deployment each host is its own PROCESS over its own chips
(``serve.local_replica_mesh()``) and the router talks the same
``HostHandle`` surface over HTTP (``/metricsz`` is already served,
``serve/http.py``) — the router and controller never know the
difference, that is the point of the handle.

Cost model: all hosts share ONE ``BucketExecutables`` per precision (and
the placed params behind it — predict is read-only), so an N-host local
fleet pays one warmup compile set per precision, not N
(``serve_precision="both"`` shares a bf16 AND an int8 set, arming the
controller's precision retune axis). Per-host state is the part that
matters for routing: each host has its own bounded queue, batcher,
preprocess pool, and metrics registry.

All hosts, the router, and the controller write into one shared metrics
stream (``cfg.metrics_file``): ``kind="serve"`` flushes tagged per host
by the registry snapshots, ``kind="route"`` windows, ``kind="fleet"``
failover/retune events — ``tools/report_run.py`` renders the lot.
"""

from __future__ import annotations

import numpy as np

from mpi_pytorch_tpu.serve.batcher import ServeError
from mpi_pytorch_tpu.serve.fleet.controller import FleetController
from mpi_pytorch_tpu.serve.fleet.router import FleetRouter, LocalHost


class FleetServer:
    """N serving hosts + router (+ spare, + controller) as one server."""

    def __init__(
        self,
        cfg,
        *,
        n_hosts: int | None = None,
        spare: bool | None = None,
        load_checkpoint: bool = True,
        state=None,
        mesh=None,
        executables=None,
    ):
        from mpi_pytorch_tpu.serve.executables import BucketExecutables
        from mpi_pytorch_tpu.serve.server import InferenceServer
        from mpi_pytorch_tpu.utils.logging import MetricsWriter, run_logger

        n = int(n_hosts if n_hosts is not None else cfg.serve_fleet_hosts)
        if n < 1:
            raise ServeError(
                f"a fleet needs at least one host, got n_hosts={n} "
                "(set --serve-fleet-hosts or pass n_hosts)"
            )
        want_spare = bool(
            cfg.serve_fleet_spare if spare is None else spare
        )
        if cfg.serve_metrics_port > 0 and n + want_spare > 1:
            raise ServeError(
                "a fixed --serve-metrics-port cannot be shared by "
                f"{n + want_spare} in-process hosts; use -1 (ephemeral "
                "per host) or 0 (off)"
            )
        self.cfg = cfg
        self._logger = run_logger()

        # Multi-model tenancy (ISSUE 14): serve_models turns every host
        # into a ZooServer — per-tenant pipelines over one mesh, fed from
        # ONE shared ZooExecutablePool (the fleet cost model generalized:
        # one warmup compile set per (model, precision), not per host).
        self.zoo_registry = None
        self._zoo_pool = None
        if cfg.serve_models:
            from mpi_pytorch_tpu.serve.zoo import (
                ModelRegistry,
                ZooExecutablePool,
            )

            self.zoo_registry = ModelRegistry.from_config(cfg)
            self._zoo_pool = ZooExecutablePool(
                cfg, self.zoo_registry, mesh=mesh,
                load_checkpoint=load_checkpoint, logger=self._logger,
            )
        elif executables is None:
            import jax

            if mesh is None:
                if jax.process_count() > 1:
                    raise ServeError(
                        "the in-process fleet harness is single-process; "
                        "on a multi-process world run one fleet host per "
                        "process over serve.local_replica_mesh() and front "
                        "them with FleetRouter directly"
                    )
                from mpi_pytorch_tpu.parallel.mesh import create_mesh

                mesh = create_mesh(cfg.mesh)
            if state is None:
                state = InferenceServer._build_state(
                    cfg, mesh, load_checkpoint
                )
            from mpi_pytorch_tpu.train.step import place_state_on_mesh

            state = place_state_on_mesh(state, mesh)
            # One executable set PER PRECISION, shared by every host —
            # serve_precision="both" is what arms the controller's
            # precision retune axis (each host switches between the two
            # shared, startup-warmed sets).
            precisions = cfg.parsed_serve_precisions()
            executables = {
                p: BucketExecutables(
                    cfg, state, mesh, logger=self._logger, precision=p
                )
                for p in precisions
            }
            for exe in executables.values():
                exe.warmup()
        self._exe = executables

        self._raw_metrics = MetricsWriter(cfg.metrics_file)
        # Fleet-wide tracing + collector (ISSUE 13): one span ring for
        # the front door's router spans; the collector scrapes it plus
        # every host's in-process /tracez twin, and fleet/fault records
        # passing through the tapped stream pin their in-flight traces.
        from mpi_pytorch_tpu.obs.collector import wire_fleet_obs

        (self.spans, self.collector, self._fleet_flight,
         self._metrics) = wire_fleet_obs(
            cfg, self._raw_metrics,
            lambda: self.router.active_hosts(), logger=self._logger,
        )
        # Quality observability (ISSUE 19): one fleet-wide canary gate +
        # drift monitor, built BEFORE the hosts so every server feeds the
        # same detectors and every mutation path consults one verdict
        # surface. Both write through the TAPPED stream — a drift alert
        # pins in-flight traces and auto-dumps the flight recorder like
        # any other fleet alert.
        self.canary = None
        self.drift = None
        self.prober = None
        if cfg.serve_drift_window > 0:
            from mpi_pytorch_tpu.obs.drift import DriftMonitor

            self.drift = DriftMonitor(
                window=cfg.serve_drift_window,
                psi_threshold=cfg.serve_drift_psi,
                chi2_threshold=cfg.serve_drift_chi2,
                cusum_h=cfg.serve_drift_cusum_h,
                metrics=self._metrics,
                logger=self._logger,
            )
        if cfg.serve_canary_probes > 0:
            from mpi_pytorch_tpu.obs.canary import CanaryGate

            self.canary = CanaryGate(
                min_top1=cfg.serve_canary_min_top1,
                fail_after=cfg.serve_canary_fail_after,
                pass_after=cfg.serve_canary_pass_after,
                metrics=self._metrics,
                collector=self.collector,
                logger=self._logger,
            )
        total = n + (1 if want_spare else 0)
        servers = []
        try:
            for i in range(total):
                if self._zoo_pool is not None:
                    from mpi_pytorch_tpu.serve.zoo import ZooServer

                    servers.append(ZooServer(
                        cfg, registry=self.zoo_registry,
                        pool=self._zoo_pool, metrics=self._metrics,
                        host_index=i, logger=self._logger,
                        canary=self.canary, drift=self.drift,
                    ))
                else:
                    servers.append(InferenceServer(
                        cfg, executables=executables, metrics=self._metrics,
                        host_index=i, drift=self.drift,
                    ))
        except BaseException:
            for s in servers:
                s.close(drain=False)
            self._raw_metrics.close()
            raise
        self._servers = servers
        if self._zoo_pool is not None:
            from mpi_pytorch_tpu.serve.zoo import ZooHost

            handles = [ZooHost(s) for s in servers]
        else:
            handles = [LocalHost(s) for s in servers]
        hosts = handles[:n]
        spare_host = handles[n] if want_spare else None

        # Per-tenant front-door budgets (ISSUE 14): each tenant gets its
        # spec's explicit admission or an equal share of the fleet
        # budget — the starvation guard the router enforces.
        tenant_budgets = None
        if self.zoo_registry is not None:
            fleet_budget = cfg.serve_admission_tokens or sum(
                h.queue_capacity for h in hosts
            )
            tenant_budgets = self.zoo_registry.tenant_budgets(fleet_budget)
        # Warmup payload for the spare's keep-warm traffic: a filler
        # request in the loader contract's raw-pixels form.
        warmup_payload = np.zeros((*cfg.image_size, 3), np.uint8)
        self.router = FleetRouter(
            hosts, spare_host,
            metrics=self._metrics,
            admission_tokens=cfg.serve_admission_tokens,
            probe_interval_s=cfg.serve_probe_interval_ms / 1e3,
            fail_probes=cfg.serve_fail_probes,
            warmup_payload=warmup_payload,
            logger=self._logger,
            trace_sample_rate=cfg.trace_sample_rate,
            spans=self.spans,
            tenant_budgets=tenant_budgets,
            hedge=cfg.serve_hedge,
            hedge_factor=cfg.serve_hedge_factor,
            hedge_floor_ms=cfg.serve_hedge_floor_ms,
        )
        if self.collector is not None:
            self.collector.start()
        if self.canary is not None:
            # The prober's probes ride the REAL front door as shadow
            # requests (real queues, real batches, real executables —
            # excluded from SLO/admission/billing counters). First cycle
            # pins the healthy references; later cycles score, and each
            # cycle drives the drift monitor's CUSUM scan.
            from mpi_pytorch_tpu.obs.canary import CanaryProber

            if self.zoo_registry is not None:
                models_fn = self.zoo_registry.models

                def _probe_submit(img, m):
                    return self.router.submit(img, model=m, shadow=True)
            else:
                single = getattr(cfg, "model_name", "") or "default"

                def models_fn():
                    return (single,)

                def _probe_submit(img, _m):
                    return self.router.submit(img, shadow=True)

            self.prober = CanaryProber(
                _probe_submit, models_fn, self.canary,
                image_size=cfg.image_size[0],
                probes=cfg.serve_canary_probes,
                seed=cfg.seed,
                interval_s=cfg.serve_canary_interval_s,
                drift=self.drift,
                collector=self.collector,
                logger=self._logger,
            )
            self.prober.start()  # no-op at interval 0: drive probe_once()
        self.controller = None
        if cfg.serve_target_p99_ms > 0:
            self.controller = FleetController(
                self.router.active_hosts,
                target_p99_ms=cfg.serve_target_p99_ms,
                metrics=self._metrics,
                interval_s=cfg.serve_retune_interval_s,
                max_wait_ms_cap=max(
                    cfg.serve_max_wait_ms * 4.0, cfg.serve_max_wait_ms + 1.0
                ),
                logger=self._logger,
                canary=self.canary,
            )
            self.controller.start()
        self.autoscaler = None
        if cfg.serve_autoscale:
            # The in-process twin of the remote autoscaler wiring: a local
            # scale-up is a new InferenceServer over the SHARED warmed
            # executable sets (zero compiles by construction — the same
            # invariant the remote path buys from the persistent cache).
            import itertools

            from mpi_pytorch_tpu.serve.fleet.autoscaler import FleetAutoscaler

            host_seq = itertools.count(total)

            def _spawn_local():
                if self._zoo_pool is not None:
                    from mpi_pytorch_tpu.serve.zoo import ZooHost, ZooServer

                    server = ZooServer(
                        cfg, registry=self.zoo_registry,
                        pool=self._zoo_pool, metrics=self._metrics,
                        host_index=next(host_seq), logger=self._logger,
                        canary=self.canary, drift=self.drift,
                    )
                    self._servers.append(server)
                    return ZooHost(server)
                server = InferenceServer(
                    cfg, executables=self._exe, metrics=self._metrics,
                    host_index=next(host_seq), drift=self.drift,
                )
                self._servers.append(server)
                return LocalHost(server)

            self.autoscaler = FleetAutoscaler(
                self.router,
                spawn_fn=_spawn_local,
                target_p99_ms=cfg.serve_target_p99_ms,
                min_hosts=cfg.serve_fleet_min_hosts,
                max_hosts=cfg.serve_fleet_max_hosts,
                cooldown_s=cfg.serve_scale_cooldown_s,
                reject_rate_up=cfg.serve_scale_reject_rate,
                interval_s=cfg.serve_retune_interval_s,
                metrics=self._metrics,
                logger=self._logger,
            )
            self.autoscaler.start()
        self._closed = False
        self._logger.info(
            "fleet: %d host(s)%s behind the router (budget %d, probe "
            "every %.0f ms, controller %s)",
            n, " + warm spare" if want_spare else "", self.router.budget,
            cfg.serve_probe_interval_ms,
            "off" if self.controller is None
            else f"targeting p99 {cfg.serve_target_p99_ms} ms",
        )

    # ------------------------------------------------------------ requests

    def submit(self, image, model: str | None = None):
        return self.router.submit(image, model=model)

    def predict_batch(self, images, timeout: float | None = None,
                      model: str | None = None):
        return self.router.predict_batch(images, timeout=timeout, model=model)

    # ----------------------------------------------------------- inspection

    def hosts(self) -> list:
        return self.router.active_hosts()

    def set_max_wait_ms(self, max_wait_ms: float) -> None:
        """Retune every live host's flush deadline (the bench sweep lever;
        the controller does this per host with its own policy)."""
        for h in self.router.active_hosts():
            h.set_max_wait_ms(max_wait_ms)
        spare = self.router.spare_host()
        if spare is not None:
            spare.set_max_wait_ms(max_wait_ms)

    @property
    def precision(self) -> str:
        """The active precision of the fleet's hosts (bench sweep surface;
        individual hosts may diverge under a mid-traffic controller
        retune — this reads the first live host)."""
        hosts = self.router.active_hosts()
        return hosts[0].precision if hosts else "bf16"

    @property
    def parity_top1(self):
        """The shared sets' int8-vs-bf16 startup parity (None when the
        fleet holds a single precision)."""
        hosts = self.router.active_hosts()
        return hosts[0].parity_top1 if hosts else None

    def set_precision(self, precision: str) -> None:
        """Switch every live host (and the spare) onto the named
        startup-compiled precision set — the bench sweep lever; the
        controller does this per host with its own policy."""
        for h in self.router.active_hosts():
            h.set_precision(precision)
        spare = self.router.spare_host()
        if spare is not None:
            spare.set_precision(precision)

    def host_snapshots(self) -> dict:
        """name → live registry snapshot, for every host still serving —
        the per-host breakdown ``bench_serve --fleet`` reports."""
        return {h.name: h.snapshot() for h in self.router.active_hosts()}

    def stats(self) -> dict:
        """Fleet-level counters. Top-level ``served``/``padded_rows``/
        ``rejected``/``compiles_after_warmup`` aggregate over the LIVE
        hosts so single-server drivers (``bench_serve.run_point``) work
        against a fleet unchanged."""
        hosts = {h.name: h.stats() for h in self.router.active_hosts()}
        out = {
            "hosts": hosts,
            "router": self.router.stats(),
            "served": sum(s["served"] for s in hosts.values()),
            "rejected": sum(s["rejected"] for s in hosts.values()),
            "padded_rows": sum(s["padded_rows"] for s in hosts.values()),
            "compiles_after_warmup": max(
                (s["compiles_after_warmup"] for s in hosts.values()),
                default=0,
            ),
        }
        if self.canary is not None:
            out["canary"] = dict(self.canary.stats)
            if self.prober is not None:
                out["canary"].update(
                    {f"prober_{k}": v for k, v in self.prober.stats.items()}
                )
        if self.drift is not None:
            out["drift"] = dict(self.drift.stats)
        return out

    def tenant_stats(self) -> dict:
        """model → fleet-wide per-tenant counters (served / padded /
        host-queue rejections summed over hosts, front-door rejections
        from the router) — the bench's per-tenant columns and the CI
        leg's starvation assertions."""
        if self.zoo_registry is None:
            return {}
        from mpi_pytorch_tpu.serve.fleet.router import aggregate_tenant_stats

        return aggregate_tenant_stats(
            (h.stats() for h in self.router.active_hosts()),
            self.router.rejections_by_model,
        )

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Prober first: it submits through the router, which is about to
        # drain its hosts — a probe cycle racing the teardown would be
        # scored against a half-closed fleet.
        if self.prober is not None:
            self.prober.stop()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.controller is not None:
            self.controller.stop()
        # Collector stops BEFORE the router closes the hosts: the final
        # scrape needs live /tracez rings, and stop() forces every open
        # trace through the tail decision + flushes the timelines.
        if self.collector is not None:
            self.collector.stop(final=True)
        if self._fleet_flight is not None:
            self._fleet_flight.close()
        # Router close drains every host (spare included); each host
        # flushes its final registry snapshot into the shared stream.
        self.router.close()
        self._raw_metrics.close()

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
