"""Test env: 8 virtual CPU devices so the real sharded code paths run without
TPU hardware — the TPU-native analogue of testing MPI code without a cluster
(SURVEY §4).

Note: this image's sitecustomize imports jax at interpreter startup and
latches ``jax_platforms`` from the env, so plain env assignment here is too
late — we must go through ``jax.config.update`` (backend init is lazy, so
this still lands before any device is created)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


# Initializing every convertible architecture is the most expensive fixture
# in the suite (XLA compiles on a single CPU core) — session-scoped and
# shared by test_models and test_torch_mapping. The list IS
# CONVERTIBLE_MODELS, so a new weight mapping is automatically covered.
TEST_NUM_CLASSES = 10


@pytest.fixture(scope="session")
def bundles():
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.models.pretrained import CONVERTIBLE_MODELS

    out = {}
    for name in CONVERTIBLE_MODELS:
        # small sizes for test speed; inception needs its real 299 spatial
        # dims for the aux-logits pooling path
        size = 299 if name == "inception_v3" else 64
        bundle, variables = create_model_bundle(
            name, TEST_NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=size
        )
        out[name] = (bundle, variables)
    return out
