"""GPipe pipeline parallelism vs the un-pipelined stacked forward on the
8-device CPU mesh — values, gradients, remat agreement, and the shape guards.

The correctness property: streaming M microbatches through S ppermute-linked
stages computes exactly ``stage_S(...stage_1(x))`` per example, and grads
through the schedule equal grads of the plain composition.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.parallel.pipeline import (
    pipeline_forward,
    stack_stage_params,
)

N_STAGES = 8
D = 16


@pytest.fixture(scope="module")
def mesh():
    dev = np.asarray(jax.devices()[:N_STAGES]).reshape(N_STAGES, 1)
    return Mesh(dev, ("pipe", "unused"))


def residual_mlp_stage(params, x):
    """One homogeneous stage: residual two-layer MLP, [mb, D] → [mb, D]."""
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"] + params["b2"]


def _stage_params(seed):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.standard_normal((D, 4 * D)) * 0.1, jnp.float32),
        "b1": jnp.zeros((4 * D,), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((4 * D, D)) * 0.1, jnp.float32),
        "b2": jnp.zeros((D,), jnp.float32),
    }


@pytest.fixture(scope="module")
def stacked():
    return stack_stage_params([_stage_params(s) for s in range(N_STAGES)])


def stacked_reference(stacked_params, x):
    """Un-pipelined composition of all stages on one device."""
    for s in range(N_STAGES):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stacked_params)
        x = residual_mlp_stage(params_s, x)
    return x


def _x(b=32, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, D)), jnp.float32)


@pytest.mark.parametrize("num_micro", [4, 8])
def test_pipeline_matches_stacked_forward(mesh, stacked, num_micro):
    x = _x()
    got = pipeline_forward(
        stacked, x, mesh, stage_fn=residual_mlp_stage, num_microbatches=num_micro
    )
    want = stacked_reference(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pipeline_grads_match_stacked(mesh, stacked):
    x = _x(seed=2)
    y = jnp.asarray(np.random.default_rng(3).standard_normal(x.shape), jnp.float32)

    def loss_pp(params, x_):
        out = pipeline_forward(
            params, x_, mesh, stage_fn=residual_mlp_stage, num_microbatches=8
        )
        return jnp.mean((out - y) ** 2)

    def loss_ref(params, x_):
        return jnp.mean((stacked_reference(params, x_) - y) ** 2)

    gp, gxp = jax.grad(loss_pp, argnums=(0, 1))(stacked, x)
    gr, gxr = jax.grad(loss_ref, argnums=(0, 1))(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gxp), np.asarray(gxr), rtol=5e-5, atol=5e-5)


def test_pipeline_remat_matches_plain(mesh, stacked):
    """remat=True re-derives stage internals in the backward; same numbers."""
    x = _x(seed=4)

    def loss(params, remat):
        out = pipeline_forward(
            params, x, mesh, stage_fn=residual_mlp_stage,
            num_microbatches=8, remat=remat,
        )
        return jnp.sum(out * out)

    g_plain = jax.grad(functools.partial(loss, remat=False))(stacked)
    g_remat = jax.grad(functools.partial(loss, remat=True))(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_pipeline_composes_with_dp():
    """PP×DP on a 4-stage × 2-data mesh: values AND grads equal the
    un-pipelined single-device composition (shard_map's transpose supplies
    the gradient psum over the data axis for the pipe-sharded params)."""
    dev = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh2d = Mesh(dev, ("pipe", "data"))
    stacked4 = stack_stage_params([_stage_params(s) for s in range(4)])

    def ref4(params, x):
        for s in range(4):
            x = residual_mlp_stage(
                jax.tree_util.tree_map(lambda p: p[s], params), x
            )
        return x

    x = _x(b=32, seed=9)
    got = pipeline_forward(
        stacked4, x, mesh2d, stage_fn=residual_mlp_stage,
        num_microbatches=8, data_axis="data",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref4(stacked4, x)), rtol=2e-5, atol=2e-5
    )

    y = jnp.asarray(np.random.default_rng(10).standard_normal(x.shape), jnp.float32)

    def loss_pp(params):
        out = pipeline_forward(
            params, x, mesh2d, stage_fn=residual_mlp_stage,
            num_microbatches=8, data_axis="data",
        )
        return jnp.mean((out - y) ** 2)

    g_pp = jax.grad(loss_pp)(stacked4)
    g_rf = jax.grad(lambda p: jnp.mean((ref4(p, x) - y) ** 2))(stacked4)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_rf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


# --- real-model stages: the ViT encoder block as a pipeline stage ---------

VIT_BLOCK = dict(num_heads=4, mlp_dim=32)
VIT_HIDDEN = 16


def vit_block_stage(params, x):
    """One ViT EncoderBlock as a pipeline stage: [mb, S, hidden] →
    [mb, S, hidden] (the homogeneous-stage property models/vit.py documents)."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    return EncoderBlock(**VIT_BLOCK).apply({"params": params}, x, train=False)


@pytest.mark.slow
def test_pipeline_runs_vit_encoder_blocks(mesh):
    """An 8-deep ViT encoder split one-block-per-stage over the pipe axis
    equals running the blocks sequentially on one device."""
    from mpi_pytorch_tpu.models.vit import EncoderBlock

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((16, 8, VIT_HIDDEN)), jnp.float32)
    block = EncoderBlock(**VIT_BLOCK)
    per_stage = [
        block.init({"params": jax.random.PRNGKey(s)}, x[:2], train=False)["params"]
        for s in range(N_STAGES)
    ]
    stacked_blocks = stack_stage_params(per_stage)

    got = pipeline_forward(
        stacked_blocks, x, mesh, stage_fn=vit_block_stage, num_microbatches=8
    )
    want = x
    for params in per_stage:
        want = block.apply({"params": params}, want, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# --- PP as a trainer capability (--pp-stages): parallel/pp_vit.py ---------


def _tiny_vit(num_classes=7, depth=4, **kw):
    from mpi_pytorch_tpu.models.vit import VisionTransformer

    return VisionTransformer(
        num_classes=num_classes, patch_size=4, hidden=16, depth=depth,
        num_heads=2, mlp_dim=32, dtype=jnp.float32, param_dtype=jnp.float32,
        **kw,
    )


def _pp_mesh(stages=4):
    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.parallel.mesh import create_mesh

    return create_mesh(MeshConfig(pipe_parallel=stages))


@pytest.mark.slow
def test_pp_apply_matches_model_apply():
    """make_pp_apply over the UNCHANGED param tree reproduces model.apply
    exactly: logits and per-param grads — pipelining is an execution
    strategy, not a different model."""
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply

    model = _tiny_vit()
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((16, 16, 16, 3)), jnp.float32
    )
    variables = model.init({"params": jax.random.PRNGKey(0)}, x[:2], train=False)
    mesh = _pp_mesh(4)
    pp_apply = make_pp_apply(model, mesh, num_microbatches=8)

    got = pp_apply(variables, x, train=False)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    labels = jnp.asarray(np.random.default_rng(1).integers(0, 7, 16), jnp.int32)

    def ce(apply_fn):
        def loss(params):
            logits = apply_fn({"params": params}, x, train=False)
            import optax

            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            )

        return jax.grad(loss)(variables["params"])

    g_pp, g_ref = ce(pp_apply), ce(model.apply)
    assert jax.tree_util.tree_structure(g_pp) == jax.tree_util.tree_structure(g_ref)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_pp_train_step_matches_unpipelined():
    """The FULL jitted train step (loss, grads, Adam update) with the PP
    apply_fn produces the same updated params as the unpipelined step —
    the --pp-stages ≡ unpipelined trajectory property, two steps deep."""
    import optax

    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply
    from mpi_pytorch_tpu.train.state import TrainState
    from mpi_pytorch_tpu.train.step import make_train_step

    model = _tiny_vit()
    mesh = _pp_mesh(4)
    rng = np.random.default_rng(2)
    x = np.asarray(rng.standard_normal((16, 16, 16, 3)), np.float32)
    labels = np.asarray(rng.integers(0, 7, 16), np.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(3)}, jnp.asarray(x[:2]), train=False
    )

    def run(apply_fn):
        # Fresh buffers per run: the jitted step donates the state, so the
        # two runs must not share the init arrays. SGD, not Adam: Adam's
        # m/sqrt(v) normalization amplifies noise-level grad differences on
        # zero-grad params into O(lr) update differences, which would force
        # a vacuous tolerance — SGD keeps the comparison linear in grads.
        fresh = jax.tree_util.tree_map(jnp.array, variables)
        state = TrainState.create(
            apply_fn=apply_fn, variables=fresh, tx=optax.sgd(1e-2),
            rng=jax.random.PRNGKey(4),
        )
        step = make_train_step(compute_dtype=jnp.float32)
        batch = shard_batch((jnp.asarray(x), jnp.asarray(labels)), mesh)
        metrics = None
        for _ in range(2):
            state, metrics = step(state, batch)
        return state, metrics

    s_pp, m_pp = run(make_pp_apply(model, mesh, num_microbatches=8))
    s_ref, m_ref = run(model.apply)
    np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_pp.params), jax.tree_util.tree_leaves(s_ref.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_pp_apply_guards():
    """make_pp_apply rejects the configurations whose semantics would
    silently differ: MoE blocks, SP attention, dropout, indivisible depth."""
    from mpi_pytorch_tpu.parallel.pp_vit import make_pp_apply

    mesh = _pp_mesh(4)
    with pytest.raises(ValueError, match="dense encoder blocks"):
        make_pp_apply(_tiny_vit(moe_every=2), mesh, num_microbatches=8)
    with pytest.raises(ValueError, match="dropout"):
        make_pp_apply(_tiny_vit(dropout=0.1), mesh, num_microbatches=8)
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_apply(_tiny_vit(depth=6), mesh, num_microbatches=8)


def test_build_inference_wires_pp(tmp_path):
    """--pp-stages reaches the EVAL driver through the same apply_fn seam as
    the trainer (no silently-ignored flag)."""
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.evaluate import build_inference

    cfg = parse_config([
        "--model-name", "vit_s16", "--pp-stages", "4", "--image-size", "32",
        "--num-classes", "1000", "--synthetic-data", "true",
    ])
    mesh, bundle, state, _ = build_inference(cfg)
    assert mesh.shape.get("pipe") == 4
    assert state.apply_fn is not bundle.model.apply  # the PP swap happened


@pytest.mark.slow
def test_pp_stages_config_trains_vit(tmp_path):
    """--pp-stages 4 end to end through parse_config/build_training/train on
    the 8-device mesh (pipe=4 × data=2): the PIPELINED multi-epoch loss
    trajectory matches the unpipelined trainer's on the identical config
    (SURVEY §2c's PP "Done =" criterion), and the checkpoint it writes
    restores into an UNPIPELINED run (PP-degree-independent checkpoints)."""
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.train.trainer import train

    common = [
        "--debug", "true", "--debug-sample-size", "64",
        "--image-size", "32", "--batch-size", "16", "--num-classes", "1000",
        "--num-epochs", "2", "--synthetic-data", "true", "--validate", "false",
        "--compute-dtype", "float32",  # tight trajectory comparison
        "--log-file", str(tmp_path / "training.log"),
        "--metrics-file", str(tmp_path / "metrics.jsonl"),
    ]
    args = ["--model-name", "vit_s16", "--pp-stages", "4",
            "--checkpoint-dir", str(tmp_path / "ckpt")] + common
    cfg = parse_config(args)
    assert cfg.mesh.pipe_parallel == 4
    summary = train(cfg)
    assert summary.epochs_run == 2
    assert np.isfinite(summary.final_loss)

    # Same config WITHOUT pipelining: the per-epoch losses must match —
    # PP is an execution strategy, not a different trajectory.
    cfg_ref = parse_config(
        ["--model-name", "vit_s16",
         "--checkpoint-dir", str(tmp_path / "ckpt_ref")] + common
    )
    summary_ref = train(cfg_ref)
    np.testing.assert_allclose(
        summary.epoch_losses, summary_ref.epoch_losses, rtol=1e-4
    )

    # Resume the PP checkpoint WITHOUT pipelining: same param tree.
    cfg2 = parse_config(
        ["--model-name", "vit_s16",
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--from-checkpoint", "true"] + common + ["--num-epochs", "3"]
    )
    assert cfg2.pp_stages == 1
    summary2 = train(cfg2)
    assert summary2.epochs_run == 1
    assert np.isfinite(summary2.final_loss)


def test_pipeline_rejects_bad_shapes(mesh, stacked):
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(
            stacked, _x(b=30), mesh,
            stage_fn=residual_mlp_stage, num_microbatches=7,
        )
    short = jax.tree_util.tree_map(lambda p: p[:4], stacked)
    with pytest.raises(ValueError, match="stage axis"):
        pipeline_forward(
            short, _x(), mesh, stage_fn=residual_mlp_stage, num_microbatches=4
        )
