"""Import every ``mpi_pytorch_tpu`` module — the version-skew tripwire.

A moving-API break (e.g. ``shard_map`` relocating between JAX versions,
see ``parallel/compat.py``) used to surface as EIGHT opaque pytest
collection errors spread across the suite. This walks the package and
imports each module so the same break surfaces as ONE named failure
pointing at the module that raised.
"""

import importlib
import pkgutil

import pytest

import mpi_pytorch_tpu

_MODULES = sorted(
    info.name
    for info in pkgutil.walk_packages(
        mpi_pytorch_tpu.__path__, prefix="mpi_pytorch_tpu."
    )
    # native/_mptnative.so is a plain ctypes shared library (built on
    # demand by native/__init__.py), not a Python extension module —
    # importlib would look for a PyInit symbol it deliberately lacks.
    if not info.name.endswith("._mptnative")
)


def test_package_walk_found_the_tree():
    # Guard against an empty walk silently passing: the package has well
    # over a dozen modules across ops/parallel/train/models/data/utils.
    assert len(_MODULES) > 20, _MODULES
    for expected in (
        "mpi_pytorch_tpu.parallel.compat",
        "mpi_pytorch_tpu.ops.fused_stem",
        "mpi_pytorch_tpu.train.step",
    ):
        assert expected in _MODULES


@pytest.mark.parametrize("name", _MODULES)
def test_module_imports(name):
    importlib.import_module(name)
