"""Tests for the remote fleet transport (ISSUE 12):
``serve/host.py`` (the serving-host process wire surface),
``serve/fleet/remote.py`` (RemoteHost / HostSupervisor / RemoteFleet),
``serve/fleet/autoscaler.py`` (FleetAutoscaler), the hardened
``ObsHTTPServer``, the generalized kill gate + ``kill-serve-host`` drill,
the retry_after_ms wire round trip honored by bench_serve's open-loop
client, schema v8, and the transport-keyed regression gate.

Most tests drive the REAL wire path (ServingHost over ObsHTTPServer ↔
RemoteHost over urllib) against a jax-free fake inference server, so the
transport/retry/timeout/taxonomy machinery is pinned in milliseconds;
one end-to-end test spawns a real ``python -m mpi_pytorch_tpu.serve.host``
subprocess, and the 3-host subprocess chaos drive (the
``_dryrun_remote_fleet`` twin) is slow-marked.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env(**extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


# ------------------------------------------------------------ fakes / helpers


class FakeInferenceServer:
    """Duck-typed server for the wire-path tests: no jax, deterministic
    answers, scriptable failure modes."""

    name = "h0"

    def __init__(self, topk=3):
        from mpi_pytorch_tpu.serve.batcher import (
            PreprocessError,
            QueueFullError,
            ServerClosedError,
        )

        self._QueueFullError = QueueFullError
        self._ServerClosedError = ServerClosedError
        self._PreprocessError = PreprocessError
        self.topk = topk
        self.mode = "ok"  # ok | reject | closed | reqfault | hostfault | pending
        self.retry_after_ms = 123.0
        self.submits = 0
        self.max_wait_ms = 2.0
        self.active = (1, 4)
        self.closed = False

    def submit(self, image):
        self.submits += 1
        if self.mode == "reject":
            raise self._QueueFullError(
                "queue full", retry_after_ms=self.retry_after_ms
            )
        if self.mode == "closed":
            raise self._ServerClosedError("server is shut down")
        fut = Future()
        if self.mode == "reqfault":
            fut.set_exception(self._PreprocessError("poison payload"))
        elif self.mode == "hostfault":
            fut.set_exception(RuntimeError("device exploded"))
        elif self.mode == "pending":
            pass  # never resolves
        else:
            arr = np.asarray(image)
            fut.set_result(
                np.full((self.topk,), int(arr.reshape(-1)[0]), np.int32)
            )
        return fut

    def set_max_wait_ms(self, v):
        self.max_wait_ms = float(v)

    def set_active_buckets(self, buckets):
        from mpi_pytorch_tpu.serve.batcher import ServeError

        if not set(buckets) <= {1, 4}:
            raise ServeError("bucket was never compiled")
        self.active = tuple(buckets)

    def set_precision(self, precision):
        from mpi_pytorch_tpu.serve.batcher import ServeError

        if precision != "bf16":
            raise ServeError("precision was never compiled")

    def stats(self):
        return {"served": self.submits, "rejected": 0, "padded_rows": 0,
                "compiles_after_warmup": 0, "by_bucket": {1: self.submits}}

    def _healthz(self):
        return {
            "status": "closing" if self.closed else "ok",
            "queue_depth": 0, "compiles_after_warmup": 0,
            "served": self.submits, "rejected": 0, "buckets": [1, 4],
            "precision": "bf16", "queue_capacity": 8,
            "max_wait_ms": self.max_wait_ms,
            "active_buckets": list(self.active),
            "precisions": ["bf16"], "parity_top1": None,
            "topk": self.topk, "host_index": 0, "pid": None,
        }

    def close(self, drain=True):
        self.closed = True


@pytest.fixture()
def wire():
    """A live (ServingHost over a fake server, RemoteHost) pair."""
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
    from mpi_pytorch_tpu.serve.host import ServingHost

    server = FakeInferenceServer()
    host = ServingHost(server, port=0)
    remote = RemoteHost(
        f"http://127.0.0.1:{host.port}", name="h0", index=0,
        poll_slice_s=0.2, result_timeout_s=5.0, probe_retries=1,
    )
    yield server, host, remote
    remote._pool.shutdown(wait=False, cancel_futures=True)
    host.close()


class FakeHost:
    """In-memory HostHandle for router/autoscaler/supervisor units."""

    transport = "local"

    def __init__(self, name, index, queue_capacity=8):
        self.name = name
        self.index = index
        self.queue_capacity = queue_capacity
        self.buckets = (1, 4)
        self.active_buckets = (1, 4)
        self.max_wait_ms = 2.0
        self.precision = "bf16"
        self.precisions = ("bf16",)
        self.parity_top1 = None
        self.fail_mode = None  # None | "future" | "raise"
        self.submitted = 0
        self.closed = False
        self.hist = {}  # histograms served via snapshot()
        self.queue_depth = 0

    def submit(self, payload):
        from mpi_pytorch_tpu.serve.batcher import HostUnavailableError

        if self.fail_mode == "raise":
            raise HostUnavailableError(f"{self.name} unreachable")
        self.submitted += 1
        fut = Future()
        if self.fail_mode == "future":
            fut.set_exception(
                HostUnavailableError(f"{self.name} died mid-flight")
            )
        else:
            fut.set_result(np.full((3,), self.index, np.int32))
        return fut

    def snapshot(self):
        return {
            "counters": {},
            "gauges": {"serve/queue_depth": self.queue_depth},
            "histograms": dict(self.hist),
        }

    def alive(self):
        return not self.closed

    def qsize(self):
        return self.queue_depth

    def stats(self):
        return {"served": self.submitted, "rejected": 0, "padded_rows": 0,
                "compiles_after_warmup": 0}

    def compiles_after_warmup(self):
        return 0

    def set_max_wait_ms(self, v):
        self.max_wait_ms = float(v)

    def close(self, drain=True):
        self.closed = True

    def kill(self):
        self.closed = True


def _make_router(hosts, spare=None, **kw):
    from mpi_pytorch_tpu.serve.fleet import FleetRouter

    kw.setdefault("probe_interval_s", 10.0)  # probes quiet in units
    return FleetRouter(hosts, spare, **kw)


# ----------------------------------------------------------- schema (v8)


def test_schema_v8_scale_and_restart_records():
    from mpi_pytorch_tpu.obs.schema import SCHEMA_VERSION, validate_record

    assert SCHEMA_VERSION >= 8
    up = {
        "kind": "fleet", "ts": 1.0, "event": "scale_up", "host": "h4",
        "hosts_from": 3, "hosts_to": 4, "reason": "admission rejects",
        "reject_rate": 2.5, "queue_depth": 17, "p99_ms": 80.0,
        "target_p99_ms": 50.0, "compiles_after_warmup": 0,
        "transport": "http",
    }
    assert validate_record(up) == []
    down = {
        "kind": "fleet", "ts": 1.0, "event": "scale_down", "host": "h1",
        "hosts_from": 4, "hosts_to": 3, "reason": "idle", "reject_rate": 0.0,
        "queue_depth": 0,
    }
    assert validate_record(down) == []
    restart = {
        "kind": "fleet", "ts": 1.0, "event": "restart", "host": "h1",
        "detail": "supervisor restart #1", "restarts": 1,
        "compiles_after_warmup": 0, "transport": "http",
    }
    assert validate_record(restart) == []
    # transport on route records; typed wrong → rejected.
    route = {
        "kind": "route", "ts": 1.0, "host": "h0", "requests": 3,
        "transport": "http",
    }
    assert validate_record(route) == []
    assert validate_record(dict(route, transport=1))
    bench = {
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "requests": 10, "p50_ms": 1.0, "p95_ms": 2.0,
        "p99_ms": 3.0, "images_per_sec": 100.0, "transport": "http",
    }
    assert validate_record(bench) == []


def test_config_remote_and_autoscale_knob_validation():
    from mpi_pytorch_tpu.config import Config

    Config(
        serve_fleet_hosts=2, serve_autoscale=True, serve_fleet_min_hosts=1,
        serve_fleet_max_hosts=4, serve_scale_cooldown_s=5.0,
    ).validate_config()
    # Autoscale is a fleet knob: silently-ignored combinations error.
    with pytest.raises(ValueError):
        Config(serve_autoscale=True).validate_config()
    with pytest.raises(ValueError):
        Config(serve_fleet_hosts=2, serve_fleet_max_hosts=3).validate_config()
    with pytest.raises(ValueError):
        Config(
            serve_fleet_hosts=2, serve_autoscale=True,
            serve_fleet_min_hosts=5, serve_fleet_max_hosts=3,
        ).validate_config()
    with pytest.raises(ValueError):
        Config(serve_connect_timeout_s=0).validate_config()
    with pytest.raises(ValueError):
        Config(serve_probe_retries=-1).validate_config()
    with pytest.raises(ValueError):
        Config(serve_port=-2).validate_config()


# ------------------------------------------------- hardened ObsHTTPServer


class _Reg:
    def prometheus_text(self):
        return "x 1\n"

    def snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}


def test_http_server_bounds_request_bodies():
    from mpi_pytorch_tpu.serve.http import ObsHTTPServer

    srv = ObsHTTPServer(
        _Reg(), port=0, max_body_bytes=1024,
        post_routes={"/echo": lambda p, q, b: (
            200, "application/octet-stream", b, {}
        )},
    )
    try:
        url = srv.url("/echo")
        # In-bound body round-trips.
        with urllib.request.urlopen(
            urllib.request.Request(url, data=b"ok", method="POST"), timeout=5
        ) as resp:
            assert resp.read() == b"ok"
        # Over the bound → 413 before any handler runs.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                urllib.request.Request(url, data=b"x" * 2048, method="POST"),
                timeout=5,
            )
        assert exc.value.code == 413
        # No Content-Length → 411 (raw socket; urllib always sends one).
        with socket.create_connection(("127.0.0.1", srv.port)) as s:
            s.sendall(b"POST /echo HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"411" in s.recv(1024).split(b"\r\n", 1)[0]
    finally:
        srv.close()


def test_http_server_cuts_hung_client_and_survives():
    """A client that never finishes its request is cut at the read
    timeout instead of pinning a handler thread — and close() is not
    hostage to it."""
    from mpi_pytorch_tpu.serve.http import ObsHTTPServer

    srv = ObsHTTPServer(_Reg(), port=0, read_timeout_s=0.3)
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(b"GET /metricsz HTT")  # never completed
        s.settimeout(5)
        assert s.recv(1024) == b""  # server closed the connection
        s.close()
        with urllib.request.urlopen(srv.url("/healthz"), timeout=5) as resp:
            assert resp.status == 200  # still serving
    finally:
        t0 = time.monotonic()
        srv.close()
        assert time.monotonic() - t0 < 5.0


def test_http_server_graceful_close_drains_inflight():
    from mpi_pytorch_tpu.serve.http import ObsHTTPServer

    started = threading.Event()

    def slow(path, query, body):
        started.set()
        time.sleep(0.5)
        return (200, "text/plain", b"slow-done", {})

    srv = ObsHTTPServer(_Reg(), port=0, get_routes={"/slow": slow})
    out = {}

    def client():
        with urllib.request.urlopen(srv.url("/slow"), timeout=10) as resp:
            out["body"] = resp.read()

    t = threading.Thread(target=client)
    t.start()
    assert started.wait(5)
    srv.close()  # stops accepting FIRST, then waits for the handler
    t.join(timeout=10)
    assert out["body"] == b"slow-done"
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", srv.port), timeout=1)


# ------------------------------------------------- ServingHost wire surface


def test_serving_host_submit_result_roundtrip_idempotent(wire):
    from mpi_pytorch_tpu.serve.host import _npy_bytes

    server, host, remote = wire
    url = f"http://127.0.0.1:{host.port}"
    body = _npy_bytes(np.full((2, 2, 3), 9, np.uint8))
    with urllib.request.urlopen(
        urllib.request.Request(f"{url}/submit", data=body, method="POST"),
        timeout=5,
    ) as resp:
        assert resp.status == 202
        rid = json.loads(resp.read())["req_id"]
    for _ in range(2):  # delivery is idempotent until the reaper expires it
        with urllib.request.urlopen(
            f"{url}/result/{rid}?timeout_s=5", timeout=10
        ) as resp:
            preds = np.load(__import__("io").BytesIO(resp.read()))
        np.testing.assert_array_equal(preds, np.full((3,), 9, np.int32))
    # Unknown id → 404 (a restarted process forgot its predecessor's ids).
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(f"{url}/result/99999?timeout_s=0", timeout=5)
    assert exc.value.code == 404
    # Malformed body → 400 tagged as a request fault.
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            urllib.request.Request(
                f"{url}/submit", data=b"not-npy", method="POST"
            ),
            timeout=5,
        )
    assert exc.value.code == 400
    assert json.loads(exc.value.read())["taxonomy"] == "request"


def test_retry_after_ms_crosses_the_wire(wire):
    """The tentpole satellite: HTTP 429 carries retry_after_ms (body +
    Retry-After header) and RemoteHost re-raises a faithful typed
    QueueFullError."""
    from mpi_pytorch_tpu.serve.batcher import QueueFullError
    from mpi_pytorch_tpu.serve.host import _npy_bytes

    server, host, remote = wire
    server.mode = "reject"
    server.retry_after_ms = 456.5
    body = _npy_bytes(np.zeros((2, 2, 3), np.uint8))
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{host.port}/submit", data=body,
                method="POST",
            ),
            timeout=5,
        )
    assert exc.value.code == 429
    assert exc.value.headers["Retry-After"] == "1"
    assert json.loads(exc.value.read())["retry_after_ms"] == 456.5
    with pytest.raises(QueueFullError) as typed:
        remote.submit(np.zeros((2, 2, 3), np.uint8))
    assert typed.value.retry_after_ms == 456.5


def test_remote_host_error_taxonomy(wire):
    """Request faults propagate typed; host faults classify into
    HostUnavailableError (the router's re-dispatch branch); a closing
    server classifies ServerClosedError."""
    from mpi_pytorch_tpu.serve.batcher import (
        HostUnavailableError,
        ServeError,
        ServerClosedError,
    )

    server, host, remote = wire
    img = np.zeros((2, 2, 3), np.uint8)
    server.mode = "reqfault"
    with pytest.raises(ServeError) as exc:
        remote.submit(img).result(timeout=10)
    assert not isinstance(
        exc.value, (HostUnavailableError, ServerClosedError)
    )
    server.mode = "hostfault"
    with pytest.raises(HostUnavailableError):
        remote.submit(img).result(timeout=10)
    server.mode = "closed"
    with pytest.raises(ServerClosedError):
        remote.submit(img)
    # Result long-poll that never resolves → host-shaped after the
    # bounded result timeout (re-polled, not hung forever).
    server.mode = "pending"
    with pytest.raises(HostUnavailableError):
        remote.submit(img).result(timeout=30)


def test_remote_host_control_and_probe_surface(wire):
    from mpi_pytorch_tpu.serve.batcher import ServeError

    server, host, remote = wire
    assert remote.queue_capacity == 8
    assert remote.buckets == (1, 4)
    assert remote.alive()
    remote.set_max_wait_ms(0.5)
    assert server.max_wait_ms == 0.5
    assert remote.max_wait_ms == 0.5  # control invalidates the facts cache
    remote.set_active_buckets((1,))
    assert server.active == (1,)
    with pytest.raises(ServeError):
        remote.set_active_buckets((1, 32))  # typed 400 crosses back
    snap = remote.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert remote.stats()["served"] == server.submits
    assert remote.compiles_after_warmup() == 0


def test_remote_host_probe_retries_but_never_submit_retries():
    """Probes (idempotent) get bounded jittered retries through a flaky
    wire; submit gets exactly ONE attempt — a retry could double-enqueue
    and exactly-once re-dispatch belongs to the router."""
    from mpi_pytorch_tpu.serve.batcher import HostUnavailableError
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
    from mpi_pytorch_tpu.serve.http import ObsHTTPServer

    calls = {"metricsz": 0, "submit": 0}
    healthz = {
        "status": "ok", "queue_capacity": 8, "buckets": [1],
        "queue_depth": 0, "compiles_after_warmup": 0, "topk": 1,
        "host_index": 0, "pid": None,
    }

    def flaky_metricsz():
        calls["metricsz"] += 1
        if calls["metricsz"] <= 2:
            raise RuntimeError("transient scrape failure")  # → 500
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def failing_submit(path, query, body):
        calls["submit"] += 1
        return (500, "application/json",
                json.dumps({"error": "internal"}).encode(), {})

    srv = ObsHTTPServer(
        _Reg(), healthz=lambda: healthz, port=0, metricsz=flaky_metricsz,
        post_routes={"/submit": failing_submit},
    )
    try:
        remote = RemoteHost(
            f"http://127.0.0.1:{srv.port}", name="h0", index=0,
            probe_retries=2,
        )
        snap = remote.snapshot()  # two 500s absorbed by the retry budget
        assert calls["metricsz"] == 3
        assert set(snap) == {"counters", "gauges", "histograms"}
        with pytest.raises(HostUnavailableError):
            remote.submit(np.zeros((2, 2, 3), np.uint8))
        assert calls["submit"] == 1, "submit must never be retried"
        remote._pool.shutdown(wait=False, cancel_futures=True)
    finally:
        srv.close()


def test_remote_host_dead_endpoint_is_loud():
    from mpi_pytorch_tpu.serve.batcher import HostUnavailableError
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    with pytest.raises(HostUnavailableError):
        RemoteHost(
            f"http://127.0.0.1:{dead_port}", name="hx", index=0,
            probe_retries=0,
        )


# ----------------------------------------- router: taxonomy + membership


def test_router_redispatches_host_unavailable_futures():
    """A future failing HostUnavailableError (the remote transport's
    mid-flight death) re-dispatches exactly once — never propagates to
    the caller as a request fault."""
    a, b = FakeHost("h0", 0), FakeHost("h1", 1)
    a.fail_mode = "future"
    router = _make_router([a, b], fail_probes=1)
    try:
        futs = [router.submit(i) for i in range(8)]
        preds = [f.result(timeout=30) for f in futs]
        assert all(p[0] == 1 for p in preds)  # every answer came from h1
        if a.submitted:  # h0 was hit before its first failure drained it
            log = router.redispatch_log
            assert log and len(log) == len(set(log))
            assert router.failovers == ["h0"]
    finally:
        router.close()


def test_router_add_and_retire_host():
    a, b = FakeHost("h0", 0), FakeHost("h1", 1)
    router = _make_router([a, b])
    try:
        assert router.budget == 16  # auto budget: sum of capacities
        c = FakeHost("h2", 2)
        router.add_host(c)
        assert {h.name for h in router.active_hosts()} == {"h0", "h1", "h2"}
        assert router.budget == 24  # auto budget grew with the host
        # Graceful retire: out of rotation, closed, nothing re-dispatched,
        # nothing marked dead.
        retired = router.retire_host("h2", wait_s=5.0)
        assert retired is c and c.closed
        assert {h.name for h in router.active_hosts()} == {"h0", "h1"}
        assert router.budget == 16
        assert router.redispatch_log == [] and router.failovers == []
        assert router.retire_host("h2") is None  # idempotent-ish
    finally:
        router.close()


def test_router_readmission_clears_dead_state():
    """The supervisor's re-admission path: a drained (dead) host name
    re-enters rotation with fresh state."""
    a, b = FakeHost("h0", 0), FakeHost("h1", 1)
    a.fail_mode = "raise"
    router = _make_router([a, b], fail_probes=1)
    try:
        assert router.budget == 16
        futs = [router.submit(i) for i in range(6)]
        for f in futs:
            f.result(timeout=30)
        deadline = time.monotonic() + 10
        while "h0" not in router.failovers and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.failovers == ["h0"]
        # Spare-less drain under an auto budget: the dead host's share
        # leaves the front door with it...
        assert router.budget == 8
        a2 = FakeHost("h0", 0)  # the restarted process, same identity
        router.add_host(a2)
        assert "h0" in {h.name for h in router.active_hosts()}
        assert "h0" not in router.stats()["dead"]
        # ...and re-admission restores it EXACTLY once — kill+restart
        # cycles must not inflate the budget.
        assert router.budget == 16
        futs = [router.submit(i) for i in range(20)]
        for f in futs:
            f.result(timeout=30)
        assert a2.submitted > 0  # traffic flows to the re-admitted host
    finally:
        router.close()


def test_router_restarted_spare_replaces_its_dead_handle():
    """A supervised spare that died and restarted re-enters as the SPARE
    (replacing the dead handle a failover would otherwise promote), not
    as an extra rotation host."""
    a = FakeHost("h0", 0)
    spare = FakeHost("h1", 1)
    router = _make_router([a], spare)
    try:
        assert router.budget == 8  # spare capacity is not admission budget
        spare2 = FakeHost("h1", 1)
        router.add_host(spare2, spare=True)
        assert router.spare_host() is spare2
        assert {h.name for h in router.active_hosts()} == {"h0"}
        assert router.budget == 8
    finally:
        router.close()


# ----------------------------------------------------------- autoscaler


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _scaler(router, clock, tmp_path=None, writer=None, **kw):
    from mpi_pytorch_tpu.serve.fleet import FleetAutoscaler

    spawned = []

    def spawn():
        h = FakeHost(f"h{10 + len(spawned)}", 10 + len(spawned))
        spawned.append(h)
        return h

    retired = []
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("idle_ticks", 2)
    scaler = FleetAutoscaler(
        router, spawn_fn=spawn, retire_fn=retired.append,
        metrics=writer, clock=clock, **kw,
    )
    return scaler, spawned, retired


def test_autoscaler_scales_up_on_reject_rate(tmp_path):
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.utils.logging import MetricsWriter

    router = _make_router([FakeHost("h0", 0)])
    path = str(tmp_path / "scale.jsonl")
    writer = MetricsWriter(path)
    clock = _FakeClock()
    scaler, spawned, _ = _scaler(
        router, clock, writer=writer, max_hosts=2, reject_rate_up=0.5,
        transport="http",
    )
    try:
        assert scaler.tick() is None  # first tick only baselines signals
        clock.t += 1.0
        router.front_door_rejections += 10  # 10 rejects/s — pressure
        assert scaler.tick() == "scale_up"
        assert spawned and len(router.active_hosts()) == 2
        # At max_hosts the bound holds even under continuing pressure.
        clock.t += 100.0
        router.front_door_rejections += 1000
        assert scaler.tick() is None
        assert len(router.active_hosts()) == 2
    finally:
        scaler.stop()
        router.close()
        writer.close()
    assert validate_jsonl(path) == []
    ups = [r for r in load_records(path) if r["event"] == "scale_up"]
    assert len(ups) == 1
    assert ups[0]["hosts_from"] == 1 and ups[0]["hosts_to"] == 2
    assert ups[0]["reject_rate"] > 0.5
    assert ups[0]["transport"] == "http"
    assert "reason" in ups[0]


def test_autoscaler_scales_up_on_p99_with_rising_queue():
    hosts = [FakeHost("h0", 0), FakeHost("h1", 1)]
    router = _make_router(hosts)
    clock = _FakeClock()
    scaler, spawned, _ = _scaler(
        router, clock, target_p99_ms=50.0, max_hosts=3, trend_window=2,
    )
    try:
        hosts[0].hist["serve/request_latency_ms"] = {"count": 5, "p99": 200.0}
        hosts[0].queue_depth = 2
        assert scaler.tick() is None  # trend not yet established
        clock.t += 1.0
        hosts[0].queue_depth = 9  # rising
        assert scaler.tick() == "scale_up"
        assert len(router.active_hosts()) == 3
    finally:
        scaler.stop()
        router.close()


def test_autoscaler_scales_down_at_idle_with_cooldown_and_min_bound(tmp_path):
    from mpi_pytorch_tpu.obs.schema import load_records
    from mpi_pytorch_tpu.utils.logging import MetricsWriter

    hosts = [FakeHost("h0", 0), FakeHost("h1", 1), FakeHost("h2", 2)]
    router = _make_router(hosts)
    path = str(tmp_path / "down.jsonl")
    writer = MetricsWriter(path)
    clock = _FakeClock()
    scaler, _, retired = _scaler(
        router, clock, writer=writer, min_hosts=2, cooldown_s=10.0,
    )
    try:
        # Make h2 the coldest (others carry traffic history).
        for h in hosts[:2]:
            for _ in range(4):
                router.submit(0).result(timeout=30)
        assert scaler.tick() is None  # idle streak 1
        clock.t += 1.0
        assert scaler.tick() == "scale_down"  # idle streak 2 → act
        assert len(router.active_hosts()) == 2
        assert retired and retired[0].closed
        # Cooldown: still idle, but no flap inside the window...
        clock.t += 1.0
        assert scaler.tick() is None
        # ...and past it, the min bound holds.
        clock.t += 20.0
        for _ in range(5):
            clock.t += 1.0
            assert scaler.tick() is None
        assert len(router.active_hosts()) == 2
    finally:
        scaler.stop()
        router.close()
        writer.close()
    downs = [r for r in load_records(path) if r["event"] == "scale_down"]
    assert len(downs) == 1
    assert downs[0]["hosts_from"] == 3 and downs[0]["hosts_to"] == 2


def test_autoscaler_rolling_restart_records():
    from mpi_pytorch_tpu.serve.fleet import FleetAutoscaler

    hosts = [FakeHost("h0", 0), FakeHost("h1", 1)]
    router = _make_router(hosts)
    cycled = []
    scaler = FleetAutoscaler(
        router, spawn_fn=lambda: None, restart_fn=cycled.append,
        cooldown_s=0.0,
    )
    try:
        assert scaler.rolling_restart() == 2
        assert [h.name for h in cycled] == ["h0", "h1"]
        assert scaler.actions == ["restart", "restart"]
    finally:
        scaler.stop()
        router.close()


# ----------------------------------------------------------- supervisor


class FakeProc:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class FakeRemoteHost(FakeHost):
    transport = "http"

    def __init__(self, name, index, compiles=0, healthy=True):
        super().__init__(name, index)
        self._compiles = compiles
        self._healthy = healthy

    def _healthz(self):
        return {
            "status": "ok" if self._healthy else "closing",
            "compiles_after_warmup": self._compiles,
        }


def test_supervisor_restart_backoff_and_warm_readmission(tmp_path):
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.serve.fleet.remote import HostSupervisor
    from mpi_pytorch_tpu.utils.logging import MetricsWriter

    router = _make_router([FakeHost("h9", 9)])  # placeholder rotation
    path = str(tmp_path / "sup.jsonl")
    writer = MetricsWriter(path)
    clock = _FakeClock()
    spawn_times = []
    spawn_fail = {"n": 0}

    def spawn(index):
        spawn_times.append(clock.t)
        if spawn_fail["n"] > 0:
            spawn_fail["n"] -= 1
            raise RuntimeError("spawn wedged")
        return FakeProc(), FakeRemoteHost(f"h{index}", index)

    sup = HostSupervisor(
        spawn, router=router, metrics=writer,
        backoff_base_s=0.5, backoff_max_s=8.0, clock=clock,
    )
    try:
        proc = FakeProc()
        sup.manage(0, proc, FakeRemoteHost("h0", 0))
        proc.rc = -9  # SIGKILL'd
        assert sup.tick() == 0  # death noticed, restart scheduled at +0.5
        clock.t = 0.4
        assert sup.tick() == 0  # backoff not elapsed
        clock.t = 0.6
        spawn_fail["n"] = 1  # first restart attempt fails → backoff doubles
        assert sup.tick() == 0
        entry = sup.entry(0)
        assert entry.state == "dead"
        # Failed attempt at 0.6 with restarts=1 → next at 0.6 + 1.0.
        clock.t = 1.2
        assert sup.tick() == 0
        clock.t = 1.7
        assert sup.tick() == 1  # restart + warm probe + re-admission
        assert spawn_times == [0.6, 1.7]  # exponential schedule, not a spin
        assert "h0" in {h.name for h in router.active_hosts()}
        assert sup.restarts_total == 1
        # Stability window forgives history.
        clock.t = 1.7 + 120.0
        sup.tick()
        assert sup.entry(0).restarts == 0
    finally:
        sup.stop()
        router.close()
        writer.close()
    assert validate_jsonl(path) == []
    restarts = [r for r in load_records(path) if r.get("event") == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["host"] == "h0"
    assert restarts[0]["compiles_after_warmup"] == 0
    assert restarts[0]["transport"] == "http"


def test_supervisor_warm_probe_rejects_compiling_host():
    """A restarted host that would compile under traffic must NOT rejoin
    rotation — the warm-start invariant is checked, not assumed."""
    from mpi_pytorch_tpu.serve.fleet.remote import HostSupervisor

    router = _make_router([FakeHost("h9", 9)])
    clock = _FakeClock()

    def spawn(index):
        return FakeProc(), FakeRemoteHost(f"h{index}", index, compiles=2)

    sup = HostSupervisor(spawn, router=router, clock=clock)
    try:
        proc = FakeProc()
        sup.manage(0, proc, FakeRemoteHost("h0", 0))
        proc.rc = 1
        sup.tick()
        clock.t = 10.0
        assert sup.tick() == 0  # spawned but failed the warm probe
        assert sup.entry(0).state == "dead"
        assert "h0" not in {h.name for h in router.active_hosts()}
    finally:
        sup.stop()
        router.close()


# ----------------------------------------------- chaos drill tooling


def test_kill_serve_host_finds_announces_and_strikes(tmp_path):
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from tools import inject_faults

    # A decoy process whose argv mimics a serving host with ANOTHER index
    # plus the real target: the finder must hit index 7 only.
    argv_extra = ["mpi_pytorch_tpu.serve.host", "--serve-host-index"]
    sleeper = "import time; time.sleep(300)"
    decoy = subprocess.Popen([sys.executable, "-c", sleeper, *argv_extra, "5"])
    target = subprocess.Popen([sys.executable, "-c", sleeper, *argv_extra, "7"])
    metrics = str(tmp_path / "kill.jsonl")
    try:
        # Between fork and exec a child's /proc cmdline still shows the
        # PARENT's argv (no marker) — on a busy single-core box the scan
        # can win that race. Wait until both children have exec'd.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if inject_faults.find_serve_host_pids(5) == [decoy.pid] and \
                    inject_faults.find_serve_host_pids(7) == [target.pid]:
                break
            time.sleep(0.05)
        pids = inject_faults.find_serve_host_pids(7)
        assert pids == [target.pid]
        assert inject_faults.main(
            ["kill-serve-host", "--host-index", "7",
             "--metrics-file", metrics]
        ) == 0
        assert target.wait(timeout=10) == -9
        assert decoy.poll() is None  # the decoy lives
        with pytest.raises(ProcessLookupError):
            inject_faults.kill_serve_host(7)
    finally:
        for p in (decoy, target):
            if p.poll() is None:
                p.kill()
                p.wait()
    assert validate_jsonl(metrics) == []
    recs = load_records(metrics)
    assert len(recs) == 1 and recs[0]["reason"] == "injected_host_kill"
    assert "--serve-host-index" not in recs[0]["detail"]
    assert "index 7" in recs[0]["detail"]


def test_list_gates_documents_generalized_kill(capsys):
    from tools import inject_faults

    assert inject_faults.main(["list-gates"]) == 0
    out = capsys.readouterr().out
    assert "MPT_FAULT_SERVE_KILL_HOST" in out
    assert "SIGKILL" in out and "SUBPROCESS" in out


def test_open_loop_honors_retry_after_hint():
    """bench_serve's open-loop client backs off by the hint instead of
    hammering a saturated host (the end-to-end half of the wire
    round-trip satellite)."""
    import importlib.util

    from mpi_pytorch_tpu.serve.batcher import QueueFullError

    spec = importlib.util.spec_from_file_location(
        "bench_serve", os.path.join(REPO, "tools", "bench_serve.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    submit_times = []
    state = {"n": 0}

    class HintingServer:
        def submit(self, image):
            submit_times.append(time.monotonic())
            state["n"] += 1
            if state["n"] == 1:
                raise QueueFullError("full", retry_after_ms=300.0)
            fut = Future()
            fut.set_result(np.int32([1]))
            return fut

    lat, wall, rejected = bench.open_loop(
        HintingServer(), pool=[np.zeros((2, 2, 3), np.uint8)],
        requests=5, rps=1000.0, seed=0, timeout_s=10.0,
    )
    assert rejected == 1
    assert len(lat) == 4
    # The submission after the hinted rejection waited out the hint
    # (Poisson gaps at 1000 rps are ~1 ms — without the backoff the gap
    # would be three orders of magnitude smaller).
    assert submit_times[1] - submit_times[0] >= 0.25


def test_check_regression_keys_transport_separately(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_regression", os.path.join(REPO, "tools", "check_regression.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base_row = {
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "offered_rps": 400.0, "model": "resnet18",
        "requests": 100, "p50_ms": 5.0, "p95_ms": 8.0, "p99_ms": 10.0,
        "images_per_sec": 1000.0, "fleet_hosts": 3,
    }
    remote_row = dict(base_row, transport="http", p99_ms=40.0)
    assert mod._serve_key(base_row) != mod._serve_key(remote_row)
    baseline, new = tmp_path / "prev.json", tmp_path / "new.json"
    with open(baseline, "w") as f:
        f.write(json.dumps(base_row) + "\n")
        f.write(json.dumps(remote_row) + "\n")
    # The remote point regressed 2×; the in-process one is unchanged —
    # exactly one violation, on the remote trend line.
    with open(new, "w") as f:
        f.write(json.dumps(base_row) + "\n")
        f.write(json.dumps(dict(remote_row, p99_ms=80.0)) + "\n")
    violations = mod.check_serve(str(new), str(baseline), 10.0)
    assert len(violations) == 1 and "http" in violations[0]


def test_report_run_renders_scale_and_restart_events(tmp_path, capsys):
    from tools import report_run

    path = tmp_path / "m.jsonl"
    records = [
        {"kind": "fleet", "ts": 1.0, "event": "scale_up", "host": "h3",
         "hosts_from": 2, "hosts_to": 3,
         "reason": "admission rejects at 2.10/s", "reject_rate": 2.1,
         "queue_depth": 14, "transport": "http"},
        {"kind": "fleet", "ts": 2.0, "event": "restart", "host": "h1",
         "detail": "supervisor restart #1", "restarts": 1,
         "compiles_after_warmup": 0, "transport": "http"},
        {"kind": "fleet", "ts": 3.0, "event": "scale_down", "host": "h0",
         "hosts_from": 3, "hosts_to": 2, "reason": "idle for 2 tick(s)",
         "reject_rate": 0.0, "queue_depth": 0},
    ]
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert report_run.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "FLEET scale_up: 2 → 3 host(s) (h3)" in out
    assert "admission rejects" in out
    assert "FLEET restart: host h1 re-admitted" in out
    assert "FLEET scale_down: 3 → 2 host(s)" in out


# ----------------------------------------- end-to-end: a real host process


def _host_argv(tmp, port_file, **over):
    flags = {
        "--model-name": "resnet18", "--num-classes": "16", "--width": "32",
        "--height": "32", "--synthetic-data": "true",
        "--compute-dtype": "float32", "--serve-buckets": "1,4",
        "--serve-max-wait-ms": "2", "--serve-topk": "3",
        "--serve-queue-depth": "64", "--loader-workers": "2",
        "--serve-host-index": "0", "--serve-port-file": port_file,
        "--metrics-file": f"{tmp}/host.jsonl", "--log-file": "",
        "--eval-log-file": "",
    }
    flags.update(over)
    argv = [sys.executable, "-m", "mpi_pytorch_tpu.serve.host"]
    for k, v in flags.items():
        argv += [k, v]
    return argv


def test_live_host_process_probe_submit_429_and_drain(tmp_path):
    """The non-slow end-to-end: spawn ONE real serving-host process,
    drive probe + submit over the wire, force deterministic 429s via the
    registered slow-flush gate, and shut it down gracefully."""
    from mpi_pytorch_tpu.obs.schema import validate_jsonl
    from mpi_pytorch_tpu.serve.batcher import QueueFullError
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
    from mpi_pytorch_tpu.serve.http import wait_port_file

    tmp = str(tmp_path)
    port_file = f"{tmp}/port.json"
    # Every flush on this fleet-host sleeps 250 ms (the registered fake
    # slow-host gate) → a tight submit loop overflows the bounded queue
    # deterministically, and the 429s carry drain-rate-derived hints.
    env = _cpu_env(
        MPT_FAULT_DELAY_STEP_MS="250", MPT_FAULT_DELAY_PROCESS="0",
    )
    proc = subprocess.Popen(
        _host_argv(tmp, port_file, **{"--serve-queue-depth": "4"}),
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        ready = wait_port_file(port_file, 240, proc)
        assert ready["host_index"] == 0 and ready["pid"] == proc.pid
        remote = RemoteHost(
            f"http://127.0.0.1:{ready['port']}", name="h0", index=0,
            pid=ready["pid"],
        )
        assert remote.alive()
        assert remote.buckets == (1, 4)
        assert remote.queue_capacity == 4
        rng = np.random.default_rng(0)
        images = [
            rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
            for _ in range(8)
        ]
        futs, rejections = [], []
        for i in range(30):
            try:
                futs.append(remote.submit(images[i % 8]))
            except QueueFullError as e:
                rejections.append(e)
        assert rejections, "the bounded queue never pushed back"
        assert all(
            e.retry_after_ms and e.retry_after_ms > 0 for e in rejections
        ), "429s must carry the retry_after_ms hint over the wire"
        for f in futs:
            assert f.result(timeout=120).shape == (3,)
        assert remote.compiles_after_warmup() == 0
        snap = remote.snapshot()
        assert snap["counters"]["serve/served"] >= len(futs)
        remote.close(drain=True)
        assert proc.wait(timeout=60) == 0  # graceful wire shutdown
        assert validate_jsonl(f"{tmp}/host.jsonl") == []
    finally:
        if proc.poll() is None:
            proc.kill()
            print(proc.communicate()[0][-3000:])
            raise AssertionError("host process had to be killed")


@pytest.mark.slow
def test_remote_fleet_subprocess_chaos_drive():
    """The 3-host subprocess chaos drive — the in-tree twin of the
    ``_dryrun_remote_fleet`` CI leg (SIGKILL mid-traffic → zero lost,
    failover, supervisor re-admission, bounded autoscale, schema-clean)."""
    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from __graft_entry__ import _dryrun_remote_fleet_child\n"
        "_dryrun_remote_fleet_child()\n"
    )
    env = _cpu_env(
        MPT_FAULT_SERVE_KILL_HOST="1", MPT_FAULT_SERVE_KILL_AFTER="8",
    )
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    out = subprocess.run(
        [sys.executable, "-c", child], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=1200,
    )
    assert out.returncode == 0 and "REMOTE_FLEET_OK" in out.stdout, out.stdout
