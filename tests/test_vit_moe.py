"""MoE-ViT (`vit_moe_s16`): the EP training-path model. Asserts the aux loss
flows through the standard train step, expert-parallel execution equals the
dense evaluation of the same network, and the registry guards.

The load-bearing property (mirroring the SP tests): sharding the experts
over a mesh is an execution layout — the EP-built model computes the same
function as the dense one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.models import create_model_bundle, initialize_model
from mpi_pytorch_tpu.models.vit import VisionTransformer

# 32px / patch 4 → 64 tokens; batch 4 → 256 tokens, divisible by 8 shards.
TINY = dict(
    num_classes=10, patch_size=4, hidden=64, depth=2, num_heads=4, mlp_dim=128,
    moe_every=2, num_experts=8, moe_capacity=256,  # no-drop capacity: EP ≡ dense
)


@pytest.fixture(scope="module")
def ep_mesh():
    dev = np.asarray(jax.devices()[:8]).reshape(8, 1)
    return Mesh(dev, ("expert", "unused"))


@pytest.fixture(scope="module")
def tiny_moe_vit():
    model = VisionTransformer(**TINY)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    variables.pop("losses", None)
    return model, variables, x


def test_moe_vit_has_experts_in_odd_blocks_only(tiny_moe_vit):
    _, variables, _ = tiny_moe_vit
    params = variables["params"]
    assert "moe" in params["block1"] and "w1" in params["block1"]["moe"]
    assert "moe" not in params["block0"] and "mlp1" in params["block0"]
    assert params["block1"]["moe"]["w1"].shape == (8, 64, 128)


def test_moe_vit_sows_aux_loss(tiny_moe_vit):
    model, variables, x = tiny_moe_vit
    logits, updated = model.apply(variables, x, train=False, mutable=["losses"])
    assert logits.shape == (4, 10)
    leaves = jax.tree_util.tree_leaves(updated["losses"])
    assert len(leaves) == 1  # one MoE block at depth 2
    aux = float(sum(jnp.sum(v) for v in leaves))
    assert np.isfinite(aux) and aux > 0.0


def test_moe_vit_ep_matches_dense(tiny_moe_vit, ep_mesh):
    model, variables, x = tiny_moe_vit
    ep_model = VisionTransformer(**TINY, ep_mesh=ep_mesh)
    got = ep_model.apply(variables, x, train=False)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_moe_vit_ep_grads_match_dense(tiny_moe_vit, ep_mesh):
    model, variables, x = tiny_moe_vit
    ep_model = VisionTransformer(**TINY, ep_mesh=ep_mesh)

    # Task-path grads only: the aux term is EXPECTED to differ between the
    # two layouts (EP computes load-balance per shard and pmeans — average of
    # per-shard frac·p̄ products ≠ the dense global product; the per-shard
    # semantics themselves are asserted in test_moe.py).
    def loss(m, params):
        out = m.apply({"params": params}, x, train=False)
        return jnp.sum(out * out)

    g_ep = jax.grad(lambda p: loss(ep_model, p))(variables["params"])
    g_de = jax.grad(lambda p: loss(model, p))(variables["params"])
    # f32 accumulation-order noise: the all_to_all regroups the expert einsum
    # into per-shard blocks, so backward sums run in a different order than
    # the dense single-einsum (measured ≤6e-5 abs on 0.05% of elements).
    for a, b in zip(jax.tree_util.tree_leaves(g_ep), jax.tree_util.tree_leaves(g_de)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=1e-4)


@pytest.mark.slow
def test_moe_vit_trains_through_standard_step():
    """The aux loss reaches the optimizer via the train step's "losses"
    collection — total loss stays finite and decreases."""
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step

    bundle, variables = create_model_bundle(
        "vit_moe_s16", 10, rng=jax.random.PRNGKey(0), image_size=32
    )
    assert "losses" not in variables
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
    )
    rng = np.random.default_rng(2)
    images = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    step = make_train_step(jnp.float32)
    losses = []
    for _ in range(3):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_vit_composes_sp_and_ep(tiny_moe_vit, ep_mesh):
    """SP attention and EP experts in the SAME blocks: ring-sharded
    attention + all_to_all-sharded experts compute the same function as the
    plain dense model (both are execution layouts over one set of params).
    Requires heads and sequence divisible by the shard count: TINY has 4
    heads, so ring (no head constraint) is the strategy under test."""
    model, variables, x = tiny_moe_vit
    sp_mesh = Mesh(
        np.asarray(jax.devices()[:8]).reshape(8, 1), ("seq", "unused")
    )
    both = VisionTransformer(
        **TINY, sp_strategy="ring", sp_mesh=sp_mesh, ep_mesh=ep_mesh
    )
    got = both.apply(variables, x, train=False)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_moe_vit_handles_awkward_token_counts():
    """Token counts that are not multiples of the default routing group
    (e.g. 20px/patch4 → 25 tokens/image, batch 8 → 200 tokens) pick the
    largest dividing group instead of crashing."""
    model = VisionTransformer(
        num_classes=10, patch_size=4, hidden=64, depth=2, num_heads=4,
        mlp_dim=128, moe_every=2, num_experts=8,
    )
    x = jnp.asarray(
        np.random.default_rng(5).standard_normal((8, 20, 20, 3)), jnp.float32
    )
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    variables.pop("losses", None)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (8, 10)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_registry_rejects_ep_on_dense_model(ep_mesh):
    with pytest.raises(ValueError, match="MoE"):
        initialize_model("vit_s16", 10, ep_mesh=ep_mesh)
