"""Lightweight HTTP exposition for the serve replica's live telemetry —
and, extended with routes, the wire surface of a remote serving host.

One daemon ``ThreadingHTTPServer`` per ``InferenceServer`` (opt-in:
``--serve-metrics-port``), serving three read-only endpoints off the live
``MetricsRegistry`` — the scrape surface a Prometheus collector or ROADMAP
item 1's fleet controller polls without touching the record stream:

- ``/metrics``  — Prometheus text exposition (``registry.prometheus_text``);
- ``/metricsz`` — the JSON registry snapshot (counters / gauges /
  histogram summaries with sketch p50/p95/p99) — the controller-friendly
  form, no Prometheus parsing required;
- ``/healthz``  — liveness JSON from the server's stats callback (queue
  depth, compiles-after-warmup, served/rejected counters).

``serve/host.py`` mounts additional routes (``POST /submit``,
``GET /result/<id>``, ``POST /control``) on the same server to make a
serving process drivable over the wire — the ``RemoteHost`` transport
(ISSUE 12). Because that turns this from a scrape endpoint into a
request-path surface facing untrusted clients, the server is hardened:

- **per-request read timeout** (``read_timeout_s``): a client that opens
  a connection and never finishes its request is cut off instead of
  pinning a handler thread forever;
- **bounded request body** (``max_body_bytes``): a POST must declare a
  ``Content-Length`` (else 411) within the bound (else 413) before a
  single body byte is read;
- **graceful shutdown**: ``close()`` stops ACCEPTING first, then waits up
  to ``drain_grace_s`` for in-flight handlers to drain before tearing the
  socket down — a hung client can delay ``close()`` by at most the grace
  period, never wedge it (previously a handler stuck on a dead client
  held ``close()`` hostage).

The handler never blocks the serve path: every read is a registry
snapshot under its own small locks; request handling runs on the HTTP
server's threads. Binds 127.0.0.1 by default — exposure beyond the host
is a deployment decision (front it with the fleet router / a sidecar),
not a default.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class ObsHTTPServer:
    """Serve /metrics, /metricsz, /healthz (plus mounted routes) for one
    registry.

    Extra routes: ``get_routes`` / ``post_routes`` map a path — or a
    prefix ending in ``/`` — to ``fn(path, query, body) -> (status,
    content_type, body_bytes, extra_headers)``. A route raising is a 500;
    routes that want typed client errors return them as statuses.
    """

    def __init__(
        self,
        registry,
        healthz=None,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        metricsz=None,
        get_routes=None,
        post_routes=None,
        read_timeout_s: float = 10.0,
        max_body_bytes: int = 64 << 20,
        drain_grace_s: float = 10.0,
    ):
        self.registry = registry
        self.healthz = healthz
        self._metricsz = metricsz or (lambda: registry.snapshot())
        self._get_routes = dict(get_routes or {})
        self._post_routes = dict(post_routes or {})
        self._drain_grace_s = float(drain_grace_s)
        self._accepting = True
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._drained = threading.Event()
        self._drained.set()
        # Per-handler-thread request headers: mounted routes read them via
        # request_headers() (the Traceparent propagation seam, ISSUE 13)
        # without changing the 3-arg route signature existing routes use.
        self._tls = threading.local()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # Socket timeout per request: BaseHTTPRequestHandler applies
            # it to the connection, and handle_one_request converts a
            # timed-out request line into a closed connection — the
            # hung-client bound.
            timeout = float(read_timeout_s)
            protocol_version = "HTTP/1.1"

            def _reply(self, status, ctype, body, headers=None):
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, str(v))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    # The client vanished mid-write (a long-poll whose
                    # poller timed out): nothing to salvage, and the
                    # serve path must not hear about it.
                    self.close_connection = True

            def _json(self, status, payload, headers=None):
                self._reply(
                    status, "application/json",
                    json.dumps(payload).encode(), headers,
                )

            def _route(self, routes, path):
                fn = routes.get(path)
                if fn is not None:
                    return fn
                for prefix, candidate in routes.items():
                    if prefix.endswith("/") and path.startswith(prefix):
                        return candidate
                return None

            def _read_body(self):
                """The bounded-body read, or None after replying with the
                typed refusal (411 undeclared / 413 oversized / 408 slow)."""
                length = self.headers.get("Content-Length")
                if length is None:
                    self._json(411, {"error": "length_required"})
                    return None
                try:
                    length = int(length)
                except ValueError:
                    self._json(400, {"error": "bad_content_length"})
                    return None
                if length < 0 or length > outer._max_body_bytes:
                    self._json(413, {
                        "error": "body_too_large",
                        "max_bytes": outer._max_body_bytes,
                    })
                    self.close_connection = True
                    return None
                try:
                    return self.rfile.read(length)
                except (TimeoutError, socket.timeout):
                    # Declared a body, never sent it: cut the connection
                    # (the read-timeout half of the hung-client bound).
                    self.close_connection = True
                    return None

            def _handle(self, method):
                if not outer._accepting:
                    self._json(503, {"error": "shutting_down"})
                    self.close_connection = True
                    return
                with outer._inflight_lock:
                    outer._inflight += 1
                    outer._drained.clear()
                try:
                    self._dispatch(method)
                finally:
                    with outer._inflight_lock:
                        outer._inflight -= 1
                        if outer._inflight == 0:
                            outer._drained.set()

            def _dispatch(self, method):
                path, _, query = self.path.partition("?")
                outer._tls.headers = self.headers
                try:
                    if method == "GET":
                        if path == "/metrics":
                            self._reply(
                                200,
                                "text/plain; version=0.0.4; charset=utf-8",
                                outer.registry.prometheus_text().encode(),
                            )
                            return
                        if path == "/metricsz":
                            self._json(200, outer._metricsz())
                            return
                        if path == "/healthz":
                            payload = (
                                outer.healthz() if outer.healthz
                                else {"status": "ok"}
                            )
                            self._json(200, payload)
                            return
                        fn = self._route(outer._get_routes, path)
                        if fn is not None:
                            self._reply(*fn(path, query, None))
                            return
                        self._json(404, {"error": "not_found"})
                        return
                    # POST
                    fn = self._route(outer._post_routes, path)
                    if fn is None:
                        self._json(404, {"error": "not_found"})
                        return
                    body = self._read_body()
                    if body is None:
                        return
                    self._reply(*fn(path, query, body))
                except Exception as e:  # noqa: BLE001 — a request must not kill serving
                    self._json(500, {
                        "error": "internal",
                        "detail": f"{type(e).__name__}: {e}",
                    })

            def do_GET(self):  # noqa: N802 (http.server API)
                self._handle("GET")

            def do_POST(self):  # noqa: N802 (http.server API)
                self._handle("POST")

            def log_message(self, *args):  # silence per-request stderr noise
                pass

        self._max_body_bytes = int(max_body_bytes)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-obs-http", daemon=True
        )
        self._thread.start()

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def request_headers(self):
        """The CURRENT request's headers (handler threads only; {} when
        called off a handler) — how a mounted route reads the
        ``Traceparent`` propagation header without a signature change."""
        return getattr(self._tls, "headers", None) or {}

    def close(self) -> None:
        """Stop accepting, drain in-flight handlers (bounded by
        ``drain_grace_s``), then tear the listener down. Idempotent."""
        self._accepting = False
        self._httpd.shutdown()
        # In-flight handlers run on daemon threads the shutdown above does
        # not touch; give them the grace period to finish their replies.
        self._drained.wait(timeout=self._drain_grace_s)
        self._httpd.server_close()
        self._thread.join(timeout=5)


def wait_port_file(path: str, timeout_s: float, proc=None) -> dict:
    """Poll for the atomic port file a serving host writes when ready
    (``serve/host.py``) and return its payload. ``proc`` (optional
    ``subprocess.Popen``) short-circuits the wait when the host died
    before ever becoming ready."""
    import os

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except ValueError:
                pass  # racing the atomic rename's predecessor — retry
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"serving host exited rc={proc.returncode} before ready"
            )
        time.sleep(0.05)
    raise TimeoutError(f"serving host never wrote {path} in {timeout_s}s")
