"""Pretrained-weight loading (the ``use_pretrained`` path).

The reference downloads torchvision ImageNet weights (``models.py:33`` etc.).
This environment has no torchvision and no network egress, so pretrained means
"load a converted checkpoint from ``pretrained_dir``" produced offline by
``tools/convert_torchvision.py`` (which maps a torchvision state_dict onto
this zoo's param tree). The backbone loads; the ``num_classes`` head keeps its
fresh initialization — exactly the reference's head-replacement semantics
(``models.py:36`` and friends).
"""

from __future__ import annotations

import os
from typing import Any

import jax
from flax import serialization

from mpi_pytorch_tpu.models.common import head_filter


# Architectures with a torchvision weight mapping — the reference's seven
# plus mobilenet_v2 and efficientnet_b0. Single source of truth:
# tools/convert_torchvision.py imports this list, and
# torch_mapping._module_prefix must cover exactly these names. The remaining
# beyond-parity family (vit_*) is random-init by design: this zoo's ViT
# variants have no torchvision-checkpoint counterpart.
CONVERTIBLE_MODELS = (
    "resnet18", "resnet34", "alexnet", "vgg11_bn",
    "squeezenet1_0", "densenet121", "inception_v3", "mobilenet_v2",
    "efficientnet_b0",
)


def pretrained_path(model_name: str, pretrained_dir: str) -> str:
    return os.path.join(pretrained_dir, f"{model_name}.msgpack")


def load_pretrained(
    model_name: str, variables: dict, pretrained_dir: str,
    stem_s2d: bool = False,
) -> dict:
    """Overlay converted backbone weights onto freshly-initialized variables,
    keeping the head's fresh init (head shape depends on num_classes).

    ``stem_s2d``: the converted file always stores the canonical 7×7 stem
    kernel; space-to-depth models load it through the exact
    ``s2d_stem_kernel`` transform (models/resnet.py), so one converted
    artifact serves both stem layouts."""
    if model_name not in CONVERTIBLE_MODELS:
        raise ValueError(
            f"use_pretrained=True is not available for {model_name!r}: the "
            "torchvision converter covers these architectures "
            f"({', '.join(CONVERTIBLE_MODELS)}); the beyond-parity families "
            "train from random init (set use_pretrained=False)."
        )
    path = pretrained_path(model_name, pretrained_dir)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"use_pretrained=True but no converted weights at {path}. Run "
            "tools/convert_torchvision.py on a machine with torchvision, or set "
            "use_pretrained=False (random init)."
        )
    with open(path, "rb") as f:
        data = f.read()
    if stem_s2d:
        from mpi_pytorch_tpu.models.resnet import s2d_stem_kernel

        loaded = serialization.msgpack_restore(data)
        loaded["params"]["conv1"]["kernel"] = s2d_stem_kernel(
            loaded["params"]["conv1"]["kernel"]
        )
    else:
        loaded = serialization.from_bytes(variables, data)

    def overlay(path_keys, fresh, pre) -> Any:
        keys = [getattr(k, "key", str(k)) for k in path_keys]
        if not head_filter(keys) and fresh.shape != pre.shape:
            raise ValueError(
                f"pretrained leaf {'/'.join(keys)} has shape {pre.shape}, "
                f"model expects {fresh.shape}"
            )
        return fresh if head_filter(keys) else pre

    return jax.tree_util.tree_map_with_path(overlay, variables, loaded)
