"""Tests for fleet-wide distributed tracing + the central collector
(ISSUE 13): W3C-style traceparent propagation surviving the REAL HTTP
stack (jax-free fake server), a failover'd request's trace carrying BOTH
dispatch attempts with exactly one completion, probe-RTT skew correction
ordering cross-host spans under an injected clock offset, tail sampling
keeping every failed/slow/re-dispatched trace and ~rate of the rest,
collector counter-reset detection (a restart is never a negative rate),
schema-v9 record shapes, the trace_report waterfall/critical-path
assembly, the bench per_phase columns + regression-gate learning, and
the end-to-end real-server span thread (serve records gain trace_ids
only when traced — byte-identical off).
"""

import json
import os
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ context basics


def test_traceparent_roundtrip():
    from mpi_pytorch_tpu.obs.context import (
        format_traceparent,
        mint_trace,
        parse_traceparent,
    )

    ctx = mint_trace()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert parse_traceparent(format_traceparent(ctx)) == ctx
    unsampled = mint_trace(sampled=False)
    back = parse_traceparent(format_traceparent(unsampled))
    assert back is not None and back.sampled is False
    child = ctx.child()
    assert child.trace_id == ctx.trace_id and child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    None, "", "junk", "00-zz-11-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
    "99",
])
def test_traceparent_malformed_is_untraced(bad):
    from mpi_pytorch_tpu.obs.context import parse_traceparent

    assert parse_traceparent(bad) is None


def test_span_recorder_ring_cursor_and_dropped():
    from mpi_pytorch_tpu.obs.context import SpanRecorder

    rec = SpanRecorder(capacity=4)
    now = time.time()
    for i in range(6):
        rec.add(name=f"s{i}", trace="t" * 32, t0=now, t1=now + 0.001,
                host="h0")
    out = rec.export(0)
    assert [s["name"] for s in out["spans"]] == ["s2", "s3", "s4", "s5"]
    assert out["dropped"] == 2  # s0/s1 lapped before the cursor saw them
    assert out["next_seq"] == 6
    again = rec.export(out["next_seq"])
    assert again["spans"] == [] and again["dropped"] == 0
    rec.add(name="s6", trace="t" * 32, t0=now, t1=now, host="h0")
    tail = rec.export(6)
    assert [s["name"] for s in tail["spans"]] == ["s6"]
    assert tail["start_ts"] == rec.start_ts


def test_head_sampling_deterministic_rate():
    from mpi_pytorch_tpu.obs.context import head_keep, new_trace_id

    ids = [new_trace_id() for _ in range(2000)]
    kept = [t for t in ids if head_keep(t, 0.2)]
    assert kept == [t for t in ids if head_keep(t, 0.2)]  # deterministic
    assert 0.1 < len(kept) / len(ids) < 0.3  # ~rate
    assert all(head_keep(t, 1.0) for t in ids[:10])
    assert not any(head_keep(t, 0.0) for t in ids[:10])


# --------------------------------------------- fakes (jax-free host handles)


class TracingFakeHost:
    """HostHandle-shaped fake that records incoming trace contexts and
    serves spans/snapshots like a real host — the router/collector unit
    target."""

    transport = "local"

    def __init__(self, name, index, fail_submits=0):
        from mpi_pytorch_tpu.obs.context import SpanRecorder

        self.name = name
        self.index = index
        self.queue_capacity = 64
        self.buckets = (1, 4)
        self.active_buckets = (1, 4)
        self.max_wait_ms = 2.0
        self.precision = "bf16"
        self.precisions = ("bf16",)
        self.parity_top1 = None
        self.fail_submits = fail_submits  # host-shaped future failures
        self.seen_traces = []
        self.spans = SpanRecorder()
        self.start_ts = time.time()
        self._seq = 0
        self.counters = {"serve/requests": 0.0, "serve/served": 0.0,
                         "serve/rejected": 0.0, "serve/failed": 0.0}
        self.clock_skew_s = 0.0
        self.closed = False

    def submit(self, payload, trace=None):
        from mpi_pytorch_tpu.serve.batcher import HostUnavailableError

        self.seen_traces.append(trace)
        self.counters["serve/requests"] += 1
        fut = Future()
        if self.fail_submits > 0:
            self.fail_submits -= 1
            self.counters["serve/failed"] += 1
            fut.set_exception(
                HostUnavailableError(f"{self.name} died mid-flight")
            )
            return fut
        self.counters["serve/served"] += 1
        if trace is not None:
            # Host-side spans stamped on the HOST's (possibly skewed)
            # clock — what the collector must correct.
            now = time.time() + self.clock_skew_s
            root = self.spans.add(
                name="serve/request", trace=trace.trace_id,
                parent=trace.span_id, t0=now, t1=now + 0.004,
                host=self.name, attrs={"status": "ok"},
            )
            self.spans.add(
                name="serve/device", trace=trace.trace_id,
                parent=root["span"], t0=now + 0.001, t1=now + 0.003,
                host=self.name,
            )
        fut.set_result(np.zeros((3,), np.int32))
        return fut

    # -- probe surface -------------------------------------------------
    def snapshot(self):
        self._seq += 1
        return {
            "counters": dict(self.counters),
            "gauges": {"serve/queue_depth": 1.0},
            "histograms": {},
            "seq": self._seq,
            "start_ts": self.start_ts,
        }

    def restart(self):
        """Simulate a process restart: counters zero, seq space fresh."""
        from mpi_pytorch_tpu.obs.context import SpanRecorder

        self.start_ts = time.time() + 1e-3
        self._seq = 0
        self.counters = {k: 0.0 for k in self.counters}
        self.spans = SpanRecorder()
        self.spans.start_ts = self.start_ts

    def traces(self, since=0):
        return self.spans.export(since)

    def clock_probe(self):
        return (0.002, self.clock_skew_s)

    def alive(self):
        return not self.closed

    def qsize(self):
        return 0

    def stats(self):
        return {}

    def set_max_wait_ms(self, v):
        self.max_wait_ms = float(v)

    def set_active_buckets(self, b):
        self.active_buckets = tuple(b)

    def set_precision(self, p):
        pass

    def compiles_after_warmup(self):
        return 0

    def close(self, drain=True):
        self.closed = True

    def kill(self):
        self.closed = True


class ListWriter:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append({"ts": time.time(), **rec})

    def close(self):
        pass


# ------------------------------------------------- router-side propagation


def _router(hosts, **kw):
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("trace_sample_rate", 1.0)
    return FleetRouter(hosts, **kw)


def test_router_mints_and_propagates_trace():
    h0 = TracingFakeHost("h0", 0)
    router = _router([h0])
    try:
        router.submit(np.zeros((2,))).result(timeout=5)
        assert len(h0.seen_traces) == 1
        ctx = h0.seen_traces[0]
        assert ctx is not None and len(ctx.trace_id) == 32
        spans = router.spans.export(0)["spans"]
        names = {s["name"] for s in spans}
        assert {"route/request", "route/admission", "route/dispatch"} <= names
        root = next(s for s in spans if s["name"] == "route/request")
        assert root["trace"] == ctx.trace_id
        assert root["attrs"]["status"] == "ok"
        dispatch = next(s for s in spans if s["name"] == "route/dispatch")
        # The host parents under the DISPATCH child context, so its spans
        # join the same trace under the right parent.
        assert dispatch["span"] == ctx.span_id
        assert dispatch["parent"] == root["span"]
    finally:
        router.close()


def test_tracing_off_is_inert():
    h0 = TracingFakeHost("h0", 0)
    router = _router([h0], trace_sample_rate=0.0)
    try:
        router.submit(np.zeros((2,))).result(timeout=5)
        assert h0.seen_traces == [None]
        assert router.spans is None
    finally:
        router.close()


def test_failover_trace_has_both_attempts_one_completion():
    """The acceptance shape: a re-dispatched request's trace carries BOTH
    dispatch attempts (first failed, second ok) and exactly ONE
    end-to-end completion."""
    bad = TracingFakeHost("h0", 0, fail_submits=1)
    good = TracingFakeHost("h1", 1)
    writer = ListWriter()
    router = _router([bad, good], metrics=writer, fail_probes=100)
    try:
        # Fresh snapshots → equal scores → _pick takes the FIRST host
        # deterministically (the stale-path po2 fallback would randomize
        # which host sees attempt 1).
        deadline = time.time() + 2
        while time.time() < deadline:
            with router._lock:
                fresh = all(
                    router._state[h.name].score is not None
                    for h in (bad, good)
                )
            if fresh:
                break
            time.sleep(0.01)
        router.submit(np.zeros((2,))).result(timeout=5)
        # Both hosts saw the SAME trace id on different dispatch spans.
        trace_id = bad.seen_traces[0].trace_id
        assert good.seen_traces[0].trace_id == trace_id
        assert bad.seen_traces[0].span_id != good.seen_traces[0].span_id
        deadline = time.time() + 5
        while time.time() < deadline:
            spans = router.spans.export(0)["spans"]
            if sum(1 for s in spans if s["name"] == "route/request") == 1:
                break
            time.sleep(0.01)
        dispatches = [s for s in spans if s["name"] == "route/dispatch"]
        assert len(dispatches) == 2, dispatches
        outcomes = sorted(d["attrs"]["outcome"] for d in dispatches)
        assert outcomes[0].startswith("failed:") and outcomes[1] == "ok"
        assert sorted(d["attrs"]["attempt"] for d in dispatches) == [1, 2]
        roots = [s for s in spans if s["name"] == "route/request"]
        assert len(roots) == 1  # exactly one completion
        assert roots[0]["attrs"]["redispatches"] == 1
    finally:
        router.close()


def test_front_door_rejection_leaves_root_span():
    h0 = TracingFakeHost("h0", 0)
    router = _router([h0], admission_tokens=1)
    from mpi_pytorch_tpu.serve.batcher import QueueFullError

    try:
        with router._lock:
            router._tokens = 0  # deterministically exhausted
        with pytest.raises(QueueFullError):
            router.submit(np.zeros((2,)))
        spans = router.spans.export(0)["spans"]
        root = next(s for s in spans if s["name"] == "route/request")
        assert root["attrs"]["status"] == "rejected"
    finally:
        router.close()


def test_route_records_carry_trace_ids_only_when_traced():
    h0 = TracingFakeHost("h0", 0)
    writer = ListWriter()
    router = _router([h0], metrics=writer, route_record_every=1)
    try:
        router.submit(np.zeros((2,))).result(timeout=5)
        router._write_route_records(force=True)
        routes = [r for r in writer.records if r["kind"] == "route"]
        assert routes and routes[-1]["trace_ids"] == [
            h0.seen_traces[0].trace_id
        ]
    finally:
        router.close()
    # And an UNTRACED router writes byte-identical v8 route records.
    h1 = TracingFakeHost("h1", 1)
    writer2 = ListWriter()
    router2 = _router([h1], metrics=writer2, trace_sample_rate=0.0,
                      route_record_every=1)
    try:
        router2.submit(np.zeros((2,))).result(timeout=5)
        router2._write_route_records(force=True)
        routes = [r for r in writer2.records if r["kind"] == "route"]
        assert routes and "trace_ids" not in routes[-1]
    finally:
        router2.close()


def test_kill_gate_fault_record_stamps_trace_id(monkeypatch):
    monkeypatch.setenv("MPT_FAULT_SERVE_KILL_HOST", "0")
    monkeypatch.setenv("MPT_FAULT_SERVE_KILL_AFTER", "1")
    h0 = TracingFakeHost("h0", 0)
    h1 = TracingFakeHost("h1", 1)
    writer = ListWriter()
    router = _router([h0, h1], metrics=writer, fail_probes=100)
    try:
        futs = [router.submit(np.zeros((2,))) for _ in range(4)]
        for f in futs:
            f.result(timeout=5)
        deadline = time.time() + 5
        while time.time() < deadline:
            faults = [r for r in writer.records if r["kind"] == "fault"]
            if faults:
                break
            time.sleep(0.01)
        assert faults and faults[0]["reason"] == "injected_host_kill"
        assert faults[0]["trace_id"] == h0.seen_traces[0].trace_id
    finally:
        router.close()


# ----------------------------------------------------- wire-path propagation


class FakeWireServer:
    """jax-free duck-typed InferenceServer with the trace surface — the
    real-HTTP-stack propagation target (test_remote_fleet's pattern)."""

    name = "h0"

    def __init__(self):
        from mpi_pytorch_tpu.obs.context import SpanRecorder

        self.spans = SpanRecorder()
        self.start_ts = time.time()
        self.seen_traces = []

    def submit(self, image, trace=None):
        self.seen_traces.append(trace)
        fut = Future()
        if trace is not None:
            now = time.time()
            self.spans.add(
                name="serve/request", trace=trace.trace_id,
                parent=trace.span_id, t0=now, t1=now + 0.002,
                host=self.name, attrs={"status": "ok"},
            )
        fut.set_result(np.arange(3, dtype=np.int32))
        return fut

    def traces(self, since=0):
        return self.spans.export(since)

    def close(self, drain=True):
        pass


def test_traceparent_survives_real_http_stack():
    """front door ctx → Traceparent header → ServingHost → server.submit
    → host span ring → GET /tracez, plus the client-side wire spans —
    the whole propagation seam over the REAL HTTP stack, no jax."""
    from mpi_pytorch_tpu.obs.context import SpanRecorder, mint_trace
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
    from mpi_pytorch_tpu.serve.host import ServingHost

    server = FakeWireServer()
    host = ServingHost(server, port=0)
    spans = SpanRecorder()
    try:
        remote = RemoteHost(
            f"http://127.0.0.1:{host.port}", name="h0", index=0,
            poll_slice_s=0.2, result_timeout_s=5.0, probe_retries=1,
            spans=spans,
        )
        ctx = mint_trace().child()
        preds = remote.submit(np.zeros((4, 4, 3), np.uint8),
                              trace=ctx).result(timeout=5)
        assert preds.shape == (3,)
        # The SAME trace id crossed the wire.
        assert len(server.seen_traces) == 1
        got = server.seen_traces[0]
        assert got is not None
        assert got.trace_id == ctx.trace_id
        assert got.span_id == ctx.span_id  # parents under the wire ctx
        # Host-side spans export over the REAL /tracez endpoint.
        out = remote.traces(0)
        assert [s["name"] for s in out["spans"]] == ["serve/request"]
        assert out["spans"][0]["trace"] == ctx.trace_id
        # The export's generation stamp is the RECORDER's start (what the
        # collector keys its cursor on), faithful across the wire.
        assert out["start_ts"] == pytest.approx(server.spans.start_ts)
        # Client-side wire spans landed in the router-process ring.
        wire_names = sorted(
            s["name"] for s in spans.export(0)["spans"]
        )
        assert wire_names == ["wire/result", "wire/submit"]
        # Clock probe: healthz has no "time" on this fake → offset 0.
        rtt, offset = remote.clock_probe()
        assert rtt >= 0 and offset == 0.0
        # An untraced submit stays untraced (no header, no spans).
        remote.submit(np.zeros((4, 4, 3), np.uint8)).result(timeout=5)
        assert server.seen_traces[1] is None
        remote._pool.shutdown(wait=False, cancel_futures=True)
    finally:
        host.close()


# ------------------------------------------------------------- collector


def _collector(hosts, writer=None, **kw):
    from mpi_pytorch_tpu.obs.collector import FleetCollector

    kw.setdefault("sample_rate", 0.0)
    kw.setdefault("trace_linger_s", 0.0)
    return FleetCollector(lambda: hosts, metrics=writer, **kw)


def test_collector_skew_correction_orders_cross_host_spans(tmp_path):
    """Host h1's clock runs 500 ms AHEAD; without correction its spans
    would start before the router's root. The collector subtracts the
    probe-measured offset at ingest, restoring causal order."""
    from mpi_pytorch_tpu.obs.context import SpanRecorder, mint_trace

    h1 = TracingFakeHost("h1", 1)
    h1.clock_skew_s = 0.5
    router_spans = SpanRecorder()
    trace_out = str(tmp_path / "spans.jsonl")
    col = _collector([h1], spans=router_spans, trace_out=trace_out,
                     sample_rate=1.0)
    col.tick()  # baseline: measures h1's offset before any span lands
    ctx = mint_trace()
    t0 = time.time()
    h1.submit(np.zeros((2,)), trace=ctx.child()).result(timeout=5)
    router_spans.add(
        name="route/request", trace=ctx.trace_id, span=ctx.span_id,
        t0=t0, t1=time.time() + 0.01, host="router",
        attrs={"status": "ok", "redispatches": 0},
    )
    col.tick()
    col.stop(final=True)
    assert col.offset_ms("h1") == pytest.approx(500.0, abs=50.0)
    spans = [json.loads(line) for line in open(trace_out)]
    by_name = {s["name"]: s for s in spans}
    root, dev = by_name["route/request"], by_name["serve/device"]
    # Corrected: the host-side span falls INSIDE the root window.
    assert root["t0"] <= by_name["serve/request"]["t0"] <= root["t1"]
    assert root["t0"] <= dev["t0"] and dev["t1"] <= root["t1"] + 0.05
    assert dev["clock_offset_ms"] == pytest.approx(500.0, abs=50.0)


def _root_span(recorder, trace_id, dur_ms=1.0, status="ok", redispatches=0):
    now = time.time()
    recorder.add(
        name="route/request", trace=trace_id, t0=now,
        t1=now + dur_ms / 1e3, host="router",
        attrs={"status": status, "redispatches": redispatches},
    )


def test_tail_sampling_keeps_failed_slow_redispatched(tmp_path):
    from mpi_pytorch_tpu.obs.context import (
        SpanRecorder,
        head_keep,
        new_trace_id,
    )

    spans = SpanRecorder(capacity=16384)
    ok_ids = [new_trace_id() for _ in range(400)]
    for t in ok_ids:
        _root_span(spans, t)
    special = {
        "failed": new_trace_id(), "rejected": new_trace_id(),
        "redisp": new_trace_id(), "slow": new_trace_id(),
    }
    _root_span(spans, special["failed"], status="failed:RuntimeError")
    _root_span(spans, special["rejected"], status="rejected")
    _root_span(spans, special["redisp"], redispatches=1)
    _root_span(spans, special["slow"], dur_ms=500.0)
    trace_out = str(tmp_path / "spans.jsonl")
    col = _collector([], spans=spans, trace_out=trace_out,
                     sample_rate=0.1, slow_ms=100.0)
    col.tick()
    col.stop(final=True)
    kept = {json.loads(line)["trace"] for line in open(trace_out)}
    # Every special trace survives regardless of the head-sample draw...
    assert set(special.values()) <= kept
    # ...and of the ordinary ones, exactly the deterministic head sample.
    assert kept - set(special.values()) == {
        t for t in ok_ids if head_keep(t, 0.1)
    }
    assert col.stats["traces_kept"] == len(kept)
    assert col.stats["traces_dropped"] == 404 - len(kept)


def test_fleet_event_pins_open_traces(tmp_path):
    """A failover record passing through the tapped stream pins every
    in-flight trace — kept even though head sampling would drop them."""
    from mpi_pytorch_tpu.obs.context import SpanRecorder, new_trace_id

    spans = SpanRecorder()
    trace_out = str(tmp_path / "spans.jsonl")
    writer = ListWriter()
    col = _collector([], writer=writer, spans=spans, trace_out=trace_out)
    tapped = col.tap(writer)
    victim = new_trace_id()
    now = time.time()
    spans.add(name="route/dispatch", trace=victim, t0=now, t1=now + 0.001,
              host="router", attrs={"host": "h1", "attempt": 1,
                                    "outcome": "ok"})
    col.tick()  # victim is now an OPEN trace (no root yet)
    tapped.write({"kind": "fleet", "event": "failover", "host": "h1"})
    assert col.stats["traces_pinned"] == 1
    _root_span(spans, victim)  # completes fine — but stays pinned
    col.tick()
    col.stop(final=True)
    kept = {json.loads(line)["trace"] for line in open(trace_out)}
    assert victim in kept
    # The tapped record itself reached the inner writer untouched.
    assert writer.records[-1]["event"] == "failover"


def test_collector_reset_detection_never_negative_rate():
    h0 = TracingFakeHost("h0", 0)
    writer = ListWriter()
    col = _collector([h0], writer=writer, timeline_every=1000)
    col.tick()
    h0.counters["serve/requests"] = 100.0
    h0.counters["serve/served"] = 100.0
    time.sleep(0.01)
    col.tick()  # positive deltas land
    h0.restart()  # counters back to zero, fresh seq + start_ts
    time.sleep(0.01)
    col.tick()  # must re-baseline, not book -100/dt
    h0.counters["serve/requests"] = 5.0
    time.sleep(0.01)
    col.tick()
    col.stop(final=True)
    assert col.stats["resets"] == 1
    assert col.stats["negative_deltas"] == 0
    rates = [
        v for (host, metric), ring in col._series.items()
        if metric.endswith(":rate") for _, v in ring
    ]
    assert rates and all(v >= 0 for v in rates)
    timelines = [r for r in writer.records if r["kind"] == "timeline"]
    assert any(r["resets"] == 1 for r in timelines)


def test_collector_timeline_records_schema_clean(tmp_path):
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    h0 = TracingFakeHost("h0", 0)
    path = str(tmp_path / "m.jsonl")

    class FileWriter:
        def __init__(self):
            self._fh = open(path, "a", buffering=1)

        def write(self, rec):
            self._fh.write(json.dumps({"ts": time.time(), **rec}) + "\n")

        def close(self):
            self._fh.close()

    writer = FileWriter()
    col = _collector([h0], writer=writer, timeline_every=1)
    for _ in range(3):
        h0.counters["serve/requests"] += 7
        time.sleep(0.01)
        col.tick()
    col.stop(final=True)
    writer.close()
    assert validate_jsonl(path) == []
    recs = [json.loads(line) for line in open(path)]
    assert any(
        r["kind"] == "timeline" and r["metric"] == "serve/requests:rate"
        for r in recs
    )
    assert all(
        v >= 0 for r in recs if r["kind"] == "timeline"
        for _, v in r["points"]
    )


def test_collector_cursor_resets_with_recorder_generation():
    """A restarted host's /tracez seq space starts over; the stale cursor
    must rewind instead of silently missing every new span."""
    h0 = TracingFakeHost("h0", 0)
    col = _collector([h0], sample_rate=1.0)
    from mpi_pytorch_tpu.obs.context import mint_trace

    h0.submit(np.zeros((2,)), trace=mint_trace().child()).result(timeout=5)
    col.tick()
    seen_before = col.stats["spans_seen"]
    assert seen_before == 2  # request + device spans
    h0.restart()
    h0.submit(np.zeros((2,)), trace=mint_trace().child()).result(timeout=5)
    col.tick()
    assert col.stats["spans_seen"] == seen_before + 2
    col.stop(final=False)


# ---------------------------------------------------------------- schema v9


def test_schema_v9_shapes():
    from mpi_pytorch_tpu.obs.schema import SCHEMA_VERSION, validate_record

    assert SCHEMA_VERSION >= 9
    assert validate_record({
        "kind": "timeline", "ts": 1.0, "host": "h0",
        "metric": "serve/queue_depth", "points": [[1.0, 2.0]],
        "window_s": 3.0, "clock_offset_ms": -0.2, "resets": 1,
    }) == []
    assert validate_record({"kind": "timeline", "ts": 1.0, "host": "h0"})
    assert validate_record({
        "kind": "serve", "ts": 1.0, "bucket": 4, "requests": 3,
        "queue_depth": 0, "fill_ratio": 0.75, "queue_wait_ms": 1.0,
        "device_ms": 2.0, "trace_ids": ["a" * 32],
    }) == []
    assert validate_record({
        "kind": "route", "ts": 1.0, "host": "h0", "requests": 2,
        "trace_ids": ["a" * 32, "b" * 32],
    }) == []
    assert validate_record({
        "kind": "fault", "ts": 1.0, "reason": "injected_host_kill",
        "trace_id": "a" * 32,
    }) == []
    assert validate_record({
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "requests": 10, "p50_ms": 1.0, "p95_ms": 2.0,
        "p99_ms": 3.0, "images_per_sec": 10.0,
        "per_phase": {"serve/device": {"count": 5, "p50_ms": 1.0,
                                       "p99_ms": 2.0}},
    }) == []


# ------------------------------------------------------------- trace_report


def _write_spans(path, spans):
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")


def _span(trace, span, parent, name, host, pid, t0, t1, attrs=None):
    s = {"trace": trace, "span": span, "parent": parent, "name": name,
         "host": host, "pid": pid, "t0": t0, "t1": t1}
    if attrs:
        s["attrs"] = attrs
    return s


def _failover_trace(trace="f" * 32, base=1000.0):
    """A synthetic re-dispatched request crossing two processes."""
    return [
        _span(trace, "r" * 16, None, "route/request", "router", 100,
              base, base + 0.100,
              attrs={"status": "ok", "redispatches": 1}),
        _span(trace, "a" * 16, "r" * 16, "route/dispatch", "router", 100,
              base + 0.001, base + 0.040,
              attrs={"host": "h1", "attempt": 1,
                     "outcome": "failed:HostUnavailableError"}),
        _span(trace, "b" * 16, "r" * 16, "route/dispatch", "router", 100,
              base + 0.045, base + 0.099,
              attrs={"host": "h2", "attempt": 2, "outcome": "ok"}),
        _span(trace, "c" * 16, "b" * 16, "serve/request", "h2", 200,
              base + 0.050, base + 0.095, attrs={"status": "ok"}),
        _span(trace, "d" * 16, "c" * 16, "serve/queue", "h2", 200,
              base + 0.050, base + 0.060),
        _span(trace, "e" * 16, "c" * 16, "serve/device", "h2", 200,
              base + 0.061, base + 0.094),
    ]


def test_trace_report_waterfall_and_critical_path(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_report

    path = str(tmp_path / "spans.jsonl")
    _write_spans(path, _failover_trace())
    rc = trace_report.main([path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "dispatch attempts=2" in out
    assert "completions=1" in out
    assert "-> h1 [failed:HostUnavailableError]" in out
    assert "-> h2 [ok]" in out
    assert "2 process(es)" in out
    # The failed 39 ms attempt (no children) + the second attempt's wire
    # overhead charge route/dispatch 48 ms of self-time — failover churn
    # owns this tail, and the report says so.
    assert "critical path: phase route/dispatch owns the p99" in out


def test_trace_report_json_and_selection(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_report

    path = str(tmp_path / "spans.jsonl")
    fast = "0" * 31 + "1"
    spans = _failover_trace() + [
        _span(fast, "1" * 16, None, "route/request", "router", 100,
              2000.0, 2000.001,
              attrs={"status": "ok", "redispatches": 0}),
    ]
    _write_spans(path, spans)
    rc = trace_report.main([path, "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["traces"] == 2
    # The re-dispatched trace is picked for the waterfall unprompted.
    assert data["waterfalls"][0]["trace_id"] == "f" * 32
    assert data["waterfalls"][0]["dispatch_attempts"] == 2
    assert data["waterfalls"][0]["processes"] == 2
    assert data["phase_breakdown"]["route/dispatch"]["count"] == 2
    assert data["critical_path"]["phase"] == "route/dispatch"
    assert data["critical_path"]["charges_ms"]["serve/device"] == 33.0
    # Explicit --trace-id; unknown id is a loud rc=1.
    assert trace_report.main([path, "--trace-id", fast]) == 0
    assert trace_report.main([path, "--trace-id", "nope"]) == 1


def test_trace_report_rejects_malformed(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_report

    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"trace": "x"}) + "\n")
    assert trace_report.main([path]) == 1


# ------------------------------------------------ regression gate per_phase


def test_check_regression_learns_per_phase(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_regression

    def row(per_phase=None, p99=5.0):
        r = {"kind": "serve_bench", "ts": 1.0, "mode": "open",
             "buckets": "1,4", "max_wait_ms": 2.0, "requests": 100,
             "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": p99,
             "images_per_sec": 100.0, "model": "resnet18",
             "offered_rps": 400.0}
        if per_phase is not None:
            r["per_phase"] = per_phase
        return r

    new = tmp_path / "serve_bench.json"
    base = tmp_path / "serve_bench_prev.json"
    # A phase p99 regression beyond tolerance fails...
    base.write_text(json.dumps(row(
        {"serve/device": {"count": 9, "p50_ms": 1.0, "p99_ms": 2.0}}
    )) + "\n")
    new.write_text(json.dumps(row(
        {"serve/device": {"count": 9, "p50_ms": 1.0, "p99_ms": 4.0}}
    )) + "\n")
    violations = check_regression.check_serve(str(new), str(base), 10.0)
    assert len(violations) == 1 and "phase serve/device" in violations[0]
    # ...an OLD baseline without per_phase cannot (the learning rule).
    base.write_text(json.dumps(row()) + "\n")
    assert check_regression.check_serve(str(new), str(base), 10.0) == []
    # Phases only on one side skip; shared healthy phases pass.
    base.write_text(json.dumps(row(
        {"serve/queue": {"count": 9, "p50_ms": 1.0, "p99_ms": 2.0},
         "serve/device": {"count": 9, "p50_ms": 1.0, "p99_ms": 4.0}}
    )) + "\n")
    assert check_regression.check_serve(str(new), str(base), 10.0) == []


# ------------------------------------------- real server (end of the thread)


@pytest.fixture(scope="module")
def tiny_server():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.server import InferenceServer

    cfg = Config(
        model_name="resnet18", num_classes=16, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=2.0, serve_topk=3,
        serve_queue_depth=64, loader_workers=2,
        metrics_file="", log_file="", eval_log_file="",
    )
    cfg.validate_config()
    server = InferenceServer(cfg, load_checkpoint=False)
    yield server
    server.close()


def test_real_server_spans_and_record_trace_ids(tiny_server, tmp_path):
    """End of the propagation thread: a REAL InferenceServer records
    queue/preprocess/device spans for a traced request, stamps trace_ids
    on the flush's serve record, and leaves untraced records untouched."""
    from mpi_pytorch_tpu.obs.context import mint_trace

    writer = ListWriter()
    old_metrics = tiny_server._metrics
    tiny_server._metrics = writer
    try:
        img = np.zeros((32, 32, 3), np.uint8)
        # Untraced first: record must NOT carry trace_ids.
        tiny_server.submit(img).result(timeout=30)
        ctx = mint_trace().child()
        before = tiny_server.traces(0)["next_seq"]
        tiny_server.submit(img, trace=ctx).result(timeout=30)
        deadline = time.time() + 10
        while time.time() < deadline:
            out = tiny_server.traces(before)
            if any(s["name"] == "serve/request" for s in out["spans"]):
                break
            time.sleep(0.02)
        names = sorted(s["name"] for s in out["spans"])
        assert names == ["serve/device", "serve/preprocess",
                         "serve/queue", "serve/request"]
        assert all(s["trace"] == ctx.trace_id for s in out["spans"])
        root = next(
            s for s in out["spans"] if s["name"] == "serve/request"
        )
        assert root["parent"] == ctx.span_id
        kids = [s for s in out["spans"] if s["name"] != "serve/request"]
        assert all(s["parent"] == root["span"] for s in kids)
        # Phases are causally ordered on the wall clock.
        by = {s["name"]: s for s in out["spans"]}
        assert by["serve/queue"]["t0"] <= by["serve/preprocess"]["t0"]
        assert by["serve/preprocess"]["t1"] <= by["serve/device"]["t1"]
        serves = [r for r in writer.records if r["kind"] == "serve"]
        assert len(serves) >= 2
        assert "trace_ids" not in serves[0]
        assert serves[-1]["trace_ids"] == [ctx.trace_id]
        assert tiny_server.compiles_after_warmup() == 0
    finally:
        tiny_server._metrics = old_metrics


def test_real_server_snapshot_seq_and_start_ts(tiny_server):
    s1 = tiny_server.registry_snapshot()
    s2 = tiny_server.registry_snapshot()
    assert s2["seq"] > s1["seq"]
    assert s1["start_ts"] == s2["start_ts"] == tiny_server.start_ts
    health = tiny_server._healthz()
    assert health["start_ts"] == tiny_server.start_ts
    assert abs(health["time"] - time.time()) < 5.0
