"""Vision Transformer in Flax (NHWC patches, TPU-native) — the zoo's
sequence-model family.

The reference zoo is seven CNNs (``models.py:16-101``); it has no attention
anywhere (SURVEY §2c). This family goes beyond parity to make the
framework's long-context machinery part of the *training path* rather than
standalone ops: the encoder's attention dispatches, per config, to plain
full attention, ring attention (``ops/ring_attention.py``), or Ulysses
all-to-all (``ops/ulysses.py``) — the same exact-numerics SP strategies,
now inside a trainable classifier that plugs into the standard
``initialize_model``/trainer/checkpoint stack like any CNN.

Architecture: patch-embed conv → learned position embeddings → pre-LN
encoder blocks (MHA + GELU MLP, residual) → final LN → global average pool
→ ``head`` Dense. GAP instead of a class token keeps the token count equal
to the patch count, so the sequence axis divides evenly over an SP mesh
axis (a class token would make S = P+1, coprime with any ring size).
All blocks are homogeneous [B, S, hidden] → [B, S, hidden] maps — exactly
the stage shape ``parallel/pipeline.py`` pipelines.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn
from jax import nn as jnn

from mpi_pytorch_tpu.models.common import Dtype


class _ProjParams(nn.Module):
    """Parameter-only twin of ``nn.DenseGeneral((H, Dh))``: declares the
    SAME variable tree (``<name>/kernel`` [in, H, Dh] lecun-normal,
    ``<name>/bias`` [H, Dh] zeros — flax folds the init RNG by module
    path, so even the initial values match), without computing anything.
    Lets the fused-QKV path own the matmul while checkpoints remain
    interchangeable with the three-DenseGeneral layout."""

    features: tuple[int, int]
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, in_features: int):
        import numpy as np

        def kernel_init(rng, shape, dtype):
            # DenseGeneral initializes the kernel in FLATTENED 2-D form
            # (fan-in = in_features, fan-out = prod(features)) and then
            # reshapes — calling lecun-normal on the 3-D shape directly
            # would compute fan-in from the wrong axis.
            flat = nn.linear.default_kernel_init(
                rng, (in_features, int(np.prod(self.features))), dtype
            )
            return flat.reshape(shape)

        kernel = self.param(
            "kernel", kernel_init, (in_features,) + self.features, self.param_dtype
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), self.features, self.param_dtype
        )
        return kernel, bias


class MultiHeadAttention(nn.Module):
    """MHA whose core attention is pluggable: ``sp_strategy`` of ``none``
    (single-device attention — vanilla ``full``, the Pallas ``flash``
    kernel, or the Pallas ``fused-small`` tiny-S kernel, ``attn_impl``),
    ``ring``, or ``ulysses`` (both SP strategies shard the sequence over
    ``sp_mesh``'s first axis)."""

    num_heads: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    sp_strategy: str = "none"
    sp_mesh: Any = None
    # "full" materializes [B,H,S,S] scores; "flash" streams k/v blocks
    # through VMEM with an online softmax (ops/flash_attention.py — Pallas
    # on TPU, identical-math fallback elsewhere); "fused-small" computes
    # scores+softmax+AV in one VMEM pass per (batch·head) group — the
    # tiny-S (S≤128) regime where flash's block machinery loses
    # (ops/fused_attention_small.py). Same function all three ways.
    attn_impl: str = "full"
    # Multi-chip fused-small attention: mesh whose leading (data) axis the
    # Mosaic call shard_maps over (ops/fused_attention_small.py,
    # Multi-chip). None = single call (single chip, or an spmd-mode step
    # whose shard_map already hands the kernel per-shard batches). Only
    # consulted by attn_impl='fused-small'.
    dp_mesh: Any = None
    # One [D, 3·H·Dh] projection matmul instead of three [D, H·Dh] ones:
    # x is read once, one MXU dispatch, same param tree (docs/RESULTS.md
    # §4 vit_s16 row). Identical math — the concatenated matmul computes
    # each output column independently.
    qkv_fused: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from mpi_pytorch_tpu.ops.flash_attention import flash_attention
        from mpi_pytorch_tpu.ops.fused_attention_small import (
            fused_attention_small,
        )
        from mpi_pytorch_tpu.ops.ring_attention import (
            full_attention,
            ring_self_attention,
        )
        from mpi_pytorch_tpu.ops.ulysses import ulysses_self_attention

        hidden = x.shape[-1]
        if hidden % self.num_heads:
            raise ValueError(f"hidden {hidden} not divisible by {self.num_heads} heads")
        head_dim = hidden // self.num_heads
        if self.qkv_fused:
            shapes = (self.num_heads, head_dim)
            wq, bq = _ProjParams(shapes, self.param_dtype, name="q")(hidden)
            wk, bk = _ProjParams(shapes, self.param_dtype, name="k")(hidden)
            wv, bv = _ProjParams(shapes, self.param_dtype, name="v")(hidden)
            wqkv = jnp.concatenate(
                [w.reshape(hidden, -1) for w in (wq, wk, wv)], axis=1
            ).astype(self.dtype)
            bqkv = jnp.concatenate(
                [b.reshape(-1) for b in (bq, bk, bv)]
            ).astype(self.dtype)
            fused = x.astype(self.dtype) @ wqkv + bqkv  # [B, S, 3·H·Dh]
            q, k, v = (
                part.reshape(x.shape[:-1] + (self.num_heads, head_dim))
                for part in jnp.split(fused, 3, axis=-1)
            )
        else:
            proj = lambda name: nn.DenseGeneral(
                (self.num_heads, head_dim), dtype=self.dtype,
                param_dtype=self.param_dtype, name=name,
            )
            q, k, v = proj("q")(x), proj("k")(x), proj("v")(x)
        if self.sp_strategy == "none":
            if self.attn_impl == "flash":
                out = flash_attention(q, k, v)
            elif self.attn_impl == "fused-small":
                out = fused_attention_small(q, k, v, dp_mesh=self.dp_mesh)
            elif self.attn_impl == "full":
                out = full_attention(q, k, v)
            else:
                raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        elif self.sp_strategy == "ring":
            out = ring_self_attention(q, k, v, self.sp_mesh)
        elif self.sp_strategy == "ulysses":
            out = ulysses_self_attention(q, k, v, self.sp_mesh)
        else:
            raise ValueError(f"unknown sp_strategy {self.sp_strategy!r}")
        return nn.DenseGeneral(
            hidden, axis=(-2, -1), dtype=self.dtype,
            param_dtype=self.param_dtype, name="out",
        )(out)


class MoEMlp(nn.Module):
    """MoE replacement for the encoder MLP: top-k routed expert FFNs over
    the tokens of the whole batch ([B, S, d] flattened to [B·S, d]).

    Routing is group-wise (``group_size`` tokens per group, ``capacity``
    slots per expert PER GROUP — see ``ops/moe.py`` ``_grouped_routing`` for
    why that is the scalable dispatch). With ``ep_mesh`` set, experts are
    sharded over the mesh's first axis and tokens travel by ``all_to_all``;
    without it, the dense evaluation of the same grouped routing runs. The
    group clamps to the per-shard token count under EP, so the two layouts
    compute the same function whenever ``group_size`` ≤ tokens/shard (and
    the no-drop tests assert it). The load-balance aux loss is sown into the
    ``losses`` collection, which the train step sums into the total loss
    (``train/step.py``)."""

    num_experts: int
    mlp_dim: int
    k: int = 2
    capacity: int | None = None  # per routing group; None → 2x balanced load
    group_size: int = 64  # tokens per routing group (see ops/moe.py grouping)
    aux_weight: float = 0.01
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from mpi_pytorch_tpu.ops.moe import dense_moe, moe_forward, pick_group_size

        b, s, d = x.shape
        e, h = self.num_experts, self.mlp_dim
        init = nn.initializers.normal
        params = {
            "gate": self.param("gate", init(d**-0.5), (d, e), self.param_dtype),
            "w1": self.param("w1", init((2.0 / d) ** 0.5), (e, d, h), self.param_dtype),
            "b1": self.param("b1", nn.initializers.zeros, (e, h), self.param_dtype),
            "w2": self.param("w2", init((2.0 / h) ** 0.5), (e, h, d), self.param_dtype),
            "b2": self.param("b2", nn.initializers.zeros, (e, d), self.param_dtype),
        }
        params = {k_: v.astype(self.dtype) for k_, v in params.items()}
        tokens = x.reshape(b * s, d)
        # Tokens route in fixed-size groups (ops/moe.py _grouped_routing):
        # the [G, g, E, C] dispatch stays linear in token count. The group
        # is the largest divisor of the (per-shard) token count that fits
        # group_size; default capacity is 2x the perfectly-balanced
        # per-group load (the standard capacity_factor=2 headroom) —
        # overflow tokens in a group are dropped from that expert (combine
        # weight 0) like production MoEs.
        n = (
            self.ep_mesh.shape[self.ep_mesh.axis_names[0]]
            if self.ep_mesh is not None
            else 1
        )
        g = pick_group_size(b * s // n, self.group_size)
        cap = (
            self.capacity
            if self.capacity is not None
            else max(1, (2 * self.k * g) // e)
        )
        if self.ep_mesh is not None:
            y, aux = moe_forward(
                params, tokens, self.ep_mesh, k=self.k, capacity=cap,
                group_size=g,
            )
        else:
            y, aux = dense_moe(
                params, tokens, k=self.k, capacity=cap, group_size=g
            )
        self.sow(
            "losses", "moe_aux", self.aux_weight * aux,
            reduce_fn=lambda a, b_: a + b_, init_fn=lambda: jnp.zeros((), jnp.float32),
        )
        return y.reshape(b, s, d)


class EncoderBlock(nn.Module):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x)). The MLP is
    dense by default, or an expert-parallel MoE when ``num_experts > 0``."""

    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    sp_strategy: str = "none"
    sp_mesh: Any = None
    attn_impl: str = "full"
    dp_mesh: Any = None  # fused-small attention's shard_map mesh (see MHA)
    qkv_fused: bool = False
    num_experts: int = 0
    moe_k: int = 2
    moe_capacity: int | None = None
    moe_group_size: int = 64
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        ln = lambda name: nn.LayerNorm(
            dtype=self.dtype, param_dtype=self.param_dtype, name=name
        )
        y = MultiHeadAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            param_dtype=self.param_dtype, sp_strategy=self.sp_strategy,
            sp_mesh=self.sp_mesh, attn_impl=self.attn_impl,
            dp_mesh=self.dp_mesh, qkv_fused=self.qkv_fused, name="attn",
        )(ln("ln1")(x))
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y

        z = ln("ln2")(x)
        if self.num_experts > 0:
            z = MoEMlp(
                num_experts=self.num_experts, mlp_dim=self.mlp_dim,
                k=self.moe_k, capacity=self.moe_capacity,
                group_size=self.moe_group_size,
                dtype=self.dtype, param_dtype=self.param_dtype,
                ep_mesh=self.ep_mesh, name="moe",
            )(z)
        else:
            z = nn.Dense(
                self.mlp_dim, dtype=self.dtype, param_dtype=self.param_dtype,
                name="mlp1",
            )(z)
            z = jnn.gelu(z)
            z = nn.Dense(
                x.shape[-1], dtype=self.dtype, param_dtype=self.param_dtype,
                name="mlp2",
            )(z)
        z = nn.Dropout(self.dropout, deterministic=not train)(z)
        return x + z


class VisionTransformer(nn.Module):
    num_classes: int
    patch_size: int = 16
    hidden: int = 384
    depth: int = 12
    num_heads: int = 6
    mlp_dim: int = 1536
    dropout: float = 0.0
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    # Checkpoint each encoder block (nn.remat), same lever as the resnets'
    # remat_blocks: backward recomputes one homogeneous block at a time.
    remat_blocks: bool = False
    sp_strategy: str = "none"
    sp_mesh: Any = None
    attn_impl: str = "full"
    dp_mesh: Any = None  # fused-small attention's shard_map mesh (see MHA)
    qkv_fused: bool = False
    # MoE: every `moe_every`-th block (0-indexed blocks moe_every-1,
    # 2·moe_every-1, ...; =2 → the odd blocks) swaps its dense MLP for a
    # `num_experts`-expert MoE. 0 disables.
    moe_every: int = 0
    num_experts: int = 8
    moe_k: int = 2
    moe_capacity: int | None = None
    moe_group_size: int = 64
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        p = self.patch_size
        if x.shape[1] % p or x.shape[2] % p:
            raise ValueError(f"image {x.shape[1]}x{x.shape[2]} not divisible by patch {p}")
        x = nn.Conv(
            self.hidden, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, param_dtype=self.param_dtype, name="patch_embed",
        )(x)
        b, gh, gw, c = x.shape
        x = x.reshape(b, gh * gw, c)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, gh * gw, c),
            self.param_dtype,
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)

        block_cls = (
            nn.remat(EncoderBlock, static_argnums=(2,))  # (self, x, train)
            if self.remat_blocks
            else EncoderBlock
        )
        for i in range(self.depth):
            is_moe = self.moe_every > 0 and i % self.moe_every == self.moe_every - 1
            x = block_cls(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dropout=self.dropout, dtype=self.dtype,
                param_dtype=self.param_dtype, sp_strategy=self.sp_strategy,
                sp_mesh=self.sp_mesh, attn_impl=self.attn_impl,
                dp_mesh=self.dp_mesh, qkv_fused=self.qkv_fused,
                num_experts=self.num_experts if is_moe else 0,
                moe_k=self.moe_k, moe_capacity=self.moe_capacity,
                moe_group_size=self.moe_group_size,
                ep_mesh=self.ep_mesh, name=f"block{i}",
            )(x, train)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype, name="ln")(x)
        x = x.mean(axis=1)  # GAP over tokens (see module docstring)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            name="head",
        )(x)


def vit_s16(num_classes: int, **kw: Any) -> VisionTransformer:
    """ViT-Small/16: 384 hidden, 12 blocks, 6 heads."""
    return VisionTransformer(num_classes=num_classes, **kw)


def vit_b16(num_classes: int, **kw: Any) -> VisionTransformer:
    """ViT-Base/16: 768 hidden, 12 blocks, 12 heads."""
    return VisionTransformer(
        num_classes=num_classes, hidden=768, num_heads=12, mlp_dim=3072, **kw
    )


def vit_moe_s16(num_classes: int, **kw: Any) -> VisionTransformer:
    """ViT-Small/16 with 8-expert top-2 MoE MLPs in every other block —
    the EP training-path model (dense routing until ``ep_mesh`` is set)."""
    kw.setdefault("moe_every", 2)
    kw.setdefault("num_experts", 8)
    return VisionTransformer(num_classes=num_classes, **kw)
