"""Tests for the online inference subsystem (mpi_pytorch_tpu/serve/).

Covers the full acceptance surface: batcher semantics (buckets, deadline,
backpressure, drain), the end-to-end server with ZERO steady-state
compiles across a multi-bucket request mix (asserted via the obs
backend-compile counter), top-k parity between the plain predict path and
the fused ``head_predict`` argmax, the ``kind="serve"`` record schema, the
``tools/bench_serve.py --smoke`` CPU bench, the persistent compilation
cache satellite, and (slow) 2-process replicated serving.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env(**extra):
    """Subprocess env pinned to a clean CPU world (the image's
    sitecustomize would otherwise register the TPU plugin)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


# ---------------------------------------------------------------- batcher


def test_parse_buckets_and_pick_bucket():
    from mpi_pytorch_tpu.serve import parse_buckets, pick_bucket

    assert parse_buckets([32, 1, 8, 8]) == (1, 8, 32)
    with pytest.raises(ValueError):
        parse_buckets([])
    with pytest.raises(ValueError):
        parse_buckets([0, 4])
    buckets = (1, 8, 32)
    assert pick_bucket(1, buckets) == 1
    assert pick_bucket(2, buckets) == 8
    assert pick_bucket(8, buckets) == 8
    assert pick_bucket(9, buckets) == 32
    assert pick_bucket(1000, buckets) == 32  # flushes cap at the largest


def test_config_serve_knobs_validate():
    from mpi_pytorch_tpu.config import Config

    cfg = Config(serve_buckets="8,1,32")
    assert cfg.parsed_serve_buckets() == (1, 8, 32)
    with pytest.raises(ValueError):
        Config(serve_buckets="").validate_config()
    with pytest.raises(ValueError):
        Config(serve_buckets="1,frog").validate_config()
    with pytest.raises(ValueError):
        Config(serve_topk=0).validate_config()
    with pytest.raises(ValueError):
        Config(serve_topk=6).validate_config()
    with pytest.raises(ValueError):
        Config(serve_max_wait_ms=-1).validate_config()
    with pytest.raises(ValueError):
        Config(serve_queue_depth=0).validate_config()
    with pytest.raises(ValueError):
        Config(serve_topk=5, num_classes=3).validate_config()


def test_batcher_deadline_flush_and_drain():
    from mpi_pytorch_tpu.serve import DynamicBatcher, PendingRequest

    b = DynamicBatcher(buckets=(8,), max_wait_s=0.05, max_queue=16)
    t0 = time.monotonic()
    for i in range(3):
        b.submit(PendingRequest(payload=i, future=None))
    flush = b.next_flush()
    waited = time.monotonic() - t0
    assert [r.payload for r in flush] == [0, 1, 2]
    # Flushed by the deadline (3 < bucket 8), not instantly and not never.
    assert 0.03 <= waited < 2.0, waited

    # A full bucket flushes immediately, without sitting out the deadline.
    b2 = DynamicBatcher(buckets=(1, 4), max_wait_s=10.0, max_queue=16)
    for i in range(4):
        b2.submit(PendingRequest(payload=i, future=None))
    t0 = time.monotonic()
    assert len(b2.next_flush()) == 4
    assert time.monotonic() - t0 < 1.0

    # close() drains: queued requests still flush, then None forever.
    b2.submit(PendingRequest(payload=9, future=None))
    b2.close()
    assert [r.payload for r in b2.next_flush()] == [9]
    assert b2.next_flush() is None


def test_batcher_backlog_coalesces_full_buckets():
    """Regression (caught by a live flood drive): requests that sat in the
    queue past their deadline must still coalesce into the LARGEST bucket —
    the pre-fix behavior flushed one overdue request per batch, i.e. the
    batch-1 regime bucketing exists to avoid."""
    from mpi_pytorch_tpu.serve import DynamicBatcher, PendingRequest

    b = DynamicBatcher(buckets=(1, 8), max_wait_s=0.0, max_queue=64)
    for i in range(20):
        b.submit(PendingRequest(payload=i, future=None))
    time.sleep(0.01)  # everything queued is long past the 0 ms deadline
    sizes = [len(b.next_flush()) for _ in range(3)]
    assert sizes == [8, 8, 4], sizes


def test_batcher_backpressure_and_closed():
    from mpi_pytorch_tpu.serve import (
        DynamicBatcher,
        PendingRequest,
        QueueFullError,
        ServerClosedError,
    )

    b = DynamicBatcher(buckets=(4,), max_wait_s=1.0, max_queue=2)
    b.submit(PendingRequest(payload=0, future=None))
    b.submit(PendingRequest(payload=1, future=None))
    with pytest.raises(QueueFullError):
        b.submit(PendingRequest(payload=2, future=None))
    b.close()
    with pytest.raises(ServerClosedError):
        b.submit(PendingRequest(payload=3, future=None))


# ------------------------------------------------------------------ server


@pytest.fixture(scope="module")
def serve_cfg(tmp_path_factory):
    from mpi_pytorch_tpu.config import Config

    scratch = tmp_path_factory.mktemp("serve")
    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,8", serve_max_wait_ms=5.0, serve_topk=3,
        serve_queue_depth=64, loader_workers=4,
        metrics_file=str(scratch / "serve_metrics.jsonl"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    return cfg


@pytest.fixture(scope="module")
def server(serve_cfg):
    from mpi_pytorch_tpu.serve import InferenceServer

    srv = InferenceServer(serve_cfg, load_checkpoint=False)
    yield srv
    srv.close()


def test_server_zero_compiles_across_bucket_mix(server):
    """The acceptance invariant: after warmup, a request mix that lands in
    BOTH buckets (1 and 8; replicated and data-sharded executables)
    performs zero XLA compiles — measured by the backend-compile
    listener, not assumed."""
    rng = np.random.default_rng(0)
    images = [
        rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
        for _ in range(13)
    ]
    preds = server.predict_batch(images, timeout=120)
    assert preds.shape == (13, 3)
    assert preds.dtype == np.int32
    assert (preds >= 0).all() and (preds < 32).all()
    # Each row's top-k indices are distinct classes.
    assert all(len(set(row.tolist())) == 3 for row in preds)

    # A second wave, single + bulk, post-warmup: still zero compiles.
    one = server.predict_batch(images[:1], timeout=120)
    again = server.predict_batch(images, timeout=120)
    stats = server.stats()
    assert stats["compiles_after_warmup"] == 0, stats
    assert set(stats["buckets"]) == {1, 8}
    assert sum(stats["by_bucket"].values()) == stats["batches"]
    assert stats["served"] >= 27
    # Determinism: the same image yields the same top-k every time.
    np.testing.assert_array_equal(one[0], preds[0])
    np.testing.assert_array_equal(again, preds)


def test_server_preprocess_contract_and_bad_request(server):
    """Float requests pass through as already-normalized; a wrong-shape
    request fails ITS OWN future (typed), never the batch or the server."""
    from mpi_pytorch_tpu.serve import ServeError

    rng = np.random.default_rng(1)
    raw = rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
    from mpi_pytorch_tpu.data.pipeline import normalize_image

    normalized = normalize_image(raw.astype(np.float32) / 255.0)
    p_raw = server.predict_batch([raw], timeout=120)
    p_norm = server.predict_batch([normalized], timeout=120)
    np.testing.assert_array_equal(p_raw, p_norm)

    bad = server.submit(np.zeros((4, 4, 3), np.uint8))
    good = server.submit(raw)
    with pytest.raises(ServeError):
        bad.result(timeout=120)
    np.testing.assert_array_equal(good.result(timeout=120), p_raw[0])


def test_server_path_request_decodes(server, tmp_path):
    """A path request goes through the real decode→resize→normalize stage
    (native → PIL fallback) and predicts identically to submitting the
    same pixels directly (PNG = lossless, so the arrays match exactly)."""
    from PIL import Image

    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
    path = tmp_path / "req.png"
    Image.fromarray(raw).save(path)
    from_path = server.predict_batch([str(path)], timeout=120)
    from_array = server.predict_batch([raw], timeout=120)
    np.testing.assert_array_equal(from_path, from_array)


def test_server_metrics_records_schema(serve_cfg, server):
    """The per-flush kind="serve" records validate against the shared obs
    schema — the same contract report_run/check_results_artifacts read."""
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    # server fixture work has already run; records are on disk (line-buffered).
    problems = validate_jsonl(serve_cfg.metrics_file)
    assert not problems, problems
    records = load_records(serve_cfg.metrics_file)
    serves = [r for r in records if r["kind"] == "serve"]
    assert serves, "no serve records written"
    assert {r["bucket"] for r in serves} <= {1, 8}
    for r in serves:
        assert 0.0 < r["fill_ratio"] <= 1.0
        assert r["requests"] <= r["bucket"]


def test_preprocess_worker_crash_typed_counted_and_batch_survives(server):
    """ISSUE 7 satellite: a preprocess-WORKER crash (a non-ServeError from
    inside the pool, injected via the MPT_FAULT_PREPROCESS_N gate) fails
    only ITS request, with the typed PreprocessError — not a silent loss,
    not a misleading ServerClosedError — while the rest of the flush
    serves; the failure is counted in stats and on the flush's
    kind=\"serve\" record (preprocess_failures)."""
    from mpi_pytorch_tpu.serve import PreprocessError
    from mpi_pytorch_tpu.utils.env import reset_fault_counters

    rng = np.random.default_rng(7)
    raw = [
        rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8) for _ in range(3)
    ]
    before = server.stats()
    os.environ["MPT_FAULT_PREPROCESS_N"] = "1"
    reset_fault_counters()
    try:
        # The first payload entering the pool crashes; submit the whole
        # wave quickly so survivors coalesce around the casualty.
        futs = [server.submit(im) for im in raw]
        results, crashes = [], []
        for f in futs:
            try:
                results.append(f.result(timeout=120))
            except PreprocessError as e:
                crashes.append(e)
        assert len(crashes) == 1 and "worker crash" in str(crashes[0])
        assert len(results) == 2  # the batch went on without the casualty
    finally:
        os.environ.pop("MPT_FAULT_PREPROCESS_N", None)
        reset_fault_counters()
    stats = server.stats()
    assert stats["preprocess_failures"] == before["preprocess_failures"] + 1
    # The flush that saw the casualty carries the count on its record (the
    # completion loop writes it just after resolving the futures — poll).
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    flagged = []
    deadline = time.monotonic() + 30
    while not flagged and time.monotonic() < deadline:
        flagged = [
            r for r in load_records(server.cfg.metrics_file)
            if r["kind"] == "serve" and r.get("preprocess_failures")
        ]
        time.sleep(0.05)
    assert validate_jsonl(server.cfg.metrics_file) == []
    assert flagged and flagged[-1]["preprocess_failures"] >= 1
    assert "worker_respawns" in flagged[-1]


def test_preprocess_all_failed_flush_emits_fault_record(server):
    """A flush in which EVERY request fails preprocess dispatches no batch
    (no kind=\"serve\" record) — the failure must surface as a
    kind=\"fault\" reason=preprocess_all_failed record instead of
    vanishing from the stream."""
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.serve import PreprocessError
    from mpi_pytorch_tpu.utils.env import reset_fault_counters

    rng = np.random.default_rng(13)
    raw = rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
    os.environ["MPT_FAULT_PREPROCESS_N"] = "1"
    reset_fault_counters()
    try:
        with pytest.raises(PreprocessError):
            server.predict_batch([raw], timeout=120)  # lone request = whole flush
    finally:
        os.environ.pop("MPT_FAULT_PREPROCESS_N", None)
        reset_fault_counters()
    faults = []
    deadline = time.monotonic() + 30
    while not faults and time.monotonic() < deadline:
        faults = [
            r for r in load_records(server.cfg.metrics_file)
            if r["kind"] == "fault" and r["reason"] == "preprocess_all_failed"
        ]
        time.sleep(0.05)
    assert faults and "1 request(s)" in faults[-1]["detail"]
    assert validate_jsonl(server.cfg.metrics_file) == []


def test_preprocess_pool_death_respawns_and_serves(server):
    """A DEAD worker pool (simulated by shutting it down under the live
    server — the BrokenThreadPool/errant-shutdown scenario) used to turn
    every subsequent request into a bogus 'server is shut down'; now the
    pool respawns once, the request retries on the fresh pool, and the
    respawn is counted."""
    rng = np.random.default_rng(11)
    raw = rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
    baseline = server.predict_batch([raw], timeout=120)

    before = server.stats()["worker_respawns"]
    server._pool.shutdown(wait=True)  # the pool dies; the server is live
    after_death = server.predict_batch([raw], timeout=120)
    np.testing.assert_array_equal(after_death, baseline)
    assert server.stats()["worker_respawns"] == before + 1


def test_server_rejects_after_close(serve_cfg):
    from mpi_pytorch_tpu.serve import InferenceServer, ServerClosedError

    # A second tiny server would recompile; reuse the executables via the
    # lru-cached predict step — construction is the cheap part. Use a
    # single-bucket config to keep it light.
    import dataclasses

    cfg = dataclasses.replace(serve_cfg, serve_buckets="8", metrics_file="")
    cfg.validate_config()
    srv = InferenceServer(cfg, load_checkpoint=False)
    img = np.zeros((32, 32, 3), np.uint8)
    fut = srv.submit(img)
    assert fut.result(timeout=120).shape == (3,)
    srv.close()  # graceful drain
    with pytest.raises(ServerClosedError):
        srv.submit(img)


# ---------------------------------------------------------- top-k parity


def test_topk_top1_matches_fused_head_argmax(monkeypatch):
    """Satellite: the plain predict path's top-k column 0 IS the argmax the
    fused head_predict computes — pinned through a real zoo model with the
    real kernel (Pallas interpreter) on the 8-device mesh."""
    import optax
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.evaluate import _make_predict_step, _make_predict_step_impl
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.train.state import TrainState

    bundle, variables = create_model_bundle(
        "resnet18", 200, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=optax.identity(), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    images = np.random.default_rng(0).normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = np.asarray([3, 5, -1, 9, 0, 1, -1, 7], np.int32)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    monkeypatch.setenv("MPT_HEAD_INTERPRET", "1")
    _make_predict_step_impl.cache_clear()
    try:
        topk = _make_predict_step(mesh, jnp.float32, topk=5)
        fused = _make_predict_step(mesh, jnp.float32, fused_head=True)
        mk, pk = topk(state, batch)
        mf, pf = fused(state, batch)
    finally:
        monkeypatch.delenv("MPT_HEAD_INTERPRET")
        _make_predict_step_impl.cache_clear()
    pk, pf = np.asarray(pk), np.asarray(pf)
    assert pk.shape == (8, 5)
    np.testing.assert_array_equal(pk[:, 0], pf)  # top-1 == fused argmax
    # Metrics agree too (same logits, same masking).
    for k in ("loss", "correct", "count"):
        np.testing.assert_allclose(float(mk[k]), float(mf[k]), rtol=1e-4, atol=1e-4)
    # topk>1 with the fused head is a contract violation, not a silent k=1.
    with pytest.raises(ValueError):
        _make_predict_step(mesh, jnp.float32, fused_head=True, topk=3)


def test_topk1_path_unchanged(monkeypatch):
    """topk=1 keeps the original [B] argmax contract (the predictions-CSV
    path depends on it)."""
    import optax
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.evaluate import _make_predict_step
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.train.state import TrainState

    bundle, variables = create_model_bundle(
        "resnet18", 50, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=optax.identity(), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    images = np.random.default_rng(2).normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = np.arange(8, dtype=np.int32)
    plain = _make_predict_step(mesh, jnp.float32)
    _, p = plain(state, (jnp.asarray(images), jnp.asarray(labels)))
    assert np.asarray(p).shape == (8,)


# ----------------------------------------------------------- bench (smoke)


def test_bench_serve_smoke(tmp_path):
    """Acceptance: the CPU smoke bench emits schema-valid p50/p95/p99 +
    throughput rows for at least two bucket sets, in both load shapes,
    with zero steady-state compiles."""
    from mpi_pytorch_tpu.obs.schema import validate_record

    out = tmp_path / "serve_bench.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serve.py"),
         "--smoke", "--out", str(out)],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(l) for l in out.read_text().splitlines() if l.strip()]
    assert len(rows) >= 4, rows
    for r in rows:
        assert not validate_record(r), validate_record(r)
        assert r["p50_ms"] <= r["p95_ms"] <= r["p99_ms"]
        assert r["images_per_sec"] > 0
        assert r["compiles_after_warmup"] == 0
        assert 0.0 < r["mean_fill_ratio"] <= 1.0
    assert len({r["buckets"] for r in rows}) >= 2  # two bucket sets
    assert {r["mode"] for r in rows} == {"closed", "open"}
    open_rows = [r for r in rows if r["mode"] == "open"]
    assert all(r["offered_rps"] for r in open_rows)


def test_committed_serve_bench_artifact_validates():
    """The committed docs/serve_bench.json rows pass the same lint CI
    applies (check_results_artifacts covers it via the metrics sweep)."""
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    path = os.path.join(REPO, "docs", "serve_bench.json")
    assert os.path.isfile(path), "docs/serve_bench.json missing"
    assert not validate_jsonl(path)


# ------------------------------------------------- compilation cache (sat)


_CACHE_CHILD = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
hits = [0]
def on_event(name, **kw):
    if name == "/jax/compilation_cache/cache_hits":
        hits[0] += 1
jax.monitoring.register_event_listener(on_event)
sys.path.insert(0, {repo!r})
from mpi_pytorch_tpu.config import Config, apply_runtime_flags
cfg = Config(compilation_cache_dir=sys.argv[1])
apply_runtime_flags(cfg)   # the real wiring under test
import jax.numpy as jnp
jax.jit(lambda x: (x * 2 + 1).sum())(jnp.arange(64.0)).block_until_ready()
print("CACHE_HITS", hits[0])
"""


def test_bench_serve_percentiles_survive_total_rejection():
    """A fully-rejected sweep point (overload regime) must yield a row, not
    an empty-array percentile crash."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_serve", os.path.join(REPO, "tools", "bench_serve.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod._percentiles([]) == {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    out = mod._percentiles([1.0, 2.0, 3.0])
    assert out["p50_ms"] <= out["p95_ms"] <= out["p99_ms"]


def test_compilation_cache_toggles_off(tmp_path, monkeypatch):
    """A later run in the same process with the flag OFF must not keep
    writing the previous run's cache dir (the jax_debug_nans rule)."""
    monkeypatch.delenv("MPT_COMPILE_CACHE_DIR", raising=False)
    from mpi_pytorch_tpu.config import enable_compilation_cache

    enable_compilation_cache(str(tmp_path))
    assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    enable_compilation_cache("")
    assert jax.config.jax_compilation_cache_dir is None


def test_compilation_cache_reused_across_processes(tmp_path):
    """Satellite: a second build in a FRESH subprocess reuses the cache dir
    the first populated — --compilation-cache-dir turns repeat-run cold
    compiles into cache hits."""
    cache_dir = tmp_path / "jax_cache"
    cache_dir.mkdir()
    script = tmp_path / "cache_child.py"
    script.write_text(_CACHE_CHILD.format(repo=REPO))

    def run():
        proc = subprocess.run(
            [sys.executable, str(script), str(cache_dir)],
            cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        line = [l for l in proc.stdout.splitlines() if l.startswith("CACHE_HITS")]
        return int(line[0].split()[1])

    assert run() == 0  # cold: populated, no hits
    assert len(list(cache_dir.iterdir())) > 0, "cache dir not populated"
    assert run() >= 1  # fresh process: served from the populated cache


# ------------------------------------------------ multi-process replicas


@pytest.mark.slow
def test_two_process_serve_replicas(tmp_path):
    """Satellite: replicated-server predictions match single-process. Two
    real processes rendezvous through jax.distributed, each serving over
    its LOCAL 4-device replica mesh; a third, plain single process runs
    the identical workload. All three top-k streams must be identical."""
    import socket

    def _free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _flags(env):
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        return " ".join(flags + ["--xla_force_host_platform_device_count=4"])

    child = os.path.join(REPO, "tests", "serve_child.py")
    port = _free_port()
    procs = []
    for pid in range(2):
        env = _cpu_env(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid), MPT_MULTIHOST="1",
        )
        env["XLA_FLAGS"] = _flags(env)
        procs.append(subprocess.Popen(
            [sys.executable, child], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    env = _cpu_env()
    env["XLA_FLAGS"] = _flags(env)
    single = subprocess.run(
        [sys.executable, child], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=900,
    )
    assert single.returncode == 0, single.stdout + single.stderr

    lines = [
        line
        for out in outs + [single.stdout]
        for line in out.splitlines()
        if line.startswith("SERVE_OK")
    ]
    assert len(lines) == 3, (outs, single.stdout)
    assert lines[0] == lines[1] == lines[2], lines


# ------------------------------------------------- live telemetry (ISSUE 8)


def test_server_obs_endpoints_request_ids_and_idempotent_close(tmp_path):
    """The serve live-telemetry surface in one server life: /metrics
    (parseable Prometheus text), /metricsz (JSON snapshot whose flush p99
    matches the kind="serve" record stream), /healthz, per-request trace
    ids threaded enqueue→preprocess→dispatch→fetch, the final registry
    snapshot record, and idempotent close (the satellite fix)."""
    import dataclasses
    import re
    import urllib.request

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=2.0, serve_topk=3,
        metrics_file=str(tmp_path / "m.jsonl"),
        trace_file=str(tmp_path / "trace.json"),
        log_file="", eval_log_file="", serve_metrics_port=-1,
    )
    cfg.validate_config()
    server = InferenceServer(cfg, load_checkpoint=False)
    try:
        rng = np.random.default_rng(0)
        images = [
            rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
            for _ in range(16)
        ]
        server.predict_batch(images, timeout=120)

        port = server.metrics_port
        assert port and port > 0
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        line_re = re.compile(
            r'^(# (TYPE|HELP) .*|'
            r'[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9+.][^ ]*)$'
        )
        for line in text.strip().splitlines():
            assert line_re.match(line), repr(line)
        assert "mpt_serve_requests_total 16" in text
        assert 'mpt_serve_flush_ms_bucket{le="+Inf"}' in text
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metricsz", timeout=10
        ).read().decode())
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ).read().decode())
        assert health["status"] == "ok"
        assert health["compiles_after_warmup"] == 0
    finally:
        server.close()
    server.close()  # idempotent: a second close is a no-op, not a crash

    assert validate_jsonl(cfg.metrics_file) == []
    records = load_records(cfg.metrics_file)
    serves = [r for r in records if r["kind"] == "serve"]
    finals = [r for r in records if r["kind"] == "metrics"]
    assert serves and len(finals) == 1  # the close-time registry snapshot
    # The scraped histogram saw exactly the flush stream: same count, and
    # p99 within the sketch's bucket error of the exact stream p99.
    flush_ms = sorted(r["total_ms"] for r in serves)
    exact_p99 = flush_ms[max(0, -(-99 * len(flush_ms) // 100) - 1)]
    scraped = snap["histograms"]["serve/flush_ms"]
    assert scraped["count"] == len(serves)
    assert abs(scraped["p99"] - exact_p99) <= 0.10 * max(exact_p99, 1e-9)
    assert snap["counters"]["serve/requests"] == 16.0
    assert finals[0]["counters"]["serve/served"] == 16.0

    # Request-id threading across the pipeline phases.
    trace = json.load(open(cfg.trace_file))
    events = trace["traceEvents"]
    enqueued = {e["args"]["req"] for e in events if e["name"] == "serve/enqueue"}
    assert enqueued == set(range(16))
    for phase in ("serve/preprocess", "serve/dispatch", "serve/fetch"):
        seen = {
            rid for e in events if e["name"] == phase
            for rid in e.get("args", {}).get("req_ids", [])
        }
        assert seen == enqueued, (phase, sorted(seen))


def test_close_flushes_sinks_even_when_drain_path_raises(tmp_path):
    """THE satellite fix pinned: close() used to flush sinks only after a
    clean drain — a failure mid-shutdown lost the per-process trace and
    the final snapshot. Now the sink flush is on the finally path."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.obs.schema import load_records
    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="4", serve_max_wait_ms=1.0, serve_topk=1,
        metrics_file=str(tmp_path / "m.jsonl"),
        trace_file=str(tmp_path / "trace.json"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    server = InferenceServer(cfg, load_checkpoint=False)

    def exploding_shutdown(wait=True):
        raise RuntimeError("injected: worker pool wedged mid-drain")

    server._pool.shutdown = exploding_shutdown
    with pytest.raises(RuntimeError, match="wedged mid-drain"):
        server.close()
    # The failure still flushed every obs sink: trace on disk, final
    # registry snapshot in the stream, and a repeat close() is a no-op.
    assert json.load(open(cfg.trace_file))["traceEvents"] is not None
    assert any(
        r["kind"] == "metrics" for r in load_records(cfg.metrics_file)
    )
    server.close()


def test_init_failure_flushes_sinks(tmp_path, monkeypatch):
    """A warmup/build crash inside __init__ must leave the trace and the
    metrics stream flushed — the aborted startup is exactly the run whose
    evidence is needed (the trainer failure-path discipline)."""
    import mpi_pytorch_tpu.serve.server as server_mod
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import InferenceServer

    def exploding_exe(*a, **kw):
        raise RuntimeError("injected: warmup compile died")

    monkeypatch.setattr(server_mod, "BucketExecutables", exploding_exe)
    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32", serve_buckets="4",
        metrics_file=str(tmp_path / "m.jsonl"),
        trace_file=str(tmp_path / "trace.json"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    with pytest.raises(RuntimeError, match="warmup compile died"):
        InferenceServer(cfg, load_checkpoint=False)
    assert (tmp_path / "trace.json").exists()  # tracer flushed on the way out


def test_serve_slo_rule_fires_on_latency_breach(tmp_path):
    """A serve-side SLO rule over the live registry: an absurdly low p99
    threshold breaches on real traffic, writing a kind="alert" record into
    the serve stream and dumping the flight ring."""
    import os as _os

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl
    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=1.0, serve_topk=1,
        metrics_file=str(tmp_path / "m.jsonl"),
        log_file="", eval_log_file="",
        slo_rules="serve/flush_ms:p99 > 0.001 name=serve_p99 action=log,metric",
        flight_dir=str(tmp_path / "flight"),
    )
    cfg.validate_config()
    with InferenceServer(cfg, load_checkpoint=False) as server:
        rng = np.random.default_rng(0)
        server.predict_batch(
            [rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
             for _ in range(6)],
            timeout=120,
        )
    assert validate_jsonl(cfg.metrics_file) == []
    records = load_records(cfg.metrics_file)
    alerts = [r for r in records if r["kind"] == "alert"]
    assert len(alerts) == 1  # latched: one alert, not one per flush
    assert alerts[0]["rule"] == "serve_p99"
    finals = [r for r in records if r["kind"] == "metrics"]
    assert finals and finals[-1]["counters"]["obs/alerts_fired"] == 1.0
    dumps = _os.listdir(cfg.flight_dir)
    assert any("alert_serve_p99" in d for d in dumps), dumps


def test_slo_evaluation_driven_from_submit_path(tmp_path):
    """An outage in which no flush ever completes must still evaluate the
    SLO rules: the submit path drives a throttled evaluation, so a
    reject-rate rule can fire while the pipeline is wedged."""
    import types

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import InferenceServer

    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32", serve_buckets="8",
        serve_max_wait_ms=50.0, serve_topk=1, serve_queue_depth=2,
        metrics_file="", log_file="", eval_log_file="",
    )
    cfg.validate_config()
    server = InferenceServer(cfg, load_checkpoint=False)
    try:
        calls = []
        server._monitor = types.SimpleNamespace(
            evaluate=lambda **kw: calls.append(1)
        )
        server._slo_eval_interval = 0.0  # un-throttle for the test
        img = np.zeros((32, 32, 3), np.uint8)
        futs = []
        for _ in range(6):  # queue_depth 2 + long max_wait: some reject
            try:
                futs.append(server.submit(img))
            except Exception:  # noqa: BLE001 — QueueFullError is the point
                pass
        assert calls, "submit path never evaluated the SLO rules"
        for f in futs:
            f.result(timeout=120)
    finally:
        server._monitor = None
        server.close()


def test_init_failure_does_not_orphan_pipeline_threads(tmp_path, monkeypatch):
    """A construction failure AFTER the worker threads start (an HTTP port
    bind, here simulated) must tear the pipeline down — a retry loop
    around a failing bind must not accumulate live serve-batch threads."""
    import threading

    import mpi_pytorch_tpu.serve.server as server_mod
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import InferenceServer

    def exploding_http(*a, **kw):
        raise OSError("injected: port already in use")

    monkeypatch.setattr(server_mod, "ObsHTTPServer", exploding_http, raising=False)
    # The import inside __init__ resolves via the module; patch there too.
    import mpi_pytorch_tpu.serve.http as http_mod

    monkeypatch.setattr(http_mod, "ObsHTTPServer", exploding_http)
    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32", serve_buckets="4",
        serve_topk=1, serve_metrics_port=-1,
        metrics_file="", log_file="", eval_log_file="",
        trace_file=str(tmp_path / "trace.json"),
    )
    cfg.validate_config()
    before = {t.name for t in threading.enumerate() if t.name.startswith("serve-")}
    with pytest.raises(OSError, match="port already in use"):
        InferenceServer(cfg, load_checkpoint=False)
    leaked = [
        t for t in threading.enumerate()
        if t.name.startswith("serve-") and t.name not in before and t.is_alive()
    ]
    assert not leaked, leaked
    assert (tmp_path / "trace.json").exists()  # sinks still flushed
