"""Framed-wire client SDK (ISSUE 16): ``WireHost``.

``WireHost`` is a ``RemoteHost`` whose DATA PLANE rides the binary
framed wire (``serve/wire.py``) instead of npy-over-POST + long-poll:
one persistent multiplexed stream per (client, host) pair, pipelined
submits, out-of-order completion by req_id, and a real CANCEL verb —
the hedge-loser revocation the router's exactly-once ledger needs.

Everything else — probes, facts cache, /control retunes, tracez scrape,
supervisor lifecycle — is inherited unchanged from ``RemoteHost`` over
its keep-alive HTTP pool: the control plane is low-rate and JSON suits
it; only the per-request path justified a wire format. The framed port
is discovered from the host's readiness payload / ``/healthz`` facts
(``wire_port``), so the HTTP surface is also the handshake.

Failure mapping is shared with the in-process path: ERROR frames carry
the PR 12 taxonomy as typed kinds, so a 429's ``retry_after_ms`` and a
dead connection's host-shaped verdict look EXACTLY like their HTTP
twins to the router — ``FleetRouter`` needs no transport branches.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np

from mpi_pytorch_tpu.serve.batcher import (
    HostUnavailableError,
    ServeError,
    ServerClosedError,
)
from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
from mpi_pytorch_tpu.serve.wire import WireClient


class WireHost(RemoteHost):
    """``HostHandle`` over the framed wire: binary SUBMIT/RESULT frames
    on persistent pooled connections for requests, inherited HTTP for
    control/probes. ``cancel(fut)`` sends the CANCEL frame for the
    future's req_id — the router's hedge loser never occupies a batch
    slot server-side."""

    transport = "framed"

    def __init__(self, base_url: str, *, wire_port: int | None = None,
                 wire_pool: int = 2, **kwargs):
        super().__init__(base_url, **kwargs)
        if wire_port is None:
            wire_port = self._facts().get("wire_port")
        if not wire_port:
            raise HostUnavailableError(
                f"{self.name}: host at {self.base_url} advertises no "
                f"wire_port — is it running with serve_transport='framed'?"
            )
        self.wire_port = int(wire_port)
        host = self._netloc.rsplit(":", 1)[0]
        self._wire = WireClient(
            host, self.wire_port, pool=wire_pool,
            connect_timeout_s=self.connect_timeout_s,
        )

    # ------------------------------------------------------------- requests

    def submit(self, image, trace=None, model=None) -> Future:
        """One pipelined SUBMIT frame; the returned future resolves from
        the reader thread's req_id match (RESULT → top-k array, ERROR →
        the exact typed exception). No wire retries — same
        non-idempotent-submit discipline as the HTTP path. The req_id
        rides the future (``wire_req_id``) as the CANCEL handle."""
        if self._closed:
            raise ServerClosedError(f"remote host {self.name} is closed")
        traceparent = None
        t_wire = 0.0
        if trace is not None:
            from mpi_pytorch_tpu.obs.context import format_traceparent

            traceparent = format_traceparent(trace)
            t_wire = time.time()
        req_id, fut = self._wire.submit(
            np.asarray(image), model=None if model is None else str(model),
            traceparent=traceparent,
        )
        fut.wire_req_id = req_id
        if trace is not None and self._spans is not None:
            t_sent = time.time()
            self._spans.add(
                name="wire/submit", trace=trace.trace_id,
                parent=trace.span_id, t0=t_wire, t1=t_sent,
                host="router", attrs={"host": self.name, "req_id": req_id},
            )
            spans, name = self._spans, self.name

            def _result_span(f: Future, _t0=t_sent) -> None:
                # The delivery half: frame sent → response matched. The
                # framed twin of the HTTP path's wire/result long-poll.
                spans.add(
                    name="wire/result", trace=trace.trace_id,
                    parent=trace.span_id, t0=_t0, t1=time.time(),
                    host="router", attrs={"host": name, "req_id": req_id},
                )

            fut.add_done_callback(_result_span)
        return fut

    def cancel(self, fut: Future) -> None:
        """Revoke an in-flight submit: best-effort CANCEL frame for the
        future's req_id. Server-side the pending future is cancelled and
        the batch loop's sweep drops it before assembly; the reply is an
        ERR_CANCELLED frame that resolves ``fut`` as cancelled-shaped.
        Idempotent — cancelling a done or unknown req_id is a no-op."""
        req_id = getattr(fut, "wire_req_id", None)
        if req_id is not None and not fut.done():
            self._wire.cancel(req_id)

    def ping_wire(self, timeout_s: float = 2.0) -> bool:
        """PING/PONG round-trip on the framed wire — the data-plane
        liveness check (the HTTP ``alive()`` only proves the control
        plane)."""
        try:
            return self._wire.ping(timeout_s=timeout_s)
        except (ServeError, OSError, FutureTimeoutError):
            return False

    # ------------------------------------------------------------ lifecycle

    def kill(self) -> None:
        super().kill()
        self._wire.close()

    def close(self, drain: bool = True) -> None:
        if self._closed:
            return
        super().close(drain=drain)
        self._wire.close()
