"""Framed wire transport tests (ISSUE 16).

Four layers, mirroring the module split:

- the pure frame codec (round-trips across every wire dtype; every
  refusal typed and immediate — truncated, malformed, oversized,
  version-skewed frames raise, never hang);
- WireListener + WireClient against a jax-free fake submit_fn
  (pipelining, out-of-order completion, CANCEL, typed errors across the
  wire, connection-death host-shaping);
- WireHost + ServingHost(wire=True) end to end, including the router
  hedge drill under an injected wire delay (exactly-once resolution,
  loser revoked);
- the real InferenceServer's zero-copy ledger (copies_per_request ==
  1.0 — the bytes-touched-once invariant as a number).
"""

import socket
import struct
import time
from concurrent.futures import CancelledError, Future

import numpy as np
import pytest


def _wait_for(cond, timeout_s=5.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------- codec


def test_frame_roundtrip_every_wire_dtype():
    from mpi_pytorch_tpu.serve import wire

    rng = np.random.default_rng(0)
    for token, dtype in wire._DTYPE_BY_TOKEN.items():
        if dtype == np.bool_:
            arr = rng.integers(0, 2, size=(2, 3)).astype(dtype)
        elif np.issubdtype(dtype, np.floating):
            arr = rng.standard_normal((4, 2, 3)).astype(dtype)
        else:
            arr = rng.integers(0, 100, size=(5,)).astype(dtype)
        frame = wire.encode_frame(
            wire.SUBMIT, 42,
            wire.pack_array_header(arr, "resnet18", "00-aa-bb-01"),
            arr.tobytes(),
        )
        ftype, req_id, hlen, plen = wire.decode_prefix(frame)
        assert (ftype, req_id) == (wire.SUBMIT, 42)
        header = frame[wire.PREFIX_LEN:wire.PREFIX_LEN + hlen]
        payload = frame[wire.PREFIX_LEN + hlen:wire.PREFIX_LEN + hlen + plen]
        out, model, trace = wire.decode_array(header, payload)
        assert out.dtype == dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)
        assert (model, trace) == ("resnet18", "00-aa-bb-01")


def test_decode_array_is_a_view_not_a_copy():
    """The zero-copy contract at the codec layer: the decoded array is a
    view over the received payload buffer (the ONE copy happens later,
    straight into the pooled bucket slot)."""
    from mpi_pytorch_tpu.serve import wire

    arr = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    header = wire.pack_array_header(arr)
    payload = arr.tobytes()
    out, _, _ = wire.decode_array(header, payload)
    assert not out.flags.owndata  # frombuffer view, no allocation
    assert not out.flags.writeable  # bytes-backed — a copy would be writable


def test_empty_model_and_trace_decode_to_none():
    from mpi_pytorch_tpu.serve import wire

    arr = np.zeros((2,), np.int32)
    _out, model, trace = wire.decode_array(
        wire.pack_array_header(arr), arr.tobytes()
    )
    assert model is None and trace is None


def test_typed_frame_rejections():
    from mpi_pytorch_tpu.serve import wire

    # Truncated prefix: typed, immediate.
    with pytest.raises(wire.TruncatedFrameError):
        wire.decode_prefix(b"MPTW\x01")
    # Bad magic.
    bad = wire.PREFIX.pack(b"HTTP", wire.WIRE_VERSION, wire.SUBMIT, 0,
                           1, 0, 0)
    with pytest.raises(wire.MalformedFrameError):
        wire.decode_prefix(bad)
    # Version skew refuses loudly (never misparses a future layout).
    skew = wire.PREFIX.pack(wire.MAGIC, 99, wire.SUBMIT, 0, 1, 0, 0)
    with pytest.raises(wire.WireVersionError):
        wire.decode_prefix(skew)
    # Unknown frame type.
    unk = wire.PREFIX.pack(wire.MAGIC, wire.WIRE_VERSION, 200, 0, 1, 0, 0)
    with pytest.raises(wire.MalformedFrameError):
        wire.decode_prefix(unk)
    # Oversized declared lengths are rejected from the prefix ALONE —
    # before any allocation could happen.
    big = wire.PREFIX.pack(wire.MAGIC, wire.WIRE_VERSION, wire.SUBMIT, 0,
                           1, 0, wire.MAX_PAYLOAD_BYTES + 1)
    with pytest.raises(wire.FrameTooLargeError):
        wire.decode_prefix(big)
    # The encoder enforces the same caps.
    with pytest.raises(wire.FrameTooLargeError):
        wire.encode_frame(wire.SUBMIT, 1, b"x" * (wire.MAX_HEADER_BYTES + 1))
    with pytest.raises(wire.MalformedFrameError):
        wire.encode_frame(77, 1)


def test_typed_header_rejections():
    from mpi_pytorch_tpu.serve import wire

    arr = np.zeros((4,), np.float32)
    # Unparseable / unknown-token array headers.
    with pytest.raises(wire.MalformedFrameError):
        wire.unpack_array_header(b"\x01")
    with pytest.raises(wire.MalformedFrameError):
        wire.unpack_array_header(
            struct.pack("<BB", 99, 1) + struct.pack("<I", 4) + b"\0\0\0\0"
        )
    # Non-wire dtype never encodes (closed set — not a pickle).
    with pytest.raises(wire.MalformedFrameError):
        wire.pack_array_header(np.zeros((2,), np.complex64))
    # Payload length must match dtype × shape exactly.
    with pytest.raises(wire.MalformedFrameError):
        wire.decode_array(wire.pack_array_header(arr), arr.tobytes()[:-1])
    # Unknown error kind.
    with pytest.raises(wire.MalformedFrameError):
        wire.error_header_to_exception(
            wire.encode_error_header(222, "from the future")
        )
    # Truncated ERROR header: a string running past the end is typed
    # malformed, never a silently-shortened detail.
    with pytest.raises(wire.MalformedFrameError):
        wire.decode_error_header(
            wire.encode_error_header(wire.ERR_REQUEST, "long detail")[:-4]
        )


def test_error_taxonomy_survives_the_wire():
    """Every typed serving failure maps to an ERROR header and BACK to
    the exact class — the router's request-vs-host-shaped logic must
    need no transport branches."""
    from mpi_pytorch_tpu.serve import wire
    from mpi_pytorch_tpu.serve.batcher import (
        HostUnavailableError,
        ModelNotResidentError,
        PreprocessError,
        QueueFullError,
        ServeError,
        ServerClosedError,
        UnknownModelError,
    )

    qf = QueueFullError("full", retry_after_ms=123.5, model="vit")
    back = wire.error_header_to_exception(wire.exception_to_error_header(qf))
    assert isinstance(back, QueueFullError)
    assert back.retry_after_ms == 123.5 and back.model == "vit"

    for exc, want in [
        (ServerClosedError("bye"), ServerClosedError),
        (UnknownModelError("who"), UnknownModelError),
        (ModelNotResidentError("cold"), ModelNotResidentError),
        (PreprocessError("bad pixels"), PreprocessError),
        (ServeError("request-shaped"), ServeError),
        (CancelledError(), CancelledError),
        # Anything non-ServeError server-side is host-shaped to clients.
        (RuntimeError("device exploded"), HostUnavailableError),
    ]:
        back = wire.error_header_to_exception(
            wire.exception_to_error_header(exc)
        )
        assert type(back) is want, (exc, back)


# --------------------------------------------- listener + client (jax-free)


class FakeWireBackend:
    """submit_fn target: records submissions, resolves (or holds)
    futures without any serving stack behind it."""

    def __init__(self):
        from mpi_pytorch_tpu.serve.batcher import QueueFullError

        self._QueueFullError = QueueFullError
        self.mode = "ok"  # ok | pending | reject
        self.submits = []  # (image copy, model, trace)
        self.futures = []

    def submit_fn(self, image, model, trace):
        self.submits.append((np.array(image), model, trace))
        if self.mode == "reject":
            raise self._QueueFullError(
                "queue full", retry_after_ms=321.0, model=model
            )
        fut = Future()
        self.futures.append(fut)
        if self.mode == "ok":
            fut.set_result(
                np.full((3,), int(np.asarray(image).reshape(-1)[0]),
                        np.int32)
            )
        return fut


@pytest.fixture()
def framed():
    """A live (backend, WireListener, WireClient) triple on loopback."""
    from mpi_pytorch_tpu.serve.wire import WireClient, WireListener

    backend = FakeWireBackend()
    listener = WireListener(backend.submit_fn, host_index=0)
    client = WireClient("127.0.0.1", listener.port, pool=1)
    yield backend, listener, client
    client.close()
    listener.close()


def test_wire_submit_roundtrip_and_metadata(framed):
    backend, _listener, client = framed
    img = np.full((4, 4, 3), 7, np.uint8)
    req_id, fut = client.submit(img, model="resnet18",
                                traceparent="00-ab-cd-01")
    out = fut.result(timeout=5)
    np.testing.assert_array_equal(out, np.full((3,), 7, np.int32))
    assert out.dtype == np.int32 and req_id > 0
    got, model, trace = backend.submits[0]
    np.testing.assert_array_equal(got, img)
    assert (model, trace) == ("resnet18", "00-ab-cd-01")


def test_out_of_order_completion_no_head_of_line_blocking(framed):
    """Two pipelined requests on ONE connection; the second completes
    first — the whole point of response matching by req_id."""
    backend, _listener, client = framed
    backend.mode = "pending"
    _r1, fut1 = client.submit(np.full((2, 2), 1, np.uint8))
    _r2, fut2 = client.submit(np.full((2, 2), 2, np.uint8))
    _wait_for(lambda: len(backend.futures) == 2, what="both submits")
    backend.futures[1].set_result(np.full((3,), 2, np.int32))
    np.testing.assert_array_equal(
        fut2.result(timeout=5), np.full((3,), 2, np.int32)
    )
    assert not fut1.done()  # the slow request blocked nobody
    backend.futures[0].set_result(np.full((3,), 1, np.int32))
    np.testing.assert_array_equal(
        fut1.result(timeout=5), np.full((3,), 1, np.int32)
    )


def test_ping_pong_handshake(framed):
    _backend, _listener, client = framed
    assert client.ping(timeout_s=5.0) is True


def test_typed_error_crosses_the_wire(framed):
    from mpi_pytorch_tpu.serve.batcher import QueueFullError

    backend, _listener, client = framed
    backend.mode = "reject"
    _rid, fut = client.submit(np.zeros((2, 2), np.uint8), model="vit")
    with pytest.raises(QueueFullError) as ei:
        fut.result(timeout=5)
    # The 429 hints rode the wire as fields, not prose.
    assert ei.value.retry_after_ms == 321.0
    assert ei.value.model == "vit"


def test_cancel_revokes_server_side_and_resolves_client_side(framed):
    backend, _listener, client = framed
    backend.mode = "pending"
    req_id, fut = client.submit(np.zeros((2, 2), np.uint8))
    # The client future is in running state: local cancel() is refused —
    # revocation is the CANCEL frame's job, not the local future's.
    assert fut.cancel() is False
    _wait_for(lambda: backend.futures, what="server-side submit")
    client.cancel(req_id)
    _wait_for(lambda: backend.futures[0].cancelled(),
              what="server-side revocation")
    with pytest.raises(CancelledError):
        fut.result(timeout=5)


def test_cancel_unknown_req_id_is_a_noop(framed):
    _backend, _listener, client = framed
    client.cancel(999999)  # must not raise, poison the stream, or hang
    _rid, fut = client.submit(np.full((2, 2), 5, np.uint8))
    np.testing.assert_array_equal(
        fut.result(timeout=5), np.full((3,), 5, np.int32)
    )


def test_malformed_stream_is_refused_then_torn_down(framed):
    """Garbage on a fresh connection: one typed ERROR frame (req_id 0)
    comes back, then the server hangs up — a framing error poisons the
    stream, it is never resynced."""
    from mpi_pytorch_tpu.serve import wire
    from mpi_pytorch_tpu.serve.batcher import ServeError

    _backend, listener, _client = framed
    sock = socket.create_connection(("127.0.0.1", listener.port), timeout=5)
    try:
        sock.sendall(b"GET / HTTP/1.1\r\n" + b"\x00" * 16)
        ftype, req_id, header, _payload = wire.read_frame(sock)
        assert (ftype, req_id) == (wire.ERROR, 0)
        assert isinstance(wire.error_header_to_exception(header), ServeError)
        sock.settimeout(5)
        try:
            assert sock.recv(1) == b""  # FIN: stream closed
        except ConnectionResetError:
            pass  # RST (unread bytes in the server's buffer): also closed
    finally:
        sock.close()


def test_listener_death_fails_inflight_host_shaped(framed):
    """A dead connection's in-flight futures fail with the host-shaped
    error — the router's re-dispatch food, same verdict as the HTTP
    twin."""
    from mpi_pytorch_tpu.serve.batcher import HostUnavailableError

    backend, listener, client = framed
    backend.mode = "pending"
    _rid, fut = client.submit(np.zeros((2, 2), np.uint8))
    _wait_for(lambda: backend.submits, what="submit to land")
    listener.close()
    with pytest.raises(HostUnavailableError):
        fut.result(timeout=5)


def test_conn_death_cancels_every_inflight_server_side(framed):
    """Client hangs up with several requests in flight on ONE
    connection: teardown must cancel EVERY pending server-side future —
    cancel() runs the done-callback synchronously, so holding pend_lock
    across it would deadlock the wire-conn thread on the first future
    and leave the rest uncancelled, silently occupying batch slots."""
    backend, _listener, client = framed
    backend.mode = "pending"
    for i in range(4):
        client.submit(np.full((2, 2), i, np.uint8))
    _wait_for(lambda: len(backend.futures) == 4, what="all submits to land")
    client.close()
    _wait_for(lambda: all(f.cancelled() for f in backend.futures),
              what="server-side cancellation of every in-flight future")


# ----------------------------------------------------- chaos: slow wire


def test_wire_delay_gate_targets_one_host(monkeypatch):
    from mpi_pytorch_tpu.serve import wire

    assert wire.maybe_fault_wire_delay(0) == 0.0  # cold gate: free
    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_MS", "30")
    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_HOST", "1")
    t0 = time.monotonic()
    assert wire.maybe_fault_wire_delay(0) == 0.0  # not the target
    assert time.monotonic() - t0 < 0.02
    slept = wire.maybe_fault_wire_delay(1)
    assert slept == 30.0
    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_HOST", "-1")
    assert wire.maybe_fault_wire_delay(0) == 30.0  # -1 = every host


def test_wire_delay_jitter_is_deterministic(monkeypatch):
    from mpi_pytorch_tpu.serve import wire

    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_MS", "10")
    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_JITTER_MS", "4")
    monkeypatch.setattr(wire, "_jitter_phase", 0)
    first = [wire.maybe_fault_wire_delay(0) for _ in range(3)]
    monkeypatch.setattr(wire, "_jitter_phase", 0)
    second = [wire.maybe_fault_wire_delay(0) for _ in range(3)]
    assert first == second == [13.0, 12.0, 11.0]  # triangle, not a PRNG
    assert all(10.0 <= d <= 14.0 for d in first)


# ------------------------------------------------- WireHost + ServingHost


class FakeInferenceServer:
    """Duck-typed server for ServingHost: the wire path without jax."""

    host_index = 0

    def __init__(self, topk=3, value=None):
        self.topk = topk
        self.value = value  # None → echo first pixel
        self.mode = "ok"  # ok | pending
        self.submits = 0
        self.pending = []
        self.closed = False

    def submit(self, image, trace=None):
        self.submits += 1
        fut = Future()
        if self.mode == "pending":
            self.pending.append(fut)
            return fut
        v = self.value
        if v is None:
            v = int(np.asarray(image).reshape(-1)[0])
        fut.set_result(np.full((self.topk,), v, np.int32))
        return fut

    def _healthz(self):
        return {
            "status": "closing" if self.closed else "ok",
            "queue_depth": 0, "compiles_after_warmup": 0,
            "served": self.submits, "rejected": 0, "buckets": [1, 4],
            "precision": "bf16", "queue_capacity": 8, "max_wait_ms": 2.0,
            "active_buckets": [1, 4], "precisions": ["bf16"],
            "parity_top1": None, "topk": self.topk,
            "host_index": self.host_index, "pid": None,
        }

    def close(self, drain=True):
        self.closed = True


def _make_framed_host(name, index, value):
    from mpi_pytorch_tpu.serve.client import WireHost
    from mpi_pytorch_tpu.serve.host import ServingHost

    server = FakeInferenceServer(value=value)
    server.host_index = index
    host = ServingHost(server, port=0, wire=True)
    whost = WireHost(
        f"http://127.0.0.1:{host.port}", name=name, index=index,
        poll_slice_s=0.2, result_timeout_s=5.0, probe_retries=1,
    )
    return server, host, whost


@pytest.fixture()
def framed_host():
    server, host, whost = _make_framed_host("h0", 0, value=None)
    yield server, host, whost
    whost._pool.shutdown(wait=False, cancel_futures=True)
    whost._wire.close()
    host.close()


def test_wirehost_discovers_port_and_serves(framed_host):
    """wire_port rides /healthz: the HTTP surface IS the handshake."""
    server, host, whost = framed_host
    assert whost.transport == "framed"
    assert whost.wire_port == host.wire_port
    fut = whost.submit(np.full((4, 4, 3), 9, np.uint8))
    np.testing.assert_array_equal(
        fut.result(timeout=5), np.full((3,), 9, np.int32)
    )
    assert whost.ping_wire() is True
    # Control plane is inherited HTTP: same host facts, same probes.
    assert whost.alive() is True


def test_wirehost_cancel_sends_the_cancel_frame(framed_host):
    server, _host, whost = framed_host
    server.mode = "pending"
    fut = whost.submit(np.zeros((4, 4, 3), np.uint8))
    _wait_for(lambda: server.pending, what="server-side submit")
    whost.cancel(fut)
    _wait_for(lambda: server.pending[0].cancelled(),
              what="server-side revocation")
    with pytest.raises(CancelledError):
        fut.result(timeout=5)


def test_wirehost_refuses_http_only_host():
    """Against a host running without the framed listener the typed
    verdict is immediate — not a hang on a port that never answers."""
    from mpi_pytorch_tpu.serve.batcher import HostUnavailableError
    from mpi_pytorch_tpu.serve.client import WireHost
    from mpi_pytorch_tpu.serve.host import ServingHost

    server = FakeInferenceServer()
    host = ServingHost(server, port=0)  # wire=False
    try:
        with pytest.raises(HostUnavailableError):
            WireHost(f"http://127.0.0.1:{host.port}", name="h9", index=9,
                     probe_retries=1)
    finally:
        host.close()


def test_remotehost_reuses_keepalive_connections(framed_host):
    """Satellite: the control plane parks its connection instead of
    dialing per request."""
    _server, _host, whost = framed_host
    assert whost.alive() is True
    _wait_for(lambda: whost._conns, what="a parked connection")
    conn = whost._conns[0]
    for _ in range(3):
        assert whost.alive() is True
    assert len(whost._conns) == 1
    assert whost._conns[0] is conn  # same socket, reused


# ------------------------------------------------------------ hedge drill


class _Recorder:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(dict(rec))

    def hedge_records(self):
        return [r for r in self.records if r.get("kind") == "hedge"]


class SilentHost:
    """Router-unit host: accepts submits, optionally never resolves."""

    transport = "local"

    def __init__(self, name, index, respond=True):
        self.name = name
        self.index = index
        self.respond = respond
        self.queue_capacity = 8
        self.submitted = 0
        self.pending = []
        self.closed = False
        self.queue_depth = 0

    def submit(self, payload):
        self.submitted += 1
        fut = Future()
        if self.respond:
            fut.set_result(np.full((3,), self.index, np.int32))
        else:
            self.pending.append(fut)
        return fut

    def snapshot(self):
        return {"counters": {}, "gauges": {"serve/queue_depth": 0.0},
                "histograms": {}}

    def alive(self):
        return not self.closed

    def qsize(self):
        return self.queue_depth

    def stats(self):
        return {"served": self.submitted, "rejected": 0, "padded_rows": 0,
                "compiles_after_warmup": 0}

    def compiles_after_warmup(self):
        return 0

    def close(self, drain=True):
        self.closed = True

    def kill(self):
        self.closed = True


def _prime_scores(router, scores):
    """Pin the dispatch scores so the drill's primary pick is
    deterministic (fresh snapshots, no probe race — the probe interval
    is set far beyond the test)."""
    now = time.monotonic()
    with router._lock:
        for name, score in scores.items():
            router._state[name].score = float(score)
            router._state[name].snapshot_t = now


def test_hedge_fires_resolves_exactly_once_and_revokes_loser():
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    slow = SilentHost("slow", 0, respond=False)  # never answers
    fast = SilentHost("fast", 1)
    rec = _Recorder()
    router = FleetRouter(
        [slow, fast], metrics=rec, hedge=True, hedge_floor_ms=40.0,
        probe_interval_s=30.0, stale_after_s=60.0,
    )
    try:
        _prime_scores(router, {"slow": 0.0, "fast": 5.0})
        fut = router.submit(np.zeros((2, 2), np.uint8))
        out = fut.result(timeout=5)
        # The hedge (to the second-best host) won; the request resolved
        # EXACTLY once, with the winner's result.
        np.testing.assert_array_equal(out, np.full((3,), 1, np.int32))
        assert slow.submitted == 1 and fast.submitted == 1
        stats = router.stats()
        assert stats["hedges"] == 1 and stats["hedge_wins"] == 1
        assert stats["inflight"] == 0
        assert stats["tokens_free"] == stats["budget"]  # token returned once
        # The loser was revoked — it never occupies a batch slot.
        _wait_for(lambda: slow.pending[0].cancelled(),
                  what="loser revocation")
        _wait_for(lambda: rec.hedge_records(), what="the hedge record")
        (hrec,) = rec.hedge_records()
        assert hrec["winner"] == "fast" and hrec["loser"] == "slow"
        assert hrec["cancelled"] == 1
        assert hrec["deadline_ms"] == 40.0  # no samples yet → the floor
    finally:
        router.close()


def test_fast_primary_never_hedges():
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    a, b = SilentHost("a", 0), SilentHost("b", 1)
    rec = _Recorder()
    router = FleetRouter(
        [a, b], metrics=rec, hedge=True, hedge_floor_ms=40.0,
        probe_interval_s=30.0, stale_after_s=60.0,
    )
    try:
        _prime_scores(router, {"a": 0.0, "b": 5.0})
        for i in range(5):
            router.submit(np.zeros((2, 2), np.uint8)).result(timeout=5)
        time.sleep(0.15)  # past any armed deadline
        stats = router.stats()
        assert stats["hedges"] == 0 and stats["hedge_wins"] == 0
        assert b.submitted == 0  # every request resolved on the primary
        assert rec.hedge_records() == []
    finally:
        router.close()


def test_stats_omit_hedge_counters_when_off():
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    router = FleetRouter(
        [SilentHost("a", 0)], probe_interval_s=30.0, stale_after_s=60.0,
    )
    try:
        assert "hedges" not in router.stats()  # absent-when-off: old
        assert "hedge_wins" not in router.stats()  # streams stay identical
    finally:
        router.close()


def test_hedge_drill_over_framed_wire_with_injected_delay(monkeypatch):
    """The ISSUE's acceptance drill, end to end: two framed hosts, the
    wire-delay gate slows host 0's response path, the router hedges to
    host 1 after the floor deadline, the request resolves exactly once
    with the fast host's answer, and the loser is revoked with a CANCEL
    frame."""
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_MS", "400")
    monkeypatch.setenv("MPT_FAULT_WIRE_DELAY_HOST", "0")
    s0, h0, w0 = _make_framed_host("h0", 0, value=0)
    s1, h1, w1 = _make_framed_host("h1", 1, value=1)
    rec = _Recorder()
    router = FleetRouter(
        [w0, w1], metrics=rec, hedge=True, hedge_floor_ms=50.0,
        probe_interval_s=30.0, stale_after_s=60.0,
    )
    try:
        _prime_scores(router, {"h0": 0.0, "h1": 5.0})
        fut = router.submit(np.zeros((4, 4, 3), np.uint8))
        out = fut.result(timeout=5)
        np.testing.assert_array_equal(out, np.full((3,), 1, np.int32))
        _wait_for(lambda: rec.hedge_records(), what="the hedge record")
        (hrec,) = rec.hedge_records()
        assert hrec["winner"] == "h1" and hrec["loser"] == "h0"
        stats = router.stats()
        assert stats["hedges"] == 1 and stats["hedge_wins"] == 1
        # Exactly-once under the late loser: host 0's delayed RESULT
        # eventually lands and must be a no-op (the claim ledger already
        # paid out) — not a double resolution, error, or host strike.
        time.sleep(0.6)
        stats = router.stats()
        assert stats["hedges"] == 1 and stats["hedge_wins"] == 1
        assert stats["inflight"] == 0 and stats["failovers"] == []
        assert stats["tokens_free"] == stats["budget"]
        np.testing.assert_array_equal(fut.result(), out)  # unchanged
    finally:
        monkeypatch.delenv("MPT_FAULT_WIRE_DELAY_MS")
        router.close()
        for whost, host in ((w0, h0), (w1, h1)):
            whost._pool.shutdown(wait=False, cancel_futures=True)
            whost._wire.close()
            host.close()


# --------------------------------------------------- zero-copy ledger (jax)


@pytest.fixture(scope="module")
def real_server(tmp_path_factory):
    """A real InferenceServer with the same shapes as tests/test_serve.py
    (in-process XLA compile cache makes the second compile cheap)."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import InferenceServer

    scratch = tmp_path_factory.mktemp("wire_serve")
    cfg = Config(
        model_name="resnet18", num_classes=32, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,8", serve_max_wait_ms=5.0, serve_topk=3,
        serve_queue_depth=64, loader_workers=4,
        metrics_file=str(scratch / "wire_serve_metrics.jsonl"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    srv = InferenceServer(cfg, load_checkpoint=False)
    yield srv
    srv.close()


def test_zero_copy_ledger_is_exactly_one_copy_per_request(real_server):
    """The tentpole invariant as a number: between arrival and
    device_put each request's pixels are touched ONCE (straight into the
    pooled, bucket-padded buffer the executable consumes)."""
    rng = np.random.default_rng(1)
    images = [
        rng.integers(0, 256, size=(32, 32, 3)).astype(np.uint8)
        for _ in range(13)
    ]
    preds = real_server.predict_batch(images, timeout=120)
    assert preds.shape == (13, 3)
    stats = real_server.stats()
    assert stats["input_copies"] == stats["served"]
    assert stats["copies_per_request"] == 1.0
    allocs = stats["buffer_allocations"]
    assert allocs >= 1
    # Steady state: another round must serve from the recycled pool,
    # not allocate fresh buffers.
    real_server.predict_batch(images, timeout=120)
    stats = real_server.stats()
    assert stats["copies_per_request"] == 1.0
    assert stats["buffer_allocations"] <= allocs + 1


def test_cancel_before_assembly_frees_the_batch_slot(real_server):
    """A request revoked while still queued is swept before bucket
    assembly: counted as cancelled, never served, no inference run."""
    # Bucket 1 would flush a lone request instantly; pin the active set
    # to 8 so the request sits out the deadline — revocable in-queue.
    real_server.set_active_buckets((8,))
    real_server.set_max_wait_ms(200.0)
    try:
        served0 = real_server.stats()["served"]
        cancelled0 = real_server.stats()["cancelled"]
        fut = real_server.submit(np.zeros((32, 32, 3), np.uint8))
        assert fut.cancel() is True  # still queued — revocable
        _wait_for(
            lambda: real_server.stats()["cancelled"] == cancelled0 + 1,
            what="the cancel sweep",
        )
        assert real_server.stats()["served"] == served0
    finally:
        real_server.set_max_wait_ms(5.0)
        real_server.set_active_buckets((1, 8))


def test_child_argv_never_forwards_hedge_knobs(tmp_path):
    """Hedging is a ROUTER decision: a spawned serving-host child is a
    single host, and forwarding serve_hedge trips its >=2-fleet-hosts
    validation before the child ever reports ready (the bench --hedge
    leg died exactly this way). The child argv must still carry the
    framed transport — that is what mounts the wire listener — and
    re-parsing the argv must build a VALID single-host config."""
    from mpi_pytorch_tpu.config import Config, parse_config
    from mpi_pytorch_tpu.serve.fleet.remote import child_host_args

    cfg = Config()
    cfg.serve_fleet_hosts = 3
    cfg.serve_transport = "framed"
    cfg.serve_hedge = True
    cfg.serve_hedge_factor = 2.5
    cfg.serve_hedge_floor_ms = 15.0
    argv = child_host_args(
        cfg, 1, str(tmp_path / "port"), str(tmp_path / "metrics.jsonl"))

    assert "--serve-hedge" not in argv
    assert "--serve-hedge-factor" not in argv
    assert "--serve-hedge-floor-ms" not in argv
    assert argv[argv.index("--serve-transport") + 1] == "framed"

    child = parse_config(argv)
    assert child.serve_transport == "framed"
    assert child.serve_hedge is False
    assert child.serve_host_index == 1
