"""Elastic training (ISSUE 7 / ROADMAP item 4): topology-manifest stamping,
cross-mesh ZeRO checkpoint round-trips (8→4, 8→1, 4→8), bounded-HBM
redistribution, corrupt-checkpoint fallback, the preemption watchdog
(sentinel file / health streaks), resume-side retry+backoff, and the
fault-injection harness — all on the 8-virtual-device CPU mesh.

Cross-mesh tolerance: the spmd gradient is the mean of P per-shard means
over the SAME global batch, so a P=8 and a P=4 run see identical math up
to reduction order — trajectories must agree to float32 reduction noise
(atol 1e-5), the documented checkpoint tolerance for exact (non-bf16-
moment) saves. BN models are excluded by design: spmd-mode LOCAL batch
statistics legitimately depend on P (reference per-rank semantics,
docs/MULTIHOST.md)."""

import json
import os
import threading
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu import checkpoint as ckpt
from mpi_pytorch_tpu.config import Config, MeshConfig
from mpi_pytorch_tpu.parallel.mesh import create_mesh, mesh_topology
from mpi_pytorch_tpu.train import elastic
from mpi_pytorch_tpu.train.state import (
    TrainState,
    make_optimizer,
    zero_shard_opt_state,
    zero_unshard_opt_state,
)
from mpi_pytorch_tpu.train.step import make_spmd_train_step, place_state_on_mesh
from mpi_pytorch_tpu.parallel.mesh import shard_batch
from mpi_pytorch_tpu.utils.env import FAULT_GATES, fault_countdown, reset_fault_counters

NUM_CLASSES = 8


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(13, name="body")(x))  # 13: uneven → ZeRO padding
        return nn.Dense(NUM_CLASSES, name="head")(x)


def _mlp_state(seed=0):
    model = MLP()
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8, 8, 3)), train=True)
    return TrainState.create(
        apply_fn=model.apply, variables=variables,
        tx=make_optimizer(1e-2), rng=jax.random.PRNGKey(seed + 1),
    )


def _mesh_of(n: int) -> Mesh:
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n, 1), ("data", "model"))


def _batch(n=16):
    rng = np.random.default_rng(0)
    images = rng.normal(size=(n, 8, 8, 3)).astype(np.float32)
    labels = (np.arange(n) % NUM_CLASSES).astype(np.int32)
    return images, labels


class FakeMetrics:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))


def _zero_steps(state, mesh, batch, n, bounded_bytes=None):
    """Run ``n`` spmd+ZeRO steps from a HOST state: place, shard, step;
    returns (state, [loss], [grad_norm])."""
    state = place_state_on_mesh(state, mesh)
    state = state.replace(
        opt_state=zero_shard_opt_state(state.opt_state, mesh, bounded_bytes=bounded_bytes)
    )
    step = make_spmd_train_step(mesh, jnp.float32, zero_opt_state=True)
    losses, norms = [], []
    for _ in range(n):
        state, m = step(state, shard_batch(batch, mesh))
        losses.append(float(m["loss"]))
        norms.append(float(m["grad_norm"]))
    return state, losses, norms


def _save_zero(state, mesh, tmp_path, epoch=0, loss=0.5):
    """Gather-on-save a ZeRO-sharded state with its topology manifest."""
    template = jax.eval_shape(state.tx.init, state.params)
    saveable = state.replace(opt_state=zero_unshard_opt_state(state.opt_state, template))
    manifest = elastic.topology_manifest(
        mesh, zero_opt_state=True, spmd_mode=True, opt_template=template
    )
    return ckpt.save_checkpoint(
        str(tmp_path), epoch=epoch, state=saveable, loss=loss, manifest=manifest
    )


# ---------------------------------------------------------------------------
# topology manifest
# ---------------------------------------------------------------------------


def test_manifest_written_read_and_retired(tmp_path):
    mesh = _mesh_of(8)
    batch = _batch()
    state, _, _ = _zero_steps(_mlp_state(), mesh, batch, 1)
    path = _save_zero(state, mesh, tmp_path, epoch=0)

    manifest = ckpt.read_manifest(path)
    assert manifest["manifest_version"] == elastic.MANIFEST_VERSION
    assert manifest["payload_schema"] == ckpt.PAYLOAD_SCHEMA
    assert manifest["device_count"] == 8
    assert manifest["mesh_shape"] == {"data": 8, "model": 1}
    assert manifest["zero_opt_state"] is True and manifest["zero_shards"] == 8
    # Per-leaf [chunk, padded] layout: the 13-unit body bias is the uneven
    # leaf — ceil(13/8)=2 rows of chunk, padded to 16.
    layout = manifest["zero_shard_layout"]
    bias_keys = [k for k in layout if "body" in k and "bias" in k]
    assert bias_keys and layout[bias_keys[0]] == [2, 16]

    # Retention retires the manifest sidecar with its payload.
    for epoch in (1, 2, 3):
        _save_zero(state, mesh, tmp_path, epoch=epoch)
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".manifest.json")

    # Legacy (manifest-less) checkpoints read as None.
    bare = ckpt.save_checkpoint(str(tmp_path / "bare"), epoch=0, state=_mlp_state(), loss=0.0)
    assert ckpt.read_manifest(bare) is None


# ---------------------------------------------------------------------------
# cross-mesh ZeRO round-trips (the satellite: 8→4, 8→1, 4→8)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("p_from,p_to", [(8, 4), (8, 1), (4, 8)])
def test_cross_mesh_zero_resume_matches_same_mesh(tmp_path, p_from, p_to):
    """A checkpoint written with --zero-opt-state on a P_from-device mesh
    resumes on a P_to mesh with the SAME post-resume loss/grad-norm
    trajectory as the same-mesh resume (float32 reduction noise only):
    the opt-state leaves are re-flattened/re-padded/re-chunked for the new
    P, including the P→1 degenerate case."""
    batch = _batch()
    mesh_from = _mesh_of(p_from)
    state, _, _ = _zero_steps(_mlp_state(), mesh_from, batch, 2)
    path = _save_zero(state, mesh_from, tmp_path, epoch=0)

    def resume_on(p):
        mesh = _mesh_of(p)
        metrics = FakeMetrics()
        res = elastic.restore_latest(
            str(tmp_path), _mlp_state(seed=7), mesh, metrics=metrics,
            zero_shards_to=p,
        )
        assert res is not None
        restored, epoch, loss, info = res
        assert (epoch, loss) == (0, 0.5)
        assert info["manifest"]["zero_shards"] == p_from
        record = [r for r in metrics.records if r["kind"] == "resume"][0]
        assert record["from_devices"] == p_from and record["to_devices"] == p
        assert record["zero_shards_from"] == p_from and record["zero_shards_to"] == p
        _, losses, norms = _zero_steps(restored, mesh, batch, 3)
        return losses, norms

    same_losses, same_norms = resume_on(p_from)
    cross_losses, cross_norms = resume_on(p_to)
    np.testing.assert_allclose(cross_losses, same_losses, rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(cross_norms, same_norms, rtol=2e-5, atol=1e-5)


def test_bounded_redistribution_matches_jitted_path():
    """The chunked per-row device redistribution (bounded_bytes=0 forces
    EVERY host leaf through it) lands bit-identical [P, chunk] shards to
    the jitted-reshape path, with each device holding exactly its 1/P row."""
    mesh = _mesh_of(8)
    state = _mlp_state()
    host_opt = jax.device_get(state.opt_state)

    jitted = zero_shard_opt_state(host_opt, mesh)
    bounded = zero_shard_opt_state(host_opt, mesh, bounded_bytes=0)
    for a, b in zip(jax.tree_util.tree_leaves(jitted), jax.tree_util.tree_leaves(bounded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if hasattr(b, "addressable_shards") and b.ndim > 0:
            assert b.sharding.spec == jax.sharding.PartitionSpec("data")
            assert b.addressable_shards[0].data.shape[0] == 1  # one row/device

    template = jax.eval_shape(state.tx.init, state.params)
    back = zero_unshard_opt_state(bounded, template)
    for a, b in zip(jax.tree_util.tree_leaves(host_opt), jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# corrupt-checkpoint fallback (satellite 1, pinned by the fault harness)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["truncate", "garbage", "empty"])
def test_corrupt_newest_falls_back_to_previous(tmp_path, mode):
    from tools.inject_faults import corrupt_latest

    mesh = _mesh_of(8)
    batch = _batch()
    state, _, _ = _zero_steps(_mlp_state(), mesh, batch, 1)
    _save_zero(state, mesh, tmp_path, epoch=0, loss=0.1)
    state2, _, _ = _zero_steps(_mlp_state(seed=3), mesh, batch, 1)
    _save_zero(state2, mesh, tmp_path, epoch=1, loss=0.2)

    newest = corrupt_latest(str(tmp_path), mode=mode)
    assert ckpt.checkpoint_epoch(newest) == 1
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.load_checkpoint(newest, _mlp_state())

    metrics = FakeMetrics()
    res = elastic.restore_latest(str(tmp_path), _mlp_state(seed=9), mesh, metrics=metrics)
    assert res is not None
    _, epoch, loss, info = res
    assert (epoch, loss) == (0, pytest.approx(0.1)) and info["corrupt_skipped"] == 1
    anomalies = [r for r in metrics.records if r["kind"] == "anomaly"]
    assert anomalies and anomalies[0]["reason"] == "corrupt_checkpoint"
    assert anomalies[0]["epoch"] == 1
    resume = [r for r in metrics.records if r["kind"] == "resume"][0]
    assert resume["corrupt_skipped"] == 1


def test_every_checkpoint_corrupt_aborts_instead_of_fresh_start(tmp_path):
    """Checkpoints existed but NONE restored: refuse to fresh-start (which
    would exit 0 and let retention delete the files) — every file failing
    identically is the template-mismatch signature, not bit rot. An EMPTY
    dir still means a legitimate fresh start (None)."""
    from tools.inject_faults import corrupt_latest

    assert elastic.restore_latest(str(tmp_path / "nothing"), _mlp_state(), _mesh_of(8)) is None

    _save_zero(*_state_on_mesh8(), tmp_path, epoch=0)
    corrupt_latest(str(tmp_path), mode="empty")
    metrics = FakeMetrics()
    with pytest.raises(ckpt.CheckpointCorruptError, match="refusing to fresh-start"):
        elastic.restore_latest(str(tmp_path), _mlp_state(), _mesh_of(8), metrics=metrics)
    assert [r["kind"] for r in metrics.records] == ["anomaly"]


def _state_on_mesh8():
    mesh = _mesh_of(8)
    state, _, _ = _zero_steps(_mlp_state(), mesh, _batch(), 1)
    return state, mesh


# ---------------------------------------------------------------------------
# trainer integration: sentinel preemption, retries, fault gates
# ---------------------------------------------------------------------------


def _train_cfg(tmp_path, **kw) -> Config:
    c = Config()
    c.debug = True
    c.debug_sample_size = 48
    c.train_csv = os.path.join(os.path.dirname(__file__), "..", "data", "train_sample.csv")
    c.test_csv = os.path.join(os.path.dirname(__file__), "..", "data", "test_sample.csv")
    c.synthetic_data = True
    c.model_name = "resnet18"
    c.num_classes = 200
    c.batch_size = 16
    c.width = c.height = 16
    c.num_epochs = 2
    c.compute_dtype = "float32"
    c.checkpoint_dir = os.path.join(str(tmp_path), "ckpt")
    c.log_file = os.path.join(str(tmp_path), "training.log")
    c.metrics_file = os.path.join(str(tmp_path), "metrics.jsonl")
    c.validate = False
    c.loader_workers = 2
    c.log_every_steps = 0
    c.spmd_mode = True
    c.zero_opt_state = True
    c.resume_backoff_s = 0.0  # tests never sleep through backoff
    for k, v in kw.items():
        setattr(c, k, v)
    c.validate_config()
    return c


def _records(cfg) -> list[dict]:
    return [json.loads(line) for line in open(cfg.metrics_file) if line.strip()]


@pytest.fixture
def clean_gates():
    """Fault-gate hygiene: counters latch env values at first use, so every
    gate test resets before AND after (a leaked countdown would fire inside
    an unrelated test's create_mesh)."""
    reset_fault_counters()
    yield
    for name in FAULT_GATES:
        os.environ.pop(name, None)
    reset_fault_counters()


def test_preexisting_sentinel_stops_before_epoch_zero(tmp_path):
    from mpi_pytorch_tpu.train.trainer import train

    sentinel = tmp_path / "preempt.now"
    sentinel.write_text("")
    cfg = _train_cfg(tmp_path, preempt_file=str(sentinel), num_epochs=5)
    summary = train(cfg)
    assert summary.preempted and summary.epochs_run == 0
    faults = [r for r in _records(cfg) if r["kind"] == "fault"]
    assert faults and faults[0]["reason"] == "preempt_file"
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(cfg.metrics_file) == []


def test_midrun_sentinel_preempts_saves_and_resumes(tmp_path):
    """The sentinel appears MID-run (the scheduler's preemption notice):
    the run stops at a safe boundary, saves, reports preempted; dropping
    the sentinel lets auto-resume finish the remaining epochs."""
    from mpi_pytorch_tpu.train.trainer import train

    sentinel = tmp_path / "preempt.now"
    cfg = _train_cfg(tmp_path, preempt_file=str(sentinel), num_epochs=30)
    out = {}

    def run():
        out["summary"] = train(cfg)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 240
    while time.time() < deadline:
        if os.path.exists(cfg.metrics_file) and any(
            r["kind"] == "epoch" for r in _records(cfg)
        ):
            break
        time.sleep(0.1)
    else:
        pytest.fail("epoch 0 never completed")
    sentinel.write_text("")
    t.join(timeout=240)
    assert not t.is_alive()
    assert out["summary"].preempted
    assert ckpt.latest_checkpoint(cfg.checkpoint_dir) is not None
    assert any(
        r["kind"] == "fault" and r["reason"] == "preempt_file" for r in _records(cfg)
    )

    sentinel.unlink()
    done = train(_train_cfg(tmp_path, preempt_file=str(sentinel),
                            num_epochs=out["summary"].epochs_run + 2,
                            from_checkpoint=True))
    assert not done.preempted and done.epochs_run >= 1
    resumes = [r for r in _records(cfg) if r["kind"] == "resume"]
    assert resumes and resumes[-1]["to_devices"] == 8


def test_backend_wedge_absorbed_by_resume_retries(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    # Seed a checkpoint, then resume through a backend that wedges twice.
    train(_train_cfg(tmp_path, num_epochs=1))
    os.environ["MPT_FAULT_BACKEND_WEDGE_N"] = "2"
    reset_fault_counters()
    summary = train(_train_cfg(tmp_path, num_epochs=2, from_checkpoint=True))
    assert summary.epochs_run == 1
    log = open(_train_cfg(tmp_path).log_file).read()
    assert "backend init (mesh build) failed" in log and "retrying" in log


def test_backend_wedge_beyond_retries_raises(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    train(_train_cfg(tmp_path, num_epochs=1))
    os.environ["MPT_FAULT_BACKEND_WEDGE_N"] = "10"
    reset_fault_counters()
    with pytest.raises(RuntimeError, match="backend init wedged"):
        train(_train_cfg(tmp_path, num_epochs=2, from_checkpoint=True, resume_retries=2))


def test_device_put_fault_absorbed_on_resume(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    train(_train_cfg(tmp_path, num_epochs=1))
    os.environ["MPT_FAULT_DEVICE_PUT_N"] = "1"
    reset_fault_counters()
    summary = train(_train_cfg(tmp_path, num_epochs=2, from_checkpoint=True))
    assert summary.epochs_run == 1
    log = open(_train_cfg(tmp_path).log_file).read()
    assert "state placement (device_put) failed" in log


def test_fault_injector_kill_gate(monkeypatch, clean_gates):
    from mpi_pytorch_tpu.train.elastic import FaultInjector

    killed = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: killed.append((pid, sig)))
    os.environ["MPT_FAULT_KILL_AT_STEP"] = "2"
    metrics = FakeMetrics()
    injector = FaultInjector(metrics=metrics)
    assert injector.active
    injector.after_step(0, 0)
    assert not killed
    injector.after_step(0, 1)
    assert killed == [(os.getpid(), 9)]
    assert metrics.records[-1] == {
        "kind": "fault", "reason": "injected_kill", "epoch": 0, "step": 1,
        "detail": "MPT_FAULT_KILL_AT_STEP=2",
    }


def test_fault_countdown_is_registered_and_bounded(clean_gates):
    os.environ["MPT_FAULT_BACKEND_WEDGE_N"] = "2"
    reset_fault_counters()
    assert fault_countdown("MPT_FAULT_BACKEND_WEDGE_N")
    assert fault_countdown("MPT_FAULT_BACKEND_WEDGE_N")
    assert not fault_countdown("MPT_FAULT_BACKEND_WEDGE_N")  # exhausted
    with pytest.raises(KeyError):
        fault_countdown("MPT_FAULT_TYPO")


def test_watchdog_streak_triggers():
    from mpi_pytorch_tpu.train.elastic import PreemptionWatchdog

    class Beat:
        straggler_streak = 0

    class Health:
        nonfinite_grad_streak = 0

    beat, health, metrics = Beat(), Health(), FakeMetrics()
    dog = PreemptionWatchdog(
        None, straggler_beats=3, nonfinite_steps=2,
        heartbeat=beat, health=health, metrics=metrics,
    )
    assert not dog.should_stop(epoch=0, step=0)
    beat.straggler_streak = 3
    assert dog.should_stop(epoch=1, step=4)
    assert dog.should_stop()  # latched
    assert len(metrics.records) == 1  # one record, not one per poll
    rec = metrics.records[0]
    assert rec["reason"] == "straggler_streak" and rec["streak"] == 3
    assert (rec["epoch"], rec["step"]) == (1, 4)

    dog2 = PreemptionWatchdog(None, nonfinite_steps=2, health=health, metrics=metrics)
    health.nonfinite_grad_streak = 2
    assert dog2.should_stop(epoch=0)
    assert metrics.records[-1]["reason"] == "nonfinite_grads"


def test_heartbeat_and_health_streak_counters():
    from mpi_pytorch_tpu.obs.health import StepHealth
    from mpi_pytorch_tpu.obs.heartbeat import Heartbeat

    metrics = FakeMetrics()
    hb = Heartbeat(
        metrics, every_steps=1, threshold=1.5,
        gather=lambda v: np.asarray([[100.0], [500.0]], np.float32),
    )
    hb.on_step(0, 0, 0.1)
    hb.on_step(0, 1, 0.1)
    assert hb.straggler_streak == 2
    hb._gather = lambda v: np.asarray([[100.0], [100.0]], np.float32)
    hb.on_step(0, 2, 0.1)
    assert hb.straggler_streak == 0  # a clean beat resets

    sh = StepHealth(metrics, step_metrics=True, nan_sentinel=False)
    m = {"loss": 1.0, "grad_norm": float("inf")}
    sh.on_step(0, 0, m)
    sh.on_step(0, 1, m)
    assert sh.nonfinite_grad_streak == 2
    sh.on_step(0, 2, {"loss": 1.0, "grad_norm": 0.5})
    assert sh.nonfinite_grad_streak == 0


def test_every_fault_gate_in_source_is_registered():
    """The check_results_artifacts-style hygiene rule: every MPT_FAULT_* /
    MPT_PREEMPT_* token anywhere in the package and tools must be a
    registered FAULT_GATES entry — a renamed or typo'd gate must fail here,
    not silently never fire inside a chaos test."""
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pat = re.compile(r"MPT_(?:FAULT|PREEMPT)_[A-Z_]*[A-Z]")
    found = set()
    for root in ("mpi_pytorch_tpu", "tools", "tests", "__graft_entry__.py"):
        full = os.path.join(repo, root)
        files = [full] if full.endswith(".py") else [
            os.path.join(d, f)
            for d, _, names in os.walk(full) for f in names if f.endswith(".py")
        ]
        for path in files:
            found |= set(pat.findall(open(path).read()))
    found.discard("MPT_FAULT_TYPO")  # this file's negative-case fixture
    assert found, "the scan found no gates — the pattern broke"
    assert found <= set(FAULT_GATES), found - set(FAULT_GATES)


def test_report_run_renders_resume_and_fault_records(tmp_path, capsys):
    from tools import report_run

    path = tmp_path / "m.jsonl"
    records = [
        {"ts": 1.0, "kind": "fault", "reason": "preempt_file",
         "detail": "sentinel exists", "epoch": 2},
        {"ts": 2.0, "kind": "resume", "epoch": 2, "to_devices": 4,
         "from_devices": 8, "from_mesh": "data=8,model=1",
         "to_mesh": "data=4,model=1", "zero_shards_from": 8,
         "zero_shards_to": 4, "corrupt_skipped": 1, "strategy": "host-reshard"},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    assert report_run.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "RESUME: epoch 2 — data=8,model=1 → data=4,model=1" in out
    assert "ZeRO P 8 → 4" in out and "1 corrupt checkpoint(s) skipped" in out
    assert "FAULT: preempt_file at epoch 2 — sentinel exists" in out


def test_config_validates_elastic_knobs():
    with pytest.raises(ValueError, match="resume_retries"):
        Config(resume_retries=-1).validate_config()
    with pytest.raises(ValueError, match="resume_backoff_s"):
        Config(resume_backoff_s=-0.1).validate_config()
    with pytest.raises(ValueError, match="preempt_straggler_beats"):
        Config(preempt_straggler_beats=2).validate_config()  # no heartbeat
    with pytest.raises(ValueError, match="preempt_nonfinite_steps"):
        Config(preempt_nonfinite_steps=2).validate_config()  # no step metrics
    Config(
        preempt_straggler_beats=2, heartbeat_every_steps=5,
        preempt_nonfinite_steps=2, step_metrics=True,
    ).validate_config()


# ---------------------------------------------------------------------------
# THE chaos drive (acceptance): SIGKILL mid-step on 8 devices + corrupt the
# newest file, auto-resume on 4 — recovery via fallback + reshard-on-load.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_kill_corrupt_and_cross_mesh_resume(tmp_path):
    import subprocess
    import sys

    from tools.inject_faults import corrupt_latest, fault_env

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [
        sys.executable, "-m", "mpi_pytorch_tpu.train",
        "--debug", "true", "--debug-sample-size", "64", "--num-classes", "200",
        "--batch-size", "16", "--width", "16", "--height", "16",
        "--synthetic-data", "true", "--validate", "false",
        "--compute-dtype", "float32", "--loader-workers", "2",
        "--log-every-steps", "0", "--spmd-mode", "true",
        "--zero-opt-state", "true", "--step-metrics", "true",
        "--num-epochs", "6", "--checkpoint-every-epochs", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--log-file", str(tmp_path / "training.log"),
        "--metrics-file", str(tmp_path / "metrics.jsonl"),
    ]

    def env_for(n, **faults):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = env["MPT_PLATFORM"] = "cpu"
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n}"]
        )
        return fault_env(base=env, **faults)

    # Kill mid-epoch 3 (4 steps/epoch, step 14 = epoch 3 step 1) on 8 devices.
    rc = subprocess.run(
        args, env=env_for(8, kill_at_step=14), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    ).returncode
    assert rc != 0
    assert len(ckpt.checkpoint_paths(str(tmp_path / "ckpt"))) >= 2

    # Corrupt whatever the crash left newest: recovery must fall back.
    corrupt_latest(str(tmp_path / "ckpt"), mode="garbage")

    # Auto-resume on HALF the mesh, through a backend that wedges once.
    subprocess.run(
        args + ["--from-checkpoint", "true"],
        env=env_for(4, backend_wedge=1), cwd=REPO, check=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )

    records = [
        json.loads(line) for line in open(tmp_path / "metrics.jsonl") if line.strip()
    ]
    kinds = {r["kind"] for r in records}
    assert {"fault", "anomaly", "resume", "epoch", "step"} <= kinds
    resume = [r for r in records if r["kind"] == "resume"][-1]
    assert resume["from_devices"] == 8 and resume["to_devices"] == 4
    assert resume["corrupt_skipped"] >= 1
    # Every epoch completed across the kill+corrupt+reshard.
    assert {r["epoch"] for r in records if r["kind"] == "epoch"} == set(range(6))
    # Zero steady-state recompiles after the cross-mesh resume.
    post = [r for r in records if r["kind"] == "step" and r["ts"] >= resume["ts"]]
    assert post and all(r["recompiles"] == 0 for r in post)
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(str(tmp_path / "metrics.jsonl")) == []
