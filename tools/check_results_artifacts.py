"""Lint docs/RESULTS.md: every numeric perf claim must cite a committed
machine-readable artifact — or be explicitly marked staged/pending/rejected.

Why (VERDICT r5 #9 / weak #1-2): the round-5 headline lived only in prose
(no raw A/B JSON, ``docs/bench_latest.json`` stale two rounds), and a
corrupt 242.4%-MFU row shipped un-annotated. The repo's brand is
measurement honesty; this linter makes claim→artifact drift a CI failure
instead of a reviewer catch (``tests/test_results_artifacts.py`` is the
tier-1 wrapper).

Contract (deliberately section-granular — prose moves, headings don't):

- The doc is split into sections at markdown headings (``#``..``####``).
- A section CLAIMS perf when any line matches a perf-number pattern
  (img/s, ms, MFU %, TFLOP/s, GB/s — the units this repo measures in).
- A claiming section PASSES when it contains at least one citation of a
  committed machine-readable artifact: a backtick-quoted token ending in
  .json/.jsonl/.log/.txt/.csv that resolves to an existing file (tried
  as-given from the repo root, then under docs/, then at the root), OR an
  explicit status marker (``staged``, ``pending``, ``rejected``,
  ``withdrawn``, ``stale``, ``not driver-confirmed``) telling the reader
  the number is not artifact-backed yet — the staleness-ledger idiom.
- Anything else fails with the section heading and the offending lines.

Run: ``python tools/check_results_artifacts.py [--file docs/RESULTS.md]``
Exit 0 = every claim maps; 1 = violations (printed).
"""

from __future__ import annotations

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The units this repo states measurements in (docs/RESULTS.md §§1-5).
PERF_CLAIM = re.compile(
    r"\d[\d\s,.]*\s*(img/s|images?/sec|ms\b|%?\s*MFU|MFU\b|TFLOP|GB/s)",
    re.IGNORECASE,
)

# Backtick-quoted machine-readable artifact path.
ARTIFACT_CITE = re.compile(r"`([^`\s]+\.(?:json|jsonl|log|txt|csv))`")

# The explicit not-yet-measured / no-longer-claimed markers (the staleness
# ledger idiom: a number may ship unbacked ONLY when the prose says so).
STATUS_MARKER = re.compile(
    r"staged|pending|rejected|withdrawn|stale|not driver-confirmed",
    re.IGNORECASE,
)

HEADING = re.compile(r"^#{1,4}\s")


def artifact_exists(path: str) -> bool:
    for cand in (path, os.path.join("docs", path), os.path.basename(path)):
        if os.path.isfile(os.path.join(REPO, cand)):
            return True
    return False


def split_sections(text: str) -> list[tuple[str, list[str]]]:
    sections: list[tuple[str, list[str]]] = [("(preamble)", [])]
    for line in text.splitlines():
        if HEADING.match(line):
            sections.append((line.strip(), []))
        else:
            sections[-1][1].append(line)
    return sections


def check(path: str) -> list[str]:
    with open(path) as f:
        text = f.read()
    violations = []
    for heading, lines in split_sections(text):
        body = "\n".join(lines)
        claims = [ln for ln in lines if PERF_CLAIM.search(ln)]
        if not claims:
            continue
        cites = [m for m in ARTIFACT_CITE.findall(heading + "\n" + body)]
        live = [c for c in cites if artifact_exists(c)]
        dead = [c for c in cites if not artifact_exists(c)]
        if live or STATUS_MARKER.search(body):
            if dead:
                violations.append(
                    f"{heading}: cites missing artifact(s): {', '.join(sorted(set(dead)))}"
                )
            continue
        sample = "; ".join(c.strip()[:80] for c in claims[:3])
        violations.append(
            f"{heading}: {len(claims)} perf-claim line(s) with no committed "
            f"artifact citation and no staged/pending marker — e.g. {sample}"
        )
    return violations


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", default=os.path.join(REPO, "docs", "RESULTS.md"))
    args = ap.parse_args()
    violations = check(args.file)
    if violations:
        print(f"{len(violations)} violation(s) in {args.file}:")
        for v in violations:
            print(" -", v)
        return 1
    print(f"ok: every perf-claiming section of {args.file} cites a committed "
          "artifact or carries an explicit staged/pending marker")
    return 0


if __name__ == "__main__":
    sys.exit(main())
