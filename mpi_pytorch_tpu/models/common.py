"""Shared building blocks for the Flax CNN zoo.

All models are NHWC (TPU-native layout: channels last keeps the lane dimension
dense for the VPU/MXU), take a ``train`` flag for BatchNorm/Dropout mode, and
thread ``dtype`` (compute, bfloat16 by default on TPU) separately from
``param_dtype`` (float32 master params).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

# torch BatchNorm defaults: eps=1e-5, momentum=0.1 (flax momentum = 1-0.1).
BN_MOMENTUM = 0.9
BN_EPS = 1e-5


def batch_norm(
    name: str | None = None,
    *,
    dtype: Dtype = jnp.float32,
    axis_name: str | None = None,
    eps: float = BN_EPS,
) -> nn.BatchNorm:
    """BatchNorm matching torch defaults. ``axis_name=None`` keeps per-replica
    local batch statistics — the reference's data-parallel semantics (only
    grads are synced, ``mpi_tools.py:30-37``; SURVEY §7 'BatchNorm under DP').
    Pass the mesh data axis name to opt into sync-BN. ``eps`` for families
    that deviate from torch's 1e-5 default (efficientnet uses 1e-3)."""
    return nn.BatchNorm(
        use_running_average=None,  # caller passes via __call__
        momentum=BN_MOMENTUM,
        epsilon=eps,
        dtype=dtype,
        axis_name=axis_name,
        name=name,
    )


def max_pool(x: jnp.ndarray, window: int, stride: int, padding: Any = "VALID") -> jnp.ndarray:
    """XLA reduce_window max pool (select-and-scatter backward).

    An index-based alternative exists (``ops/pooling.py``) but measured
    WORSE as a general drop-in: XLA materializes the scatter's dilated
    pads (or the phase-interleave copies) instead of fusing them, so the
    roofline bound regressed 62.4→79.5 ms on resnet18 (docs/RESULTS.md
    §4d records the full negative result). It is kept, unused, as the
    pinned-semantics base for a future VMEM-resident fused-stem kernel."""
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding=padding)


max_pool_xla = max_pool  # reference implementation alias for tests/benches


def adaptive_avg_pool(x: jnp.ndarray, out_hw: tuple[int, int]) -> jnp.ndarray:
    """torch AdaptiveAvgPool2d for static input shapes.

    Output cell (i, j) averages rows [floor(i*H/th), ceil((i+1)*H/th)) — the
    exact torch window algorithm. Shapes are static under jit, so the window
    arithmetic unrolls at trace time into th+tw strided slices; XLA fuses the
    means. Separable because the window bounds factor by axis.
    """
    th, tw = out_hw
    h, w = x.shape[1], x.shape[2]
    if h == th and w == tw:
        return x
    if h % th == 0 and w % tw == 0:
        # Fast path: equal windows → single reshape-mean (the common case).
        x = x.reshape(x.shape[0], th, h // th, tw, w // tw, x.shape[3])
        return x.mean(axis=(2, 4))
    rows = [
        x[:, (i * h) // th : -(-((i + 1) * h) // th), :, :].mean(axis=1, keepdims=True)
        for i in range(th)
    ]
    x = jnp.concatenate(rows, axis=1)
    cols = [
        x[:, :, (j * w) // tw : -(-((j + 1) * w) // tw), :].mean(axis=2, keepdims=True)
        for j in range(tw)
    ]
    return jnp.concatenate(cols, axis=2)


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    return x.mean(axis=(1, 2))


class Classifier(nn.Module):
    """Final dense head. Kept as its own module so (a) `feature_extract`
    freezing can target the `head` subtree by name across every architecture
    (parity: the reference swaps/unfreezes exactly this layer,
    ``models.py:36,44,53,62,80``), and (b) tensor-parallel sharding rules can
    match the 64 500-wide kernel by path (`.../head/kernel`)."""

    num_classes: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)


def head_filter(path: Sequence[str]) -> bool:
    """True for params belonging to a classification head — the subtree that
    stays trainable under feature_extract (reference ``models.py:5-13`` +
    head swap)."""
    return any(p in ("head", "aux_head") for p in path)
