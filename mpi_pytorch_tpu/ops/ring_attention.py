"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has no attention anywhere (it is a CNN trainer — SURVEY §2c),
but this framework treats long-context scale as first-class: sequences too
long for one chip's HBM are sharded over a mesh axis, and attention runs as
a ring — each device computes blockwise attention against the K/V block it
currently holds while ``lax.ppermute`` rotates K/V blocks around the ring,
overlapping ICI transfer with compute. Numerics are the online-softmax
(flash) recurrence, so results are exact (not approximated) regardless of
ring size: running max ``m``, normalizer ``l``, and unnormalized accumulator
``o`` are carried across ring steps and renormalized once at the end.

Layout: [batch, seq, heads, head_dim] ("BSHD"), sequence axis sharded.
``ring_attention`` is the per-shard SPMD function (call inside ``shard_map``
with the sequence axis bound); ``ring_self_attention`` wraps it for direct
use from un-sharded code. Causal masking uses *global* positions, so the
sharded result matches single-device causal attention exactly
(tests/test_ring_attention.py asserts both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_pytorch_tpu.parallel.compat import shard_map


def full_attention(q, k, v, *, causal: bool = False) -> jnp.ndarray:
    """Single-device reference attention ([B,S,H,D], f32 accumulation)."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False) -> jnp.ndarray:
    """Per-shard ring attention. Must run inside an SPMD context binding
    ``axis_name``; each shard holds the local sequence block of q/k/v."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d**-0.5

    qf = q.astype(jnp.float32) * scale
    q_pos = me * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_iota = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def accumulate(t, k_blk, v_blk, m, l, o):
        # after t rotations this shard holds the block that originated at
        # ring position (me - t) mod n
        src = (me - t) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * sk + k_iota
            scores = jnp.where((k_pos > q_pos)[None, None], -jnp.inf, scores)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # exp(-inf - -inf) guard: rows with no visible keys yet keep m=-inf
        p = jnp.exp(scores - jnp.where(jnp.isinf(m_new), 0.0, m_new)[..., None])
        p = jnp.where(jnp.isinf(scores), 0.0, p)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(jnp.isinf(m) & jnp.isinf(m_new), 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l, o

    def body(t, carry):
        k_blk, v_blk, m, l, o = carry
        m, l, o = accumulate(t, k_blk, v_blk, m, l, o)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    # n-1 rotate-and-accumulate steps, then a final accumulate without the
    # wasted last rotation (its result would be discarded).
    k_blk, v_blk, m, l, o = lax.fori_loop(0, n - 1, body, (k, v, m0, l0, o0))
    _, l, o = accumulate(n - 1, k_blk, v_blk, m, l, o)
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


@functools.lru_cache(maxsize=None)
def _sp_jit(mesh, causal, seq_axis, per_shard_fn):
    """Shared scaffolding for both SP strategies (ring here, Ulysses in
    ops/ulysses.py): shard q/k/v's sequence axis over ``seq_axis`` and jit
    the given per-shard attention function under shard_map."""
    spec = P(None, seq_axis, None, None)
    fn = shard_map(
        functools.partial(per_shard_fn, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)


def sp_self_attention(
    per_shard_fn, q, k, v, mesh: Mesh, *, seq_axis: str | None = None,
    causal: bool = False,
) -> jnp.ndarray:
    """Driver-facing wrapper shared by the SP strategies: shards [B,S,H,D]
    tensors over ``seq_axis`` of ``mesh`` and runs ``per_shard_fn``. S must
    divide evenly by the axis size."""
    seq_axis = seq_axis or mesh.axis_names[0]
    if q.shape[1] % mesh.shape[seq_axis] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"'{seq_axis}' of size {mesh.shape[seq_axis]}"
        )
    return _sp_jit(mesh, causal, seq_axis, per_shard_fn)(q, k, v)


def ring_self_attention(
    q, k, v, mesh: Mesh, *, seq_axis: str | None = None, causal: bool = False
) -> jnp.ndarray:
    """Ring attention over ``seq_axis``-sharded [B,S,H,D] tensors."""
    return sp_self_attention(
        ring_attention, q, k, v, mesh, seq_axis=seq_axis, causal=causal
    )
