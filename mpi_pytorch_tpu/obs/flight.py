"""Anomaly flight recorder: a per-process ring of recent records, dumped
with context when something fires (ISSUE 8).

A postmortem on a JSONL stream answers "what happened eventually"; the
question during an incident is "what were the last N things this process
saw when the alert fired". The flight recorder answers it without
retaining the run: every record the process emits (``FlightRecorder.tap``
wraps the ``MetricsWriter``, BEFORE its process-0-only file gate, so every
process records even though only process 0 persists the stream) lands in a
bounded ring, and any ``kind="fault"`` or ``kind="alert"`` record passing
through triggers a dump — a self-contained JSON file with the ring's
contents. That wires EVERY fault source at once (the preemption watchdog,
the fault injector, serve's preprocess_all_failed, the SLO monitor)
without touching each site.

Dump layout (``--flight-dir DIR``)::

    DIR/flight_000_alert_step_drift.p0.json   # {"reason", "ts", "process",
    DIR/flight_001_fault_preempt_file.p0.json #  "records": [last N records]}
    DIR/xla_000/ ...                          # optional profiler window

Optionally (``--flight-profile-window-s S`` > 0) a dump also opens a
``jax.profiler`` trace for the NEXT ``S`` seconds of run — captured
forward from the trigger, closed on a later record or at ``close()`` — so
the incident's device-side aftermath lands next to the host evidence.
Profiler failures are swallowed: evidence capture must never take the run
down. Dumps are capped (``max_dumps``) so a flapping alert cannot fill the
disk; the trainer's failure path calls ``dump("crash")`` the same way it
flushes the tracer, so an aborted run keeps its last-moments ring too.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque

_AUTO_DUMP_KINDS = ("fault", "alert", "rollback")
_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")

# One profiler window process-wide: jax.profiler.start_trace raises if a
# trace is already active, and two recorders (trainer + serve in one
# process) must not fight over it.
_profiler_lock = threading.Lock()
_profiler_active = False


class FlightRecorder:
    """Bounded ring of recent metrics records + evidence dumps."""

    def __init__(
        self,
        out_dir: str,
        *,
        capacity: int = 256,
        max_dumps: int = 16,
        profile_window_s: float = 0.0,
        auto_dump_kinds=_AUTO_DUMP_KINDS,
        clock=time.monotonic,
    ):
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.out_dir = out_dir
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._max_dumps = max_dumps
        self._profile_window_s = float(profile_window_s)
        self._auto_kinds = tuple(auto_dump_kinds)
        self._clock = clock
        self._window_until: float | None = None
        self._closed = False

    # ---------------------------------------------------------------- record

    def record(self, rec: dict) -> None:
        """Append one record; auto-dump on fault/alert kinds. Called for
        EVERY record on EVERY process (via ``tap``)."""
        with self._lock:
            self._ring.append(rec)
        self._poll_profiler()
        if rec.get("kind") in self._auto_kinds:
            reason = rec.get("reason") or rec.get("rule") or ""
            self.dump(f"{rec.get('kind')}_{reason}" if reason else rec.get("kind"))

    def tap(self, writer):
        """Wrap a ``MetricsWriter``-shaped sink: every ``write`` records
        into the ring first (stamped with the ts the stream will carry),
        then forwards. ``close`` closes the inner writer only — the
        recorder itself outlives it for the failure-path ``dump``."""
        return _TappedWriter(writer, self)

    # ------------------------------------------------------------------ dumps

    def dump(self, reason: str) -> str | None:
        """Write the ring to a dump file; returns its path (None when the
        dump cap is reached or the recorder is closed)."""
        with self._lock:
            if self._closed or self._seq >= self._max_dumps:
                return None
            seq = self._seq
            self._seq += 1
            records = list(self._ring)
        from mpi_pytorch_tpu.utils.logging import process_index

        safe = _SAFE.sub("_", reason).strip("_") or "dump"
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir, f"flight_{seq:03d}_{safe}.p{process_index()}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "reason": reason,
                    "ts": time.time(),
                    "process": process_index(),
                    "records": records,
                },
                f,
            )
        os.replace(tmp, path)  # atomic: a dump mid-crash is whole or absent
        self._start_profiler_window(seq)
        return path

    # ----------------------------------------------------- profiler windows

    def _start_profiler_window(self, seq: int) -> None:
        global _profiler_active
        if self._profile_window_s <= 0:
            return
        with _profiler_lock:
            if _profiler_active:
                return
            try:
                import jax

                jax.profiler.start_trace(
                    os.path.join(self.out_dir, f"xla_{seq:03d}")
                )
            except Exception:
                return
            _profiler_active = True
            self._window_until = self._clock() + self._profile_window_s

    def _poll_profiler(self) -> None:
        """Close an elapsed profiler window — piggybacked on record()/close()
        so no extra thread exists just to stop a trace."""
        global _profiler_active
        if self._window_until is None:
            return
        if self._clock() < self._window_until:
            return
        with _profiler_lock:
            self._window_until = None
            if not _profiler_active:
                return
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            _profiler_active = False

    # ------------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Stop any open profiler window; idempotent. Deliberately does NOT
        clear the ring — a post-close ``dump`` is refused, but the evidence
        stays inspectable in-process."""
        if self._window_until is not None:
            self._window_until = self._clock()  # force the window shut
            self._poll_profiler()
        self._closed = True


class _TappedWriter:
    """A MetricsWriter front that copies every record into the recorder's
    ring before forwarding. The ts is stamped HERE (once), so the ring and
    the persisted stream carry the identical record."""

    def __init__(self, inner, recorder: FlightRecorder):
        self._inner = inner
        self._recorder = recorder

    def write(self, record) -> None:
        rec = {"ts": time.time(), **record}
        self._recorder.record(rec)
        self._inner.write(rec)

    def close(self) -> None:
        self._inner.close()
