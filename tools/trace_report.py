"""Assemble cross-process request waterfalls from collected trace spans
(ISSUE 13 tentpole 3).

Input is the fleet collector's trace file (``--fleet-trace-file``): one
JSON span per line, skew-corrected at collection time (``obs/collector.py``
subtracts each host's probe-RTT clock-offset estimate at ingest), so
spans from different PROCESSES share one time base and order correctly.

Three outputs:

- **per-request waterfalls** — the span tree of one trace rendered as a
  timeline across process lanes: every dispatch attempt (a failover'd
  request shows BOTH), the wire hops, and the host-side
  queue/preprocess/device phases, each bar positioned on the request's
  own clock;
- **fleet per-phase latency breakdown** — span-name → count/p50/p99
  over every collected trace (the attribution table: where fleet time
  actually goes);
- **critical-path attribution** — per trace, each span's SELF time
  (duration minus the time covered by its children) is charged to its
  phase; the report names the phase that owns the p99 (the largest
  self-time charge across the slowest traces — "which phase do I fix to
  move the tail", arXiv 1711.00705's question asked of a fleet).

Run::

    python tools/trace_report.py /tmp/fleet_trace.jsonl            # summary
    python tools/trace_report.py TRACE.jsonl --trace-id <32hex>    # one waterfall
    python tools/trace_report.py TRACE.jsonl --waterfalls 3 --json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_BAR_WIDTH = 40


def load_spans(path: str) -> tuple[list[dict], list[str]]:
    """(spans, problems): every line must be a span-shaped JSON object
    (trace/span/name/host/pid/t0/t1) — the collector's contract."""
    spans, problems = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                s = json.loads(line)
            except ValueError as e:
                problems.append(f"line {lineno}: not JSON ({e})")
                continue
            missing = [
                k for k in ("trace", "span", "name", "host", "pid", "t0", "t1")
                if k not in s
            ]
            if missing:
                problems.append(f"line {lineno}: span missing {missing}")
                continue
            spans.append(s)
    return spans, problems


def group_traces(spans: list[dict]) -> dict[str, list[dict]]:
    traces: dict[str, list[dict]] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
    for members in traces.values():
        members.sort(key=lambda s: (s["t0"], s["t1"]))
    return traces


def _percentile(sorted_vals: list[float], q: float) -> float:
    n = len(sorted_vals)
    return sorted_vals[max(0, math.ceil(q * n) - 1)]


def phase_breakdown(spans: list[dict]) -> dict[str, dict]:
    """Span-name → {count, p50_ms, p99_ms, max_ms} over raw durations —
    the fleet per-phase latency table."""
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(1e3 * (s["t1"] - s["t0"]))
    out = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "p50_ms": round(_percentile(durs, 0.50), 3),
            "p99_ms": round(_percentile(durs, 0.99), 3),
            "max_ms": round(durs[-1], 3),
        }
    return out


def self_times(members: list[dict]) -> dict[str, float]:
    """Per-phase SELF time (ms) within one trace: each span's duration
    minus the union of its children's intervals — the critical-path
    charge (concurrent children don't double-bill the parent)."""
    children: dict[str, list[dict]] = {}
    for s in members:
        if s.get("parent"):
            children.setdefault(s["parent"], []).append(s)
    charge: dict[str, float] = {}
    for s in members:
        kids = children.get(s["span"], ())
        intervals = sorted(
            (max(k["t0"], s["t0"]), min(k["t1"], s["t1"])) for k in kids
        )
        covered, cursor = 0.0, s["t0"]
        for a, b in intervals:
            if b <= cursor:
                continue
            covered += b - max(a, cursor)
            cursor = max(cursor, b)
        self_ms = max(0.0, 1e3 * ((s["t1"] - s["t0"]) - covered))
        charge[s["name"]] = charge.get(s["name"], 0.0) + self_ms
    return charge


def trace_summary(trace_id: str, members: list[dict]) -> dict:
    root = next(
        (s for s in members if s["name"] == "route/request"), None
    )
    t0 = min(s["t0"] for s in members)
    t1 = max(s["t1"] for s in members)
    attrs = (root or {}).get("attrs") or {}
    return {
        "trace_id": trace_id,
        "spans": len(members),
        "processes": len({s["pid"] for s in members}),
        "hosts": sorted({s["host"] for s in members}),
        "duration_ms": round(1e3 * (t1 - t0), 3),
        "status": attrs.get("status"),
        "redispatches": attrs.get("redispatches", 0),
        "dispatch_attempts": sum(
            1 for s in members if s["name"] == "route/dispatch"
        ),
        "completions": sum(
            1 for s in members if s["name"] == "route/request"
        ),
        "self_times_ms": {
            k: round(v, 3) for k, v in sorted(self_times(members).items())
        },
    }


def critical_path(traces: dict[str, list[dict]]) -> dict | None:
    """Which phase owns the p99: take the slowest percentile of traces
    (at least one) and name the phase with the largest total self-time
    charge inside them — the phase to fix to move the tail."""
    if not traces:
        return None
    durations = sorted(
        (max(s["t1"] for s in m) - min(s["t0"] for s in m), t)
        for t, m in traces.items()
    )
    cut = max(1, math.ceil(0.01 * len(durations)))
    slowest = [t for _, t in durations[-cut:]]
    charge: dict[str, float] = {}
    for t in slowest:
        for name, ms in self_times(traces[t]).items():
            charge[name] = charge.get(name, 0.0) + ms
    if not charge:
        return None
    owner = max(charge, key=charge.get)
    total = sum(charge.values()) or 1.0
    return {
        "phase": owner,
        "share_pct": round(100.0 * charge[owner] / total, 1),
        "traces_examined": len(slowest),
        "p99_trace": slowest[-1],
        "charges_ms": {k: round(v, 3) for k, v in sorted(charge.items())},
    }


def _depth(span: dict, by_id: dict[str, dict]) -> int:
    d, seen = 0, set()
    cur = span
    while cur.get("parent") and cur["parent"] in by_id:
        if cur["span"] in seen:  # defensive: a cycle must not hang the tool
            break
        seen.add(cur["span"])
        cur = by_id[cur["parent"]]
        d += 1
    return d


def render_waterfall(trace_id: str, members: list[dict]) -> str:
    """One trace as a text timeline: lanes are (pid, host), bars are
    positioned on the request's own clock — the end-to-end waterfall."""
    t0 = min(s["t0"] for s in members)
    t1 = max(s["t1"] for s in members)
    span_s = max(t1 - t0, 1e-9)
    by_id = {s["span"]: s for s in members}
    summary = trace_summary(trace_id, members)
    out = [
        f"trace {trace_id} — {summary['duration_ms']} ms, "
        f"{summary['spans']} span(s) across {summary['processes']} "
        f"process(es) {summary['hosts']}, status={summary['status']}, "
        f"dispatch attempts={summary['dispatch_attempts']}, "
        f"completions={summary['completions']}"
    ]
    label_w = max(
        len("  " * _depth(s, by_id) + s["name"]) for s in members
    )
    for s in members:
        start = 1e3 * (s["t0"] - t0)
        dur = 1e3 * (s["t1"] - s["t0"])
        lo = int(_BAR_WIDTH * (s["t0"] - t0) / span_s)
        hi = int(math.ceil(_BAR_WIDTH * (s["t1"] - t0) / span_s))
        hi = min(max(hi, lo + 1), _BAR_WIDTH)
        bar = " " * lo + "#" * (hi - lo) + " " * (_BAR_WIDTH - hi)
        label = "  " * _depth(s, by_id) + s["name"]
        attrs = s.get("attrs") or {}
        note = ""
        if s["name"] == "route/dispatch":
            note = f" -> {attrs.get('host')} [{attrs.get('outcome')}]"
        elif attrs.get("status") and attrs["status"] != "ok":
            note = f" [{attrs['status']}]"
        out.append(
            f"  {label.ljust(label_w)} |{bar}| "
            f"{start:8.2f} +{dur:8.2f} ms  "
            f"p{s['pid']}/{s['host']}{note}"
        )
    return "\n".join(out)


def pick_default_traces(traces: dict[str, list[dict]], n: int) -> list[str]:
    """The traces worth a waterfall unprompted: re-dispatched ones first
    (the failover evidence), then the slowest."""
    redispatched = [
        t for t, m in traces.items()
        if sum(1 for s in m if s["name"] == "route/dispatch") > 1
    ]
    by_dur = sorted(
        traces,
        key=lambda t: max(s["t1"] for s in traces[t])
        - min(s["t0"] for s in traces[t]),
        reverse=True,
    )
    picked = list(dict.fromkeys(redispatched + by_dur))
    return picked[:n]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="assemble cross-process request waterfalls from a "
        "fleet trace file (obs/collector.py output)"
    )
    ap.add_argument("trace_file", help="collector span JSONL")
    ap.add_argument("--trace-id", default="",
                    help="render exactly this trace's waterfall")
    ap.add_argument("--waterfalls", type=int, default=1,
                    help="how many waterfalls to render unprompted "
                    "(re-dispatched traces first, then slowest)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of text")
    args = ap.parse_args(argv)

    spans, problems = load_spans(args.trace_file)
    if problems:
        print(f"{len(problems)} malformed line(s) in {args.trace_file}:")
        for p in problems:
            print(" -", p)
        return 1
    if not spans:
        print(f"{args.trace_file}: no spans")
        return 1
    traces = group_traces(spans)
    breakdown = phase_breakdown(spans)
    crit = critical_path(traces)
    if args.trace_id:
        if args.trace_id not in traces:
            print(f"trace {args.trace_id} not in {args.trace_file} "
                  f"({len(traces)} trace(s) present)")
            return 1
        picked = [args.trace_id]
    else:
        picked = pick_default_traces(traces, args.waterfalls)

    if args.json:
        print(json.dumps({
            "spans": len(spans),
            "traces": len(traces),
            "phase_breakdown": breakdown,
            "critical_path": crit,
            "waterfalls": [
                trace_summary(t, traces[t]) for t in picked
            ],
        }, indent=2))
        return 0

    print(f"fleet trace report: {args.trace_file}")
    print(f"  {len(spans)} span(s) in {len(traces)} trace(s) across "
          f"{len({s['pid'] for s in spans})} process(es)")
    print()
    print("per-phase latency breakdown (all collected spans):")
    name_w = max(len(n) for n in breakdown)
    print(f"  {'phase'.ljust(name_w)}  {'count':>7}  {'p50_ms':>9}  "
          f"{'p99_ms':>9}  {'max_ms':>9}")
    for name, st in breakdown.items():
        print(f"  {name.ljust(name_w)}  {st['count']:>7}  "
              f"{st['p50_ms']:>9.3f}  {st['p99_ms']:>9.3f}  "
              f"{st['max_ms']:>9.3f}")
    if crit is not None:
        print()
        print(
            f"critical path: phase {crit['phase']} owns the p99 "
            f"({crit['share_pct']}% of self-time across the "
            f"{crit['traces_examined']} slowest trace(s))"
        )
    for t in picked:
        print()
        print(render_waterfall(t, traces[t]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
