"""Inception-v3 in Flax (NHWC), with auxiliary logits handled *correctly*.

Parity with the reference's torchvision inception_v3 factory
(``models.py:83-95``), which replaces both ``AuxLogits.fc`` and ``fc``
(``models.py:90-94``) — but whose training path is latently broken: the
reference feeds 128×128 inputs (needs ≥299) and never unpacks the
``(logits, aux_logits)`` train-mode output (``main.py:149-150``; SURVEY §3
quirks). Here inception runs at 299×299 and the train step applies the
standard 0.4-weighted aux loss (see ``ops/losses.py``).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import adaptive_avg_pool, global_avg_pool, max_pool


class BasicConv(nn.Module):
    """Conv + BN(eps=1e-3, as in torchvision inception) + ReLU."""

    features: int
    kernel: tuple[int, int]
    stride: int = 1
    padding: Any = 0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad), (pad, pad)]
        elif isinstance(pad, tuple):
            pad = [(pad[0], pad[0]), (pad[1], pad[1])]
        x = nn.Conv(
            self.features, self.kernel, strides=(self.stride, self.stride), padding=pad,
            use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype, name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype, axis_name=self.bn_axis_name, name="bn",
        )(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    def _c(self, f, k, s=1, p=0, name=None):
        return BasicConv(f, k if isinstance(k, tuple) else (k, k), s, p,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         bn_axis_name=self.bn_axis_name, name=name)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        b1 = self._c(64, 1, name="branch1x1")(x, train)
        b5 = self._c(48, 1, name="branch5x5_1")(x, train)
        b5 = self._c(64, 5, p=2, name="branch5x5_2")(b5, train)
        b3 = self._c(64, 1, name="branch3x3dbl_1")(x, train)
        b3 = self._c(96, 3, p=1, name="branch3x3dbl_2")(b3, train)
        b3 = self._c(96, 3, p=1, name="branch3x3dbl_3")(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])
        bp = self._c(self.pool_features, 1, name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    def _c(self, f, k, s=1, p=0, name=None):
        return BasicConv(f, k if isinstance(k, tuple) else (k, k), s, p,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         bn_axis_name=self.bn_axis_name, name=name)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        b3 = self._c(384, 3, s=2, name="branch3x3")(x, train)
        bd = self._c(64, 1, name="branch3x3dbl_1")(x, train)
        bd = self._c(96, 3, p=1, name="branch3x3dbl_2")(bd, train)
        bd = self._c(96, 3, s=2, name="branch3x3dbl_3")(bd, train)
        bp = max_pool(x, 3, 2)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    def _c(self, f, k, s=1, p=0, name=None):
        return BasicConv(f, k if isinstance(k, tuple) else (k, k), s, p,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         bn_axis_name=self.bn_axis_name, name=name)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        c7 = self.channels_7x7
        b1 = self._c(192, 1, name="branch1x1")(x, train)
        b7 = self._c(c7, 1, name="branch7x7_1")(x, train)
        b7 = self._c(c7, (1, 7), p=(0, 3), name="branch7x7_2")(b7, train)
        b7 = self._c(192, (7, 1), p=(3, 0), name="branch7x7_3")(b7, train)
        bd = self._c(c7, 1, name="branch7x7dbl_1")(x, train)
        bd = self._c(c7, (7, 1), p=(3, 0), name="branch7x7dbl_2")(bd, train)
        bd = self._c(c7, (1, 7), p=(0, 3), name="branch7x7dbl_3")(bd, train)
        bd = self._c(c7, (7, 1), p=(3, 0), name="branch7x7dbl_4")(bd, train)
        bd = self._c(192, (1, 7), p=(0, 3), name="branch7x7dbl_5")(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])
        bp = self._c(192, 1, name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    def _c(self, f, k, s=1, p=0, name=None):
        return BasicConv(f, k if isinstance(k, tuple) else (k, k), s, p,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         bn_axis_name=self.bn_axis_name, name=name)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        b3 = self._c(192, 1, name="branch3x3_1")(x, train)
        b3 = self._c(320, 3, s=2, name="branch3x3_2")(b3, train)
        b7 = self._c(192, 1, name="branch7x7x3_1")(x, train)
        b7 = self._c(192, (1, 7), p=(0, 3), name="branch7x7x3_2")(b7, train)
        b7 = self._c(192, (7, 1), p=(3, 0), name="branch7x7x3_3")(b7, train)
        b7 = self._c(192, 3, s=2, name="branch7x7x3_4")(b7, train)
        bp = max_pool(x, 3, 2)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    def _c(self, f, k, s=1, p=0, name=None):
        return BasicConv(f, k if isinstance(k, tuple) else (k, k), s, p,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         bn_axis_name=self.bn_axis_name, name=name)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        b1 = self._c(320, 1, name="branch1x1")(x, train)
        b3 = self._c(384, 1, name="branch3x3_1")(x, train)
        b3 = jnp.concatenate(
            [
                self._c(384, (1, 3), p=(0, 1), name="branch3x3_2a")(b3, train),
                self._c(384, (3, 1), p=(1, 0), name="branch3x3_2b")(b3, train),
            ],
            axis=-1,
        )
        bd = self._c(448, 1, name="branch3x3dbl_1")(x, train)
        bd = self._c(384, 3, p=1, name="branch3x3dbl_2")(bd, train)
        bd = jnp.concatenate(
            [
                self._c(384, (1, 3), p=(0, 1), name="branch3x3dbl_3a")(bd, train),
                self._c(384, (3, 1), p=(1, 0), name="branch3x3dbl_3b")(bd, train),
            ],
            axis=-1,
        )
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])
        bp = self._c(192, 1, name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionAux(nn.Module):
    """Aux classifier; its Dense is named ``aux_head`` so feature_extract and
    the head-replacement semantics cover it (reference ``models.py:90-91``)."""

    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = BasicConv(128, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype,
                      bn_axis_name=self.bn_axis_name, name="conv0")(x, train)
        x = BasicConv(768, (5, 5), dtype=self.dtype, param_dtype=self.param_dtype,
                      bn_axis_name=self.bn_axis_name, name="conv1")(x, train)
        x = adaptive_avg_pool(x, (1, 1)).reshape(x.shape[0], -1)
        # Head matmul in compute dtype; the loss computes softmax in float32.
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            name="aux_head",
        )(x)


class InceptionV3(nn.Module):
    num_classes: int
    aux_logits: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    dropout_rate: float = 0.5
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False):
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype,
                  bn_axis_name=self.bn_axis_name)
        c = lambda f, k, s=1, p=0, name=None: BasicConv(
            f, k if isinstance(k, tuple) else (k, k), s, p, name=name, **kw
        )
        x = c(32, 3, s=2, name="Conv2d_1a_3x3")(x, train)
        x = c(32, 3, name="Conv2d_2a_3x3")(x, train)
        x = c(64, 3, p=1, name="Conv2d_2b_3x3")(x, train)
        x = max_pool(x, 3, 2)
        x = c(80, 1, name="Conv2d_3b_1x1")(x, train)
        x = c(192, 3, name="Conv2d_4a_3x3")(x, train)
        x = max_pool(x, 3, 2)
        x = InceptionA(pool_features=32, name="Mixed_5b", **kw)(x, train)
        x = InceptionA(pool_features=64, name="Mixed_5c", **kw)(x, train)
        x = InceptionA(pool_features=64, name="Mixed_5d", **kw)(x, train)
        x = InceptionB(name="Mixed_6a", **kw)(x, train)
        x = InceptionC(channels_7x7=128, name="Mixed_6b", **kw)(x, train)
        x = InceptionC(channels_7x7=160, name="Mixed_6c", **kw)(x, train)
        x = InceptionC(channels_7x7=160, name="Mixed_6d", **kw)(x, train)
        x = InceptionC(channels_7x7=192, name="Mixed_6e", **kw)(x, train)

        aux = None
        if self.aux_logits and train:
            aux = InceptionAux(self.num_classes, name="AuxLogits", **kw)(x, train)

        x = InceptionD(name="Mixed_7a", **kw)(x, train)
        x = InceptionE(name="Mixed_7b", **kw)(x, train)
        x = InceptionE(name="Mixed_7c", **kw)(x, train)

        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # Head matmul in compute dtype; the loss computes softmax in float32.
        logits = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)
        if aux is not None:
            return logits, aux
        return logits


def inception_v3(num_classes: int, **kw: Any) -> InceptionV3:
    return InceptionV3(num_classes=num_classes, **kw)
