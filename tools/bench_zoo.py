"""Per-architecture training throughput across the full model zoo.

The headline ``bench.py`` measures the reference's north-star workload
(resnet18); this sweeps all seven architectures of the zoo
(≙ ``models.py:16-101``) through the same jitted DP train step on whatever
chips are present, and prints one JSON line per architecture:

    {"model": ..., "images_per_sec_per_chip": N, "mfu_pct": N, ...}

Run: ``python tools/bench_zoo.py [--steps 20] [--out docs/zoo_bench.json]``

Per-arch batch sizes are throughput-reasonable single-chip defaults, scaled
down where activation memory is the binding constraint (vgg11_bn's big
early feature maps; inception's 299px input — the size the reference would
have needed for inception to work at all, SURVEY §3 quirks).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMG_PER_SEC_PER_WORKER = 4.4  # BASELINE.md, training.log:1268-1275
NUM_CLASSES = 64500  # utils.py:39

# (batch per chip, image size). 128px mirrors utils.py:33-34 except
# inception_v3, which genuinely requires 299 (models.py:95, SURVEY §3).
ZOO = {
    "resnet18": (2048, 128),
    "resnet34": (2048, 128),
    "alexnet": (2048, 128),
    "vgg11_bn": (512, 128),
    # squeezenet's classifier is a 1x1 conv applied BEFORE global pooling
    # (≙ models.py:70), so its head activation is [B, 8, 8, 64500] — 64x the
    # other archs' logits per example. Batch 2048 blows compile memory.
    "squeezenet1_0": (512, 128),
    "densenet121": (1024, 128),
    "inception_v3": (256, 299),
    "mobilenet_v2": (1024, 128),
    "efficientnet_b0": (1024, 128),
    # vit at 128px/patch16 = 64 tokens; large batches keep the MXU fed.
    "vit_s16": (2048, 128),
    "vit_b16": (1024, 128),
    "vit_moe_s16": (1024, 128),
}


def build_state_and_batch(
    model_name: str, batch_per_chip: int, image: int, optimizer: bool = True,
    remat_blocks: bool = False, attn_impl: str = "full", stem_s2d: bool = False,
    fused_stem: bool | None = None, qkv_fused: bool = False, mesh_pods: int = 1,
):
    """Shared harness setup (also used by tools/bench_eval.py and
    tools/profile_step.py): mesh, placed train state, and a random sharded
    device batch. ``optimizer=False`` skips the Adam moment trees (~2x params
    of f32 HBM) for forward-only benches. ``mesh_pods > 1`` nests the data
    axis (pod, ici) for the hierarchical-sync profiles (ISSUE 15)."""
    import optax

    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    mesh = create_mesh(MeshConfig(pods=mesh_pods))
    if fused_stem is None:
        # Same contract as bench.py: the fused stem is the headline resnet
        # configuration on TPU; MPT_FUSED_STEM=0 reverts for A/B.
        from mpi_pytorch_tpu.models.registry import fused_stem_default

        fused_stem = fused_stem_default(model_name)
    bundle, variables = create_model_bundle(
        model_name, NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=image,
        dtype=jnp.bfloat16, param_dtype=jnp.float32, remat_blocks=remat_blocks,
        attn_impl=attn_impl, stem_s2d=stem_s2d, fused_stem=fused_stem,
        # Multi-chip: the fused kernels (stem, fused-small attention)
        # shard_map themselves over the data axis (ops/fused_stem.py /
        # ops/fused_attention_small.py, Multi-chip) instead of degrading to
        # an activation all-gather around a replicated Mosaic call.
        dp_mesh=mesh if (fused_stem or attn_impl == "fused-small") else None,
        qkv_fused=qkv_fused,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(4e-4) if optimizer else optax.identity(),
        rng=jax.random.PRNGKey(1),
    )
    state = place_state_on_mesh(state, mesh)
    rng = np.random.default_rng(0)
    device_batch = shard_batch(
        (rng.standard_normal((batch, image, image, 3), np.float32),
         rng.integers(0, NUM_CLASSES, size=(batch,)).astype(np.int32)),
        mesh,
    )
    return mesh, state, device_batch, n_chips, batch


def timed_train_steps(compiled, state, device_batch, steps, warmup, trace_dir=""):
    """Warmup then time ``steps`` calls of a compiled train step, blocking on
    the DONATED STATE, not a metrics scalar — scalar futures can resolve
    early through the remote-PJRT relay and overstate throughput (bench.py).
    Optionally wraps the timed steps in a jax.profiler trace."""
    for _ in range(warmup):
        state, _ = compiled(state, device_batch)
    jax.block_until_ready(state.params)

    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, _ = compiled(state, device_batch)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    if trace_dir:
        jax.profiler.stop_trace()
    return dt, state


def bench_one(model_name: str, batch_per_chip: int, image: int, steps: int,
              warmup: int, attn_impl: str = "full", stem_s2d: bool = False,
              qkv_fused: bool = False):
    from mpi_pytorch_tpu.train.step import make_train_step
    from mpi_pytorch_tpu.utils.hardware import peak_bf16_tflops, step_flops

    from mpi_pytorch_tpu.models.registry import fused_stem_default

    fused_stem = fused_stem_default(model_name)  # what the harness resolves
    mesh, state, device_batch, n_chips, batch = build_state_and_batch(
        model_name, batch_per_chip, image, attn_impl=attn_impl,
        stem_s2d=stem_s2d, qkv_fused=qkv_fused, fused_stem=fused_stem,
    )
    step = make_train_step(jnp.bfloat16)

    # Same channel and contract as bench.py: a set MPT_COMPILER_OPTIONS
    # (JSON dict) is applied verbatim as per-compile options (client-side
    # XLA_FLAGS parsing is fatal for TPU-only flags under the relay). The
    # zoo applies NO default options so cross-model rows stay comparable
    # across rounds.
    options = json.loads(os.environ.get("MPT_COMPILER_OPTIONS", "null"))
    compiled = step.lower(state, device_batch).compile(
        compiler_options=options or None
    )
    flops_per_step = step_flops(compiled)
    dt, state = timed_train_steps(compiled, state, device_batch, steps, warmup)

    ips = steps * batch / dt
    tflops_per_chip = flops_per_step * steps / dt / 1e12  # cost analysis is per-device
    peak = peak_bf16_tflops(jax.devices()[0])
    rec = {
        "model": model_name,
        "batch_per_chip": batch_per_chip,
        "image_size": image,
        "chips": n_chips,
        "images_per_sec_per_chip": round(ips / n_chips, 1),
        "vs_baseline": round(ips / n_chips / REFERENCE_IMG_PER_SEC_PER_WORKER, 1),
        "step_ms": round(dt / steps * 1e3, 2),
        "tflops_per_chip": round(tflops_per_chip, 2),
    }
    if attn_impl != "full":
        rec["attn_impl"] = attn_impl
    if stem_s2d:
        rec["stem_s2d"] = True
    if qkv_fused:
        rec["qkv_fused"] = True
    if fused_stem:
        rec["fused_stem"] = True
    if peak and flops_per_step > 0:
        rec["mfu_pct"] = round(100.0 * tflops_per_chip / peak, 1)
    return rec


def bench_one_in_child(name: str, steps: int, warmup: int, timeout_s: int,
                       attn_impl: str = "full", stem_s2d: bool = False,
                       qkv_fused: bool = False) -> dict:
    """Run one model's bench in a fresh child interpreter with a hard
    timeout. A wedged TPU relay blocks inside a compile/execute RPC that no
    in-process watchdog can interrupt (observed: a full-sweep hang with zero
    rows produced) — killing a child instead turns the wedge into an error
    row and lets the remaining models run if the relay recovers."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [
        sys.executable, os.path.abspath(__file__), "--in-process",
        "--models", name, "--steps", str(steps), "--warmup", str(warmup),
        "--attn-impl", attn_impl,
    ] + (["--stem-s2d"] if stem_s2d else []) + (
        ["--qkv-fused"] if qkv_fused else [])
    try:
        proc = subprocess.run(
            cmd, cwd=repo, capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired:
        return {"model": name, "error": f"child exceeded {timeout_s}s (wedged TPU relay?)"}
    for line in (proc.stdout or "").splitlines()[::-1]:
        if line.startswith("{"):
            return json.loads(line)
    tail = (proc.stderr or "").strip().splitlines()[-3:]
    return {"model": name, "error": f"no JSON (rc={proc.returncode}): " + " | ".join(tail)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--attn-impl", default="full",
                    choices=["full", "flash", "fused-small"],
                    help="vit family only: dense-attention implementation "
                    "(fused-small = the tiny-S Pallas kernel, "
                    "ops/fused_attention_small.py — the vit_s16 A/B row)")
    ap.add_argument("--models", default=",".join(ZOO), help="comma-separated subset")
    ap.add_argument("--qkv-fused", action="store_true",
                    help="fuse q/k/v projections into one matmul (vit family)")
    ap.add_argument("--stem-s2d", action="store_true",
                    help="resnet family only: space-to-depth stem conv")
    ap.add_argument("--out", default="", help="also write a JSON array to this path")
    ap.add_argument(
        "--in-process", action="store_true",
        help="bench in this process (no per-model watchdog child); the "
        "default isolates each model in a child with --model-timeout",
    )
    ap.add_argument("--model-timeout", type=int, default=1200)
    args = ap.parse_args()

    records = []
    for name in (m.strip() for m in args.models.split(",") if m.strip()):
        try:
            batch, image = ZOO[name]  # inside try: a typo'd name must not
            if args.in_process:  # kill the sweep or discard --out
                rec = bench_one(name, batch, image, args.steps, args.warmup,
                                attn_impl=args.attn_impl, stem_s2d=args.stem_s2d,
                                qkv_fused=args.qkv_fused)
            else:
                rec = bench_one_in_child(
                    name, args.steps, args.warmup, args.model_timeout,
                    attn_impl=args.attn_impl, stem_s2d=args.stem_s2d,
                    qkv_fused=args.qkv_fused,
                )
        except Exception as e:
            rec = {"model": name, "error": f"{type(e).__name__}: {e}"[:300]}
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
