"""Registry-driven fleet autoscaler: grow/shrink the host set from the
telemetry the fleet already publishes (ISSUE 12 / ROADMAP item 2).

The serving-side twin of PR 7's elastic training: where elastic resume
re-shapes a TRAINING world around preemption, this loop re-shapes the
SERVING world around load — the close of the millions-of-users path the
reference's fixed N-rank pipeline (arXiv 1603.02339's lineage) never had.

Signals, per tick, all read from surfaces that already exist:

- **admission-reject rate** — the router's front-door rejection counter,
  differenced against the last tick (rejects/s). The front door rejects
  only when the FLEET-WIDE budget is exhausted, so a sustained rate is
  the cleanest "the fleet is too small" signal there is.
- **p99 vs target** — the worst per-host cumulative sketch p99
  (``serve/request_latency_ms``) from the merged registry snapshots, the
  same percentile the ``FleetController`` steers on. The controller owns
  the PER-HOST knobs (wait/buckets/precision); this loop owns the host
  COUNT — it only acts on latency when the queue-depth trend confirms
  the fleet is genuinely filling up, so the two loops cannot fight over
  a transient.
- **queue-depth trend** — the sum of host queue depths over a sliding
  window; monotone growth means the backlog is structural.

Policy (deliberately boring — the bounds are the feature):

- scale **up** when rejects flow or (p99 breaches AND the queue trend
  rises), below ``max_hosts``: ``spawn_fn()`` brings up a host WARMED
  from the persistent compilation cache (the spawner asserts zero
  steady-state compiles before handing it over) and the router admits it.
- scale **down** after ``idle_ticks`` consecutive quiet ticks (no
  rejects, empty queues, p99 under half target), above ``min_hosts``:
  the router drains the COLDEST host (fewest outstanding + least
  dispatched) and ``retire_fn`` reaps its process.
- a **cooldown** between actions bounds the loop's slew rate — scaling
  can lag, it must never flap.
- ``rolling_restart()`` walks the fleet host-by-host through the
  supervisor's drain → restart → warm → re-admit path (config push,
  binary upgrade) without dropping below N-1 live hosts.

Every action writes a schema-stamped ``kind="fleet"`` record
(``event="scale_up" | "scale_down" | "restart"``, schema v8) carrying the
evidence it acted on — hosts_from/to, reject rate, p99, queue depth.
Drive it with ``tick()`` (tests, a fake clock) or ``start()``/``stop()``.
"""

from __future__ import annotations

import collections
import threading
import time

from mpi_pytorch_tpu.serve.batcher import ServeError


class FleetAutoscaler:
    """Scale the host set up/down from registry metrics, bounded and
    cooled down; every action a ``kind="fleet"`` record."""

    def __init__(
        self,
        router,
        *,
        spawn_fn,
        retire_fn=None,
        restart_fn=None,
        target_p99_ms: float = 0.0,
        min_hosts: int = 1,
        max_hosts: int = 8,
        cooldown_s: float = 30.0,
        reject_rate_up: float = 0.5,
        idle_ticks: int = 2,
        trend_window: int = 3,
        interval_s: float = 2.0,
        latency_metric: str = "serve/request_latency_ms",
        metrics=None,
        transport: str | None = None,
        logger=None,
        clock=time.monotonic,
    ):
        from mpi_pytorch_tpu.utils.logging import run_logger

        if min_hosts < 1:
            raise ValueError(f"min_hosts must be >= 1, got {min_hosts}")
        if max_hosts < min_hosts:
            raise ValueError(
                f"max_hosts ({max_hosts}) must be >= min_hosts ({min_hosts})"
            )
        self._router = router
        self._spawn_fn = spawn_fn  # () -> HostHandle, warmed
        # (host) -> None: detach the host from supervision/process
        # management — called BEFORE the router drains it, so the
        # supervisor never reads the deliberate shutdown as a death.
        self._retire_fn = retire_fn
        self._restart_fn = restart_fn  # (host) -> None, rolling unit
        self.target_p99_ms = float(target_p99_ms)
        self.min_hosts = int(min_hosts)
        self.max_hosts = int(max_hosts)
        self.cooldown_s = float(cooldown_s)
        self.reject_rate_up = float(reject_rate_up)
        self.idle_ticks = int(idle_ticks)
        self._interval_s = float(interval_s)
        self._latency_metric = latency_metric
        self._metrics = metrics
        self._transport = transport
        self._logger = logger or run_logger()
        self._clock = clock
        self._last_rejects = 0
        self._last_rejects_by_model: dict[str, int] = {}
        self._last_tick_t: float | None = None
        self._last_action_t: float | None = None
        self._idle_streak = 0
        self._queue_trend: collections.deque = collections.deque(
            maxlen=max(2, int(trend_window))
        )
        self.actions: list[str] = []  # event kinds, append-only (tests/CI)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- signals

    def _signals(self) -> dict:
        now = self._clock()
        rejects = self._router.front_door_rejections
        dt = (
            now - self._last_tick_t
            if self._last_tick_t is not None else None
        )
        reject_rate = (
            (rejects - self._last_rejects) / dt if dt and dt > 0 else 0.0
        )
        self._last_rejects = rejects
        # Tenant-aware pressure (ISSUE 14): per-model front-door reject
        # deltas name WHICH tenant is starved — the scale-up record (and
        # its reason) carry the pressured tenant, so "why did the fleet
        # grow" is answerable per model.
        pressured_model = None
        by_model = dict(
            getattr(self._router, "rejections_by_model", None) or {}
        )
        if by_model and dt and dt > 0:
            deltas = {
                m: (n - self._last_rejects_by_model.get(m, 0)) / dt
                for m, n in by_model.items()
            }
            worst = max(deltas, key=deltas.get)
            if deltas[worst] > 0:
                pressured_model = worst
        self._last_rejects_by_model = by_model
        self._last_tick_t = now

        p99 = None
        queue_depth = 0
        for host in self._router.active_hosts():
            try:
                snap = host.snapshot()
            except ServeError:
                continue  # the router's probe loop owns unreachable hosts
            hist = snap.get("histograms", {}).get(self._latency_metric)
            if hist and hist.get("count"):
                p99 = max(p99 or 0.0, hist["p99"])
            qd = snap.get("gauges", {}).get("serve/queue_depth") or 0
            queue_depth += int(qd)
        self._queue_trend.append(queue_depth)
        trend = list(self._queue_trend)
        rising = (
            len(trend) == self._queue_trend.maxlen
            and all(b > a for a, b in zip(trend, trend[1:]))
            and trend[-1] > 0
        )
        return {
            "reject_rate": reject_rate,
            "p99_ms": p99,
            "queue_depth": queue_depth,
            "queue_rising": rising,
            "pressured_model": pressured_model,
        }

    # ------------------------------------------------------------- the tick

    def tick(self) -> str | None:
        """Evaluate once; returns the action taken ("scale_up" /
        "scale_down") or None. Signal state updates every tick — cooldown
        suppresses ACTIONS, never observation."""
        hosts = self._router.active_hosts()
        n = len(hosts)
        sig = self._signals()

        breach = self.target_p99_ms > 0 and (
            sig["p99_ms"] is not None and sig["p99_ms"] > self.target_p99_ms
        )
        pressure = (
            sig["reject_rate"] > self.reject_rate_up
            or (breach and sig["queue_rising"])
        )
        idle = (
            sig["reject_rate"] <= 0
            and sig["queue_depth"] == 0
            and not breach
        )
        self._idle_streak = self._idle_streak + 1 if idle else 0

        if pressure and n >= self.max_hosts:
            self._logger.warning(
                "autoscaler: pressure at the max_hosts=%d bound "
                "(reject_rate %.2f/s, p99 %s ms) — cannot scale further",
                self.max_hosts, sig["reject_rate"],
                "-" if sig["p99_ms"] is None else f"{sig['p99_ms']:.1f}",
            )
        in_cooldown = (
            self._last_action_t is not None
            and self._clock() - self._last_action_t < self.cooldown_s
        )
        if in_cooldown:
            return None
        if pressure and n < self.max_hosts:
            return self._scale_up(n, sig)
        if (
            n > self.min_hosts
            and self._idle_streak >= self.idle_ticks
        ):
            return self._scale_down(n, sig, hosts)
        return None

    def _record(self, event: str, n_from: int, n_to: int, sig: dict,
                host_name: str | None, reason: str,
                compiles: int | None = None) -> None:
        self.actions.append(event)
        self._last_action_t = self._clock()
        self._idle_streak = 0
        self._logger.info(
            "autoscaler: %s %d → %d host(s) — %s", event, n_from, n_to,
            reason,
        )
        if self._metrics is None:
            return
        record = {
            "kind": "fleet", "event": event,
            "hosts_from": n_from, "hosts_to": n_to,
            "reason": reason,
            "reject_rate": round(sig["reject_rate"], 4),
            "queue_depth": sig["queue_depth"],
        }
        if host_name is not None:
            record["host"] = host_name
        if sig["p99_ms"] is not None:
            record["p99_ms"] = round(sig["p99_ms"], 3)
        if sig.get("pressured_model") is not None:
            # Schema-v10: the tenant whose rejections drove the action.
            record["model"] = sig["pressured_model"]
        if self.target_p99_ms > 0:
            record["target_p99_ms"] = self.target_p99_ms
        if compiles is not None:
            record["compiles_after_warmup"] = compiles
        if self._transport is not None:
            record["transport"] = self._transport
        self._metrics.write(record)

    def _scale_up(self, n: int, sig: dict) -> str | None:
        reason = (
            f"admission rejects at {sig['reject_rate']:.2f}/s"
            if sig["reject_rate"] > self.reject_rate_up
            else f"p99 {sig['p99_ms']:.1f} ms over target "
                 f"{self.target_p99_ms:.1f} with rising queues"
        )
        if sig.get("pressured_model") is not None:
            reason += f" (pressured tenant: {sig['pressured_model']})"
        try:
            host = self._spawn_fn()
        except Exception as e:  # noqa: BLE001 — a failed spawn must not kill the loop
            self._logger.warning("autoscaler: scale-up spawn failed: %s", e)
            return None
        compiles = None
        try:
            compiles = int(host.compiles_after_warmup())
        except ServeError:
            pass
        if compiles:
            self._logger.error(
                "autoscaler: new host %s shows %d steady-state compile(s) "
                "— the warm-start invariant is broken", host.name, compiles,
            )
        self._router.add_host(host)
        self._record("scale_up", n, n + 1, sig, host.name, reason,
                     compiles=compiles)
        return "scale_up"

    def _scale_down(self, n: int, sig: dict, hosts) -> str | None:
        stats = self._router.stats()
        outstanding = stats.get("outstanding_by_host", {})
        dispatched = stats.get("dispatched_by_host", {})
        coldest = min(
            hosts,
            key=lambda h: (
                outstanding.get(h.name, 0), dispatched.get(h.name, 0)
            ),
        )
        # Detach from supervision BEFORE initiating the shutdown: the
        # retired host's process exits as part of the drain, and a still-
        # supervising loop would read that exit as a death and resurrect
        # the host the fleet just decided to shed.
        if self._retire_fn is not None:
            try:
                self._retire_fn(coldest)
            except Exception as e:  # noqa: BLE001 — still drain it
                self._logger.warning(
                    "autoscaler: detach of %s failed: %s", coldest.name, e,
                )
        retired = self._router.retire_host(coldest.name, wait_s=30.0)
        if retired is None:
            # Raced a failover: the host is gone either way (and already
            # detached) — the failover record tells that story.
            return None
        self._record(
            "scale_down", n, n - 1, sig, coldest.name,
            f"idle for {self._idle_streak} tick(s); retiring coldest",
        )
        return "scale_down"

    # ------------------------------------------------------ rolling restart

    def rolling_restart(self, reason: str = "rolling restart") -> int:
        """Drain → restart → warm → re-admit every active host in turn
        (needs ``restart_fn``; the supervisor's ``restart_host`` is the
        canonical one). Returns how many hosts were cycled."""
        if self._restart_fn is None:
            raise ServeError(
                "rolling_restart needs a restart_fn (the supervisor's "
                "restart-host path)"
            )
        cycled = 0
        for host in list(self._router.active_hosts()):
            n = len(self._router.active_hosts())
            self._restart_fn(host)
            cycled += 1
            sig = {
                "reject_rate": 0.0, "p99_ms": None,
                "queue_depth": 0, "queue_rising": False,
            }
            self._record("restart", n, n, sig, host.name, reason)
        return cycled

    # ----------------------------------------------------------- background

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — scaling must not kill serving
                self._logger.warning("autoscaler tick failed: %s", e)
