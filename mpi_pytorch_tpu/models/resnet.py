"""ResNet-18/34 in Flax (NHWC, TPU-native).

Capability parity with the reference's torchvision resnet18/34 factories
(``models.py:30-45``): same architecture family (BasicBlock stacks [2,2,2,2] /
[3,4,6,3]), same replaceable ``num_classes`` head. Built from scratch against
the ResNet paper topology; parameter names are chosen so a torchvision
state_dict maps 1:1 for the optional pretrained-weight converter
(tools/convert_torchvision.py).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import batch_norm, global_avg_pool, max_pool


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        conv = lambda f, s, name: nn.Conv(
            f, (3, 3), strides=(s, s), padding=1, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name=name,
        )
        bn = lambda name: batch_norm(name, dtype=self.dtype, axis_name=self.bn_axis_name)

        residual = x
        y = conv(self.features, self.stride, "conv1")(x)
        y = bn("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.features, 1, "conv2")(y)
        y = bn("bn2")(y, use_running_average=not train)

        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features, (1, 1), strides=(self.stride, self.stride), use_bias=False,
                dtype=self.dtype, param_dtype=self.param_dtype, name="downsample_conv",
            )(x)
            residual = batch_norm("downsample_bn", dtype=self.dtype, axis_name=self.bn_axis_name)(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None
    # Checkpoint each BasicBlock (nn.remat): the backward pass recomputes one
    # block at a time instead of keeping every block's activations live —
    # the per-stage placement whole-forward jax.checkpoint can't give
    # (docs/RESULTS.md §4b). Param tree paths are unchanged (lifted
    # transforms preserve scopes), so checkpoints/converters are unaffected.
    remat_blocks: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="conv1",
        )(x)
        x = batch_norm("bn1", dtype=self.dtype, axis_name=self.bn_axis_name)(
            x, use_running_average=not train
        )
        x = nn.relu(x)
        x = max_pool(x, 3, 2, padding=1)

        block_cls = (
            nn.remat(BasicBlock, static_argnums=(2,))  # (self, x, train)
            if self.remat_blocks
            else BasicBlock
        )
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                x = block_cls(
                    features=64 * 2**stage,
                    stride=stride,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    bn_axis_name=self.bn_axis_name,
                    name=f"layer{stage + 1}_{block}",
                )(x, train)

        x = global_avg_pool(x)
        # Head matmul in compute dtype (bf16 rides the MXU; measured 2.38 vs
        # 2.96 ms fwd+bwd at B=512/V=64500 on v5e); the loss re-casts logits
        # to float32 for a stable softmax (ops/losses.py). Under bfloat16 the
        # logits (and therefore eval argmax on near-ties) carry bf16
        # quantization — compute_dtype=float32 restores exact f32 semantics
        # for parity comparisons.
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)


def resnet18(num_classes: int, **kw: Any) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, **kw)


def resnet34(num_classes: int, **kw: Any) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)
