"""Boolean ``MPT_*`` env-knob parsing — ONE definition of truthiness.

Every boolean knob in the framework reads through here so the convention
(case-insensitive; '', '0', 'false', 'no', 'off' mean off, anything else
means on — the same falsy set the CLI's ``--flag`` parser accepts,
``config._str2bool``) cannot drift between call sites. Advisor r5: 'no'
used to silently mean ON because only ''/'0'/'false' were recognized.
"""

from __future__ import annotations

import os

FALSY = ("", "0", "false", "no", "off")


def env_flag(name: str, default: bool = False) -> bool:
    """The value of boolean env knob ``name``; ``default`` when unset."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() not in FALSY
