"""End-to-end ``use_pretrained`` coverage (reference ``utils.py:45`` +
``models.py:33``): a torchvision-style state_dict saved as a real ``.pth``
file goes through the offline converter's convert+save path, and
``create_model_bundle(use_pretrained=True)`` loads the result — backbone
weights match the converted tensors, the num_classes head keeps fresh init."""

import importlib.util
import os

import jax
import numpy as np
import pytest

from mpi_pytorch_tpu.models import create_model_bundle
from mpi_pytorch_tpu.models.common import head_filter
from mpi_pytorch_tpu.models.torch_mapping import tv_entries

# The whole module rides the expensive session-scoped model-zoo
# compile (or end-to-end trainer runs): core-suite runs skip it
# (pytest -m 'not slow').
pytestmark = pytest.mark.slow

ARCH = "resnet18"
NUM_CLASSES = 50


def _load_converter():
    spec = importlib.util.spec_from_file_location(
        "convert_torchvision",
        os.path.join(os.path.dirname(__file__), "..", "tools", "convert_torchvision.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _flat(tree):
    return [
        (tuple(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


def _torch_shape(flax_shape):
    if len(flax_shape) == 4:
        return (flax_shape[3], flax_shape[2], flax_shape[0], flax_shape[1])
    if len(flax_shape) == 2:
        return (flax_shape[1], flax_shape[0])
    return flax_shape


def test_use_pretrained_end_to_end(tmp_path):
    torch = pytest.importorskip("torch")

    # 1. a synthetic torchvision-style state_dict, saved as a real .pth
    _, template = create_model_bundle(
        ARCH, NUM_CLASSES, rng=jax.random.PRNGKey(0)
    )
    rng = np.random.default_rng(7)
    state_dict, transforms = {}, {}
    for collection in ("params", "batch_stats"):
        if collection not in template:
            continue
        for path, leaf in _flat(template[collection]):
            entry = tv_entries(ARCH, collection, path, tuple(leaf.shape))
            if entry is None:
                continue
            key, transform = entry
            arr = rng.standard_normal(_torch_shape(tuple(leaf.shape))).astype(np.float32)
            state_dict[key] = torch.from_numpy(arr)
            transforms[(collection,) + path] = (transform, arr)
    pth = str(tmp_path / f"{ARCH}.pth")
    torch.save(state_dict, pth)

    # 2. the converter's real convert+save path (torch .pth → msgpack)
    converter = _load_converter()
    out = converter.convert(ARCH, str(tmp_path / "pretrained"), pth, NUM_CLASSES)
    assert os.path.exists(out)

    # 3. the driver-facing load path
    bundle, variables = create_model_bundle(
        ARCH, NUM_CLASSES, use_pretrained=True,
        pretrained_dir=str(tmp_path / "pretrained"),
        rng=jax.random.PRNGKey(1),
    )
    fresh_bundle, fresh = create_model_bundle(
        ARCH, NUM_CLASSES, rng=jax.random.PRNGKey(1)
    )
    for collection in ("params", "batch_stats"):
        for (path, loaded), (_, fresh_leaf) in zip(
            _flat(variables[collection]), _flat(fresh[collection])
        ):
            full = (collection,) + path
            if head_filter(path):
                # head keeps the fresh num_classes init (≙ reference head
                # replacement, models.py:36)
                np.testing.assert_array_equal(np.asarray(loaded), np.asarray(fresh_leaf))
            else:
                transform, arr = transforms[full]
                np.testing.assert_allclose(
                    np.asarray(loaded), transform(arr), atol=1e-6,
                    err_msg=f"backbone leaf {full} does not match converted weights",
                )
